"""AST → HIR query planning: scopes, name resolution, aggregate planning.

Analog of the reference's ``plan_query``/``plan_select`` path
(sql/src/plan/query.rs, dispatched from sql/src/plan/statement.rs:288):
FROM clause folding with binary joins, WHERE, GROUP BY/HAVING with
aggregate extraction, SELECT item planning, DISTINCT, set operations,
CTEs (Let) and WITH MUTUALLY RECURSIVE (LetRec), ORDER BY/LIMIT as TopK.
"""

from __future__ import annotations

from typing import Optional

from ..expr.relation import AggregateFunc
from ..expr.scalar import BinaryFunc, UnaryFunc, VariadicFunc
from ..repr.schema import GLOBAL_DICT, Column, ColumnType, Schema
from . import ast
from .hir import (
    CatalogInterface,
    HAggregate,
    HCallBinary,
    HCallUnary,
    HCallVariadic,
    HColumn,
    HConstant,
    HDistinct,
    HExists,
    HFilter,
    HGet,
    HIf,
    HInSubquery,
    HJoin,
    HLet,
    HLetRec,
    HLiteral,
    HMap,
    HNegate,
    HProject,
    HReduce,
    HRename,
    HScalarSubquery,
    HTopK,
    HUnion,
    HirRelation,
    PlanError,
    Scope,
    ScopeItem,
    typ_of,
)

# string functions lower to dictionary side-table gathers
# (expr/strings.py): HCallVariadic("str:<fn>", (col, literal params...))
_STR = "str:"

# name -> (env func, n_args incl. the string column, param positions)
_STRING_FUNCS_1 = {
    "upper": "upper",
    "lower": "lower",
    "initcap": "initcap",
    "reverse": "reverse",
    "length": "length",
    "char_length": "length",
    "character_length": "length",
    "ascii": "ascii",
    "bit_length": "bit_length",
    "octet_length": "octet_length",
    "trim": "trim",
    "btrim": "trim",
    "ltrim": "ltrim",
    "rtrim": "rtrim",
}

_UNARY_FUNC_NAMES = {
    "abs": UnaryFunc.ABS,
    "floor": UnaryFunc.FLOOR,
    "ceil": UnaryFunc.CEIL,
    "ceiling": UnaryFunc.CEIL,
    "trunc": UnaryFunc.TRUNC,
    "sqrt": UnaryFunc.SQRT,
    "cbrt": UnaryFunc.CBRT,
    "exp": UnaryFunc.EXP,
    "ln": UnaryFunc.LN,
    "log2": UnaryFunc.LOG2,
    "log10": UnaryFunc.LOG10,
    "sign": UnaryFunc.SIGN,
    "sin": UnaryFunc.SIN,
    "cos": UnaryFunc.COS,
    "tan": UnaryFunc.TAN,
    "asin": UnaryFunc.ASIN,
    "acos": UnaryFunc.ACOS,
    "atan": UnaryFunc.ATAN,
    "radians": UnaryFunc.RADIANS,
    "degrees": UnaryFunc.DEGREES,
}


def _parse_datetime_literal(text: str, ty: ColumnType) -> int:
    """'1994-01-01' -> days since epoch; with a time part -> ms since
    epoch. Plan-time analog of the reference's string-to-date casts."""
    import datetime as _dt

    s = text.strip()
    try:
        if ty is ColumnType.DATE:
            d = _dt.date.fromisoformat(s)
            return (d - _dt.date(1970, 1, 1)).days
        if " " in s or "T" in s:
            dt = _dt.datetime.fromisoformat(s.replace("T", " "))
        else:
            d = _dt.date.fromisoformat(s)
            dt = _dt.datetime(d.year, d.month, d.day)
        epoch = _dt.datetime(1970, 1, 1)
        return int((dt - epoch).total_seconds() * 1000)
    except ValueError as exc:
        raise PlanError(f"invalid {ty.value} literal {text!r}") from exc


_BINOPS = {
    "+": BinaryFunc.ADD,
    "-": BinaryFunc.SUB,
    "*": BinaryFunc.MUL,
    "/": BinaryFunc.DIV,
    "%": BinaryFunc.MOD,
    "=": BinaryFunc.EQ,
    "<>": BinaryFunc.NEQ,
    "<": BinaryFunc.LT,
    "<=": BinaryFunc.LTE,
    ">": BinaryFunc.GT,
    ">=": BinaryFunc.GTE,
}

_VAR_AGGS = {
    "stddev",
    "stddev_samp",
    "stddev_pop",
    "variance",
    "var_samp",
    "var_pop",
}
_BASIC_AGGS = {"string_agg", "array_agg", "list_agg"}
_AGG_FUNCS = (
    {"count", "sum", "min", "max", "avg", "bool_and", "bool_or", "every"}
    | _VAR_AGGS
    | _BASIC_AGGS
)


def _number_literal(text: str) -> HLiteral:
    if "." in text:
        frac = text.split(".", 1)[1]
        scale = len(frac)
        return HLiteral(
            int(text.replace(".", "")), ColumnType.DECIMAL, scale
        )
    return HLiteral(int(text), ColumnType.INT64)


class QueryPlanner:
    def __init__(self, catalog: CatalogInterface):
        self.catalog = catalog
        self._ctes: dict[str, Schema] = {}
        # Stack of enclosing queries' scopes (innermost last): pushed
        # around subquery planning so correlated names resolve to
        # HOuterColumn(level, index) (the reference's leveled ColumnRef,
        # sql/src/plan/scope.rs resolution order).
        self._outer_scopes: list[Scope] = []

    # -- queries ---------------------------------------------------------
    def plan_query(self, q: ast.Query) -> tuple[HirRelation, Scope]:
        saved = dict(self._ctes)
        try:
            if q.mutually_recursive:
                rel, scope = self._plan_wmr(q)
            else:
                lets = []
                for cte in q.ctes:
                    value, vscope = self.plan_query(cte.query)
                    vschema = value.schema()
                    if cte.columns:
                        names = [c[0] for c in cte.columns]
                        if len(names) != vschema.arity:
                            raise PlanError(
                                f"cte {cte.name}: {len(names)} aliases for "
                                f"{vschema.arity} columns"
                            )
                        vschema = vschema.rename(names)
                        value = _rebrand(value, vschema)
                    self._ctes[cte.name] = vschema
                    lets.append((cte.name, value))
                rel, scope = self._plan_set_expr(q.body)
                for name, value in reversed(lets):
                    rel = HLet(name, value, rel)
            rel, scope = self._apply_finishing(rel, scope, q)
            return rel, scope
        finally:
            self._ctes = saved

    def _apply_finishing(self, rel, scope, q: ast.Query):
        # Result-order finishing (RowSetFinishing): recorded for the
        # adapter to apply to peek results. Nested plan_query calls run
        # before the outermost _apply_finishing, so the last write is
        # the top-level query's ordering.
        self.finishing_order = ()
        if q.order_by:
            order = []
            for ob in q.order_by:
                if isinstance(ob.expr, ast.NumberLit):
                    ordinal = int(ob.expr.text)  # ORDER BY 2
                    if not 1 <= ordinal <= len(scope.items):
                        raise PlanError(
                            f"ORDER BY position {ordinal} is not in "
                            f"the select list (1..{len(scope.items)})"
                        )
                    idx = ordinal - 1
                else:
                    idx = scope.resolve(_ident_parts(ob.expr))
                nulls_last = (
                    ob.nulls_last
                    if ob.nulls_last is not None
                    else not ob.desc  # PG default: ASC->LAST, DESC->FIRST
                )
                order.append((idx, ob.desc, nulls_last))
            self.finishing_order = tuple(order)
            if q.limit is not None or q.offset:
                rel = HTopK(rel, (), tuple(order), q.limit, q.offset)
            # bare ORDER BY on an unordered collection is a no-op (the
            # peek finishing layer re-sorts; reference RowSetFinishing)
        elif q.limit is not None or q.offset:
            rel = HTopK(rel, (), (), q.limit, q.offset)
        return rel, scope

    def _plan_wmr(self, q: ast.Query):
        names, value_schemas = [], []
        for cte in q.ctes:
            if not cte.columns or any(t is None for _, t in cte.columns):
                raise PlanError(
                    "WITH MUTUALLY RECURSIVE bindings need (name type, ...)"
                )
            from .hir import parse_type

            cols = []
            for n, t in cte.columns:
                ty, scale = parse_type(t)
                cols.append(Column(n, ty, True, scale))
            sch = Schema(cols)
            names.append(cte.name)
            value_schemas.append(sch)
            self._ctes[cte.name] = sch
        values = []
        for cte, sch in zip(q.ctes, value_schemas):
            v, _ = self.plan_query(cte.query)
            vs = v.schema()
            if vs.arity != sch.arity:
                raise PlanError(
                    f"binding {cte.name}: arity {vs.arity} != declared "
                    f"{sch.arity}"
                )
            values.append(_rebrand(v, sch))
        body, scope = self._plan_set_expr(q.body)
        return (
            HLetRec(
                tuple(names), tuple(values), tuple(value_schemas), body,
                q.recursion_limit,
            ),
            scope,
        )

    def _plan_set_expr(self, se: ast.SetExpr):
        if isinstance(se, ast.SelectExpr):
            return self._plan_select(se.select)
        if isinstance(se, ast.SetOp):
            left, lscope = self._plan_set_expr(se.left)
            right, _ = self._plan_set_expr(se.right)
            ls, rs = left.schema(), right.schema()
            if ls.arity != rs.arity:
                raise PlanError("set operation arity mismatch")
            if se.op == "union":
                rel = HUnion((left, right))
                if not se.all:
                    rel = HDistinct(rel)
                return rel, lscope
            if se.op == "except":
                if not se.all:
                    left, right = HDistinct(left), HDistinct(right)
                from .hir import HNegate, HThreshold

                return HThreshold(HUnion((left, HNegate(right)))), lscope
            if se.op == "intersect":
                from .hir import HNegate, HThreshold

                if not se.all:
                    left, right = HDistinct(left), HDistinct(right)
                # a ∩ b = a - (a - b)
                a_minus_b = HThreshold(HUnion((left, HNegate(right))))
                return (
                    HThreshold(HUnion((left, HNegate(a_minus_b)))),
                    lscope,
                )
        raise NotImplementedError(type(se).__name__)

    # -- FROM ------------------------------------------------------------
    def _plan_table_factor(self, f: ast.TableFactor):
        if isinstance(f, ast.TableName):
            if f.name in self._ctes:
                sch = self._ctes[f.name]
            else:
                sch = self.catalog.resolve_item(f.name)
            rel = HGet(f.name, sch)
            alias = f.alias.name if f.alias else f.name
            names = (
                list(f.alias.columns)
                if f.alias and f.alias.columns
                else list(sch.names)
            )
            scope = Scope(
                [ScopeItem(alias, n) for n in names],
                [
                    Column(n, c.ctype, c.nullable, c.scale)
                    for n, c in zip(names, sch.columns)
                ],
            )
            return rel, scope
        if isinstance(f, ast.DerivedTable):
            rel, inner_scope = self.plan_query(f.query)
            sch = rel.schema()
            if f.alias is None:
                raise PlanError("subquery in FROM requires an alias")
            names = (
                list(f.alias.columns)
                if f.alias.columns
                else [it.name for it in inner_scope.items]
            )
            scope = Scope(
                [ScopeItem(f.alias.name, n) for n in names],
                [
                    Column(n, c.ctype, c.nullable, c.scale)
                    for n, c in zip(names, sch.columns)
                ],
            )
            return rel, scope
        raise NotImplementedError(type(f).__name__)

    def _plan_from(self, from_: tuple):
        rel, scope = None, None
        for item in from_:
            r, s = self._plan_table_factor(item.factor)
            for jc in item.joins:
                jr, js = self._plan_table_factor(jc.factor)
                combined = s.concat(js)
                on: list = []
                if jc.using:
                    larity = len(s.items)
                    from .hir import ScopeItem as _SI

                    # pg `*` order: USING-merged columns first, outermost
                    # join first. Later joins get smaller (more negative)
                    # rank bases so their merged columns sort ahead.
                    self._using_join_seq = getattr(
                        self, "_using_join_seq", 0
                    ) + 1
                    rank_base = -(self._using_join_seq << 16)
                    for uidx, name in enumerate(jc.using):
                        li = s.resolve((name,))
                        ri = js.resolve((name,))
                        on.append(
                            HCallBinary(
                                BinaryFunc.EQ,
                                HColumn(li),
                                HColumn(larity + ri),
                            )
                        )
                        # Merge the shared column: hide the copy whose
                        # side can be NULL on unmatched rows, so the
                        # surviving unqualified column carries the
                        # merged value (pg USING semantics). FULL
                        # would need a COALESCE column — refuse rather
                        # than return wrong NULLs.
                        if jc.kind == "full":
                            raise PlanError(
                                "FULL JOIN ... USING is not supported; "
                                "use ON with explicit COALESCE"
                            )
                        hide = (
                            li if jc.kind == "right" else larity + ri
                        )
                        keep = (
                            larity + ri if jc.kind == "right" else li
                        )
                        it = combined.items[hide]
                        combined.items[hide] = _SI(
                            it.table, it.name, hidden=True
                        )
                        kt = combined.items[keep]
                        combined.items[keep] = _SI(
                            kt.table, kt.name, hidden=kt.hidden,
                            star_rank=rank_base + uidx,
                        )
                elif jc.on is not None:
                    on = self._conjuncts(jc.on, combined)
                r = HJoin(r, jr, tuple(on), jc.kind)
                s = combined
            if rel is None:
                rel, scope = r, s
            else:
                rel = HJoin(rel, r, (), "cross")
                scope = scope.concat(s)
        return rel, scope

    def _conjuncts(self, e: ast.Expr, scope: Scope) -> list:
        if isinstance(e, ast.BinaryOp) and e.op == "and":
            return self._conjuncts(e.left, scope) + self._conjuncts(
                e.right, scope
            )
        return [self.plan_expr(e, scope)]

    # -- SELECT ----------------------------------------------------------
    def _plan_select(self, sel: ast.Select):
        if sel.from_:
            rel, scope = self._plan_from(sel.from_)
        else:
            rel = HConstant(((tuple(), 1),), Schema([]))
            scope = Scope([], [])

        if sel.where is not None:
            rel = HFilter(rel, tuple(self._conjuncts(sel.where, scope)))

        # Expand stars and name outputs.
        items: list[tuple[ast.Expr, str]] = []
        for it in sel.items:
            if isinstance(it.expr, ast.Star):
                # pg column order for unqualified `*` over USING joins:
                # merged join columns first (outermost join first, then
                # USING-clause order), remaining columns positionally.
                expand = [
                    (i, sc) for i, sc in enumerate(scope.items)
                ]
                if not it.expr.qualifier:
                    expand.sort(
                        key=lambda t: (
                            t[1].star_rank
                            if t[1].star_rank is not None
                            else t[0]
                        )
                    )
                for i, sc in expand:
                    if it.expr.qualifier and sc.table != it.expr.qualifier:
                        continue
                    if not it.expr.qualifier and sc.hidden:
                        continue  # USING-merged duplicate
                    items.append((ast.Ident((sc.table, sc.name)), sc.name))
            else:
                items.append((it.expr, it.alias or _default_name(it.expr)))

        has_aggs = bool(sel.group_by) or any(
            _contains_agg(e) for e, _ in items
        ) or (sel.having is not None and _contains_agg(sel.having))

        if has_aggs:
            rel, scope, items, having = self._plan_aggregation(
                rel, scope, sel, items
            )
            if having is not None:
                rel = HFilter(rel, (having,))
        elif sel.having is not None:
            raise PlanError("HAVING without aggregation")

        # Map select expressions, project to output columns.
        schema = rel.schema()
        scalars, outputs = [], []
        for e, name in items:
            h = self.plan_expr(e, scope)
            if isinstance(h, HColumn):
                outputs.append(h.index)
            else:
                c = typ_of(h, schema_with(schema, scalars))
                scalars.append((h, Column(name, c.ctype, c.nullable, c.scale)))
                outputs.append(schema.arity + len(scalars) - 1)
        if scalars:
            rel = HMap(rel, tuple(scalars))
        rel = HProject(rel, tuple(outputs))
        out_scope = Scope(
            [ScopeItem(None, n) for _, n in items],
            list(rel.schema().columns),
        )
        # Rename projected columns to their aliases.
        rel = _rebrand(rel, rel.schema().rename([n for _, n in items]))
        if sel.distinct:
            rel = HDistinct(rel)
        return rel, out_scope

    def _plan_aggregation(self, rel, scope, sel: ast.Select, items):
        schema = rel.schema()
        # 1. group key expressions -> map non-column exprs first
        key_sources: list[ast.Expr] = list(sel.group_by)
        pre_scalars: list = []
        key_indices: list[int] = []
        resolved_keys: list[ast.Expr] = []
        aliases = {name: e for e, name in items}
        for ge in key_sources:
            if isinstance(ge, ast.NumberLit):  # GROUP BY 1
                e, _ = items[int(ge.text) - 1]
            elif (
                isinstance(ge, ast.Ident)
                and len(ge.parts) == 1
                and scope.maybe_resolve(ge.parts) is None
                and ge.parts[0] in aliases
            ):
                # GROUP BY <select alias> (a real column wins, pg-style)
                e = aliases[ge.parts[0]]
            else:
                e = ge
            resolved_keys.append(e)
            h = self.plan_expr(e, scope)
            if isinstance(h, HColumn):
                key_indices.append(h.index)
            else:
                c = typ_of(h, schema_with(schema, pre_scalars))
                pre_scalars.append((h, c))
                key_indices.append(schema.arity + len(pre_scalars) - 1)
        if pre_scalars:
            rel = HMap(rel, tuple(pre_scalars))
            schema = rel.schema()

        # 2. collect aggregate calls from items + having
        aggs: list[HAggregate] = []

        def plan_agg(fc: ast.FuncCall) -> tuple:
            """Returns (kind, [agg indices]) — composite aggregates
            (avg, stddev/variance) decompose into sums and counts, like
            the reference's sql func library (sql/src/func.rs)."""
            name = fc.name
            dist = fc.distinct
            if fc.star or (name == "count" and not fc.args):
                inner = HLiteral(True, ColumnType.BOOL)
            else:
                inner = self.plan_expr(fc.args[0], scope)
            ityp = typ_of(inner, schema)
            if name == "count":
                func, out = AggregateFunc.COUNT, Column(
                    "count", ColumnType.INT64, False
                )
                aggs.append(HAggregate(func, inner, dist, out))
                return ("plain", [len(aggs) - 1])
            if name == "sum":
                if ityp.ctype is ColumnType.FLOAT64:
                    func = AggregateFunc.SUM_FLOAT
                    out = Column("sum", ColumnType.FLOAT64, True)
                elif ityp.ctype is ColumnType.BOOL:
                    raise PlanError("sum over boolean is not defined")
                else:
                    func = AggregateFunc.SUM_INT
                    out = Column("sum", ityp.ctype, True, ityp.scale)
                aggs.append(HAggregate(func, inner, dist, out))
                return ("plain", [len(aggs) - 1])
            if name in ("min", "max"):
                # STRING included: order-preserving dictionary codes
                # make min/max over text a plain hierarchical reduce.
                func = (
                    AggregateFunc.MIN if name == "min" else AggregateFunc.MAX
                )
                out = Column(name, ityp.ctype, True, ityp.scale)
                aggs.append(HAggregate(func, inner, False, out))
                return ("plain", [len(aggs) - 1])
            if name in ("bool_and", "every", "bool_or"):
                if ityp.ctype is not ColumnType.BOOL:
                    raise PlanError(f"{name} requires a boolean argument")
                func = (
                    AggregateFunc.ANY
                    if name == "bool_or"
                    else AggregateFunc.ALL
                )
                out = Column(name, ColumnType.BOOL, True)
                aggs.append(HAggregate(func, inner, False, out))
                return ("plain", [len(aggs) - 1])
            if name in _BASIC_AGGS:
                # Basic (collection) aggregates: maintained as a sorted
                # (key, value) multiset + change digest on device,
                # materialized at the serving edge (ops/reduce.py;
                # render/reduce.rs:369 build_basic_aggregate analog).
                if dist:
                    raise PlanError(
                        f"{name}(DISTINCT ...) is not supported"
                    )
                params: tuple = ()
                if name == "string_agg":
                    if len(fc.args) != 2:
                        raise PlanError(
                            "string_agg requires (value, separator)"
                        )
                    sep_ast = fc.args[1]
                    if not isinstance(sep_ast, ast.StringLit):
                        raise PlanError(
                            "string_agg separator must be a string "
                            "literal"
                        )
                    params = (sep_ast.value,)
                    if ityp.ctype is not ColumnType.STRING:
                        raise PlanError(
                            "string_agg requires a text argument"
                        )
                    func = AggregateFunc.STRING_AGG
                else:
                    if ityp.ctype is ColumnType.FLOAT64:
                        raise PlanError(
                            f"{name} over double precision is not "
                            "supported yet (int64-lane values only)"
                        )
                    func = (
                        AggregateFunc.ARRAY_AGG
                        if name == "array_agg"
                        else AggregateFunc.LIST_AGG
                    )
                out = Column(name, ColumnType.STRING, True)
                aggs.append(
                    HAggregate(func, inner, False, out, params)
                )
                return ("plain", [len(aggs) - 1])
            if name == "avg":
                _, s = plan_agg(
                    ast.FuncCall("sum", fc.args, distinct=dist)
                )
                _, c = plan_agg(
                    ast.FuncCall("count", fc.args, distinct=dist)
                )
                return ("avg", s + c)
            if name in _VAR_AGGS:
                if dist:
                    # sum(DISTINCT x*x) dedups on x*x, not on x, so the
                    # decomposition would be wrong for {-a, a} inputs
                    raise PlanError(
                        f"{name}(DISTINCT ...) is not supported"
                    )
                dbl = ast.Cast(fc.args[0], "double")
                sq = ast.BinaryOp("*", dbl, dbl)
                _, s = plan_agg(ast.FuncCall("sum", (dbl,), distinct=dist))
                _, ss = plan_agg(ast.FuncCall("sum", (sq,), distinct=dist))
                _, c = plan_agg(
                    ast.FuncCall("count", (dbl,), distinct=dist)
                )
                return (name, s + ss + c)
            raise PlanError(f"unknown aggregate {name}")

        n_key = len(key_indices)
        agg_refs: dict[int, list] = {}

        def rewrite(e: ast.Expr):
            """Replace aggregate calls with post-reduce column refs;
            a select item STRUCTURALLY equal to a GROUP BY expression
            references that key column (the reference's group-key
            matching in sql/src/plan/query.rs)."""
            for kpos, ke in enumerate(resolved_keys):
                if e == ke and not isinstance(e, ast.NumberLit):
                    return _PostAggColumn(kpos)
            if isinstance(e, ast.FuncCall) and (
                e.name in _AGG_FUNCS or e.star
            ):
                # Structural dedup: count(*) in SELECT and HAVING is
                # ONE aggregate in the reduce (frozen AST nodes hash).
                key = e
                if key not in agg_refs:
                    agg_refs[key] = plan_agg(e)
                kind, idxs = agg_refs[key]
                cols_ = [_PostAggColumn(n_key + i) for i in idxs]
                if kind == "plain":
                    return cols_[0]
                if kind == "avg":
                    # avg(int) divides as double (pg returns numeric;
                    # `/` on two ints is INTEGER division since the
                    # int8div fix). Decimal sums keep decimal division.
                    s_col = aggs[idxs[0]].out
                    if s_col.ctype in (
                        ColumnType.INT32, ColumnType.INT64
                    ):
                        return ast.BinaryOp(
                            "/", ast.Cast(cols_[0], "double"), cols_[1]
                        )
                    return ast.BinaryOp("/", cols_[0], cols_[1])
                # variance family: E[x^2] and E[x]^2 from (sum, sum_sq,
                # count); sample variants divide by (count - 1), whose
                # zero denominator yields NULL (matching pg's NULL for
                # n<2); numeric noise is clamped at 0 before sqrt
                s, ss, c = cols_
                num = ast.BinaryOp(
                    "-",
                    ss,
                    ast.BinaryOp("/", ast.BinaryOp("*", s, s), c),
                )
                num = ast.FuncCall("greatest", (num, ast.NumberLit("0.0")))
                denom = (
                    ast.BinaryOp("-", c, ast.NumberLit("1"))
                    if kind in ("stddev", "stddev_samp", "var_samp",
                                "variance")
                    else c
                )
                # n<2 (or empty-group) denominators are NULL, not a
                # division-by-zero error: CASE is SQL's error guard and
                # the eval layer suppresses unselected-branch errors
                var = ast.Case(
                    None,
                    (
                        (
                            ast.BinaryOp("=", denom, ast.NumberLit("0")),
                            ast.NullLit(),
                        ),
                    ),
                    ast.BinaryOp("/", num, denom),
                )
                if kind in ("stddev", "stddev_samp", "stddev_pop"):
                    var = ast.FuncCall("sqrt", (var,))
                # all-NULL groups: sum is NULL and must stay NULL (the
                # greatest() clamp above would otherwise turn it into 0)
                return ast.Case(
                    None,
                    ((ast.IsNull(s, negated=True), var),),
                    ast.NullLit(),
                )
            if isinstance(e, ast.BinaryOp):
                return ast.BinaryOp(e.op, rewrite(e.left), rewrite(e.right))
            if isinstance(e, ast.UnaryOp):
                return ast.UnaryOp(e.op, rewrite(e.expr))
            if isinstance(e, ast.Cast):
                return ast.Cast(rewrite(e.expr), e.to_type)
            if isinstance(e, ast.IsNull):
                return ast.IsNull(rewrite(e.expr), e.negated)
            if isinstance(e, ast.Extract):
                return ast.Extract(e.part, rewrite(e.expr))
            if isinstance(e, ast.InList):
                return ast.InList(
                    rewrite(e.expr),
                    tuple(rewrite(x) for x in e.items),
                    e.negated,
                )
            if isinstance(e, ast.Between):
                return ast.Between(
                    rewrite(e.expr), rewrite(e.low), rewrite(e.high),
                    e.negated,
                )
            if isinstance(e, ast.Case):
                return ast.Case(
                    rewrite(e.operand) if e.operand is not None else None,
                    tuple(
                        (rewrite(c), rewrite(r)) for c, r in e.whens
                    ),
                    rewrite(e.else_) if e.else_ is not None else None,
                )
            if isinstance(e, ast.FuncCall):
                return ast.FuncCall(
                    e.name, tuple(rewrite(a) for a in e.args), e.distinct
                )
            return e

        new_items = []
        for e, name in items:
            re_ = rewrite(e)
            new_items.append((re_, name))
        having = None
        if sel.having is not None:
            having_ast = rewrite(sel.having)
            rel2 = self._reduce_with_defaults(rel, key_indices, aggs)
            post_scope = self._post_agg_scope(scope, key_indices, aggs)
            having = self.plan_expr(having_ast, post_scope)
            return rel2, post_scope, new_items, having
        rel2 = self._reduce_with_defaults(rel, key_indices, aggs)
        post_scope = self._post_agg_scope(scope, key_indices, aggs)
        return rel2, post_scope, new_items, None

    def _reduce_with_defaults(self, rel, key_indices, aggs):
        """HReduce, plus — for GLOBAL aggregates (no group key) — the SQL
        default row over empty input (COUNT -> 0, others NULL): the
        reference's lowering emits reduce ∪ (defaults ∖ nonempty-flag)
        (sql/src/plan/lowering.rs reduce defaults)."""
        red = HReduce(rel, tuple(key_indices), tuple(aggs))
        if key_indices:
            return red
        from .hir import is_correlated

        # Check the REDUCE, not just its input: correlation can live in
        # aggregate argument expressions alone.
        if is_correlated(red):
            # Correlated global aggregate: under decorrelation the
            # reduce becomes per-outer-key and this one-row defaults
            # union would be wrong; the branch lowering pads missing
            # keys with per-aggregate defaults instead (lowering.py).
            return red
        # Let-bind the reduce: it appears twice in the union (directly
        # and inside the nonempty flag) and must be computed ONCE (the
        # render layer shares Let bindings; without it the whole
        # upstream pipeline would be maintained twice).
        self._defaults_seq = getattr(self, "_defaults_seq", 0) + 1
        bind = f"__agg{self._defaults_seq}"
        red_get = HGet(bind, red.schema())
        flag_col = Column("f", ColumnType.INT64)
        flag_schema = Schema([flag_col])
        # One (1,) row iff the reduce output is nonempty.
        has = HProject(
            HMap(red_get, ((HLiteral(1, ColumnType.INT64), flag_col),)),
            (len(aggs),),
        )
        miss = HUnion(
            (
                HConstant((((1,), 1),), flag_schema),
                HNegate(has),
            )
        )
        defaults = []
        for a in aggs:
            if a.func is AggregateFunc.COUNT:
                defaults.append((HLiteral(0, ColumnType.INT64), a.out))
            else:
                defaults.append(
                    (HLiteral(None, a.out.ctype, a.out.scale), a.out)
                )
        deflt = HProject(
            HMap(miss, tuple(defaults)),
            tuple(range(1, len(aggs) + 1)),
        )
        return HLet(bind, red, HUnion((red_get, deflt)))

    def _post_agg_scope(self, scope, key_indices, aggs):
        items = []
        cols = []
        for i in key_indices:
            if i < len(scope.items):
                items.append(
                    ScopeItem(scope.items[i].table, scope.items[i].name)
                )
                cols.append(
                    scope.columns[i]
                    if scope.columns is not None and i < len(scope.columns)
                    else None
                )
            else:
                # GROUP BY <expression>: the key is a pre-mapped column
                # beyond the input scope; positionally addressable only.
                # '#' cannot appear in identifiers, so the name can
                # never capture a real column reference.
                items.append(ScopeItem(None, f"#gkey{i}"))
                cols.append(None)
        items += [ScopeItem(None, a.out.name) for a in aggs]
        cols += [a.out for a in aggs]
        return Scope(items, cols if all(c is not None for c in cols) else None)

    # -- scalar expressions ----------------------------------------------
    def plan_expr(self, e: ast.Expr, scope: Scope):
        if isinstance(e, _PostAggColumn):
            return HColumn(e.index)
        if isinstance(e, ast.Ident):
            idx = scope.maybe_resolve(e.parts)
            if idx is not None:
                return HColumn(idx)
            # Correlated reference: resolve against enclosing scopes,
            # innermost first.
            from .hir import HOuterColumn

            for level, oscope in enumerate(
                reversed(self._outer_scopes), start=1
            ):
                oidx = oscope.maybe_resolve(e.parts)
                if oidx is not None:
                    if oscope.columns is None:
                        raise PlanError(
                            "correlated reference into an untyped scope"
                        )
                    return HOuterColumn(level, oidx, oscope.columns[oidx])
            raise PlanError(f"unknown column {'.'.join(e.parts)!r}")
        if isinstance(e, ast.NumberLit):
            return _number_literal(e.text)
        if isinstance(e, ast.StringLit):
            return HLiteral(
                GLOBAL_DICT.encode(e.value), ColumnType.STRING
            )
        if isinstance(e, ast.BoolLit):
            return HLiteral(e.value, ColumnType.BOOL)
        if isinstance(e, ast.NullLit):
            return HLiteral(None, ColumnType.INT64)
        if isinstance(e, ast.IntervalLit):
            raise PlanError(
                "interval literals are only supported in +/- expressions"
            )
        if isinstance(e, ast.BinaryOp):
            if e.op in ("+", "-") and isinstance(e.right, ast.IntervalLit):
                iv = e.right
                sgn = 1 if e.op == "+" else -1
                return HCallVariadic(
                    VariadicFunc.ADD_INTERVAL,
                    (
                        self.plan_expr(e.left, scope),
                        HLiteral(sgn * iv.months, ColumnType.INT64),
                        HLiteral(sgn * iv.days, ColumnType.INT64),
                        HLiteral(sgn * iv.ms, ColumnType.INT64),
                    ),
                )
            if e.op == "+" and isinstance(e.left, ast.IntervalLit):
                return self.plan_expr(
                    ast.BinaryOp("+", e.right, e.left), scope
                )
            if e.op == "and":
                return HCallVariadic(
                    VariadicFunc.AND,
                    (
                        self.plan_expr(e.left, scope),
                        self.plan_expr(e.right, scope),
                    ),
                )
            if e.op == "or":
                return HCallVariadic(
                    VariadicFunc.OR,
                    (
                        self.plan_expr(e.left, scope),
                        self.plan_expr(e.right, scope),
                    ),
                )
            if e.op == "||":
                return self._plan_concat(e, scope)
            if e.op in _BINOPS:
                return HCallBinary(
                    _BINOPS[e.op],
                    self.plan_expr(e.left, scope),
                    self.plan_expr(e.right, scope),
                )
            raise PlanError(f"unsupported operator {e.op!r}")
        if isinstance(e, ast.UnaryOp):
            inner = self.plan_expr(e.expr, scope)
            if e.op == "-":
                if (
                    isinstance(inner, HLiteral)
                    and inner.value is not None
                    and inner.ctype is not ColumnType.STRING
                ):
                    # fold -literal so literal-argument positions
                    # (round(x, -1), LIMIT arithmetic) see a Literal
                    return HLiteral(
                        -inner.value, inner.ctype, inner.scale
                    )
                return HCallUnary(UnaryFunc.NEG, inner)
            if e.op == "not":
                return HCallUnary(UnaryFunc.NOT, inner)
        if isinstance(e, ast.Like):
            x = self.plan_expr(e.expr, scope)
            pat = self.plan_expr(e.pattern, scope)
            if not (
                isinstance(pat, HLiteral)
                and pat.ctype is ColumnType.STRING
            ):
                raise PlanError(
                    "LIKE patterns must be string literals (the match "
                    "table is precomputed per dictionary entry)"
                )
            fn = "ilike" if e.case_insensitive else "like"
            out = HCallVariadic(_STR + fn, (x, pat))
            return HCallUnary(UnaryFunc.NOT, out) if e.negated else out
        if isinstance(e, ast.IsNull):
            inner = HCallUnary(
                UnaryFunc.IS_NULL, self.plan_expr(e.expr, scope)
            )
            return (
                HCallUnary(UnaryFunc.NOT, inner) if e.negated else inner
            )
        if isinstance(e, ast.Between):
            x = self.plan_expr(e.expr, scope)
            lo = self.plan_expr(e.low, scope)
            hi = self.plan_expr(e.high, scope)
            within = HCallVariadic(
                VariadicFunc.AND,
                (
                    HCallBinary(BinaryFunc.GTE, x, lo),
                    HCallBinary(BinaryFunc.LTE, x, hi),
                ),
            )
            return (
                HCallUnary(UnaryFunc.NOT, within) if e.negated else within
            )
        if isinstance(e, ast.InList):
            x = self.plan_expr(e.expr, scope)
            eqs = tuple(
                HCallBinary(BinaryFunc.EQ, x, self.plan_expr(i, scope))
                for i in e.items
            )
            anyeq = HCallVariadic(VariadicFunc.OR, eqs)
            return HCallUnary(UnaryFunc.NOT, anyeq) if e.negated else anyeq
        if isinstance(e, ast.Case):
            if e.operand is not None:
                op = self.plan_expr(e.operand, scope)
                whens = [
                    (
                        HCallBinary(
                            BinaryFunc.EQ, op, self.plan_expr(c, scope)
                        ),
                        self.plan_expr(r, scope),
                    )
                    for c, r in e.whens
                ]
            else:
                whens = [
                    (self.plan_expr(c, scope), self.plan_expr(r, scope))
                    for c, r in e.whens
                ]
            els = (
                self.plan_expr(e.else_, scope)
                if e.else_ is not None
                else HLiteral(None, ColumnType.INT64)
            )
            out = els
            for cond, res in reversed(whens):
                out = HIf(cond, res, out)
            return out
        if isinstance(e, ast.Cast):
            return self._plan_cast(e, scope)
        if isinstance(e, ast.Extract):
            if e.part not in UnaryFunc.EXTRACTS:
                raise PlanError(f"EXTRACT({e.part}) unsupported")
            return HCallUnary(
                UnaryFunc.EXTRACTS[e.part], self.plan_expr(e.expr, scope)
            )
        if isinstance(e, ast.FuncCall):
            if e.name in _AGG_FUNCS or e.star:
                raise PlanError(
                    f"aggregate {e.name} in a non-aggregated context"
                )
            return self._plan_func(e, scope)
        if isinstance(e, ast.Exists):
            rel, _ = self._plan_subquery(e.query, scope)
            return HExists(rel)
        if isinstance(e, ast.ScalarSubquery):
            rel, _ = self._plan_subquery(e.query, scope)
            return HScalarSubquery(rel)
        if isinstance(e, ast.InSubquery):
            rel, _ = self._plan_subquery(e.query, scope)
            x = self.plan_expr(e.expr, scope)
            return HInSubquery(x, rel, e.negated)
        raise NotImplementedError(type(e).__name__)

    def _plan_subquery(self, q: ast.Query, scope: Scope):
        """Plan a subquery with ``scope`` available as an outer scope for
        correlated name resolution."""
        self._outer_scopes.append(scope)
        try:
            return self.plan_query(q)
        finally:
            self._outer_scopes.pop()

    def _plan_cast(self, e: ast.Cast, scope: Scope):
        """CAST(expr AS type) — the typeconv analog (sql/src/plan/typeconv.rs).

        String literals cast to DATE/TIMESTAMP are parsed at plan time;
        decimal(p,s) casts carry the target scale as a literal operand."""
        from .hir import parse_type

        ty, cast_scale = parse_type(e.to_type)
        inner_ast = e.expr
        if ty in (ColumnType.DATE, ColumnType.TIMESTAMP) and isinstance(
            inner_ast, ast.StringLit
        ):
            return HLiteral(_parse_datetime_literal(inner_ast.value, ty), ty)
        inner = self.plan_expr(inner_ast, scope)
        if ty is ColumnType.INT64:
            return HCallUnary(UnaryFunc.CAST_INT64, inner)
        if ty is ColumnType.INT32:
            return HCallUnary(UnaryFunc.CAST_INT32, inner)
        if ty is ColumnType.FLOAT64:
            return HCallUnary(UnaryFunc.CAST_FLOAT64, inner)
        if ty is ColumnType.BOOL:
            return HCallUnary(UnaryFunc.CAST_BOOL, inner)
        if ty is ColumnType.DATE:
            return HCallUnary(UnaryFunc.CAST_DATE, inner)
        if ty is ColumnType.TIMESTAMP:
            return HCallUnary(UnaryFunc.CAST_TIMESTAMP, inner)
        if ty is ColumnType.DECIMAL:
            return HCallBinary(
                BinaryFunc.CAST_DECIMAL,
                inner,
                HLiteral(cast_scale, ColumnType.INT64),
            )
        if ty is ColumnType.STRING and isinstance(inner, HLiteral):
            if inner.ctype is ColumnType.STRING:
                return inner
        raise PlanError(f"unsupported cast to {e.to_type}")

    def _plan_func(self, e: ast.FuncCall, scope: Scope):
        """Scalar function dispatch (the func.rs library analog)."""
        name = e.name

        def arg(i: int):
            return self.plan_expr(e.args[i], scope)

        def allargs():
            return tuple(self.plan_expr(a, scope) for a in e.args)

        if name == "coalesce":
            return HCallVariadic(VariadicFunc.COALESCE, allargs())
        if name in ("greatest", "least"):
            return HCallVariadic(
                VariadicFunc.GREATEST
                if name == "greatest"
                else VariadicFunc.LEAST,
                allargs(),
            )
        if name == "nullif":
            a, b = arg(0), arg(1)
            # NULL only when a = b is TRUE (an unknown comparison —
            # either side NULL — returns a, per pg); the untyped NULL
            # branch defers typing to a (If._principal)
            return HIf(
                HCallBinary(BinaryFunc.EQ, a, b),
                HLiteral(None, ColumnType.INT64),
                a,
            )
        if name in _STRING_FUNCS_1 or name in (
            "substr", "substring", "left", "right", "replace", "lpad",
            "rpad", "strpos", "position", "split_part",
        ):
            return self._plan_string_func(name, e, scope)
        if name in _UNARY_FUNC_NAMES:
            if len(e.args) != 1:
                raise PlanError(f"{name} takes one argument")
            return HCallUnary(_UNARY_FUNC_NAMES[name], arg(0))
        if name == "round":
            if len(e.args) == 1:
                return HCallUnary(UnaryFunc.ROUND, arg(0))
            return HCallBinary(BinaryFunc.ROUND_TO, arg(0), arg(1))
        if name == "log":
            if len(e.args) == 1:
                return HCallUnary(UnaryFunc.LOG10, arg(0))
            return HCallBinary(BinaryFunc.LOG_BASE, arg(0), arg(1))
        if name in ("power", "pow"):
            return HCallBinary(BinaryFunc.POWER, arg(0), arg(1))
        if name == "mod":
            return HCallBinary(BinaryFunc.MOD, arg(0), arg(1))
        if name == "pi":
            import math

            return HLiteral(math.pi, ColumnType.FLOAT64)
        if name in ("date_trunc", "date_part"):
            part_ast = e.args[0]
            if not isinstance(part_ast, ast.StringLit):
                raise PlanError(f"{name}: part must be a string literal")
            part = part_ast.value.lower()
            if name == "date_trunc":
                table = UnaryFunc.DATE_TRUNCS
            else:
                table = UnaryFunc.EXTRACTS
            if part not in table:
                raise PlanError(f"{name}({part!r}) unsupported")
            return HCallUnary(table[part], arg(1))
        if name == "mz_now":
            if e.args:
                raise PlanError("mz_now() takes no arguments")
            from .hir import HMzNow

            return HMzNow()
        raise PlanError(f"unknown function {name}")

    def _require_literal(self, h, what: str) -> HLiteral:
        if not isinstance(h, HLiteral):
            raise PlanError(
                f"{what} must be a literal (string-function parameters "
                "are baked into the dictionary side-table)"
            )
        return h

    def _plan_string_func(self, name: str, e: ast.FuncCall, scope):
        """String function library (the dictionary-gather lowering;
        reference: expr/src/scalar/func/impls/string.rs)."""
        args = [self.plan_expr(a, scope) for a in e.args]
        if name in _STRING_FUNCS_1:
            if len(args) == 1:
                return HCallVariadic(
                    _STR + _STRING_FUNCS_1[name], (args[0],)
                )
            if name in ("trim", "btrim", "ltrim", "rtrim") and len(
                args
            ) == 2:
                chars = self._require_literal(args[1], f"{name} chars")
                return HCallVariadic(
                    _STR + _STRING_FUNCS_1[name], (args[0], chars)
                )
            raise PlanError(f"wrong argument count for {name}")
        def need(n_min: int, n_max: int):
            if not (n_min <= len(args) <= n_max):
                raise PlanError(
                    f"wrong argument count for {name} "
                    f"(got {len(args)})"
                )

        if name in ("substr", "substring"):
            need(2, 3)
            params = tuple(
                self._require_literal(a, "substr bounds")
                for a in args[1:]
            )
            return HCallVariadic(_STR + "substr", (args[0],) + params)
        if name in ("left", "right"):
            need(2, 2)
            n = self._require_literal(args[1], f"{name} count")
            return HCallVariadic(_STR + name, (args[0], n))
        if name == "replace":
            need(3, 3)
            p = self._require_literal(args[1], "replace from")
            q = self._require_literal(args[2], "replace to")
            return HCallVariadic(_STR + "replace", (args[0], p, q))
        if name in ("lpad", "rpad"):
            need(2, 3)
            params = tuple(
                self._require_literal(a, f"{name} params")
                for a in args[1:]
            )
            return HCallVariadic(_STR + name, (args[0],) + params)
        if name in ("strpos", "position"):
            need(2, 2)
            sub = self._require_literal(args[1], "substring")
            return HCallVariadic(_STR + "position", (args[0], sub))
        if name == "split_part":
            need(3, 3)
            d = self._require_literal(args[1], "delimiter")
            i = self._require_literal(args[2], "field index")
            return HCallVariadic(_STR + "split_part", (args[0], d, i))
        raise PlanError(f"unknown string function {name}")

    def _plan_concat(self, e: ast.BinaryOp, scope):
        """a || b: string concatenation. One side must be a literal
        (the side-table maps each dictionary entry through the append);
        literal||literal folds at plan time; column||column requires
        materializing the cross product of dictionaries and is not
        supported."""
        left = self.plan_expr(e.left, scope)
        right = self.plan_expr(e.right, scope)

        def lit_text(h: HLiteral) -> str:
            if h.ctype is ColumnType.STRING:
                return GLOBAL_DICT.decode(int(h.value))
            return str(h.value)

        lish = isinstance(left, HLiteral)
        rish = isinstance(right, HLiteral)
        # NULL || anything is NULL (pg)
        if (lish and left.value is None) or (
            rish and right.value is None
        ):
            return HLiteral(None, ColumnType.STRING)
        if lish and rish:
            return HLiteral(
                GLOBAL_DICT.encode(lit_text(left) + lit_text(right)),
                ColumnType.STRING,
            )
        if rish:
            return HCallVariadic(
                _STR + "concat_r",
                (left, HLiteral(
                    GLOBAL_DICT.encode(lit_text(right)),
                    ColumnType.STRING,
                )),
            )
        if lish:
            return HCallVariadic(
                _STR + "concat_l",
                (right, HLiteral(
                    GLOBAL_DICT.encode(lit_text(left)),
                    ColumnType.STRING,
                )),
            )
        raise PlanError(
            "column || column concatenation is not supported (one side "
            "must be a literal; see expr/strings.py)"
        )


from dataclasses import dataclass


@dataclass(frozen=True)
class _PostAggColumn(ast.Expr):
    """Internal AST marker: a column of the post-reduce relation."""

    index: int


def schema_with(schema: Schema, scalars) -> Schema:
    return Schema(tuple(schema.columns) + tuple(c for _, c in scalars))


def _rebrand(rel: HirRelation, schema: Schema) -> HirRelation:
    return HRename(rel, schema)


def _default_name(e: ast.Expr) -> str:
    if isinstance(e, ast.Ident):
        return e.parts[-1]
    if isinstance(e, ast.FuncCall):
        return e.name
    return "column"


def _contains_agg(e: ast.Expr) -> bool:
    if isinstance(e, ast.FuncCall):
        if e.name in _AGG_FUNCS or e.star:
            return True
        return any(_contains_agg(a) for a in e.args)
    if isinstance(e, ast.BinaryOp):
        return _contains_agg(e.left) or _contains_agg(e.right)
    if isinstance(e, ast.UnaryOp):
        return _contains_agg(e.expr)
    if isinstance(e, ast.Cast):
        return _contains_agg(e.expr)
    if isinstance(e, ast.Case):
        parts = [c for c, _ in e.whens] + [r for _, r in e.whens]
        if e.operand:
            parts.append(e.operand)
        if e.else_:
            parts.append(e.else_)
        return any(_contains_agg(p) for p in parts)
    return False


def _ident_parts(e: ast.Expr) -> tuple:
    if isinstance(e, ast.Ident):
        return e.parts
    raise PlanError("ORDER BY supports columns and output positions only")
