"""HIR → MIR lowering: subquery removal and outer-join expansion.

Analog of the reference's ``sql/src/plan/lowering.rs:188`` (HIR→MIR with
subquery decorrelation and outer-join lowering;
doc/developer/101-query-compilation.md:51-62). v1 handles uncorrelated
subqueries (correlated references fail name resolution upstream):

- scalar subquery  -> cross join against the (single-row) subquery
- x IN (SELECT..)  -> semijoin against DISTINCT(subquery)
- EXISTS(..)       -> cross join against DISTINCT(project-to-zero-cols)
- LEFT/RIGHT/FULL  -> inner join ∪ null-padded antijoin remainders
  (the reference's outer-join lowering pattern)
"""

from __future__ import annotations

from ..expr import relation as mir
from ..expr import scalar as ms
from ..expr.relation import AggregateExpr
from ..repr.schema import Column, Schema
from . import hir as h
from .hir import PlanError


def lower(rel: h.HirRelation) -> mir.RelationExpr:
    if isinstance(rel, h.HGet):
        return mir.Get(rel.name, rel._schema)
    if isinstance(rel, h.HConstant):
        return mir.Constant(rel.rows, rel._schema)
    if isinstance(rel, h.HRename):
        inner = lower(rel.input)
        return _rename(inner, rel._schema)
    if isinstance(rel, h.HProject):
        return mir.Project(lower(rel.input), tuple(rel.outputs))
    if isinstance(rel, h.HMap):
        inner = lower(rel.input)
        inner, scalars = _lower_scalars(
            inner, [s for s, _ in rel.scalars]
        )
        base_arity = rel.input.schema().arity
        cur = inner
        if _arity(cur) != base_arity:
            # subquery columns appended: map exprs then project them away
            cur = mir.Map(cur, tuple(scalars))
            n = len(scalars)
            keep = list(range(base_arity)) + list(
                range(_arity(cur) - n, _arity(cur))
            )
            return mir.Project(cur, tuple(keep))
        return mir.Map(cur, tuple(scalars))
    if isinstance(rel, h.HFilter):
        return _lower_filter(rel)
    if isinstance(rel, h.HJoin):
        return _lower_join(rel)
    if isinstance(rel, h.HReduce):
        inner = lower(rel.input)
        aggs = tuple(
            AggregateExpr(a.func, _scalar(a.expr), a.distinct)
            for a in rel.aggregates
        )
        return mir.Reduce(inner, tuple(rel.group_key), aggs)
    if isinstance(rel, h.HDistinct):
        inner = lower(rel.input)
        return mir.Reduce(
            inner, tuple(range(rel.input.schema().arity)), ()
        )
    if isinstance(rel, h.HTopK):
        return mir.TopK(
            lower(rel.input),
            tuple(rel.group_key),
            tuple(rel.order_by),
            rel.limit,
            rel.offset,
        )
    if isinstance(rel, h.HNegate):
        return mir.Negate(lower(rel.input))
    if isinstance(rel, h.HThreshold):
        return mir.Threshold(lower(rel.input))
    if isinstance(rel, h.HUnion):
        return mir.Union(tuple(lower(i) for i in rel.inputs))
    if isinstance(rel, h.HLet):
        return mir.Let(rel.name, lower(rel.value), lower(rel.body))
    if isinstance(rel, h.HLetRec):
        return mir.LetRec(
            tuple(rel.names),
            tuple(lower(v) for v in rel.values),
            tuple(rel.value_schemas),
            lower(rel.body),
            rel.max_iters,
        )
    raise NotImplementedError(type(rel).__name__)


def _arity(m: mir.RelationExpr) -> int:
    return m.schema().arity


def _rename(inner: mir.RelationExpr, schema: Schema) -> mir.RelationExpr:
    """MIR has no rename: Get/Constant carry schemas, everything else
    derives names structurally. A no-op Project keeps the tree shape and
    downstream code reads names off the HIR side."""
    if isinstance(inner, mir.Get):
        return mir.Get(inner.name, schema)
    if isinstance(inner, mir.Constant):
        return mir.Constant(inner.rows, schema)
    return inner


# -- scalar lowering with subquery extraction --------------------------------


def _scalar(e: h.HirScalar) -> ms.ScalarExpr:
    """Subquery-free HIR scalar -> MIR scalar."""
    return h._to_mir_shape(e)


def _lower_scalars(cur: mir.RelationExpr, exprs):
    """Lower scalars that may contain HScalarSubquery: each subquery is
    cross-joined once and replaced by a column reference. Returns
    (new_relation, mir scalar exprs referring to it)."""

    def walk(e, appended):
        if isinstance(e, h.HScalarSubquery):
            sub = lower(e.rel)
            if sub.schema().arity != 1:
                raise PlanError("scalar subquery must return one column")
            idx = appended["arity"]
            appended["joins"].append(sub)
            appended["arity"] += 1
            return ms.ColumnRef(idx)
        if isinstance(e, h.HColumn):
            return ms.ColumnRef(e.index)
        if isinstance(e, h.HMzNow):
            return ms.MzNow()
        if isinstance(e, h.HLiteral):
            return ms.Literal(e.value, e.ctype, e.scale)
        if isinstance(e, h.HCallUnary):
            return ms.CallUnary(e.func, walk(e.expr, appended))
        if isinstance(e, h.HCallBinary):
            return ms.CallBinary(
                e.func, walk(e.left, appended), walk(e.right, appended)
            )
        if isinstance(e, h.HCallVariadic):
            return ms.CallVariadic(
                e.func, [walk(x, appended) for x in e.exprs]
            )
        if isinstance(e, h.HIf):
            return ms.If(
                walk(e.cond, appended),
                walk(e.then, appended),
                walk(e.els, appended),
            )
        if isinstance(e, (h.HExists, h.HInSubquery)):
            raise PlanError(
                "EXISTS/IN subqueries are supported as top-level WHERE "
                "conjuncts only"
            )
        raise NotImplementedError(type(e).__name__)

    base = _arity(cur)
    appended = {"arity": base, "joins": []}
    out = [walk(e, appended) for e in exprs]
    for sub in appended["joins"]:
        cur = mir.Join((cur, sub), equivalences=())
    # References were assigned positions base..base+k in append order —
    # consistent with the join concatenation order.
    return cur, out


def _lower_filter(rel: h.HFilter) -> mir.RelationExpr:
    cur = lower(rel.input)
    base = _arity(cur)
    plain: list = []
    for p in rel.predicates:
        if isinstance(p, h.HInSubquery):
            cur = _semijoin(cur, p, base)
            continue
        if isinstance(p, h.HExists):
            sub = lower(p.rel)
            flag = mir.Reduce(
                mir.Project(sub, ()), (), ()
            )  # zero-col distinct: one row iff sub nonempty
            cur = mir.Join((cur, flag), equivalences=())
            continue
        plain.append(p)
    if plain:
        cur, preds = _lower_scalars(cur, plain)
    else:
        preds = []
    if _arity(cur) != base:
        cur = mir.Filter(cur, tuple(preds)) if preds else cur
        return mir.Project(cur, tuple(range(base)))
    return mir.Filter(cur, tuple(preds)) if preds else cur


def _semijoin(cur, p: h.HInSubquery, base: int):
    """x IN (sub): join against DISTINCT(sub) on x; NOT IN via threshold
    antijoin. x must be a column (pre-mapped by the planner if complex)."""
    sub = lower(p.rel)
    if sub.schema().arity != 1:
        raise PlanError("IN subquery must return one column")
    d = mir.Reduce(sub, (0,), ())  # distinct values
    if not isinstance(p.expr, h.HColumn):
        raise PlanError("IN subquery left side must be a column (v1)")
    xcol = p.expr.index
    semi = mir.Project(
        mir.Join(
            (cur, d),
            equivalences=((ms.ColumnRef(xcol), ms.ColumnRef(base)),),
        ),
        tuple(range(base)),
    )
    if not p.negated:
        return semi
    return mir.Threshold(mir.Union((cur, mir.Negate(semi))))


# -- join lowering -----------------------------------------------------------


def _split_on(on, l_arity: int, r_arity: int):
    """Partition ON conjuncts into column-equivalence pairs and residual
    predicates (over the concatenated columns)."""
    equivs: list = []
    residual: list = []
    for c in on:
        if (
            isinstance(c, h.HCallBinary)
            and c.func == ms.BinaryFunc.EQ
            and isinstance(c.left, h.HColumn)
            and isinstance(c.right, h.HColumn)
        ):
            a, b = c.left.index, c.right.index
            if (a < l_arity) != (b < l_arity):
                equivs.append(
                    (ms.ColumnRef(min(a, b)), ms.ColumnRef(max(a, b)))
                )
                continue
        residual.append(c)
    return equivs, residual


def _lower_join(rel: h.HJoin) -> mir.RelationExpr:
    left = lower(rel.left)
    right = lower(rel.right)
    la, ra = _arity(left), _arity(right)
    equivs, residual = _split_on(rel.on, la, ra)
    inner = mir.Join((left, right), equivalences=tuple(equivs))
    if residual:
        inner = mir.Filter(
            inner, tuple(_scalar(c) for c in residual)
        )
    if rel.kind in ("inner", "cross"):
        return inner
    out_schema = rel.schema()

    def pad(unmatched, null_ctypes_cols, nulls_first: bool):
        """Append (or prepend, via projection) NULL columns."""
        scalars = tuple(
            ms.Literal(None, c.ctype, c.scale) for c in null_ctypes_cols
        )
        m = mir.Map(unmatched, scalars)
        if not nulls_first:
            return m
        n_u = _arity(unmatched)
        n_n = len(null_ctypes_cols)
        perm = tuple(range(n_u, n_u + n_n)) + tuple(range(n_u))
        return mir.Project(m, perm)

    def antijoin(side, side_arity, inner_proj):
        """Rows of `side` with no match: side - (side ⋉ matched-rows)."""
        matched = mir.Reduce(
            mir.Project(inner, inner_proj), tuple(range(side_arity)), ()
        )
        semi = mir.Project(
            mir.Join(
                (side, matched),
                equivalences=tuple(
                    (ms.ColumnRef(i), ms.ColumnRef(side_arity + i))
                    for i in range(side_arity)
                ),
            ),
            tuple(range(side_arity)),
        )
        return mir.Threshold(mir.Union((side, mir.Negate(semi))))

    parts = [inner]
    if rel.kind in ("left", "full"):
        lu = antijoin(left, la, tuple(range(la)))
        parts.append(pad(lu, out_schema.columns[la:], nulls_first=False))
    if rel.kind in ("right", "full"):
        ru = antijoin(right, ra, tuple(range(la, la + ra)))
        parts.append(pad(ru, out_schema.columns[:la], nulls_first=True))
    return mir.Union(tuple(parts))
