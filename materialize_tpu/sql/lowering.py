"""HIR → MIR lowering: subquery decorrelation and outer-join expansion.

Analog of the reference's ``sql/src/plan/lowering.rs:188`` ("rewriting all
correlated subqueries ... into flat queries";
doc/developer/101-query-compilation.md:51-62), re-designed around the same
apply/branch scheme:

- Every correlated subquery is lowered against the DISTINCT KEYS of the
  columns it references in its enclosing queries (``_branch``): the
  subquery is recursively "applied" to that keys relation (``_apply``),
  producing ``keys ++ subquery_columns``; the enclosing relation then
  joins against it on those keys.
- scalar subquery  -> left-join semantics: matched rows take the
  subquery value, unmatched rows take the aggregate's default (COUNT -> 0,
  otherwise NULL — the reference's aggregate defaults).
- EXISTS           -> semijoin on the keys (NOT EXISTS -> antijoin).
- x IN (SELECT..)  -> rewritten to EXISTS(sub WHERE sub.col = x) with x
  shifted into the subquery as a correlated reference, then handled by
  the EXISTS machinery (so correlated and uncorrelated IN share a path).
- LEFT/RIGHT/FULL  -> inner join ∪ null-padded antijoin remainders.

Known deviations (documented, acceptable for the TPCH-class workloads):
- A scalar subquery returning >1 row multiplies rows instead of raising
  (error streams are the ok/err collection work).
- NOT IN with NULLs on either side uses antijoin semantics, not SQL
  three-valued logic.
- Correlated references through outer joins / CTE values raise.
"""

from __future__ import annotations

import itertools

from ..expr import relation as mir
from ..expr import scalar as ms
from ..expr.relation import AggregateExpr, AggregateFunc
from ..repr.schema import Column, ColumnType, Schema
from . import hir as h
from .hir import PlanError

_IDS = itertools.count()


def lower(rel: h.HirRelation) -> mir.RelationExpr:
    """Public entry: lower one HIR relation to MIR, scoping the
    free-ref memo (hir._FREE_CACHE) to this pass — the memo keys by
    id() and keeps strong references, so leaving it populated across
    statements grows coordinator memory without bound."""
    try:
        return _lower(rel)
    finally:
        h._FREE_CACHE.clear()


def _lower(rel: h.HirRelation) -> mir.RelationExpr:
    if isinstance(rel, h.HGet):
        return mir.Get(rel.name, rel._schema)
    if isinstance(rel, h.HConstant):
        return mir.Constant(rel.rows, rel._schema)
    if isinstance(rel, h.HRename):
        inner = _lower(rel.input)
        return _rename(inner, rel._schema)
    if isinstance(rel, h.HProject):
        return mir.Project(_lower(rel.input), tuple(rel.outputs))
    if isinstance(rel, h.HMap):
        return _lower_map(_lower(rel.input), rel, shift=0, cmap={})
    if isinstance(rel, h.HFilter):
        cur = _lower(rel.input)
        base = _arity(cur)
        return _lower_filter_preds(
            cur, rel.predicates, keep_arity=base, shift=0, cmap={}
        )
    if isinstance(rel, h.HJoin):
        return _lower_join(rel)
    if isinstance(rel, h.HReduce):
        return _lower_reduce(_lower(rel.input), rel, shift=0, cmap={})
    if isinstance(rel, h.HDistinct):
        inner = _lower(rel.input)
        return mir.Reduce(
            inner, tuple(range(rel.input.schema().arity)), ()
        )
    if isinstance(rel, h.HTopK):
        return mir.TopK(
            _lower(rel.input),
            tuple(rel.group_key),
            tuple(rel.order_by),
            rel.limit,
            rel.offset,
        )
    if isinstance(rel, h.HNegate):
        return mir.Negate(_lower(rel.input))
    if isinstance(rel, h.HThreshold):
        return mir.Threshold(_lower(rel.input))
    if isinstance(rel, h.HUnion):
        return mir.Union(tuple(_lower(i) for i in rel.inputs))
    if isinstance(rel, h.HLet):
        return mir.Let(rel.name, _lower(rel.value), _lower(rel.body))
    if isinstance(rel, h.HLetRec):
        return mir.LetRec(
            tuple(rel.names),
            tuple(_lower(v) for v in rel.values),
            tuple(rel.value_schemas),
            _lower(rel.body),
            rel.max_iters,
        )
    raise NotImplementedError(type(rel).__name__)


def _arity(m: mir.RelationExpr) -> int:
    return m.schema().arity


def _rename(inner: mir.RelationExpr, schema: Schema) -> mir.RelationExpr:
    """MIR has no rename: Get/Constant carry schemas, everything else
    derives names structurally. A no-op Project keeps the tree shape and
    downstream code reads names off the HIR side."""
    if isinstance(inner, mir.Get):
        return mir.Get(inner.name, schema)
    if isinstance(inner, mir.Constant):
        return mir.Constant(inner.rows, schema)
    return inner


# -- shared node lowerings (uncorrelated shift=0 / applied shift=ka) ---------


def _lower_map(inner, rel: h.HMap, shift: int, cmap: dict):
    base = shift + rel.input.schema().arity
    inner2, scalars = _lower_scalars(
        inner, [s for s, _ in rel.scalars], shift=shift, cmap=cmap
    )
    cur = mir.Map(inner2, tuple(scalars))
    if _arity(inner2) != base:
        # subquery columns appended: map exprs then project them away
        n = len(scalars)
        keep = list(range(base)) + list(
            range(_arity(cur) - n, _arity(cur))
        )
        return mir.Project(cur, tuple(keep))
    return cur


def _lower_reduce(inner, rel: h.HReduce, shift: int, cmap: dict):
    inner2, agg_exprs = _lower_scalars(
        inner, [a.expr for a in rel.aggregates], shift=shift, cmap=cmap
    )
    aggs = tuple(
        AggregateExpr(a.func, e, a.distinct, getattr(a, "params", ()))
        for a, e in zip(rel.aggregates, agg_exprs)
    )
    gk = tuple(range(shift)) + tuple(shift + i for i in rel.group_key)
    return mir.Reduce(inner2, gk, aggs)


# -- scalar lowering with subquery decorrelation ------------------------------


def _scalar_at(e: h.HirScalar, shift: int, cmap: dict) -> ms.ScalarExpr:
    """HIR scalar -> MIR scalar with column shift and correlated-ref map;
    raises on embedded subqueries (use _lower_scalars for those)."""
    if isinstance(e, h.HColumn):
        return ms.ColumnRef(shift + e.index)
    if isinstance(e, h.HOuterColumn):
        try:
            return ms.ColumnRef(cmap[(e.level, e.index)])
        except KeyError:
            raise PlanError(
                f"unbound correlated reference (level {e.level})"
            ) from None
    if isinstance(e, h.HMzNow):
        return ms.MzNow()
    if isinstance(e, h.HLiteral):
        return ms.Literal(e.value, e.ctype, e.scale)
    if isinstance(e, h.HCallUnary):
        return ms.CallUnary(e.func, _scalar_at(e.expr, shift, cmap))
    if isinstance(e, h.HCallBinary):
        return ms.CallBinary(
            e.func,
            _scalar_at(e.left, shift, cmap),
            _scalar_at(e.right, shift, cmap),
        )
    if isinstance(e, h.HCallVariadic):
        return ms.CallVariadic(
            e.func, [_scalar_at(x, shift, cmap) for x in e.exprs]
        )
    if isinstance(e, h.HIf):
        return ms.If(
            _scalar_at(e.cond, shift, cmap),
            _scalar_at(e.then, shift, cmap),
            _scalar_at(e.els, shift, cmap),
        )
    if isinstance(e, (h.HExists, h.HScalarSubquery, h.HInSubquery)):
        raise PlanError("subquery in an unsupported scalar position")
    raise NotImplementedError(type(e).__name__)


def _scalar(e: h.HirScalar) -> ms.ScalarExpr:
    """Subquery-free, uncorrelated HIR scalar -> MIR scalar."""
    return _scalar_at(e, 0, {})


def _lower_scalars(cur, exprs, shift: int = 0, cmap: dict | None = None):
    """Lower scalars that may contain subqueries: each subquery's value
    columns are appended to `cur` (cross join when uncorrelated,
    key-branch left-join when correlated) and replaced by column
    references. Returns (new_relation, mir scalar exprs over it)."""
    cmap = cmap or {}
    state = {"cur": cur}

    def walk(e):
        if isinstance(e, h.HScalarSubquery):
            if e.rel.schema().arity != 1:
                raise PlanError("scalar subquery must return one column")
            # Uncorrelated subqueries go through the same branch (with
            # an empty key set): a zero-row subquery then correctly
            # pads NULL for every outer row instead of annihilating
            # the relation via an empty cross join.
            state["cur"], pos = _branch(
                state["cur"], shift, cmap, e.rel, mode="scalar"
            )
            return ms.ColumnRef(pos)
        if isinstance(e, h.HExists):
            state["cur"], pos = _branch(
                state["cur"], shift, cmap, e.rel, mode="exists"
            )
            return ms.ColumnRef(pos)
        if isinstance(e, h.HInSubquery):
            ex = _in_to_exists(e, state["cur"], shift)
            state["cur"], pos = _branch(
                state["cur"], shift, cmap, ex.rel, mode="exists"
            )
            ref = ms.ColumnRef(pos)
            if e.negated:
                return ms.CallUnary(ms.UnaryFunc.NOT, ref)
            return ref
        if isinstance(e, h.HColumn):
            return ms.ColumnRef(shift + e.index)
        if isinstance(e, h.HOuterColumn):
            try:
                return ms.ColumnRef(cmap[(e.level, e.index)])
            except KeyError:
                raise PlanError(
                    f"unbound correlated reference (level {e.level})"
                ) from None
        if isinstance(e, h.HMzNow):
            return ms.MzNow()
        if isinstance(e, h.HLiteral):
            return ms.Literal(e.value, e.ctype, e.scale)
        if isinstance(e, h.HCallUnary):
            return ms.CallUnary(e.func, walk(e.expr))
        if isinstance(e, h.HCallBinary):
            return ms.CallBinary(e.func, walk(e.left), walk(e.right))
        if isinstance(e, h.HCallVariadic):
            return ms.CallVariadic(e.func, [walk(x) for x in e.exprs])
        if isinstance(e, h.HIf):
            return ms.If(walk(e.cond), walk(e.then), walk(e.els))
        raise NotImplementedError(type(e).__name__)

    out = [walk(e) for e in exprs]
    return state["cur"], out


def _shift_into_subquery(e: h.HirScalar, cur_schema: Schema, shift: int):
    """Rewrite a scalar over the enclosing relation into a scalar valid
    INSIDE a subquery of that relation: columns become level-1 outer
    references; existing outer references go one level further out."""
    if isinstance(e, h.HColumn):
        col = cur_schema[shift + e.index]
        return h.HOuterColumn(1, e.index, col)
    if isinstance(e, h.HOuterColumn):
        return h.HOuterColumn(e.level + 1, e.index, e.column)
    if isinstance(e, h.HLiteral):
        return e
    if isinstance(e, h.HMzNow):
        return e
    if isinstance(e, h.HCallUnary):
        return h.HCallUnary(
            e.func, _shift_into_subquery(e.expr, cur_schema, shift)
        )
    if isinstance(e, h.HCallBinary):
        return h.HCallBinary(
            e.func,
            _shift_into_subquery(e.left, cur_schema, shift),
            _shift_into_subquery(e.right, cur_schema, shift),
        )
    if isinstance(e, h.HCallVariadic):
        return h.HCallVariadic(
            e.func,
            tuple(
                _shift_into_subquery(x, cur_schema, shift) for x in e.exprs
            ),
        )
    if isinstance(e, h.HIf):
        return h.HIf(
            _shift_into_subquery(e.cond, cur_schema, shift),
            _shift_into_subquery(e.then, cur_schema, shift),
            _shift_into_subquery(e.els, cur_schema, shift),
        )
    raise PlanError("unsupported IN-subquery left side")


def _in_to_exists(p: h.HInSubquery, cur, shift: int) -> h.HExists:
    """x IN (sub) -> EXISTS(sub WHERE sub.col0 = x), with x shifted into
    the subquery as a correlated reference. The shared EXISTS machinery
    then handles correlated and uncorrelated IN uniformly."""
    if p.rel.schema().arity != 1:
        raise PlanError("IN subquery must return one column")
    x = _shift_into_subquery(p.expr, cur.schema(), shift)
    eq = h.HCallBinary(ms.BinaryFunc.EQ, h.HColumn(0), x)
    return h.HExists(h.HFilter(p.rel, (eq,)))


# -- the branch: correlated subquery -> keys-applied left join ---------------


def _correlation_map(cur, shift: int, cmap: dict, subrel):
    """Positions in `cur` for each of subrel's free outer refs.

    Returns (corr_positions sorted, inner_cmap for lowering subrel over
    the keys relation)."""
    free = sorted(
        h.free_outer_refs(subrel), key=lambda t: (t[0], t[1])
    )
    pos_of = {}
    for lvl, idx, _col in free:
        if lvl == 1:
            pos = shift + idx
        else:
            try:
                pos = cmap[(lvl - 1, idx)]
            except KeyError:
                raise PlanError(
                    f"unbound correlated reference (level {lvl})"
                ) from None
        pos_of[(lvl, idx)] = pos
    corr = sorted(set(pos_of.values()))
    rank = {p: j for j, p in enumerate(corr)}
    inner_cmap = {key: rank[pos] for key, pos in pos_of.items()}
    return corr, inner_cmap


class _BranchKeys:
    """Shared setup for _branch/_branch_semi: the outer-keys relation
    plus NULL-safe join machinery.

    The branch join must treat NULL outer-key values as EQUAL (an outer
    row with a NULL correlated column still gets ITS key's subquery
    result — IS NOT DISTINCT FROM semantics, as in the reference's
    applied_to), but the device equijoin drops NULL keys like SQL `=`.
    So for nullable correlated columns the join runs on an appended
    (coalesce(c, 0), is_null(c)) encoding — the same trick as
    plan_distinct_aggregates — while the keys relation's LEADING columns
    stay the RAW values (which is what the applied subquery reads
    through `cmap`)."""

    def __init__(self, cur, shift: int, cmap: dict, subrel):
        self.corr, self.inner_cmap = _correlation_map(
            cur, shift, cmap, subrel
        )
        self.cur = cur
        self.cur_arity = _arity(cur)
        schema = cur.schema()
        n = next(_IDS)
        self.cname = f"__dc_cur{n}"
        self.kname = f"__dc_keys{n}"
        self.aname = f"__dc_app{n}"
        self.cur_get = mir.Get(self.cname, schema)

        enc_exprs: list = []
        # Per corr col: positions of its join columns on the cur side
        # (raw col, or the two encoded cols appended by the Map).
        cur_join_cols: list = []
        for p in self.corr:
            c = schema[p]
            if c.nullable:
                zero = ms.Literal(
                    False if c.ctype is ColumnType.BOOL else 0,
                    c.ctype,
                    c.scale,
                )
                a = self.cur_arity + len(enc_exprs)
                enc_exprs.append(
                    ms.CallVariadic(
                        ms.VariadicFunc.COALESCE,
                        (ms.ColumnRef(p), zero),
                    )
                )
                enc_exprs.append(
                    ms.CallUnary(ms.UnaryFunc.IS_NULL, ms.ColumnRef(p))
                )
                cur_join_cols.append((a, a + 1))
            else:
                cur_join_cols.append((p,))
        self.n_enc = len(enc_exprs)
        self.cur_enc = (
            mir.Map(self.cur_get, tuple(enc_exprs))
            if enc_exprs
            else self.cur_get
        )
        self.enc_arity = self.cur_arity + self.n_enc
        # keys = DISTINCT(raw corr cols ++ encoded join cols).
        k0 = len(self.corr)
        extra = [
            c for cols in cur_join_cols if len(cols) == 2 for c in cols
        ]
        key_proj = tuple(self.corr) + tuple(extra)
        self.ka = len(key_proj)
        self.keys = mir.Reduce(
            mir.Project(self.cur_enc, key_proj),
            tuple(range(self.ka)),
            (),
        )
        # Key-side positions of each corr col's join columns.
        key_join_cols: list = []
        next_extra = k0
        for j, cols in enumerate(cur_join_cols):
            if len(cols) == 2:
                key_join_cols.append((next_extra, next_extra + 1))
                next_extra += 2
            else:
                key_join_cols.append((j,))
        self.cur_join_cols = cur_join_cols
        self.key_join_cols = key_join_cols

    def equivs(self, right_offset: int):
        """Join equivalences cur_enc ⋈ (keys-prefixed right side)."""
        out = []
        for cc, kc in zip(self.cur_join_cols, self.key_join_cols):
            for a, b in zip(cc, kc):
                out.append(
                    (ms.ColumnRef(a), ms.ColumnRef(right_offset + b))
                )
        return tuple(out)


def _branch(cur, shift: int, cmap: dict, subrel, mode: str):
    """Append the subquery's value column(s) to every row of `cur` with
    left-join semantics (lowering.rs's branch + left join + defaults):

      keys    = DISTINCT(project(cur, correlated columns))
      applied = subrel applied over keys            (keys ++ sub cols)
      matched = cur JOIN applied ON corr = keys     (NULL-safe)
      missing = (cur ∖ cur ⋉ applied's keys) ++ defaults
      result  = matched ∪ missing

    mode 'scalar': appended col = the subquery's single output; default =
    0 for a COUNT output, NULL otherwise. mode 'exists': appended col =
    TRUE for keys with >=1 row, default FALSE. Works for uncorrelated
    subqueries too (empty key set: the keys relation is the nonempty
    flag and a zero-row subquery pads every outer row with the default).

    Returns (new_relation, appended column position)."""
    bk = _BranchKeys(cur, shift, cmap, subrel)
    ka = bk.ka
    cur_arity = bk.cur_arity
    applied = _apply(bk.kname, bk.keys.schema(), subrel, bk.inner_cmap)
    if mode == "exists":
        applied = mir.Map(
            mir.Reduce(
                mir.Project(applied, tuple(range(ka))),
                tuple(range(ka)),
                (),
            ),
            (ms.Literal(True, ColumnType.BOOL),),
        )
        defaults = (ms.Literal(False, ColumnType.BOOL),)
    else:
        n_out = _arity(applied) - ka
        defaults = tuple(
            _output_default(subrel, j) for j in range(n_out)
        )
    applied_get = mir.Get(bk.aname, applied.schema())
    n_out = _arity(applied_get) - ka
    equivs = bk.equivs(bk.enc_arity)
    matched = mir.Project(
        mir.Join((bk.cur_enc, applied_get), equivalences=equivs),
        tuple(range(cur_arity))
        + tuple(bk.enc_arity + ka + t for t in range(n_out)),
    )
    present = mir.Reduce(
        mir.Project(applied_get, tuple(range(ka))), tuple(range(ka)), ()
    )
    semi = mir.Project(
        mir.Join((bk.cur_enc, present), equivalences=equivs),
        tuple(range(cur_arity)),
    )
    unmatched = mir.Threshold(
        mir.Union((bk.cur_get, mir.Negate(semi)))
    )
    padded = mir.Map(unmatched, defaults)
    body = mir.Union((matched, padded))
    out = mir.Let(
        bk.cname,
        cur,
        mir.Let(bk.kname, bk.keys, mir.Let(bk.aname, applied, body)),
    )
    return out, cur_arity


def _branch_semi(cur, shift: int, cmap: dict, subrel, negated: bool):
    """Semijoin (EXISTS) / antijoin (NOT EXISTS) of `cur` against a
    correlated (or not) subquery, keeping cur's columns. NULL-safe on
    the correlated key columns (see _BranchKeys)."""
    bk = _BranchKeys(cur, shift, cmap, subrel)
    ka = bk.ka
    cur_arity = bk.cur_arity
    applied = _apply(bk.kname, bk.keys.schema(), subrel, bk.inner_cmap)
    present = mir.Reduce(
        mir.Project(applied, tuple(range(ka))), tuple(range(ka)), ()
    )
    equivs = bk.equivs(bk.enc_arity)
    semi = mir.Project(
        mir.Join((bk.cur_enc, present), equivalences=equivs),
        tuple(range(cur_arity)),
    )
    if negated:
        body = mir.Threshold(mir.Union((bk.cur_get, mir.Negate(semi))))
    else:
        body = semi
    return mir.Let(bk.cname, cur, mir.Let(bk.kname, bk.keys, body))


def _output_default(rel: h.HirRelation, col: int) -> ms.Literal:
    """Default value for a subquery output column over an empty group:
    COUNT aggregates default to 0, everything else to NULL (the
    reference's AggregateFunc::default)."""
    sch = rel.schema()
    c = sch[col]
    if isinstance(rel, h.HRename):
        return _output_default(rel.input, col)
    if isinstance(rel, h.HProject):
        return _output_default(rel.input, rel.outputs[col])
    if isinstance(rel, h.HMap):
        ia = rel.input.schema().arity
        if col < ia:
            return _output_default(rel.input, col)
        return ms.Literal(None, c.ctype, c.scale)
    if isinstance(rel, h.HReduce):
        nk = len(rel.group_key)
        if (
            col >= nk
            and rel.aggregates[col - nk].func is AggregateFunc.COUNT
        ):
            return ms.Literal(0, ColumnType.INT64)
        return ms.Literal(None, c.ctype, c.scale)
    return ms.Literal(None, c.ctype, c.scale)


# -- apply: lower a subquery over an outer-keys relation ----------------------


def _apply(kname: str, kschema: Schema, rel: h.HirRelation, cmap: dict):
    """Lower `rel` so every row is computed per outer key: the result's
    schema is ``keys ++ rel_columns``. ``cmap`` maps rel's free outer
    references (level, index) to key positions. The applied analog of
    lowering.rs ``HirRelationExpr::applied_to``."""
    ka = kschema.arity
    kget = mir.Get(kname, kschema)
    if not h.is_correlated(rel):
        return mir.Join((kget, _lower(rel)), equivalences=())
    if isinstance(rel, h.HRename):
        return _apply(kname, kschema, rel.input, cmap)
    if isinstance(rel, h.HProject):
        inner = _apply(kname, kschema, rel.input, cmap)
        return mir.Project(
            inner,
            tuple(range(ka)) + tuple(ka + i for i in rel.outputs),
        )
    if isinstance(rel, h.HMap):
        inner = _apply(kname, kschema, rel.input, cmap)
        return _lower_map(inner, rel, shift=ka, cmap=cmap)
    if isinstance(rel, h.HFilter):
        inner = _apply(kname, kschema, rel.input, cmap)
        keep = ka + rel.input.schema().arity
        return _lower_filter_preds(
            inner, rel.predicates, keep_arity=keep, shift=ka, cmap=cmap
        )
    if isinstance(rel, h.HReduce):
        inner = _apply(kname, kschema, rel.input, cmap)
        return _lower_reduce(inner, rel, shift=ka, cmap=cmap)
    if isinstance(rel, h.HDistinct):
        inner = _apply(kname, kschema, rel.input, cmap)
        return mir.Reduce(inner, tuple(range(_arity(inner))), ())
    if isinstance(rel, h.HTopK):
        inner = _apply(kname, kschema, rel.input, cmap)
        gk = tuple(range(ka)) + tuple(ka + i for i in rel.group_key)
        ob = tuple((ka + c, d, nl) for c, d, nl in rel.order_by)
        return mir.TopK(inner, gk, ob, rel.limit, rel.offset)
    if isinstance(rel, h.HNegate):
        return mir.Negate(_apply(kname, kschema, rel.input, cmap))
    if isinstance(rel, h.HThreshold):
        return mir.Threshold(_apply(kname, kschema, rel.input, cmap))
    if isinstance(rel, h.HUnion):
        return mir.Union(
            tuple(_apply(kname, kschema, i, cmap) for i in rel.inputs)
        )
    if isinstance(rel, h.HJoin):
        if rel.kind not in ("inner", "cross"):
            raise NotImplementedError(
                "correlated references through outer joins"
            )
        left = _apply(kname, kschema, rel.left, cmap)
        right = _apply(kname, kschema, rel.right, cmap)
        la = rel.left.schema().arity
        ra = rel.right.schema().arity
        # Join the two applied sides on key equality, drop the duplicate
        # key copy: [keys, L, keys', R] -> [keys, L, R].
        join = mir.Join(
            (left, right),
            equivalences=tuple(
                (ms.ColumnRef(j), ms.ColumnRef(ka + la + j))
                for j in range(ka)
            ),
        )
        out = mir.Project(
            join,
            tuple(range(ka + la))
            + tuple(range(ka + la + ka, ka + la + ka + ra)),
        )
        if rel.on:
            keep = ka + la + ra
            out = _lower_filter_preds(
                out, rel.on, keep_arity=keep, shift=ka, cmap=cmap
            )
        return out
    if isinstance(rel, h.HLet):
        if h.is_correlated(rel.value):
            raise NotImplementedError("correlated CTE value")
        return mir.Let(
            rel.name,
            _lower(rel.value),
            _apply(kname, kschema, rel.body, cmap),
        )
    raise NotImplementedError(
        f"apply: {type(rel).__name__} under correlation"
    )


# -- filters ------------------------------------------------------------------


def _lower_filter_preds(
    cur, predicates, keep_arity: int, shift: int, cmap: dict
):
    """Lower filter conjuncts over `cur`: EXISTS/NOT EXISTS/IN/NOT IN
    conjuncts become semijoins/antijoins; remaining predicates (possibly
    containing scalar subqueries) become a Filter; any appended subquery
    columns are projected away down to `keep_arity`.

    Subquery-FREE conjuncts are applied FIRST (conjunct order is
    semantically free): the correlated branches then key off the
    filtered, equality-constrained relation — in particular the plain
    join equalities of the enclosing WHERE land as a Filter directly
    over the join, where predicate pushdown lifts them into join
    equivalences BEFORE the branch machinery snapshots `cur` into a Let
    (a filter above the Let could no longer be pushed into it, leaving
    the join a cross product)."""
    semis: list = []
    subq_preds: list = []
    pure: list = []
    for p in predicates:
        if isinstance(p, (h.HInSubquery, h.HExists)) or (
            isinstance(p, h.HCallUnary)
            and p.func is ms.UnaryFunc.NOT
            and isinstance(p.expr, h.HExists)
        ):
            semis.append(p)
        elif any(True for _ in h.scalar_subqueries(p)):
            subq_preds.append(p)
        else:
            pure.append(p)
    if pure:
        cur = mir.Filter(
            cur, tuple(_scalar_at(p, shift, cmap) for p in pure)
        )
    for p in semis:
        if isinstance(p, h.HInSubquery):
            ex = _in_to_exists(p, cur, shift)
            cur = _branch_semi(cur, shift, cmap, ex.rel, p.negated)
        elif isinstance(p, h.HExists):
            cur = _branch_semi(cur, shift, cmap, p.rel, negated=False)
        else:
            cur = _branch_semi(
                cur, shift, cmap, p.expr.rel, negated=True
            )
    if subq_preds:
        cur, preds = _lower_scalars(
            cur, subq_preds, shift=shift, cmap=cmap
        )
        cur = mir.Filter(cur, tuple(preds))
    if _arity(cur) != keep_arity:
        cur = mir.Project(cur, tuple(range(keep_arity)))
    return cur


# -- join lowering -----------------------------------------------------------


def _split_on(on, l_arity: int, r_arity: int):
    """Partition ON conjuncts into column-equivalence pairs and residual
    predicates (over the concatenated columns)."""
    equivs: list = []
    residual: list = []
    for c in on:
        if (
            isinstance(c, h.HCallBinary)
            and c.func == ms.BinaryFunc.EQ
            and isinstance(c.left, h.HColumn)
            and isinstance(c.right, h.HColumn)
        ):
            a, b = c.left.index, c.right.index
            if (a < l_arity) != (b < l_arity):
                equivs.append(
                    (ms.ColumnRef(min(a, b)), ms.ColumnRef(max(a, b)))
                )
                continue
        residual.append(c)
    return equivs, residual


def _lower_join(rel: h.HJoin) -> mir.RelationExpr:
    left = _lower(rel.left)
    right = _lower(rel.right)
    la, ra = _arity(left), _arity(right)
    equivs, residual = _split_on(rel.on, la, ra)
    inner = mir.Join((left, right), equivalences=tuple(equivs))
    if residual:
        inner = mir.Filter(
            inner, tuple(_scalar(c) for c in residual)
        )
    if rel.kind in ("inner", "cross"):
        return inner
    out_schema = rel.schema()

    def pad(unmatched, null_ctypes_cols, nulls_first: bool):
        """Append (or prepend, via projection) NULL columns."""
        scalars = tuple(
            ms.Literal(None, c.ctype, c.scale) for c in null_ctypes_cols
        )
        m = mir.Map(unmatched, scalars)
        if not nulls_first:
            return m
        n_u = _arity(unmatched)
        n_n = len(null_ctypes_cols)
        perm = tuple(range(n_u, n_u + n_n)) + tuple(range(n_u))
        return mir.Project(m, perm)

    def antijoin(side, side_arity, inner_proj):
        """Rows of `side` with no match: side - (side ⋉ matched-rows)."""
        matched = mir.Reduce(
            mir.Project(inner, inner_proj), tuple(range(side_arity)), ()
        )
        semi = mir.Project(
            mir.Join(
                (side, matched),
                equivalences=tuple(
                    (ms.ColumnRef(i), ms.ColumnRef(side_arity + i))
                    for i in range(side_arity)
                ),
            ),
            tuple(range(side_arity)),
        )
        return mir.Threshold(mir.Union((side, mir.Negate(semi))))

    parts = [inner]
    if rel.kind in ("left", "full"):
        lu = antijoin(left, la, tuple(range(la)))
        parts.append(pad(lu, out_schema.columns[la:], nulls_first=False))
    if rel.kind in ("right", "full"):
        ru = antijoin(right, ra, tuple(range(la, la + ra)))
        parts.append(pad(ru, out_schema.columns[:la], nulls_first=True))
    return mir.Union(tuple(parts))
