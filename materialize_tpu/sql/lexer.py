"""SQL lexer.

Analog of the reference's ``sql-lexer`` crate (src/sql-lexer): a small,
hand-written tokenizer producing keyword/ident/literal/symbol tokens with
positions for error messages. Keywords are case-insensitive; identifiers
are lower-cased unless double-quoted (PostgreSQL rules, which the
reference follows).
"""

from __future__ import annotations

import enum
from dataclasses import dataclass


class TokKind(enum.Enum):
    KEYWORD = "keyword"
    IDENT = "ident"
    NUMBER = "number"
    STRING = "string"
    SYMBOL = "symbol"
    EOF = "eof"


KEYWORDS = {
    "select", "from", "where", "group", "by", "having", "order", "limit",
    "offset", "asc", "desc", "nulls", "first", "last", "as", "on", "using",
    "join", "inner", "left", "right", "full", "outer", "cross", "and",
    "or", "not", "in", "exists", "between", "like", "ilike", "is", "null", "true",
    "false", "case", "when", "then", "else", "end", "cast", "distinct",
    "union", "all", "except", "intersect", "with", "recursive", "mutually",
    "create", "drop", "view", "materialized", "index", "source", "sink",
    "table", "cluster", "load", "generator", "for", "if", "replace",
    "explain", "plan", "raw", "decorrelated", "optimized", "physical",
    "analysis",
    "show", "insert", "into", "values", "subscribe", "count", "sum",
    "min", "max", "avg", "coalesce", "interval", "extract", "year",
    "default", "return", "at", "recursion", "tpch", "auction", "counter",
    "scale", "factor", "up", "to", "tick", "in", "columns", "of",
    "delete", "update", "set",
    "copy", "stdin", "stdout",
}

SYMBOLS = (
    "<=", ">=", "<>", "!=", "||", "::", "(", ")", ",", ";", ".", "+",
    "-", "*", "/", "%", "<", ">", "=",
)


@dataclass(frozen=True)
class Token:
    kind: TokKind
    text: str  # normalized: keywords/idents lower-cased
    pos: int   # byte offset for error messages

    def is_kw(self, kw: str) -> bool:
        return self.kind is TokKind.KEYWORD and self.text == kw


class LexError(ValueError):
    pass


def lex(sql: str) -> list[Token]:
    toks: list[Token] = []
    i, n = 0, len(sql)
    while i < n:
        c = sql[i]
        if c.isspace():
            i += 1
            continue
        if c == "-" and sql[i : i + 2] == "--":  # line comment
            j = sql.find("\n", i)
            i = n if j < 0 else j + 1
            continue
        if c == "/" and sql[i : i + 2] == "/*":  # block comment
            j = sql.find("*/", i)
            if j < 0:
                raise LexError(f"unterminated block comment at {i}")
            i = j + 2
            continue
        if c == "'":  # string literal, '' escapes a quote
            j = i + 1
            buf = []
            while True:
                if j >= n:
                    raise LexError(f"unterminated string at {i}")
                if sql[j] == "'":
                    if sql[j : j + 2] == "''":
                        buf.append("'")
                        j += 2
                        continue
                    break
                buf.append(sql[j])
                j += 1
            toks.append(Token(TokKind.STRING, "".join(buf), i))
            i = j + 1
            continue
        if c == '"':  # quoted identifier (case-preserving)
            j = sql.find('"', i + 1)
            if j < 0:
                raise LexError(f"unterminated quoted identifier at {i}")
            toks.append(Token(TokKind.IDENT, sql[i + 1 : j], i))
            i = j + 1
            continue
        if c.isdigit() or (
            c == "." and i + 1 < n and sql[i + 1].isdigit()
        ):
            j = i
            seen_dot = False
            while j < n and (sql[j].isdigit() or (sql[j] == "." and not seen_dot)):
                seen_dot = seen_dot or sql[j] == "."
                j += 1
            toks.append(Token(TokKind.NUMBER, sql[i:j], i))
            i = j
            continue
        if c.isalpha() or c == "_":
            j = i
            while j < n and (sql[j].isalnum() or sql[j] == "_"):
                j += 1
            word = sql[i:j].lower()
            kind = TokKind.KEYWORD if word in KEYWORDS else TokKind.IDENT
            toks.append(Token(kind, word, i))
            i = j
            continue
        for sym in SYMBOLS:
            if sql.startswith(sym, i):
                toks.append(Token(TokKind.SYMBOL, sym, i))
                i += len(sym)
                break
        else:
            raise LexError(f"unexpected character {c!r} at {i}")
    toks.append(Token(TokKind.EOF, "", n))
    return toks
