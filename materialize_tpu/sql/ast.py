"""SQL abstract syntax tree.

Analog of the reference's ``sql-parser`` AST (src/sql-parser/src/ast/defs;
``Statement`` has 74 variants there — statement.rs:43). This covers the
statement subset the TPU framework serves: queries, view/index/source DDL,
EXPLAIN, SUBSCRIBE; the shape (Query/SetExpr/TableFactor split) mirrors
the reference so later statements slot in naturally.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional, Sequence

# -- scalar expressions ------------------------------------------------------


class Expr:
    pass


@dataclass(frozen=True)
class Ident(Expr):
    """Possibly-qualified name: a / t.a."""

    parts: tuple  # ("t", "a") or ("a",)


@dataclass(frozen=True)
class NumberLit(Expr):
    text: str  # original digits; planner decides int vs decimal


@dataclass(frozen=True)
class StringLit(Expr):
    value: str


@dataclass(frozen=True)
class IntervalLit(Expr):
    """INTERVAL '...' literal, normalized to (months, days, ms)."""

    months: int = 0
    days: int = 0
    ms: int = 0


@dataclass(frozen=True)
class BoolLit(Expr):
    value: bool


@dataclass(frozen=True)
class NullLit(Expr):
    pass


@dataclass(frozen=True)
class BinaryOp(Expr):
    op: str  # +,-,*,/,%,=,<>,<,<=,>,>=,and,or,||
    left: Expr
    right: Expr


@dataclass(frozen=True)
class UnaryOp(Expr):
    op: str  # -, not
    expr: Expr


@dataclass(frozen=True)
class IsNull(Expr):
    expr: Expr
    negated: bool


@dataclass(frozen=True)
class Like(Expr):
    expr: Expr
    pattern: Expr  # must plan to a string literal
    negated: bool = False
    case_insensitive: bool = False  # ILIKE


@dataclass(frozen=True)
class InList(Expr):
    expr: Expr
    items: tuple
    negated: bool


@dataclass(frozen=True)
class Between(Expr):
    expr: Expr
    low: Expr
    high: Expr
    negated: bool


@dataclass(frozen=True)
class Case(Expr):
    operand: Optional[Expr]
    whens: tuple  # (cond, result) pairs
    else_: Optional[Expr]


@dataclass(frozen=True)
class Cast(Expr):
    expr: Expr
    to_type: str


@dataclass(frozen=True)
class FuncCall(Expr):
    name: str
    args: tuple
    distinct: bool = False
    star: bool = False  # count(*)


@dataclass(frozen=True)
class Extract(Expr):
    part: str  # "year"
    expr: Expr


@dataclass(frozen=True)
class Exists(Expr):
    query: "Query"
    negated: bool = False


@dataclass(frozen=True)
class ScalarSubquery(Expr):
    query: "Query"


@dataclass(frozen=True)
class InSubquery(Expr):
    expr: Expr
    query: "Query"
    negated: bool


@dataclass(frozen=True)
class Star(Expr):
    """SELECT * or t.*"""

    qualifier: Optional[str] = None


# -- query structure ---------------------------------------------------------


@dataclass(frozen=True)
class SelectItem:
    expr: Expr
    alias: Optional[str] = None


@dataclass(frozen=True)
class OrderByItem:
    expr: Expr
    desc: bool = False
    nulls_last: Optional[bool] = None  # None = dialect default


@dataclass(frozen=True)
class TableAlias:
    name: str
    columns: tuple = ()


class TableFactor:
    pass


@dataclass(frozen=True)
class TableName(TableFactor):
    name: str
    alias: Optional[TableAlias] = None


@dataclass(frozen=True)
class DerivedTable(TableFactor):
    query: "Query"
    alias: Optional[TableAlias] = None


@dataclass(frozen=True)
class JoinClause:
    kind: str  # inner/left/right/full/cross
    factor: TableFactor
    on: Optional[Expr] = None
    using: tuple = ()


@dataclass(frozen=True)
class FromItem:
    factor: TableFactor
    joins: tuple = ()  # JoinClause


@dataclass(frozen=True)
class Select:
    items: tuple  # SelectItem
    from_: tuple = ()  # FromItem (comma list)
    where: Optional[Expr] = None
    group_by: tuple = ()
    having: Optional[Expr] = None
    distinct: bool = False


class SetExpr:
    pass


@dataclass(frozen=True)
class SelectExpr(SetExpr):
    select: Select


@dataclass(frozen=True)
class SetOp(SetExpr):
    op: str  # union/except/intersect
    all: bool
    left: SetExpr
    right: SetExpr


@dataclass(frozen=True)
class Cte:
    name: str
    columns: tuple  # (name, type) pairs for WMR; plain names for WITH
    query: "Query"


@dataclass(frozen=True)
class Query:
    body: SetExpr
    ctes: tuple = ()
    mutually_recursive: bool = False
    recursion_limit: Optional[int] = None
    order_by: tuple = ()  # OrderByItem
    limit: Optional[int] = None
    offset: int = 0


# -- statements --------------------------------------------------------------


class Statement:
    pass


@dataclass(frozen=True)
class SelectStatement(Statement):
    query: Query
    # SELECT ... AS OF <time>: read at an explicit timestamp inside the
    # multiversion window (reference: sql-parser AS OF on SELECT/
    # SUBSCRIBE, adapter/src/coord/read_policy.rs lag windows)
    as_of: Optional[int] = None


@dataclass(frozen=True)
class CreateView(Statement):
    name: str
    query: Query
    materialized: bool = False
    or_replace: bool = False


@dataclass(frozen=True)
class CreateIndex(Statement):
    name: Optional[str]
    on: str
    key: tuple = ()  # expressions; empty = default key (all columns)


@dataclass(frozen=True)
class CreateSource(Statement):
    name: str
    generator: str  # tpch/auction/counter/... or "kafka"
    options: dict = field(default_factory=dict)
    # declared columns for external-format sources (kafka):
    # (name, type_name, nullable) triples, like CreateTable
    columns: tuple = ()


@dataclass(frozen=True)
class CreateSink(Statement):
    """CREATE SINK name FROM obj INTO KAFKA (options...)."""

    name: str
    from_obj: str
    options: dict = field(default_factory=dict)


@dataclass(frozen=True)
class CreateTable(Statement):
    name: str
    columns: tuple  # (name, type_name, nullable) triples


@dataclass(frozen=True)
class CreateWebhook(Statement):
    """CREATE SOURCE ... FROM WEBHOOK (cols): HTTP-ingested source
    (the reference's webhook sources, adapter/src/webhook.rs)."""

    name: str
    columns: tuple  # (name, type_name, nullable) triples


@dataclass(frozen=True)
class Insert(Statement):
    table: str
    rows: tuple  # tuple of tuples of Expr (constant values)
    columns: tuple = ()  # optional explicit column list


@dataclass(frozen=True)
class Delete(Statement):
    table: str
    where: Optional[Expr] = None


@dataclass(frozen=True)
class Update(Statement):
    table: str
    assignments: tuple  # (column_name, Expr)
    where: Optional[Expr] = None


@dataclass(frozen=True)
class SetVar(Statement):
    name: str
    value: object  # python scalar or None (RESET)


@dataclass(frozen=True)
class ShowVar(Statement):
    name: str


@dataclass(frozen=True)
class DropObject(Statement):
    kind: str  # view/index/source
    name: str
    if_exists: bool = False


@dataclass(frozen=True)
class Explain(Statement):
    stage: str  # raw/decorrelated/optimized/physical/analysis
    statement: Statement


@dataclass(frozen=True)
class Subscribe(Statement):
    query: Query
    as_of: Optional[int] = None


@dataclass(frozen=True)
class CopyFrom(Statement):
    """COPY table [(cols)] FROM STDIN (text format)."""

    table: str
    columns: tuple = ()


@dataclass(frozen=True)
class CopyTo(Statement):
    """COPY (query) TO STDOUT / COPY table TO STDOUT."""

    query: Query


@dataclass(frozen=True)
class ShowObjects(Statement):
    kind: str  # sources/views/indexes
