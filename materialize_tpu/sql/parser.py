"""SQL parser: hand-written recursive descent over the lexer's tokens.

Analog of the reference's ``sql-parser`` crate (forked from sqlparser-rs;
doc/developer/life-of-a-query.md:104-107). Precedence climbing for scalar
expressions; the statement grammar covers queries (joins, subqueries,
CTEs, WITH MUTUALLY RECURSIVE), CREATE SOURCE ... FROM LOAD GENERATOR,
CREATE [MATERIALIZED] VIEW, CREATE [DEFAULT] INDEX, DROP, EXPLAIN
[RAW|DECORRELATED|OPTIMIZED|PHYSICAL] PLAN FOR, SUBSCRIBE, SHOW.
"""

from __future__ import annotations

from typing import Optional

from . import ast
from .lexer import Token, TokKind, lex


class ParseError(ValueError):
    pass


# binding powers, loosest to tightest (the reference's precedence ladder)
_BINARY_PREC = {
    "or": 10,
    "and": 20,
    # NOT handled as prefix at 25
    "=": 40, "<>": 40, "!=": 40, "<": 40, "<=": 40, ">": 40, ">=": 40,
    "like": 40, "ilike": 40, "between": 40, "in": 40, "is": 40,
    "||": 50,
    "+": 60, "-": 60,
    "*": 70, "/": 70, "%": 70,
}


class Parser:
    def __init__(self, sql: str):
        self.toks = lex(sql)
        self.i = 0

    # -- token helpers -----------------------------------------------------
    def peek(self, ahead: int = 0) -> Token:
        j = self.i + ahead
        return self.toks[min(j, len(self.toks) - 1)]

    def next(self) -> Token:
        t = self.toks[self.i]
        self.i += 1
        return t

    def accept_kw(self, *kws: str) -> Optional[str]:
        t = self.peek()
        if t.kind is TokKind.KEYWORD and t.text in kws:
            self.i += 1
            return t.text
        return None

    def expect_kw(self, *kws: str) -> str:
        got = self.accept_kw(*kws)
        if got is None:
            raise ParseError(
                f"expected {'/'.join(kws).upper()}, got "
                f"{self.peek().text!r} at {self.peek().pos}"
            )
        return got

    def accept_sym(self, sym: str) -> bool:
        t = self.peek()
        if t.kind is TokKind.SYMBOL and t.text == sym:
            self.i += 1
            return True
        return False

    def expect_sym(self, sym: str) -> None:
        if not self.accept_sym(sym):
            raise ParseError(
                f"expected {sym!r}, got {self.peek().text!r} at "
                f"{self.peek().pos}"
            )

    def expect_ident(self) -> str:
        t = self.peek()
        # Allow non-reserved keywords as identifiers where unambiguous.
        if t.kind in (TokKind.IDENT, TokKind.KEYWORD):
            self.i += 1
            return t.text
        raise ParseError(f"expected identifier, got {t.text!r} at {t.pos}")

    # -- entry -------------------------------------------------------------
    def parse_statement(self) -> ast.Statement:
        stmt = self._statement()
        self.accept_sym(";")
        t = self.peek()
        if t.kind is not TokKind.EOF:
            raise ParseError(f"trailing input at {t.pos}: {t.text!r}")
        return stmt

    def _statement(self) -> ast.Statement:
        if self.accept_kw("explain"):
            return self._explain()
        if self.accept_kw("create"):
            return self._create()
        if self.accept_kw("drop"):
            return self._drop()
        if self.accept_kw("insert"):
            return self._insert()
        if self.accept_kw("delete"):
            self.expect_kw("from")
            table = self.expect_ident()
            where = None
            if self.accept_kw("where"):
                where = self.parse_expr()
            return ast.Delete(table, where)
        if self.accept_kw("update"):
            table = self.expect_ident()
            self.expect_kw("set")
            assignments = []
            while True:
                col = self.expect_ident()
                self.expect_sym("=")
                assignments.append((col, self.parse_expr()))
                if not self.accept_sym(","):
                    break
            where = None
            if self.accept_kw("where"):
                where = self.parse_expr()
            return ast.Update(table, tuple(assignments), where)
        if self.accept_kw("set"):
            name = self.expect_ident()
            if not self.accept_sym("="):
                self.expect_kw("to")
            t = self.peek()
            if t.kind is TokKind.NUMBER:
                self.next()
                value = float(t.text) if "." in t.text else int(t.text)
            elif t.kind is TokKind.STRING:
                self.next()
                value = t.text
            elif t.is_kw("default"):
                self.next()
                value = None
            elif t.is_kw("true") or t.is_kw("false"):
                self.next()
                value = t.text == "true"
            else:
                value = self.expect_ident()
            return ast.SetVar(name, value)
        if self.accept_kw("copy"):
            if self.accept_sym("("):
                q = self.parse_query()
                self.expect_sym(")")
                self.expect_kw("to")
                self.expect_kw("stdout")
                return ast.CopyTo(q)
            table = self.expect_ident()
            cols: list = []
            if self.accept_sym("("):
                cols.append(self.expect_ident())
                while self.accept_sym(","):
                    cols.append(self.expect_ident())
                self.expect_sym(")")
            if self.accept_kw("to"):
                self.expect_kw("stdout")
                # build the query as AST (no SQL-text round trip: quoted
                # / case-preserving identifiers must survive)
                items = (
                    tuple(
                        ast.SelectItem(ast.Ident((c,))) for c in cols
                    )
                    if cols
                    else (ast.SelectItem(ast.Star()),)
                )
                q = ast.Query(
                    ast.SelectExpr(
                        ast.Select(
                            items,
                            (ast.FromItem(ast.TableName(table)),),
                        )
                    )
                )
                return ast.CopyTo(q)
            self.expect_kw("from")
            self.expect_kw("stdin")
            # optional WITH (FORMAT TEXT) — text is the only format
            if self.accept_kw("with"):
                self.expect_sym("(")
                depth = 1
                while depth:
                    t = self.next()
                    if t.kind is TokKind.EOF:
                        raise ParseError("unterminated COPY options")
                    if t.text == "(":
                        depth += 1
                    elif t.text == ")":
                        depth -= 1
            return ast.CopyFrom(table, tuple(cols))
        if self.accept_kw("subscribe"):
            self.accept_kw("to")
            t = self.peek()
            # A bare relation name (keywords double as identifiers here,
            # as everywhere expect_ident does — relations may be named
            # 'counter' etc.): SUBSCRIBE r == SUBSCRIBE (SELECT * FROM r)
            if (
                t.kind is TokKind.IDENT
                or (
                    t.kind is TokKind.KEYWORD
                    and t.text not in ("select", "with", "values")
                )
            ):
                name = self.expect_ident()
                return ast.Subscribe(
                    Parser(f"SELECT * FROM {name}").parse_query(),
                    self._parse_as_of(),
                )
            q = self.parse_query()
            return ast.Subscribe(q, self._parse_as_of())
        if self.accept_kw("show"):
            kind = self.expect_ident()
            if kind.lower() in (
                "objects", "sources", "views", "indexes", "tables",
                "source", "view", "index", "table",
            ):
                return ast.ShowObjects(kind)
            return ast.ShowVar(kind)  # SHOW <system variable>
        q = self.parse_query()
        return ast.SelectStatement(q, self._parse_as_of())

    def _parse_as_of(self):
        """Optional statement-level ``AS OF <int>`` (reference:
        sql-parser AS OF on SELECT/SUBSCRIBE). Only legal AFTER a full
        query — table-alias AS never reaches here."""
        if not self.accept_kw("as"):
            return None
        self.expect_kw("of")
        t = self.next()
        if t.kind is not TokKind.NUMBER:
            raise ParseError(
                f"AS OF expects an integer timestamp at {t.pos}"
            )
        return int(t.text)

    # -- DDL ---------------------------------------------------------------
    def _create(self) -> ast.Statement:
        or_replace = False
        if self.accept_kw("or"):
            self.expect_kw("replace")
            or_replace = True
        if self.accept_kw("materialized"):
            self.expect_kw("view")
            return self._create_view(materialized=True, or_replace=or_replace)
        if self.accept_kw("view"):
            return self._create_view(materialized=False, or_replace=or_replace)
        if self.accept_kw("source"):
            return self._create_source()
        if self.accept_kw("sink"):
            return self._create_sink()
        if self.accept_kw("table"):
            return self._create_table()
        if self.accept_kw("default"):
            self.expect_kw("index")
            self.expect_kw("on")
            return ast.CreateIndex(None, self.expect_ident())
        if self.accept_kw("index"):
            name = None
            if not self.peek().is_kw("on"):
                name = self.expect_ident()
            self.expect_kw("on")
            on = self.expect_ident()
            key = ()
            if self.accept_sym("("):
                exprs = [self.parse_expr()]
                while self.accept_sym(","):
                    exprs.append(self.parse_expr())
                self.expect_sym(")")
                key = tuple(exprs)
            return ast.CreateIndex(name, on, key)
        raise ParseError(f"unsupported CREATE at {self.peek().pos}")

    def _create_table(self) -> ast.Statement:
        name = self.expect_ident()
        return ast.CreateTable(name, self._column_defs())

    def _column_defs(self) -> tuple:
        """'(' col type [NOT NULL|NULL], ... ')' — shared by CREATE
        TABLE and CREATE SOURCE ... FROM WEBHOOK."""
        self.expect_sym("(")
        columns = []
        while True:
            col = self.expect_ident()
            ty = self._type_name()
            nullable = True
            if self.accept_kw("not"):
                self.expect_kw("null")
                nullable = False
            elif self.accept_kw("null"):
                pass
            columns.append((col, ty, nullable))
            if not self.accept_sym(","):
                break
        self.expect_sym(")")
        return tuple(columns)

    def expect_ident_or_number(self) -> str:
        t = self.peek()
        if t.kind is TokKind.NUMBER:
            self.next()
            return t.text
        return self.expect_ident()

    def _insert(self) -> ast.Statement:
        self.expect_kw("into")
        table = self.expect_ident()
        columns: tuple = ()
        if self.accept_sym("("):
            cols = [self.expect_ident()]
            while self.accept_sym(","):
                cols.append(self.expect_ident())
            self.expect_sym(")")
            columns = tuple(cols)
        self.expect_kw("values")
        rows = []
        while True:
            self.expect_sym("(")
            vals = [self.parse_expr()]
            while self.accept_sym(","):
                vals.append(self.parse_expr())
            self.expect_sym(")")
            rows.append(tuple(vals))
            if not self.accept_sym(","):
                break
        return ast.Insert(table, tuple(rows), columns)

    def _create_view(self, materialized: bool, or_replace: bool):
        name = self.expect_ident()
        self.expect_kw("as")
        q = self.parse_query()
        return ast.CreateView(name, q, materialized, or_replace)

    def _create_source(self):
        name = self.expect_ident()
        columns: tuple = ()
        if self.peek().text == "(":
            columns = self._column_defs()
        self.expect_kw("from")
        if self.peek().text == "webhook":
            self.next()
            if columns:
                raise ParseError(
                    "webhook columns go after FROM WEBHOOK"
                )
            return ast.CreateWebhook(name, self._column_defs())
        if self.peek().text == "kafka":
            self.next()
            options = self._source_options()
            return ast.CreateSource(name, "kafka", options, columns)
        self.expect_kw("load")
        self.expect_kw("generator")
        gen = self.expect_ident()
        return ast.CreateSource(name, gen, self._source_options())

    def _source_options(self) -> dict:
        """'(' KEY [WORDS...] value, ... ')' — shared by LOAD GENERATOR,
        KAFKA sources, and sinks (SCALE FACTOR 0.1 / TOPIC 'events')."""
        options: dict = {}
        if not self.accept_sym("("):
            return options
        while True:
            key_parts = [self.expect_ident()]
            while self.peek().kind in (TokKind.IDENT, TokKind.KEYWORD) \
                    and not self.peek().is_kw("for"):
                # multi-word option names (SCALE FACTOR, TICK INTERVAL)
                if self.peek().kind is TokKind.SYMBOL:
                    break
                nxt = self.peek()
                if nxt.kind is TokKind.SYMBOL:
                    break
                if nxt.text in (",",):
                    break
                # value follows as number/string; stop if next is value
                if nxt.kind is TokKind.IDENT and len(key_parts) >= 2:
                    break
                if nxt.kind in (TokKind.NUMBER, TokKind.STRING):
                    break
                key_parts.append(self.expect_ident())
            key = " ".join(key_parts)
            t = self.peek()
            if t.kind is TokKind.NUMBER:
                self.next()
                val = float(t.text) if "." in t.text else int(t.text)
            elif t.kind is TokKind.STRING:
                self.next()
                val = t.text
            else:
                val = True
            options[key] = val
            if not self.accept_sym(","):
                break
        self.expect_sym(")")
        return options

    def _create_sink(self):
        name = self.expect_ident()
        self.expect_kw("from")
        from_obj = self.expect_ident()
        self.expect_kw("into")
        if self.peek().text != "kafka":
            raise ParseError("CREATE SINK supports INTO KAFKA")
        self.next()
        return ast.CreateSink(name, from_obj, self._source_options())

    def _drop(self):
        kind = self.expect_ident()
        if_exists = False
        if self.accept_kw("if"):
            self.expect_ident()  # "exists"
            if_exists = True
        name = self.expect_ident()
        return ast.DropObject(kind, name, if_exists)

    def _explain(self):
        stage = self.accept_kw(
            "raw", "decorrelated", "optimized", "physical", "analysis"
        )
        if stage is None:
            stage = "optimized"
        self.accept_kw("plan")
        self.accept_kw("for")
        return ast.Explain(stage, self._statement())

    # -- queries -----------------------------------------------------------
    def parse_query(self) -> ast.Query:
        ctes: list = []
        mutually_recursive = False
        recursion_limit = None
        if self.accept_kw("with"):
            if self.accept_kw("mutually"):
                self.expect_kw("recursive")
                mutually_recursive = True
                if self.accept_sym("("):  # (RETURN AT RECURSION LIMIT n)
                    self.expect_kw("return")
                    self.expect_kw("at")
                    self.expect_kw("recursion")
                    self.expect_kw("limit")
                    recursion_limit = int(self.next().text)
                    self.expect_sym(")")
            while True:
                name = self.expect_ident()
                cols: list = []
                if self.accept_sym("("):
                    while True:
                        cname = self.expect_ident()
                        ctype = None
                        if mutually_recursive:
                            ctype = self._type_name()
                        cols.append((cname, ctype))
                        if not self.accept_sym(","):
                            break
                    self.expect_sym(")")
                self.expect_kw("as")
                self.expect_sym("(")
                q = self.parse_query()
                self.expect_sym(")")
                ctes.append(ast.Cte(name, tuple(cols), q))
                if not self.accept_sym(","):
                    break
        body = self._set_expr()
        order_by: list = []
        if self.accept_kw("order"):
            self.expect_kw("by")
            while True:
                e = self.parse_expr()
                desc = False
                if self.accept_kw("desc"):
                    desc = True
                else:
                    self.accept_kw("asc")
                nulls_last = None
                if self.accept_kw("nulls"):
                    nulls_last = self.expect_kw("first", "last") == "last"
                order_by.append(ast.OrderByItem(e, desc, nulls_last))
                if not self.accept_sym(","):
                    break
        limit = None
        offset = 0
        if self.accept_kw("limit"):
            limit = int(self.next().text)
        if self.accept_kw("offset"):
            offset = int(self.next().text)
        return ast.Query(
            body, tuple(ctes), mutually_recursive, recursion_limit,
            tuple(order_by), limit, offset,
        )

    def _type_name(self) -> str:
        parts = [self.expect_ident()]
        # e.g. double precision / timestamp with time zone (one word here)
        if parts[0] == "double" and self.peek().text == "precision":
            parts.append(self.expect_ident())
        name = " ".join(parts)
        # parameterized types: decimal(12,2), varchar(10), ...
        if self.accept_sym("("):
            args = [self.expect_ident_or_number()]
            while self.accept_sym(","):
                args.append(self.expect_ident_or_number())
            self.expect_sym(")")
            name += "(" + ",".join(args) + ")"
        return name

    def _set_expr(self) -> ast.SetExpr:
        left = self._set_atom()
        while True:
            op = self.accept_kw("union", "except", "intersect")
            if op is None:
                return left
            all_ = bool(self.accept_kw("all"))
            if not all_:
                self.accept_kw("distinct")
            right = self._set_atom()
            left = ast.SetOp(op, all_, left, right)

    def _set_atom(self) -> ast.SetExpr:
        if self.accept_sym("("):
            inner = self._set_expr()
            self.expect_sym(")")
            return inner
        self.expect_kw("select")
        return ast.SelectExpr(self._select_body())

    def _select_body(self) -> ast.Select:
        distinct = bool(self.accept_kw("distinct"))
        if not distinct:
            self.accept_kw("all")
        items = [self._select_item()]
        while self.accept_sym(","):
            items.append(self._select_item())
        from_: list = []
        if self.accept_kw("from"):
            from_.append(self._from_item())
            while self.accept_sym(","):
                from_.append(self._from_item())
        where = None
        if self.accept_kw("where"):
            where = self.parse_expr()
        group_by: list = []
        if self.accept_kw("group"):
            self.expect_kw("by")
            group_by.append(self.parse_expr())
            while self.accept_sym(","):
                group_by.append(self.parse_expr())
        having = None
        if self.accept_kw("having"):
            having = self.parse_expr()
        return ast.Select(
            tuple(items), tuple(from_), where, tuple(group_by), having,
            distinct,
        )

    def _select_item(self) -> ast.SelectItem:
        if self.accept_sym("*"):
            return ast.SelectItem(ast.Star())
        e = self.parse_expr()
        alias = None
        if self.accept_kw("as"):
            alias = self.expect_ident()
        elif self.peek().kind is TokKind.IDENT:
            alias = self.expect_ident()
        return ast.SelectItem(e, alias)

    def _from_item(self) -> ast.FromItem:
        factor = self._table_factor()
        joins: list = []
        while True:
            kind = None
            if self.accept_kw("cross"):
                self.expect_kw("join")
                kind = "cross"
            elif self.accept_kw("inner"):
                self.expect_kw("join")
                kind = "inner"
            elif self.accept_kw("left"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "left"
            elif self.accept_kw("right"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "right"
            elif self.accept_kw("full"):
                self.accept_kw("outer")
                self.expect_kw("join")
                kind = "full"
            elif self.accept_kw("join"):
                kind = "inner"
            if kind is None:
                return ast.FromItem(factor, tuple(joins))
            f = self._table_factor()
            on = None
            using: tuple = ()
            if kind != "cross":
                if self.accept_kw("on"):
                    on = self.parse_expr()
                elif self.accept_kw("using"):
                    self.expect_sym("(")
                    names = [self.expect_ident()]
                    while self.accept_sym(","):
                        names.append(self.expect_ident())
                    self.expect_sym(")")
                    using = tuple(names)
            joins.append(ast.JoinClause(kind, f, on, using))

    def _table_factor(self) -> ast.TableFactor:
        if self.accept_sym("("):
            # subquery or parenthesized join tree (only subquery supported)
            q = self.parse_query()
            self.expect_sym(")")
            alias = self._table_alias()
            return ast.DerivedTable(q, alias)
        name = self.expect_ident()
        alias = self._table_alias()
        return ast.TableName(name, alias)

    def _table_alias(self) -> Optional[ast.TableAlias]:
        # `AS OF <n>` after a table factor is the statement-level
        # timestamp clause, never an alias named "of" (OF is reserved
        # in alias position, as in the reference's parser).
        if self.peek().is_kw("as") and self.peek(1).is_kw("of") \
                and self.peek(2).kind is TokKind.NUMBER:
            return None
        if self.accept_kw("as"):
            name = self.expect_ident()
        elif self.peek().kind is TokKind.IDENT:
            name = self.expect_ident()
        else:
            return None
        cols: tuple = ()
        if self.accept_sym("("):
            names = [self.expect_ident()]
            while self.accept_sym(","):
                names.append(self.expect_ident())
            self.expect_sym(")")
            cols = tuple(names)
        return ast.TableAlias(name, cols)

    # -- scalar expressions (precedence climbing) --------------------------
    def parse_expr(self, min_prec: int = 0) -> ast.Expr:
        left = self._prefix()
        while True:
            t = self.peek()
            op = None
            if t.kind is TokKind.SYMBOL and t.text in _BINARY_PREC:
                op = t.text
            elif t.kind is TokKind.KEYWORD and t.text in (
                "and", "or", "like", "ilike", "between", "in", "is",
                "not",
            ):
                op = t.text
            if op is None:
                return left
            # NOT IN / NOT LIKE / NOT BETWEEN
            negated = False
            if op == "not":
                nxt = self.toks[self.i + 1]
                if nxt.kind is TokKind.KEYWORD and nxt.text in (
                    "in", "like", "ilike", "between",
                ):
                    negated = True
                    op = nxt.text
                else:
                    return left
            prec = _BINARY_PREC[op]
            if prec < min_prec:
                return left
            self.next()
            if negated:
                self.next()
            if op == "is":
                neg = bool(self.accept_kw("not"))
                self.expect_kw("null")
                left = ast.IsNull(left, neg)
                continue
            if op == "between":
                low = self.parse_expr(_BINARY_PREC["between"] + 1)
                self.expect_kw("and")
                high = self.parse_expr(_BINARY_PREC["between"] + 1)
                left = ast.Between(left, low, high, negated)
                continue
            if op == "in":
                self.expect_sym("(")
                if self.peek().is_kw("select") or self.peek().is_kw("with"):
                    q = self.parse_query()
                    self.expect_sym(")")
                    left = ast.InSubquery(left, q, negated)
                else:
                    items = [self.parse_expr()]
                    while self.accept_sym(","):
                        items.append(self.parse_expr())
                    self.expect_sym(")")
                    left = ast.InList(left, tuple(items), negated)
                continue
            right = self.parse_expr(prec + 1)
            if op in ("like", "ilike"):
                left = ast.Like(left, right, negated, op == "ilike")
                continue
            if op == "!=":
                op = "<>"
            left = ast.BinaryOp(op, left, right)

    def _prefix(self) -> ast.Expr:
        t = self.peek()
        if self.accept_sym("-"):
            return ast.UnaryOp("-", self.parse_expr(65))
        if self.accept_sym("+"):
            return self.parse_expr(65)
        if self.accept_kw("not"):
            return ast.UnaryOp("not", self.parse_expr(25))
        if self.accept_sym("("):
            if self.peek().is_kw("select") or self.peek().is_kw("with"):
                q = self.parse_query()
                self.expect_sym(")")
                return ast.ScalarSubquery(q)
            e = self.parse_expr()
            self.expect_sym(")")
            return self._postfix(e)
        if self.accept_kw("exists"):
            self.expect_sym("(")
            q = self.parse_query()
            self.expect_sym(")")
            return ast.Exists(q)
        if self.accept_kw("case"):
            operand = None
            if not self.peek().is_kw("when"):
                operand = self.parse_expr()
            whens = []
            while self.accept_kw("when"):
                cond = self.parse_expr()
                self.expect_kw("then")
                whens.append((cond, self.parse_expr()))
            else_ = None
            if self.accept_kw("else"):
                else_ = self.parse_expr()
            self.expect_kw("end")
            return ast.Case(operand, tuple(whens), else_)
        if self.accept_kw("cast"):
            self.expect_sym("(")
            e = self.parse_expr()
            self.expect_kw("as")
            ty = self._type_name()
            self.expect_sym(")")
            return self._postfix(ast.Cast(e, ty))
        if self.accept_kw("extract"):
            self.expect_sym("(")
            part = self.expect_ident()
            self.expect_kw("from")
            e = self.parse_expr()
            self.expect_sym(")")
            return ast.Extract(part, e)
        if self.accept_kw("true"):
            return ast.BoolLit(True)
        if self.accept_kw("false"):
            return ast.BoolLit(False)
        if self.accept_kw("null"):
            return ast.NullLit()
        if t.kind is TokKind.NUMBER:
            self.next()
            return self._postfix(ast.NumberLit(t.text))
        if t.kind is TokKind.STRING:
            self.next()
            return self._postfix(ast.StringLit(t.text))
        if self.accept_kw("interval"):
            s = self.peek()
            if s.kind is not TokKind.STRING:
                raise ParseError("INTERVAL requires a string literal")
            self.next()
            unit = None
            u = self.peek()
            if u.kind in (TokKind.IDENT, TokKind.KEYWORD) and (
                u.text.lower().rstrip("s") in _INTERVAL_UNITS
            ):
                unit = u.text.lower().rstrip("s")
                self.next()
            return _interval_literal(s.text, unit)
        if t.kind in (TokKind.IDENT, TokKind.KEYWORD):
            # typed literal (DATE '1994-01-01', TIMESTAMP '...')
            if t.text.lower() in ("date", "timestamp") and (
                self.peek(1).kind is TokKind.STRING
            ):
                ty = t.text.lower()
                self.next()
                s = self.peek()
                self.next()
                return self._postfix(ast.Cast(ast.StringLit(s.text), ty))
            # function call or (qualified) column reference
            name = self.expect_ident()
            if self.accept_sym("("):
                if self.accept_sym("*"):
                    self.expect_sym(")")
                    return ast.FuncCall(name, (), star=True)
                distinct = bool(self.accept_kw("distinct"))
                args: list = []
                if not self.accept_sym(")"):
                    args.append(self.parse_expr())
                    while self.accept_sym(","):
                        args.append(self.parse_expr())
                    self.expect_sym(")")
                return ast.FuncCall(name, tuple(args), distinct)
            parts = [name]
            while self.accept_sym("."):
                if self.accept_sym("*"):
                    return ast.Star(qualifier=".".join(parts))
                parts.append(self.expect_ident())
            return self._postfix(ast.Ident(tuple(parts)))
        raise ParseError(f"unexpected token {t.text!r} at {t.pos}")

    def _postfix(self, e: ast.Expr) -> ast.Expr:
        while self.accept_sym("::"):
            e = ast.Cast(e, self._type_name())
        return e


_INTERVAL_UNITS = {
    "year": ("months", 12),
    "quarter": ("months", 3),
    "month": ("months", 1),
    "week": ("days", 7),
    "day": ("days", 1),
    "hour": ("ms", 3_600_000),
    "minute": ("ms", 60_000),
    "second": ("ms", 1_000),
    "millisecond": ("ms", 1),
}


def _interval_literal(text: str, unit: str | None) -> ast.IntervalLit:
    """INTERVAL '1' YEAR / INTERVAL '3 months' / INTERVAL '1 day 2:30'
    -> normalized (months, days, ms), like the reference's interval
    parsing (repr/src/adt/interval.rs). Bare numbers are SECONDS and
    H[:M[:S]] groups are time-of-day, both as in pg; fractional months
    spill into days (30/month) and fractional days into ms."""
    monthsf = daysf = 0.0
    msf = 0.0

    def add(qty: float, u: str) -> None:
        nonlocal monthsf, daysf, msf
        field, mult = _INTERVAL_UNITS[u]
        if field == "months":
            monthsf += qty * mult
        elif field == "days":
            daysf += qty * mult
        else:
            msf += qty * mult

    def num(word: str) -> float:
        try:
            return float(word)
        except ValueError:
            raise ParseError(
                f"bad interval literal {text!r}"
            ) from None

    def add_clock(word: str) -> None:
        nonlocal msf
        segs = word.split(":")
        if len(segs) not in (2, 3) or not segs[0]:
            raise ParseError(f"bad interval literal {text!r}")
        sign = -1 if segs[0].lstrip().startswith("-") else 1
        h = abs(num(segs[0]))
        m = num(segs[1])
        s = num(segs[2]) if len(segs) == 3 else 0.0
        msf += sign * (h * 3_600_000 + m * 60_000 + s * 1_000)

    words = text.strip().split()
    if not words:
        raise ParseError(f"bad interval literal {text!r}")
    if unit is not None:
        if len(words) != 1:
            raise ParseError(f"bad interval literal {text!r}")
        add(num(words[0]), unit)
    else:
        i = 0
        while i < len(words):
            w = words[i]
            if ":" in w:
                add_clock(w)
                i += 1
                continue
            qty = num(w)
            if i + 1 < len(words):
                if ":" in words[i + 1]:
                    # pg day-then-clock shorthand: '1 2:30' = 1 day 02:30
                    daysf += qty
                    i += 1
                    continue
                u = words[i + 1].lower().rstrip("s")
                if u not in _INTERVAL_UNITS:
                    raise ParseError(
                        f"unknown interval unit {words[i + 1]!r}"
                    )
                add(qty, u)
                i += 2
            else:
                msf += qty * 1_000  # bare number: seconds (pg)
                i += 1
    # spill fractional months -> days (30/month), days -> ms
    months = int(monthsf)
    daysf += (monthsf - months) * 30
    days = int(daysf)
    msf += (daysf - days) * 86_400_000
    return ast.IntervalLit(months, days, int(round(msf)))


def parse_statement(sql: str) -> ast.Statement:
    return Parser(sql).parse_statement()


def parse_query(sql: str) -> ast.Query:
    p = Parser(sql)
    q = p.parse_query()
    p.accept_sym(";")
    if p.peek().kind is not TokKind.EOF:
        raise ParseError(f"trailing input at {p.peek().pos}")
    return q
