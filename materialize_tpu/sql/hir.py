"""HIR: high-level relational IR + AST→HIR planning (name resolution).

Analog of the reference's ``sql`` crate planning layer: scope/column
resolution (sql/src/plan/scope.rs), ``plan()`` producing HIR
(sql/src/plan/hir.rs:109). HIR differs from MIR in that joins are binary
with arbitrary ON predicates (incl. outer kinds) and scalar expressions
may contain subqueries (Exists/ScalarSubquery) — lowering.py removes both
(the decorrelation step, sql/src/plan/lowering.rs:188 analog).

v1 scope: uncorrelated subqueries only (correlated ones raise — the
reference's full decorrelation is future work); no outer-level columns in
scalar exprs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..expr.relation import AggregateFunc
from ..expr.scalar import (
    BinaryFunc,
    UnaryFunc,
    VariadicFunc,
)
from ..repr.schema import Column, ColumnType, Schema
from . import ast


class PlanError(ValueError):
    pass


# -- HIR scalar expressions --------------------------------------------------


class HirScalar:
    pass


@dataclass(frozen=True)
class HColumn(HirScalar):
    index: int  # position in the current relation


@dataclass(frozen=True)
class HLiteral(HirScalar):
    value: object  # python scalar; None = NULL
    ctype: ColumnType
    scale: int = 0


@dataclass(frozen=True)
class HMzNow(HirScalar):
    """mz_now(): the current virtual timestamp (temporal filters)."""


@dataclass(frozen=True)
class HCallUnary(HirScalar):
    func: str
    expr: HirScalar


@dataclass(frozen=True)
class HCallBinary(HirScalar):
    func: str
    left: HirScalar
    right: HirScalar


@dataclass(frozen=True)
class HCallVariadic(HirScalar):
    func: str
    exprs: tuple


@dataclass(frozen=True)
class HIf(HirScalar):
    cond: HirScalar
    then: HirScalar
    els: HirScalar


@dataclass(frozen=True)
class HExists(HirScalar):
    rel: "HirRelation"


@dataclass(frozen=True)
class HScalarSubquery(HirScalar):
    rel: "HirRelation"


@dataclass(frozen=True)
class HInSubquery(HirScalar):
    """x IN (SELECT ...): lowered to a semijoin (lowering.py)."""

    expr: HirScalar
    rel: "HirRelation"
    negated: bool


# -- HIR relation expressions ------------------------------------------------


class HirRelation:
    def schema(self) -> Schema:
        raise NotImplementedError


@dataclass(frozen=True)
class HGet(HirRelation):
    name: str
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HConstant(HirRelation):
    rows: tuple
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HProject(HirRelation):
    input: HirRelation
    outputs: tuple

    def schema(self):
        return self.input.schema().project(self.outputs)


@dataclass(frozen=True)
class HMap(HirRelation):
    input: HirRelation
    scalars: tuple  # (HirScalar, Column) — the planner types every expr

    def schema(self):
        return Schema(
            tuple(self.input.schema().columns)
            + tuple(c for _, c in self.scalars)
        )


@dataclass(frozen=True)
class HFilter(HirRelation):
    input: HirRelation
    predicates: tuple

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HJoin(HirRelation):
    """Binary join with an ON predicate; kind in
    inner/left/right/full/cross (hir.rs HirRelationExpr::Join)."""

    left: HirRelation
    right: HirRelation
    on: tuple  # conjunction of HirScalar over concat(left, right) columns
    kind: str

    def schema(self):
        lcols = list(self.left.schema().columns)
        rcols = list(self.right.schema().columns)
        if self.kind in ("left", "full"):
            rcols = [Column(c.name, c.ctype, True, c.scale) for c in rcols]
        if self.kind in ("right", "full"):
            lcols = [Column(c.name, c.ctype, True, c.scale) for c in lcols]
        return Schema(lcols + rcols)


@dataclass(frozen=True)
class HAggregate:
    func: AggregateFunc
    expr: HirScalar
    distinct: bool
    out: Column


@dataclass(frozen=True)
class HReduce(HirRelation):
    input: HirRelation
    group_key: tuple  # column indices
    aggregates: tuple  # HAggregate

    def schema(self):
        in_s = self.input.schema()
        return Schema(
            [in_s[i] for i in self.group_key]
            + [a.out for a in self.aggregates]
        )


@dataclass(frozen=True)
class HDistinct(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HTopK(HirRelation):
    input: HirRelation
    group_key: tuple
    order_by: tuple  # (col, desc, nulls_last)
    limit: Optional[int]
    offset: int

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HNegate(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HThreshold(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HUnion(HirRelation):
    inputs: tuple

    def schema(self):
        return self.inputs[0].schema()


@dataclass(frozen=True)
class HRename(HirRelation):
    """Identity on rows; output columns renamed (alias application)."""

    input: HirRelation
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HLet(HirRelation):
    name: str
    value: HirRelation
    body: HirRelation

    def schema(self):
        return self.body.schema()


@dataclass(frozen=True)
class HLetRec(HirRelation):
    names: tuple
    values: tuple
    value_schemas: tuple
    body: HirRelation
    max_iters: Optional[int]

    def schema(self):
        return self.body.schema()


# -- scopes ------------------------------------------------------------------


@dataclass(frozen=True)
class ScopeItem:
    table: Optional[str]  # alias the column is reachable under
    name: str


@dataclass
class Scope:
    """Column-name resolution for one relation (scope.rs analog)."""

    items: list

    def resolve(self, parts: tuple) -> int:
        if len(parts) == 1:
            hits = [
                i for i, it in enumerate(self.items) if it.name == parts[0]
            ]
        elif len(parts) == 2:
            hits = [
                i
                for i, it in enumerate(self.items)
                if it.table == parts[0] and it.name == parts[1]
            ]
        else:
            raise PlanError(f"too many name parts: {'.'.join(parts)}")
        if not hits:
            raise PlanError(f"unknown column {'.'.join(parts)!r}")
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {'.'.join(parts)!r}")
        return hits[0]

    def concat(self, other: "Scope") -> "Scope":
        return Scope(self.items + other.items)


# -- catalog interface -------------------------------------------------------


class CatalogInterface:
    """What planning needs from the catalog: name -> relation schema."""

    def resolve_item(self, name: str) -> Schema:
        raise NotImplementedError


_TYPE_NAMES = {
    "int": ColumnType.INT64,
    "integer": ColumnType.INT64,
    "bigint": ColumnType.INT64,
    "int4": ColumnType.INT32,
    "int8": ColumnType.INT64,
    "smallint": ColumnType.INT32,
    "double precision": ColumnType.FLOAT64,
    "double": ColumnType.FLOAT64,
    "float": ColumnType.FLOAT64,
    "float8": ColumnType.FLOAT64,
    "real": ColumnType.FLOAT64,
    "bool": ColumnType.BOOL,
    "boolean": ColumnType.BOOL,
    "text": ColumnType.STRING,
    "varchar": ColumnType.STRING,
    "string": ColumnType.STRING,
    "date": ColumnType.DATE,
    "timestamp": ColumnType.TIMESTAMP,
    "numeric": ColumnType.DECIMAL,
    "decimal": ColumnType.DECIMAL,
}


def type_from_name(name: str) -> ColumnType:
    try:
        return _TYPE_NAMES[name]
    except KeyError:
        raise PlanError(f"unknown type {name!r}") from None


def parse_type(name: str) -> tuple:
    """'decimal(12,2)' -> (ColumnType.DECIMAL, 2); the single home of
    type-name parameter parsing (precision is accepted and ignored —
    decimals are scaled int64)."""
    t = name.strip().lower()
    base, args = t, []
    if "(" in t:
        if ")" not in t:
            raise PlanError(f"malformed type name {name!r}")
        base = t[: t.index("(")].strip()
        args = [
            a.strip()
            for a in t[t.index("(") + 1 : t.rindex(")")].split(",")
        ]
    ty = type_from_name(base)
    scale = 0
    if ty is ColumnType.DECIMAL and len(args) > 1:
        try:
            scale = int(args[1])
        except ValueError:
            raise PlanError(f"malformed type name {name!r}") from None
    return ty, scale


# -- typing HIR scalars ------------------------------------------------------

from ..expr import scalar as mscalar


def _to_mir_shape(e: HirScalar):
    """Structural HIR->MIR scalar conversion for TYPING only (subqueries
    unsupported here; lowering replaces them with columns first)."""
    if isinstance(e, HColumn):
        return mscalar.ColumnRef(e.index)
    if isinstance(e, HMzNow):
        return mscalar.MzNow()
    if isinstance(e, HLiteral):
        return mscalar.Literal(e.value, e.ctype, e.scale)
    if isinstance(e, HCallUnary):
        return mscalar.CallUnary(e.func, _to_mir_shape(e.expr))
    if isinstance(e, HCallBinary):
        return mscalar.CallBinary(
            e.func, _to_mir_shape(e.left), _to_mir_shape(e.right)
        )
    if isinstance(e, HCallVariadic):
        return mscalar.CallVariadic(
            e.func, [_to_mir_shape(x) for x in e.exprs]
        )
    if isinstance(e, HIf):
        return mscalar.If(
            _to_mir_shape(e.cond),
            _to_mir_shape(e.then),
            _to_mir_shape(e.els),
        )
    if isinstance(e, (HExists, HScalarSubquery)):
        raise PlanError("subquery not lowered before typing")
    raise NotImplementedError(type(e).__name__)


def typ_of(e: HirScalar, schema: Schema) -> Column:
    if isinstance(e, HScalarSubquery):
        sub = e.rel.schema()
        if sub.arity != 1:
            raise PlanError("scalar subquery must return one column")
        c = sub[0]
        return Column(c.name, c.ctype, True, c.scale)
    if isinstance(e, HExists):
        return Column("exists", ColumnType.BOOL, False)
    return _to_mir_shape(e).typ(schema)
