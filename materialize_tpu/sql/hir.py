"""HIR: high-level relational IR + AST→HIR planning (name resolution).

Analog of the reference's ``sql`` crate planning layer: scope/column
resolution (sql/src/plan/scope.rs), ``plan()`` producing HIR
(sql/src/plan/hir.rs:109). HIR differs from MIR in that joins are binary
with arbitrary ON predicates (incl. outer kinds) and scalar expressions
may contain subqueries (Exists/ScalarSubquery) — lowering.py removes both
(the decorrelation step, sql/src/plan/lowering.rs:188 analog).

v1 scope: uncorrelated subqueries only (correlated ones raise — the
reference's full decorrelation is future work); no outer-level columns in
scalar exprs.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..expr.relation import AggregateFunc
from ..expr.scalar import (
    BinaryFunc,
    UnaryFunc,
    VariadicFunc,
)
from ..repr.schema import Column, ColumnType, Schema
from . import ast


class PlanError(ValueError):
    pass


# -- HIR scalar expressions --------------------------------------------------


class HirScalar:
    pass


@dataclass(frozen=True)
class HColumn(HirScalar):
    index: int  # position in the current relation


@dataclass(frozen=True)
class HOuterColumn(HirScalar):
    """A correlated reference to an enclosing query's relation.

    ``level`` counts query nestings outward (1 = the immediately
    enclosing query); ``index`` is the column position in that query's
    relation; ``column`` carries the resolved type so typing needs no
    outer-schema context. The analog of the reference HIR's leveled
    ``ColumnRef {level, column}`` (sql/src/plan/hir.rs), removed by
    decorrelation in lowering.py."""

    level: int
    index: int
    column: "Column"


@dataclass(frozen=True)
class HLiteral(HirScalar):
    value: object  # python scalar; None = NULL
    ctype: ColumnType
    scale: int = 0


@dataclass(frozen=True)
class HMzNow(HirScalar):
    """mz_now(): the current virtual timestamp (temporal filters)."""


@dataclass(frozen=True)
class HCallUnary(HirScalar):
    func: str
    expr: HirScalar


@dataclass(frozen=True)
class HCallBinary(HirScalar):
    func: str
    left: HirScalar
    right: HirScalar


@dataclass(frozen=True)
class HCallVariadic(HirScalar):
    func: str
    exprs: tuple


@dataclass(frozen=True)
class HIf(HirScalar):
    cond: HirScalar
    then: HirScalar
    els: HirScalar


@dataclass(frozen=True)
class HExists(HirScalar):
    rel: "HirRelation"


@dataclass(frozen=True)
class HScalarSubquery(HirScalar):
    rel: "HirRelation"


@dataclass(frozen=True)
class HInSubquery(HirScalar):
    """x IN (SELECT ...): lowered to a semijoin (lowering.py)."""

    expr: HirScalar
    rel: "HirRelation"
    negated: bool


# -- HIR relation expressions ------------------------------------------------


class HirRelation:
    def schema(self) -> Schema:
        raise NotImplementedError


@dataclass(frozen=True)
class HGet(HirRelation):
    name: str
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HConstant(HirRelation):
    rows: tuple
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HProject(HirRelation):
    input: HirRelation
    outputs: tuple

    def schema(self):
        return self.input.schema().project(self.outputs)


@dataclass(frozen=True)
class HMap(HirRelation):
    input: HirRelation
    scalars: tuple  # (HirScalar, Column) — the planner types every expr

    def schema(self):
        return Schema(
            tuple(self.input.schema().columns)
            + tuple(c for _, c in self.scalars)
        )


@dataclass(frozen=True)
class HFilter(HirRelation):
    input: HirRelation
    predicates: tuple

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HJoin(HirRelation):
    """Binary join with an ON predicate; kind in
    inner/left/right/full/cross (hir.rs HirRelationExpr::Join)."""

    left: HirRelation
    right: HirRelation
    on: tuple  # conjunction of HirScalar over concat(left, right) columns
    kind: str

    def schema(self):
        lcols = list(self.left.schema().columns)
        rcols = list(self.right.schema().columns)
        if self.kind in ("left", "full"):
            rcols = [Column(c.name, c.ctype, True, c.scale) for c in rcols]
        if self.kind in ("right", "full"):
            lcols = [Column(c.name, c.ctype, True, c.scale) for c in lcols]
        return Schema(lcols + rcols)


@dataclass(frozen=True)
class HAggregate:
    func: AggregateFunc
    expr: HirScalar
    distinct: bool
    out: Column
    # Host-side plan parameters (string_agg separator).
    params: tuple = ()


@dataclass(frozen=True)
class HReduce(HirRelation):
    input: HirRelation
    group_key: tuple  # column indices
    aggregates: tuple  # HAggregate

    def schema(self):
        in_s = self.input.schema()
        return Schema(
            [in_s[i] for i in self.group_key]
            + [a.out for a in self.aggregates]
        )


@dataclass(frozen=True)
class HDistinct(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HTopK(HirRelation):
    input: HirRelation
    group_key: tuple
    order_by: tuple  # (col, desc, nulls_last)
    limit: Optional[int]
    offset: int

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HNegate(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HThreshold(HirRelation):
    input: HirRelation

    def schema(self):
        return self.input.schema()


@dataclass(frozen=True)
class HUnion(HirRelation):
    inputs: tuple

    def schema(self):
        return self.inputs[0].schema()


@dataclass(frozen=True)
class HRename(HirRelation):
    """Identity on rows; output columns renamed (alias application)."""

    input: HirRelation
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class HLet(HirRelation):
    name: str
    value: HirRelation
    body: HirRelation

    def schema(self):
        return self.body.schema()


@dataclass(frozen=True)
class HLetRec(HirRelation):
    names: tuple
    values: tuple
    value_schemas: tuple
    body: HirRelation
    max_iters: Optional[int]

    def schema(self):
        return self.body.schema()


# -- scopes ------------------------------------------------------------------


@dataclass(frozen=True)
class ScopeItem:
    table: Optional[str]  # alias the column is reachable under
    name: str
    # JOIN ... USING merges the shared column: the non-preferred side's
    # copy stays addressable by qualified name but is skipped by
    # unqualified lookup and bare `*` (pg join-USING scope semantics).
    hidden: bool = False
    # pg emits USING-merged columns FIRST in unqualified `*` expansion
    # (outermost join's columns first, then USING-clause order). Items
    # with a star_rank sort ascending before unranked items, which keep
    # positional order.
    star_rank: Optional[int] = None


@dataclass
class Scope:
    """Column-name resolution for one relation (scope.rs analog).

    ``columns`` optionally carries the relation's Column types in
    parallel with ``items`` — needed when this scope serves as an OUTER
    scope for a correlated subquery (the resolved type rides on the
    HOuterColumn node)."""

    items: list
    columns: Optional[list] = None

    def maybe_resolve(self, parts: tuple) -> Optional[int]:
        """Index for the name, None if unknown; ambiguity still raises."""
        if len(parts) == 1:
            hits = [
                i for i, it in enumerate(self.items) if it.name == parts[0]
            ]
            visible = [i for i in hits if not self.items[i].hidden]
            if visible:
                hits = visible
        elif len(parts) == 2:
            hits = [
                i
                for i, it in enumerate(self.items)
                if it.table == parts[0] and it.name == parts[1]
            ]
        else:
            raise PlanError(f"too many name parts: {'.'.join(parts)}")
        if not hits:
            return None
        if len(hits) > 1:
            raise PlanError(f"ambiguous column {'.'.join(parts)!r}")
        return hits[0]

    def resolve(self, parts: tuple) -> int:
        idx = self.maybe_resolve(parts)
        if idx is None:
            raise PlanError(f"unknown column {'.'.join(parts)!r}")
        return idx

    def concat(self, other: "Scope") -> "Scope":
        cols = None
        if self.columns is not None and other.columns is not None:
            cols = self.columns + other.columns
        return Scope(self.items + other.items, cols)


# -- catalog interface -------------------------------------------------------


class CatalogInterface:
    """What planning needs from the catalog: name -> relation schema."""

    def resolve_item(self, name: str) -> Schema:
        raise NotImplementedError


_TYPE_NAMES = {
    "int": ColumnType.INT64,
    "integer": ColumnType.INT64,
    "bigint": ColumnType.INT64,
    "int4": ColumnType.INT32,
    "int8": ColumnType.INT64,
    "smallint": ColumnType.INT32,
    "double precision": ColumnType.FLOAT64,
    "double": ColumnType.FLOAT64,
    "float": ColumnType.FLOAT64,
    "float8": ColumnType.FLOAT64,
    "real": ColumnType.FLOAT64,
    "bool": ColumnType.BOOL,
    "boolean": ColumnType.BOOL,
    "text": ColumnType.STRING,
    "varchar": ColumnType.STRING,
    "string": ColumnType.STRING,
    "date": ColumnType.DATE,
    "timestamp": ColumnType.TIMESTAMP,
    "numeric": ColumnType.DECIMAL,
    "decimal": ColumnType.DECIMAL,
}


def type_from_name(name: str) -> ColumnType:
    try:
        return _TYPE_NAMES[name]
    except KeyError:
        raise PlanError(f"unknown type {name!r}") from None


def parse_type(name: str) -> tuple:
    """'decimal(12,2)' -> (ColumnType.DECIMAL, 2); the single home of
    type-name parameter parsing (precision is accepted and ignored —
    decimals are scaled int64)."""
    t = name.strip().lower()
    base, args = t, []
    if "(" in t:
        if ")" not in t:
            raise PlanError(f"malformed type name {name!r}")
        base = t[: t.index("(")].strip()
        args = [
            a.strip()
            for a in t[t.index("(") + 1 : t.rindex(")")].split(",")
        ]
    ty = type_from_name(base)
    scale = 0
    if ty is ColumnType.DECIMAL and len(args) > 1:
        try:
            scale = int(args[1])
        except ValueError:
            raise PlanError(f"malformed type name {name!r}") from None
    return ty, scale


# -- typing HIR scalars ------------------------------------------------------

from ..expr import scalar as mscalar


def _to_mir_shape(e: HirScalar):
    """Structural HIR->MIR scalar conversion for TYPING only (subqueries
    unsupported here; lowering replaces them with columns first)."""
    if isinstance(e, HColumn):
        return mscalar.ColumnRef(e.index)
    if isinstance(e, HMzNow):
        return mscalar.MzNow()
    if isinstance(e, HLiteral):
        return mscalar.Literal(e.value, e.ctype, e.scale)
    if isinstance(e, HCallUnary):
        return mscalar.CallUnary(e.func, _to_mir_shape(e.expr))
    if isinstance(e, HCallBinary):
        return mscalar.CallBinary(
            e.func, _to_mir_shape(e.left), _to_mir_shape(e.right)
        )
    if isinstance(e, HCallVariadic):
        return mscalar.CallVariadic(
            e.func, [_to_mir_shape(x) for x in e.exprs]
        )
    if isinstance(e, HIf):
        return mscalar.If(
            _to_mir_shape(e.cond),
            _to_mir_shape(e.then),
            _to_mir_shape(e.els),
        )
    if isinstance(e, (HExists, HScalarSubquery)):
        raise PlanError("subquery not lowered before typing")
    if isinstance(e, HOuterColumn):
        raise PlanError(
            "correlated reference not decorrelated before MIR conversion"
        )
    raise NotImplementedError(type(e).__name__)


def _strip_outer_for_typing(e: HirScalar) -> HirScalar:
    """Replace correlated references with typed NULL placeholders so the
    expression can be typed against the inner schema alone (nullability
    is pessimistic: an outer reference types as nullable)."""
    if isinstance(e, HOuterColumn):
        return HLiteral(None, e.column.ctype, e.column.scale)
    if isinstance(e, HCallUnary):
        return HCallUnary(e.func, _strip_outer_for_typing(e.expr))
    if isinstance(e, HCallBinary):
        return HCallBinary(
            e.func,
            _strip_outer_for_typing(e.left),
            _strip_outer_for_typing(e.right),
        )
    if isinstance(e, HCallVariadic):
        return HCallVariadic(
            e.func, tuple(_strip_outer_for_typing(x) for x in e.exprs)
        )
    if isinstance(e, HIf):
        return HIf(
            _strip_outer_for_typing(e.cond),
            _strip_outer_for_typing(e.then),
            _strip_outer_for_typing(e.els),
        )
    return e


def typ_of(e: HirScalar, schema: Schema) -> Column:
    if isinstance(e, HScalarSubquery):
        sub = e.rel.schema()
        if sub.arity != 1:
            raise PlanError("scalar subquery must return one column")
        c = sub[0]
        return Column(c.name, c.ctype, True, c.scale)
    if isinstance(e, HExists):
        return Column("exists", ColumnType.BOOL, False)
    if isinstance(e, HOuterColumn):
        c = e.column
        return Column(c.name, c.ctype, c.nullable, c.scale)
    return _to_mir_shape(_strip_outer_for_typing(e)).typ(schema)


# -- correlation analysis -----------------------------------------------------


def scalar_subqueries(e: HirScalar):
    """The subquery-bearing nodes directly inside a scalar."""
    if isinstance(e, (HExists, HScalarSubquery, HInSubquery)):
        yield e
    elif isinstance(e, HCallUnary):
        yield from scalar_subqueries(e.expr)
    elif isinstance(e, HCallBinary):
        yield from scalar_subqueries(e.left)
        yield from scalar_subqueries(e.right)
    elif isinstance(e, HCallVariadic):
        for x in e.exprs:
            yield from scalar_subqueries(x)
    elif isinstance(e, HIf):
        yield from scalar_subqueries(e.cond)
        yield from scalar_subqueries(e.then)
        yield from scalar_subqueries(e.els)
    if isinstance(e, HInSubquery):
        yield from scalar_subqueries(e.expr)


def _relation_scalars(rel: HirRelation):
    if isinstance(rel, HMap):
        return [s for s, _ in rel.scalars]
    if isinstance(rel, HFilter):
        return list(rel.predicates)
    if isinstance(rel, HJoin):
        return list(rel.on)
    if isinstance(rel, HReduce):
        return [a.expr for a in rel.aggregates]
    return []


def _relation_children(rel: HirRelation):
    if isinstance(rel, (HProject, HMap, HFilter, HReduce, HDistinct,
                        HTopK, HNegate, HThreshold, HRename)):
        return [rel.input]
    if isinstance(rel, HJoin):
        return [rel.left, rel.right]
    if isinstance(rel, HUnion):
        return list(rel.inputs)
    if isinstance(rel, HLet):
        return [rel.value, rel.body]
    if isinstance(rel, HLetRec):
        return list(rel.values) + [rel.body]
    return []


# Identity-keyed memo: HIR nodes are immutable (frozen dataclasses), and
# decorrelation calls free_outer_refs/is_correlated at every _apply
# recursion level — without memoization lowering is O(n^2) in subquery
# size. The cache entry keeps a strong reference to the node so an id()
# can never be reused while its entry is live.
_FREE_CACHE: dict = {}


def _scalar_free(e: HirScalar) -> frozenset:
    """Free (level, index, Column) refs of one scalar, relative to the
    relation it is evaluated over."""
    if isinstance(e, HOuterColumn):
        return frozenset({(e.level, e.index, e.column)})
    if isinstance(e, (HExists, HScalarSubquery, HInSubquery)):
        # Refs inside the subquery: level 1 refers to OUR relation (not
        # free here), deeper levels shift down by one.
        out = {
            (lvl - 1, idx, col)
            for lvl, idx, col in free_outer_refs(e.rel)
            if lvl >= 2
        }
        if isinstance(e, HInSubquery):
            out |= _scalar_free(e.expr)
        return frozenset(out)
    if isinstance(e, HCallUnary):
        return _scalar_free(e.expr)
    if isinstance(e, HCallBinary):
        return _scalar_free(e.left) | _scalar_free(e.right)
    if isinstance(e, HCallVariadic):
        out: frozenset = frozenset()
        for x in e.exprs:
            out |= _scalar_free(x)
        return out
    if isinstance(e, HIf):
        return (
            _scalar_free(e.cond)
            | _scalar_free(e.then)
            | _scalar_free(e.els)
        )
    return frozenset()


def free_outer_refs(rel: HirRelation) -> frozenset:
    """(level, index, Column) triples of correlated references escaping
    ``rel``, with level counted relative to rel's immediately enclosing
    query (level 1 = that query's relation)."""
    hit = _FREE_CACHE.get(id(rel))
    if hit is not None and hit[0] is rel:
        return hit[1]
    out: frozenset = frozenset()
    for s in _relation_scalars(rel):
        out |= _scalar_free(s)
    for c in _relation_children(rel):
        out |= free_outer_refs(c)
    _FREE_CACHE[id(rel)] = (rel, out)
    return out


def is_correlated(rel: HirRelation) -> bool:
    return bool(free_outer_refs(rel))
