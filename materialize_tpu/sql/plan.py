"""Statement planning: AST → Plan (the adapter's unit of sequencing).

Analog of the reference's ``plan()`` dispatch (sql/src/plan/statement.rs:288)
producing per-statement ``Plan`` variants (sql/src/plan.rs:133), and the
EXPLAIN stage pipeline (EXPLAIN RAW|DECORRELATED|OPTIMIZED|PHYSICAL PLAN,
sql-parser statement.rs ExplainStage).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Optional

from ..expr import relation as mir
from ..repr.schema import ColumnType, Schema
from . import ast
from .hir import CatalogInterface, HirRelation, PlanError
from .lowering import lower
from .parser import parse_statement
from .plan_query import QueryPlanner


class Plan:
    pass


@dataclass
class SelectPlan(Plan):
    expr: mir.RelationExpr
    column_names: tuple
    # RowSetFinishing ordering: (col_idx, desc, nulls_last) triples,
    # applied adapter-side to peek results (coord/peek.rs:910 analog).
    order_by: tuple = ()
    # host-side LIMIT/OFFSET finishing: used when a top-level TopK
    # orders by a text column (device TopK cannot key on string ranks)
    limit: object = None
    offset: int = 0
    # COPY (query) TO STDOUT: stream the result over the COPY-out
    # subprotocol instead of DataRows
    copy_out: bool = False
    # SELECT ... AS OF <t>: read at an explicit timestamp inside the
    # multiversion window (read_policy.rs lag analog); None = latest
    as_of: Optional[int] = None


@dataclass
class CreateViewPlan(Plan):
    name: str
    expr: mir.RelationExpr
    column_names: tuple
    materialized: bool
    or_replace: bool


@dataclass
class CreateIndexPlan(Plan):
    name: str
    on: str


@dataclass
class CreateSourcePlan(Plan):
    name: str
    generator: str
    options: dict
    # declared Schema for external-format sources (kafka); None for
    # generators whose schemas are intrinsic
    schema: object = None


@dataclass
class CreateSinkPlan(Plan):
    name: str
    from_obj: str
    options: dict


@dataclass
class DropPlan(Plan):
    kind: str
    name: str
    if_exists: bool


@dataclass
class CreateTablePlan(Plan):
    name: str
    schema: Schema


@dataclass
class CreateWebhookPlan(Plan):
    name: str
    schema: Schema


@dataclass
class InsertPlan(Plan):
    table: str
    rows: list  # python value tuples, coerced to the table schema


@dataclass
class CopyFromPlan(Plan):
    """COPY table FROM STDIN: the wire layer drives row collection and
    hands text rows back to the coordinator (pgwire COPY-in;
    reference protocol.rs COPY subprotocol)."""

    table: str
    columns: tuple  # optional column-name subset (empty = all)


@dataclass
class DeletePlan(Plan):
    """Read-then-write: the expr selects the rows to retract."""

    table: str
    expr: mir.RelationExpr


@dataclass
class UpdatePlan(Plan):
    """Read-then-write: expr = SELECT *, new_values... FROM t WHERE p;
    set_positions maps target column index -> appended column index."""

    table: str
    expr: mir.RelationExpr
    set_positions: dict
    expr_schema: Schema


@dataclass
class SetVarPlan(Plan):
    name: str
    value: object  # None = RESET to default


@dataclass
class ShowVarPlan(Plan):
    name: str


@dataclass
class SubscribePlan(Plan):
    expr: mir.RelationExpr
    column_names: tuple
    as_of: Optional[int] = None


@dataclass
class ExplainPlan(Plan):
    stage: str
    text: str


@dataclass
class ShowPlan(Plan):
    kind: str


def plan_statement(sql_or_stmt, catalog: CatalogInterface) -> Plan:
    stmt = (
        parse_statement(sql_or_stmt)
        if isinstance(sql_or_stmt, str)
        else sql_or_stmt
    )
    return _plan(stmt, catalog)


def _plan(stmt: ast.Statement, catalog: CatalogInterface) -> Plan:
    qp = QueryPlanner(catalog)
    if isinstance(stmt, ast.SelectStatement):
        hir_rel, scope = qp.plan_query(stmt.query)
        m = lower(hir_rel)
        plan = SelectPlan(
            m,
            tuple(it.name for it in scope.items),
            getattr(qp, "finishing_order", ()),
        )
        plan.as_of = stmt.as_of
        # A top-level LIMIT ordered by text cannot run as a device TopK
        # (string ranks shift as the dictionary grows; ops/topk.py):
        # strip it and finish host-side with the peek's RowSetFinishing.
        if (
            isinstance(m, mir.TopK)
            and m.group_key == ()
            and any(
                m.input.schema()[i].ctype is ColumnType.STRING
                for i, _, _ in m.order_by
            )
        ):
            plan.expr = m.input
            plan.limit = m.limit
            plan.offset = m.offset
        return plan
    if isinstance(stmt, ast.CreateView):
        hir_rel, scope = qp.plan_query(stmt.query)
        return CreateViewPlan(
            stmt.name,
            lower(hir_rel),
            tuple(it.name for it in scope.items),
            stmt.materialized,
            stmt.or_replace,
        )
    if isinstance(stmt, ast.CreateIndex):
        return CreateIndexPlan(
            stmt.name or f"{stmt.on}_primary_idx", stmt.on
        )
    if isinstance(stmt, ast.CreateSource):
        return CreateSourcePlan(
            stmt.name,
            stmt.generator,
            stmt.options,
            _table_schema(stmt.columns) if stmt.columns else None,
        )
    if isinstance(stmt, ast.CreateSink):
        return CreateSinkPlan(stmt.name, stmt.from_obj, stmt.options)
    if isinstance(stmt, ast.DropObject):
        return DropPlan(stmt.kind, stmt.name, stmt.if_exists)
    if isinstance(stmt, ast.CreateTable):
        return CreateTablePlan(stmt.name, _table_schema(stmt.columns))
    if isinstance(stmt, ast.CreateWebhook):
        return CreateWebhookPlan(stmt.name, _table_schema(stmt.columns))
    if isinstance(stmt, ast.Insert):
        return _plan_insert(stmt, catalog)
    if isinstance(stmt, ast.CopyFrom):
        return CopyFromPlan(stmt.table, stmt.columns)
    if isinstance(stmt, ast.CopyTo):
        hir_rel, scope = qp.plan_query(stmt.query)
        plan = SelectPlan(
            lower(hir_rel),
            tuple(it.name for it in scope.items),
            getattr(qp, "finishing_order", ()),
        )
        plan.copy_out = True
        return plan
    if isinstance(stmt, ast.Delete):
        hir_rel, _ = qp.plan_query(_table_query(stmt.table, stmt.where))
        return DeletePlan(stmt.table, lower(hir_rel))
    if isinstance(stmt, ast.Update):
        return _plan_update(stmt, catalog, qp)
    if isinstance(stmt, ast.SetVar):
        return SetVarPlan(stmt.name, stmt.value)
    if isinstance(stmt, ast.ShowVar):
        return ShowVarPlan(stmt.name)
    if isinstance(stmt, ast.Subscribe):
        hir_rel, scope = qp.plan_query(stmt.query)
        return SubscribePlan(
            lower(hir_rel),
            tuple(it.name for it in scope.items),
            stmt.as_of,
        )
    if isinstance(stmt, ast.Explain):
        return _explain(stmt, catalog)
    if isinstance(stmt, ast.ShowObjects):
        return ShowPlan(stmt.kind)
    raise PlanError(f"cannot plan {type(stmt).__name__}")


def _table_schema(columns) -> Schema:
    """CREATE TABLE column list -> Schema (type parsing: hir.parse_type)."""
    from ..repr.schema import Column
    from .hir import parse_type

    cols = []
    for name, type_name, nullable in columns:
        ty, scale = parse_type(type_name)
        cols.append(Column(name, ty, nullable, scale))
    return Schema(cols)


def _eval_insert_value(e: ast.Expr, col):
    """INSERT literal coerced to the target column: string literals
    parse per the column type (DATE '1994-01-01', decimal text), and
    CAST(lit AS type) / typed literals evaluate at plan time — the same
    coercions the COPY text path applies (parse_text_value)."""
    from ..repr.schema import Column, ColumnType, parse_text_value
    from .hir import parse_type

    if isinstance(e, ast.Cast):
        ty, scale = parse_type(e.to_type)
        v = _eval_insert_value(
            e.expr, Column(col.name, ty, True, scale)
        )
        if v is None:
            return None
        # Re-coerce into the DESTINATION column when the cast type
        # differs: a text-valued cast result parses per the column
        # (CAST('1994-01-01' AS text) into a date column).
        if ty != col.ctype and isinstance(v, str):
            return parse_text_value(v, col)
        return v
    if isinstance(e, ast.StringLit) and col.ctype is not ColumnType.STRING:
        return parse_text_value(e.value, col)
    return _eval_literal(e)


def _eval_literal(e: ast.Expr):
    if isinstance(e, ast.NumberLit):
        return float(e.text) if "." in e.text or "e" in e.text.lower() \
            else int(e.text)
    if isinstance(e, ast.StringLit):
        return e.value
    if isinstance(e, ast.BoolLit):
        return e.value
    if isinstance(e, ast.NullLit):
        return None
    if isinstance(e, ast.UnaryOp) and e.op == "-":
        v = _eval_literal(e.expr)
        return -v if v is not None else None
    raise PlanError(
        f"INSERT values must be constants, got {type(e).__name__}"
    )


def _table_query(
    table: str, where, extra_items: tuple = ()
) -> ast.Query:
    """Build `SELECT *, extra... FROM table WHERE ...` programmatically
    (read-then-write DML plans over the ordinary query planner)."""
    items = (ast.SelectItem(ast.Star(None)),) + tuple(
        ast.SelectItem(e) for e in extra_items
    )
    return ast.Query(
        body=ast.SelectExpr(
            ast.Select(
                items=items,
                from_=(ast.FromItem(ast.TableName(table)),),
                where=where,
            )
        )
    )


def _plan_update(
    stmt: ast.Update, catalog: CatalogInterface, qp
) -> Plan:
    schema = catalog.resolve_item(stmt.table)
    names = list(schema.names)
    set_positions = {}
    exprs = []
    for j, (col, e) in enumerate(stmt.assignments):
        if col not in names:
            raise PlanError(
                f"unknown column {col!r} in table {stmt.table!r}"
            )
        if names.index(col) in set_positions:
            raise PlanError(f"column {col!r} assigned more than once")
        set_positions[names.index(col)] = schema.arity + j
        exprs.append(e)
    hir_rel, _ = qp.plan_query(
        _table_query(stmt.table, stmt.where, tuple(exprs))
    )
    expr = lower(hir_rel)
    return UpdatePlan(stmt.table, expr, set_positions, expr.schema())


def _plan_insert(stmt: ast.Insert, catalog: CatalogInterface) -> Plan:
    schema = catalog.resolve_item(stmt.table)
    names = list(schema.names)
    if stmt.columns:
        if len(set(stmt.columns)) != len(stmt.columns):
            raise PlanError(
                f"column specified more than once in INSERT: "
                f"{list(stmt.columns)}"
            )
        order = []
        for c in stmt.columns:
            if c not in names:
                raise PlanError(
                    f"unknown column {c!r} in table {stmt.table!r}"
                )
            order.append(names.index(c))
    else:
        order = list(range(len(names)))
    rows = []
    for r in stmt.rows:
        if len(r) != len(order):
            raise PlanError(
                f"INSERT row has {len(r)} values, expected {len(order)}"
            )
        full = [None] * len(names)
        for slot, e in zip(order, r):
            full[slot] = _eval_insert_value(e, schema.columns[slot])
        for i, col in enumerate(schema.columns):
            if full[i] is None and not col.nullable:
                raise PlanError(
                    f"null value in non-nullable column {col.name!r}"
                )
        rows.append(tuple(full))
    return InsertPlan(stmt.table, rows)


def _defn_has_basic_aggs(expr, catalog, _seen=None) -> bool:
    """Does this definition contain a basic (collection) aggregate,
    resolving Get(view) transitively? Mirror of the coordinator's
    _has_basic_aggs, local to keep sql free of coord imports."""
    if isinstance(expr, mir.Reduce) and any(
        a.func.is_basic for a in expr.aggregates
    ):
        return True
    if isinstance(expr, mir.Get):
        seen = _seen or set()
        if expr.name in seen:
            return False
        it = getattr(catalog, "items", {}).get(expr.name)
        if it is not None and it.kind == "view":
            return _defn_has_basic_aggs(
                it.definition, catalog, seen | {expr.name}
            )
        return False
    return any(
        _defn_has_basic_aggs(c, catalog, _seen)
        for c in expr.children()
    )


def _explain(stmt: ast.Explain, catalog: CatalogInterface) -> Plan:
    inner = stmt.statement
    if isinstance(inner, ast.SelectStatement):
        query = inner.query
    elif isinstance(inner, ast.CreateView):
        query = inner.query
    else:
        raise PlanError("EXPLAIN supports queries and views")
    if stmt.stage == "raw":
        return ExplainPlan("raw", _fmt(query))
    qp = QueryPlanner(catalog)
    hir_rel, _ = qp.plan_query(query)
    if stmt.stage == "decorrelated":
        return ExplainPlan("decorrelated", explain_mir(lower(hir_rel)))
    m = lower(hir_rel)
    if stmt.stage in ("optimized", "physical", "analysis"):
        from ..transform.optimizer import optimize

        m = optimize(m)
    if stmt.stage == "analysis":
        # Static-analysis verdicts over the optimized plan: typecheck,
        # monotonicity facts, LIR plan-decision consistency
        # (materialize_tpu/analysis — doc/analysis.md catalogue), plus
        # the peek fast-path decision (plan/decisions.peek_fast_path —
        # the same recognizer the coordinator serves with).
        from ..analysis import report
        from ..plan.decisions import peek_fast_path
        from ..utils.dyncfg import COMPUTE_CONFIGS, PEEK_FAST_PATH

        peekable = set()
        basic_names = set()
        for it in getattr(catalog, "items", {}).values():
            if it.kind == "materialized-view":
                peekable.add(it.name)
                d = it.definition
                expr = (
                    d.get("expr") if isinstance(d, dict) else None
                )
                if expr is not None and _defn_has_basic_aggs(
                    expr, catalog
                ):
                    basic_names.add(it.name)
            elif it.kind == "index" and isinstance(it.definition, dict):
                on = it.definition.get("on")
                if on is not None:
                    peekable.add(on)
                    on_it = getattr(catalog, "items", {}).get(on)
                    if (
                        on_it is not None
                        and on_it.kind == "view"
                        and _defn_has_basic_aggs(
                            on_it.definition, catalog
                        )
                    ):
                        # The coordinator always INLINES basic-agg
                        # views (even indexed ones) — they serve slow.
                        basic_names.add(on)
        dec = (
            peek_fast_path(m, frozenset(peekable))
            if PEEK_FAST_PATH(COMPUTE_CONFIGS)
            else None
        )
        if dec is not None and dec.name in basic_names:
            # The coordinator disqualifies basic-aggregate outputs
            # (their maintained columns are digests finalized only at
            # the serving edge) — print what actually serves.
            dec = None
        text = report(m) + "\npeek: " + (
            dec.describe()
            if dec is not None
            else "slow path (transient dataflow render)"
        )
        return ExplainPlan("analysis", text)
    if stmt.stage == "physical":
        # LIR: the operator-level physical plans (ReducePlan/TopKPlan/
        # JoinPlan) actually chosen by the render layer — lowered by the
        # shared decision functions (materialize_tpu/plan/decisions.py).
        from ..plan import explain_lir, lower_mir

        return ExplainPlan("physical", explain_lir(lower_mir(m)))
    return ExplainPlan(stmt.stage, explain_mir(m))


def _fmt(node, indent: int = 0) -> str:
    pad = "  " * indent
    name = type(node).__name__
    return f"{pad}{name}"


def explain_mir(expr: mir.RelationExpr, indent: int = 0) -> str:
    """Readable MIR tree, one operator per line (EXPLAIN output;
    reference sql-pretty / explain API)."""
    pad = "  " * indent
    name = type(expr).__name__
    detail = ""
    if isinstance(expr, mir.Get):
        detail = f" {expr.name}"
    elif isinstance(expr, mir.Project):
        detail = f" outputs={list(expr.outputs)}"
    elif isinstance(expr, mir.Filter):
        detail = f" predicates={len(expr.predicates)}"
    elif isinstance(expr, mir.Map):
        detail = f" scalars={len(expr.scalars)}"
    elif isinstance(expr, mir.Join):
        detail = (
            f" implementation={expr.implementation}"
            f" equivalences={len(expr.equivalences)}"
        )
    elif isinstance(expr, mir.Reduce):
        detail = (
            f" group_key={list(expr.group_key)}"
            f" aggregates={[a.func.value for a in expr.aggregates]}"
        )
    elif isinstance(expr, mir.TopK):
        detail = f" group_key={list(expr.group_key)} limit={expr.limit}"
    elif isinstance(expr, mir.LetRec):
        detail = f" bindings={list(expr.names)}"
    elif isinstance(expr, mir.Let):
        detail = f" name={expr.name}"
    lines = [f"{pad}{name}{detail}"]
    for c in expr.children():
        lines.append(explain_mir(c, indent + 1))
    return "\n".join(lines)
