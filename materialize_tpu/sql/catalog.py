"""Planning catalog: name -> relation schema resolution.

The in-memory side of the reference's ``Catalog``
(adapter/src/catalog.rs:139; memory layer catalog/src/memory). The
coordinator owns the authoritative catalog (coord/); this interface is
what SQL planning needs (sql/src/names.rs resolution analog).
"""

from __future__ import annotations

from dataclasses import dataclass, field

from ..repr.schema import Schema
from .hir import CatalogInterface, PlanError


@dataclass
class CatalogItem:
    name: str
    kind: str  # source | view | materialized-view | index
    schema: Schema
    # views keep their definition for EXPLAIN / dependency rebuilds
    definition: object | None = None
    column_names: tuple = ()


class Catalog(CatalogInterface):
    """In-memory catalog of named relations."""

    def __init__(self):
        self.items: dict[str, CatalogItem] = {}

    def create(self, item: CatalogItem, or_replace: bool = False) -> None:
        if item.name in self.items and not or_replace:
            raise PlanError(f"catalog item {item.name!r} already exists")
        self.items[item.name] = item

    def drop(self, name: str, if_exists: bool = False) -> None:
        if name not in self.items:
            if if_exists:
                return
            raise PlanError(f"unknown catalog item {name!r}")
        del self.items[name]

    def resolve_item(self, name: str) -> Schema:
        it = self.items.get(name)
        if it is None:
            raise PlanError(f"unknown relation {name!r}")
        return it.schema

    def get(self, name: str) -> CatalogItem:
        return self.items[name]
