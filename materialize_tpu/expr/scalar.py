"""Scalar expressions (MIR) and their XLA evaluation.

Analog of the reference's ``MirScalarExpr``
(src/expr/src/scalar.rs:69: Column / Literal / CallUnary / CallBinary /
CallVariadic / If) and its scalar function library
(src/expr/src/scalar/func.rs). Where the reference interprets expressions
row-at-a-time over ``Datum``s, here evaluation happens at *trace time*:
``eval_expr`` recursively builds a fused XLA computation over whole columns
— the "MirScalarExpr JIT-compiled to XLA" of the north star
(BASELINE.json). SQL NULL semantics are carried as an optional bool mask
per intermediate (three-valued logic).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from ..repr.batch import Batch
from ..repr.schema import Column, ColumnType, Schema

# ---------------------------------------------------------------------------
# Expression tree


class ScalarExpr:
    """Base class. Subclasses are immutable dataclasses."""

    def typ(self, schema: Schema) -> Column:
        raise NotImplementedError

    # convenience builders
    def __add__(self, other):
        return CallBinary(BinaryFunc.ADD, self, _lift(other))

    def __sub__(self, other):
        return CallBinary(BinaryFunc.SUB, self, _lift(other))

    def __mul__(self, other):
        return CallBinary(BinaryFunc.MUL, self, _lift(other))

    def eq(self, other):
        return CallBinary(BinaryFunc.EQ, self, _lift(other))

    def lt(self, other):
        return CallBinary(BinaryFunc.LT, self, _lift(other))

    def lte(self, other):
        return CallBinary(BinaryFunc.LTE, self, _lift(other))

    def gt(self, other):
        return CallBinary(BinaryFunc.GT, self, _lift(other))

    def gte(self, other):
        return CallBinary(BinaryFunc.GTE, self, _lift(other))


def _lift(x) -> "ScalarExpr":
    if isinstance(x, ScalarExpr):
        return x
    if isinstance(x, bool):
        return Literal(x, ColumnType.BOOL)
    if isinstance(x, int):
        return Literal(x, ColumnType.INT64)
    if isinstance(x, float):
        return Literal(x, ColumnType.FLOAT64)
    raise TypeError(x)


@dataclass(frozen=True)
class MzNow(ScalarExpr):
    """The current virtual timestamp: CallUnmaterializable::MzNow
    (expr/src/scalar.rs). Evaluates to the step's time; predicates over
    it become TEMPORAL FILTERS (expr/src/linear.rs:404-408) that
    schedule future retractions/insertions."""

    def typ(self, schema: Schema) -> Column:
        return Column("mz_now", ColumnType.TIMESTAMP)


def contains_mz_now(expr: ScalarExpr) -> bool:
    if isinstance(expr, MzNow):
        return True
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, ScalarExpr) and contains_mz_now(v):
            return True
        if isinstance(v, tuple) and any(
            isinstance(x, ScalarExpr) and contains_mz_now(x) for x in v
        ):
            return True
    return False


@dataclass(frozen=True)
class ColumnRef(ScalarExpr):
    """Column reference by position (like MirScalarExpr::Column)."""

    index: int

    def typ(self, schema):
        return schema[self.index]


@dataclass(frozen=True)
class Literal(ScalarExpr):
    value: Any  # python scalar; None = NULL
    ctype: ColumnType
    scale: int = 0

    def typ(self, schema):
        return Column("literal", self.ctype, self.value is None, self.scale)


class UnaryFunc:
    NOT = "not"
    NEG = "neg"
    IS_NULL = "is_null"
    ABS = "abs"
    # cast family
    CAST_INT64 = "cast_int64"
    CAST_FLOAT64 = "cast_float64"
    # date parts (DATE = days since epoch)
    EXTRACT_YEAR = "extract_year"
    EXTRACT_MONTH = "extract_month"
    EXTRACT_DAY = "extract_day"
    EXTRACT_QUARTER = "extract_quarter"


class BinaryFunc:
    ADD = "add"
    SUB = "sub"
    MUL = "mul"
    DIV = "div"
    MOD = "mod"
    EQ = "eq"
    NEQ = "neq"
    LT = "lt"
    LTE = "lte"
    GT = "gt"
    GTE = "gte"


class VariadicFunc:
    AND = "and"
    OR = "or"
    COALESCE = "coalesce"


@dataclass(frozen=True)
class CallUnary(ScalarExpr):
    func: str
    expr: ScalarExpr

    def typ(self, schema):
        inner = self.expr.typ(schema)
        if self.func in (UnaryFunc.NOT,):
            return Column("f", ColumnType.BOOL, inner.nullable)
        if self.func == UnaryFunc.IS_NULL:
            return Column("f", ColumnType.BOOL, False)
        if self.func == UnaryFunc.CAST_INT64:
            return Column("f", ColumnType.INT64, inner.nullable)
        if self.func == UnaryFunc.CAST_FLOAT64:
            return Column("f", ColumnType.FLOAT64, inner.nullable)
        if self.func in (
            UnaryFunc.EXTRACT_YEAR,
            UnaryFunc.EXTRACT_MONTH,
            UnaryFunc.EXTRACT_DAY,
            UnaryFunc.EXTRACT_QUARTER,
        ):
            return Column("f", ColumnType.INT64, inner.nullable)
        return inner  # NEG, ABS preserve type


@dataclass(frozen=True)
class CallBinary(ScalarExpr):
    func: str
    left: ScalarExpr
    right: ScalarExpr

    def typ(self, schema):
        lt_, rt = self.left.typ(schema), self.right.typ(schema)
        nullable = lt_.nullable or rt.nullable
        if self.func in (
            BinaryFunc.EQ,
            BinaryFunc.NEQ,
            BinaryFunc.LT,
            BinaryFunc.LTE,
            BinaryFunc.GT,
            BinaryFunc.GTE,
        ):
            return Column("f", ColumnType.BOOL, nullable)
        if self.func == BinaryFunc.DIV:
            # SQL: division may produce NULL (div by zero -> error in MZ;
            # we produce NULL for now) and floats for non-decimals.
            if lt_.ctype is ColumnType.DECIMAL:
                return Column("f", ColumnType.DECIMAL, True, lt_.scale)
            return Column("f", ColumnType.FLOAT64, True)
        # arithmetic: unify types
        ctype, scale = _unify_arith(lt_, rt, self.func)
        return Column("f", ctype, nullable, scale)


@dataclass(frozen=True)
class CallVariadic(ScalarExpr):
    func: str
    exprs: tuple

    def __init__(self, func, exprs):
        object.__setattr__(self, "func", func)
        object.__setattr__(self, "exprs", tuple(exprs))

    def typ(self, schema):
        if self.func in (VariadicFunc.AND, VariadicFunc.OR):
            nullable = any(e.typ(schema).nullable for e in self.exprs)
            return Column("f", ColumnType.BOOL, nullable)
        if self.func == VariadicFunc.COALESCE:
            first = self.exprs[0].typ(schema)
            nullable = all(e.typ(schema).nullable for e in self.exprs)
            return Column("f", first.ctype, nullable, first.scale)
        raise NotImplementedError(self.func)


@dataclass(frozen=True)
class If(ScalarExpr):
    cond: ScalarExpr
    then: ScalarExpr
    els: ScalarExpr

    def typ(self, schema):
        t = self.then.typ(schema)
        e = self.els.typ(schema)
        return Column("f", t.ctype, t.nullable or e.nullable, t.scale)


def _unify_arith(lt_: Column, rt: Column, func: str) -> tuple[ColumnType, int]:
    a, b = lt_.ctype, rt.ctype
    if ColumnType.FLOAT64 in (a, b):
        return ColumnType.FLOAT64, 0
    if a is ColumnType.DECIMAL or b is ColumnType.DECIMAL:
        if func == BinaryFunc.MUL:
            return ColumnType.DECIMAL, lt_.scale + rt.scale
        scale = max(lt_.scale, rt.scale)
        return ColumnType.DECIMAL, scale
    if a is ColumnType.DATE and b in (ColumnType.INT32, ColumnType.INT64):
        return ColumnType.DATE, 0
    if ColumnType.INT64 in (a, b):
        return ColumnType.INT64, 0
    return a, 0


# ---------------------------------------------------------------------------
# Evaluation: trace-time construction of fused XLA ops


@dataclass
class Evaled:
    """An evaluated intermediate: column values + optional null mask."""

    values: jnp.ndarray
    nulls: jnp.ndarray | None
    col: Column  # type info

    def null_mask(self) -> jnp.ndarray:
        if self.nulls is None:
            return jnp.zeros(self.values.shape, dtype=bool)
        return self.nulls


def _to_decimal_scale(e: Evaled, scale: int) -> jnp.ndarray:
    """Rescale a decimal (or int) value array to the given decimal scale."""
    if e.col.ctype is ColumnType.DECIMAL:
        shift = scale - e.col.scale
    else:
        shift = scale
    v = e.values.astype(jnp.int64)
    if shift > 0:
        return v * (10**shift)
    if shift < 0:
        return v // (10 ** (-shift))
    return v


def eval_expr(expr: ScalarExpr, batch: Batch, time=None) -> Evaled:
    """Recursively build the XLA computation for `expr` over `batch`.

    ``time`` is the step's virtual timestamp, consumed by MzNow (the
    CallUnmaterializable mz_now() of expr/src/scalar.rs) — None outside
    a timed step, where MzNow is an error."""
    schema = batch.schema
    cap = batch.capacity

    if isinstance(expr, MzNow):
        if time is None:
            raise ValueError(
                "mz_now() evaluated outside a timed dataflow step"
            )
        vals = jnp.full(cap, time, dtype=jnp.int64)
        return Evaled(vals, None, expr.typ(schema))

    if isinstance(expr, ColumnRef):
        return Evaled(
            batch.cols[expr.index], batch.nulls[expr.index], schema[expr.index]
        )

    if isinstance(expr, Literal):
        col = expr.typ(schema)
        if expr.value is None:
            vals = jnp.zeros(cap, dtype=col.dtype)
            return Evaled(vals, jnp.ones(cap, dtype=bool), col)
        vals = jnp.full(cap, expr.value, dtype=col.dtype)
        return Evaled(vals, None, col)

    if isinstance(expr, CallUnary):
        e = eval_expr(expr.expr, batch, time)
        col = expr.typ(schema)
        f = expr.func
        if f == UnaryFunc.NOT:
            return Evaled(jnp.logical_not(e.values), e.nulls, col)
        if f == UnaryFunc.NEG:
            return Evaled(-e.values, e.nulls, col)
        if f == UnaryFunc.ABS:
            return Evaled(jnp.abs(e.values), e.nulls, col)
        if f == UnaryFunc.IS_NULL:
            return Evaled(e.null_mask(), None, col)
        if f == UnaryFunc.CAST_INT64:
            if e.col.ctype is ColumnType.DECIMAL:
                v = e.values // (10**e.col.scale)
            else:
                v = e.values.astype(jnp.int64)
            return Evaled(v, e.nulls, col)
        if f == UnaryFunc.CAST_FLOAT64:
            if e.col.ctype is ColumnType.DECIMAL:
                v = e.values.astype(jnp.float64) / (10.0**e.col.scale)
            else:
                v = e.values.astype(jnp.float64)
            return Evaled(v, e.nulls, col)
        if f in (
            UnaryFunc.EXTRACT_YEAR,
            UnaryFunc.EXTRACT_MONTH,
            UnaryFunc.EXTRACT_DAY,
            UnaryFunc.EXTRACT_QUARTER,
        ):
            # days-since-epoch -> part; proleptic Gregorian civil_from_days
            y, m, d = _civil_from_days(e.values.astype(jnp.int64))
            v = {
                UnaryFunc.EXTRACT_YEAR: y,
                UnaryFunc.EXTRACT_MONTH: m,
                UnaryFunc.EXTRACT_DAY: d,
                UnaryFunc.EXTRACT_QUARTER: (m + 2) // 3,
            }[f]
            return Evaled(v, e.nulls, col)
        raise NotImplementedError(f)

    if isinstance(expr, CallBinary):
        l = eval_expr(expr.left, batch, time)
        r = eval_expr(expr.right, batch, time)
        col = expr.typ(schema)
        nulls = _merge_nulls(l, r)
        f = expr.func
        if f in (
            BinaryFunc.EQ,
            BinaryFunc.NEQ,
            BinaryFunc.LT,
            BinaryFunc.LTE,
            BinaryFunc.GT,
            BinaryFunc.GTE,
        ):
            lv, rv = _coerce_comparable(l, r)
            op = {
                BinaryFunc.EQ: jnp.equal,
                BinaryFunc.NEQ: jnp.not_equal,
                BinaryFunc.LT: jnp.less,
                BinaryFunc.LTE: jnp.less_equal,
                BinaryFunc.GT: jnp.greater,
                BinaryFunc.GTE: jnp.greater_equal,
            }[f]
            return Evaled(op(lv, rv), nulls, col)
        if col.ctype is ColumnType.DECIMAL:
            if f == BinaryFunc.MUL:
                v = l.values.astype(jnp.int64) * r.values.astype(jnp.int64)
                return Evaled(v, nulls, col)
            lv = _to_decimal_scale(l, col.scale)
            rv = _to_decimal_scale(r, col.scale)
            if f == BinaryFunc.ADD:
                return Evaled(lv + rv, nulls, col)
            if f == BinaryFunc.SUB:
                return Evaled(lv - rv, nulls, col)
            if f == BinaryFunc.DIV:
                # decimal / decimal at left scale; NULL on zero divisor
                zero = rv == 0
                safe = jnp.where(zero, 1, rv)
                v = (lv * (10**r.col.scale)) // safe
                nulls = _or_nulls(nulls, zero)
                return Evaled(v, nulls, col)
        if f == BinaryFunc.ADD:
            return Evaled(l.values + r.values, nulls, col)
        if f == BinaryFunc.SUB:
            return Evaled(l.values - r.values, nulls, col)
        if f == BinaryFunc.MUL:
            return Evaled(l.values * r.values, nulls, col)
        if f == BinaryFunc.DIV:
            lv = _as_float(l)
            rv = _as_float(r)
            zero = rv == 0.0
            v = lv / jnp.where(zero, 1.0, rv)
            return Evaled(v, _or_nulls(nulls, zero), col)
        if f == BinaryFunc.MOD:
            zero = r.values == 0
            v = jnp.where(zero, 0, l.values % jnp.where(zero, 1, r.values))
            return Evaled(v, _or_nulls(nulls, zero), col)
        raise NotImplementedError(f)

    if isinstance(expr, CallVariadic):
        col = expr.typ(schema)
        parts = [eval_expr(e, batch, time) for e in expr.exprs]
        if expr.func == VariadicFunc.AND:
            # SQL 3VL: FALSE dominates NULL
            val = jnp.ones(cap, dtype=bool)
            known_false = jnp.zeros(cap, dtype=bool)
            any_null = jnp.zeros(cap, dtype=bool)
            for p in parts:
                val = jnp.logical_and(val, p.values)
                known_false = jnp.logical_or(
                    known_false,
                    jnp.logical_and(
                        jnp.logical_not(p.values),
                        jnp.logical_not(p.null_mask()),
                    ),
                )
                any_null = jnp.logical_or(any_null, p.null_mask())
            nulls = jnp.logical_and(any_null, jnp.logical_not(known_false))
            return Evaled(
                jnp.logical_and(val, jnp.logical_not(known_false)), nulls, col
            )
        if expr.func == VariadicFunc.OR:
            val = jnp.zeros(cap, dtype=bool)
            known_true = jnp.zeros(cap, dtype=bool)
            any_null = jnp.zeros(cap, dtype=bool)
            for p in parts:
                val = jnp.logical_or(val, p.values)
                known_true = jnp.logical_or(
                    known_true,
                    jnp.logical_and(p.values, jnp.logical_not(p.null_mask())),
                )
                any_null = jnp.logical_or(any_null, p.null_mask())
            nulls = jnp.logical_and(any_null, jnp.logical_not(known_true))
            return Evaled(val, nulls, col)
        if expr.func == VariadicFunc.COALESCE:
            out_v = parts[-1].values
            out_n = parts[-1].null_mask()
            for p in reversed(parts[:-1]):
                take = jnp.logical_not(p.null_mask())
                out_v = jnp.where(take, p.values, out_v)
                out_n = jnp.where(take, jnp.zeros_like(out_n), out_n)
            return Evaled(out_v, out_n, col)
        raise NotImplementedError(expr.func)

    if isinstance(expr, If):
        c = eval_expr(expr.cond, batch, time)
        t = eval_expr(expr.then, batch, time)
        e = eval_expr(expr.els, batch, time)
        col = expr.typ(schema)
        cond = jnp.logical_and(c.values, jnp.logical_not(c.null_mask()))
        vals = jnp.where(cond, t.values, e.values)
        nulls = jnp.where(cond, t.null_mask(), e.null_mask())
        return Evaled(vals, nulls, col)

    raise NotImplementedError(type(expr))


def _merge_nulls(l: Evaled, r: Evaled):
    if l.nulls is None and r.nulls is None:
        return None
    return jnp.logical_or(l.null_mask(), r.null_mask())


def _or_nulls(nulls, extra):
    if nulls is None:
        return extra
    return jnp.logical_or(nulls, extra)


def _as_float(e: Evaled) -> jnp.ndarray:
    if e.col.ctype is ColumnType.DECIMAL:
        return e.values.astype(jnp.float64) / (10.0**e.col.scale)
    return e.values.astype(jnp.float64)


def _coerce_comparable(l: Evaled, r: Evaled):
    """Align decimal scales / numeric types for comparison."""
    if (
        l.col.ctype is ColumnType.DECIMAL
        or r.col.ctype is ColumnType.DECIMAL
    ) and ColumnType.FLOAT64 not in (l.col.ctype, r.col.ctype):
        scale = max(l.col.scale, r.col.scale)
        return _to_decimal_scale(l, scale), _to_decimal_scale(r, scale)
    if ColumnType.FLOAT64 in (l.col.ctype, r.col.ctype):
        return _as_float(l), _as_float(r)
    return l.values, r.values


def _civil_from_days(days: jnp.ndarray):
    """Howard Hinnant's civil_from_days, vectorized: (year, month, day)
    int64 arrays from days-since-epoch (proleptic Gregorian)."""
    z = days + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    return jnp.where(m <= 2, y + 1, y), m, d


# Convenience helpers for building expressions in tests/plans.
def col(i: int) -> ColumnRef:
    return ColumnRef(i)


def lit(value, ctype: ColumnType | None = None, scale: int = 0) -> Literal:
    if ctype is None:
        return _lift(value)
    return Literal(value, ctype, scale)


def and_(*exprs) -> CallVariadic:
    return CallVariadic(VariadicFunc.AND, [_lift(e) for e in exprs])


def or_(*exprs) -> CallVariadic:
    return CallVariadic(VariadicFunc.OR, [_lift(e) for e in exprs])
