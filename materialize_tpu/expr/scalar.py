"""Scalar expressions (MIR) and their XLA evaluation.

Analog of the reference's ``MirScalarExpr``
(src/expr/src/scalar.rs:69: Column / Literal / CallUnary / CallBinary /
CallVariadic / If) and its scalar function library
(src/expr/src/scalar/func.rs). Where the reference interprets expressions
row-at-a-time over ``Datum``s, here evaluation happens at *trace time*:
``eval_expr`` recursively builds a fused XLA computation over whole columns
— the "MirScalarExpr JIT-compiled to XLA" of the north star
(BASELINE.json). SQL NULL semantics are carried as an optional bool mask
per intermediate (three-valued logic).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Any, Sequence

import jax.numpy as jnp
import numpy as np

from ..repr.batch import Batch
from ..repr.schema import Column, ColumnType, Schema

# ---------------------------------------------------------------------------
# Expression tree


class ScalarExpr:
    """Base class. Subclasses are immutable dataclasses."""

    def typ(self, schema: Schema) -> Column:
        raise NotImplementedError

    # convenience builders
    def __add__(self, other):
        return CallBinary(BinaryFunc.ADD, self, _lift(other))

    def __sub__(self, other):
        return CallBinary(BinaryFunc.SUB, self, _lift(other))

    def __mul__(self, other):
        return CallBinary(BinaryFunc.MUL, self, _lift(other))

    def eq(self, other):
        return CallBinary(BinaryFunc.EQ, self, _lift(other))

    def lt(self, other):
        return CallBinary(BinaryFunc.LT, self, _lift(other))

    def lte(self, other):
        return CallBinary(BinaryFunc.LTE, self, _lift(other))

    def gt(self, other):
        return CallBinary(BinaryFunc.GT, self, _lift(other))

    def gte(self, other):
        return CallBinary(BinaryFunc.GTE, self, _lift(other))


def _lift(x) -> "ScalarExpr":
    if isinstance(x, ScalarExpr):
        return x
    if isinstance(x, bool):
        return Literal(x, ColumnType.BOOL)
    if isinstance(x, int):
        return Literal(x, ColumnType.INT64)
    if isinstance(x, float):
        return Literal(x, ColumnType.FLOAT64)
    raise TypeError(x)


@dataclass(frozen=True)
class MzNow(ScalarExpr):
    """The current virtual timestamp: CallUnmaterializable::MzNow
    (expr/src/scalar.rs). Evaluates to the step's time; predicates over
    it become TEMPORAL FILTERS (expr/src/linear.rs:404-408) that
    schedule future retractions/insertions."""

    def typ(self, schema: Schema) -> Column:
        return Column("mz_now", ColumnType.TIMESTAMP)


def contains_mz_now(expr: ScalarExpr) -> bool:
    if isinstance(expr, MzNow):
        return True
    for f in getattr(expr, "__dataclass_fields__", {}):
        v = getattr(expr, f)
        if isinstance(v, ScalarExpr) and contains_mz_now(v):
            return True
        if isinstance(v, tuple) and any(
            isinstance(x, ScalarExpr) and contains_mz_now(x) for x in v
        ):
            return True
    return False


@dataclass(frozen=True)
class ColumnRef(ScalarExpr):
    """Column reference by position (like MirScalarExpr::Column)."""

    index: int

    def typ(self, schema):
        return schema[self.index]


@dataclass(frozen=True)
class Literal(ScalarExpr):
    value: Any  # python scalar; None = NULL
    ctype: ColumnType
    scale: int = 0

    def typ(self, schema):
        return Column("literal", self.ctype, self.value is None, self.scale)


class UnaryFunc:
    NOT = "not"
    NEG = "neg"
    IS_NULL = "is_null"
    ABS = "abs"
    # math family (scalar func library analog, expr/src/scalar/func/impls)
    FLOOR = "floor"
    CEIL = "ceil"
    ROUND = "round"
    TRUNC = "trunc"
    SQRT = "sqrt"
    CBRT = "cbrt"
    EXP = "exp"
    LN = "ln"
    LOG10 = "log10"
    LOG2 = "log2"
    SIGN = "sign"
    SIN = "sin"
    COS = "cos"
    TAN = "tan"
    ASIN = "asin"
    ACOS = "acos"
    ATAN = "atan"
    RADIANS = "radians"
    DEGREES = "degrees"
    # cast family
    CAST_INT64 = "cast_int64"
    CAST_INT32 = "cast_int32"
    CAST_FLOAT64 = "cast_float64"
    CAST_BOOL = "cast_bool"
    CAST_DATE = "cast_date"
    CAST_TIMESTAMP = "cast_timestamp"
    # date parts (DATE = days since epoch; TIMESTAMP = ms since epoch)
    EXTRACT_YEAR = "extract_year"
    EXTRACT_MONTH = "extract_month"
    EXTRACT_DAY = "extract_day"
    EXTRACT_QUARTER = "extract_quarter"
    EXTRACT_DOW = "extract_dow"
    EXTRACT_ISODOW = "extract_isodow"
    EXTRACT_DOY = "extract_doy"
    EXTRACT_WEEK = "extract_week"
    EXTRACT_EPOCH = "extract_epoch"
    EXTRACT_HOUR = "extract_hour"
    EXTRACT_MINUTE = "extract_minute"
    EXTRACT_SECOND = "extract_second"
    EXTRACT_MILLENNIUM = "extract_millennium"
    EXTRACT_CENTURY = "extract_century"
    EXTRACT_DECADE = "extract_decade"
    # date_trunc family: value-preserving truncation to a boundary
    DATE_TRUNC_YEAR = "date_trunc_year"
    DATE_TRUNC_QUARTER = "date_trunc_quarter"
    DATE_TRUNC_MONTH = "date_trunc_month"
    DATE_TRUNC_WEEK = "date_trunc_week"
    DATE_TRUNC_DAY = "date_trunc_day"
    DATE_TRUNC_HOUR = "date_trunc_hour"
    DATE_TRUNC_MINUTE = "date_trunc_minute"
    DATE_TRUNC_SECOND = "date_trunc_second"

    EXTRACTS = {}  # filled below
    DATE_TRUNCS = {}  # filled below


UnaryFunc.EXTRACTS = {
    "year": UnaryFunc.EXTRACT_YEAR,
    "month": UnaryFunc.EXTRACT_MONTH,
    "day": UnaryFunc.EXTRACT_DAY,
    "quarter": UnaryFunc.EXTRACT_QUARTER,
    "dow": UnaryFunc.EXTRACT_DOW,
    "isodow": UnaryFunc.EXTRACT_ISODOW,
    "doy": UnaryFunc.EXTRACT_DOY,
    "week": UnaryFunc.EXTRACT_WEEK,
    "epoch": UnaryFunc.EXTRACT_EPOCH,
    "hour": UnaryFunc.EXTRACT_HOUR,
    "minute": UnaryFunc.EXTRACT_MINUTE,
    "second": UnaryFunc.EXTRACT_SECOND,
    "millennium": UnaryFunc.EXTRACT_MILLENNIUM,
    "century": UnaryFunc.EXTRACT_CENTURY,
    "decade": UnaryFunc.EXTRACT_DECADE,
}

UnaryFunc.DATE_TRUNCS = {
    "year": UnaryFunc.DATE_TRUNC_YEAR,
    "quarter": UnaryFunc.DATE_TRUNC_QUARTER,
    "month": UnaryFunc.DATE_TRUNC_MONTH,
    "week": UnaryFunc.DATE_TRUNC_WEEK,
    "day": UnaryFunc.DATE_TRUNC_DAY,
    "hour": UnaryFunc.DATE_TRUNC_HOUR,
    "minute": UnaryFunc.DATE_TRUNC_MINUTE,
    "second": UnaryFunc.DATE_TRUNC_SECOND,
}


class BinaryFunc:
    ADD = "add"
    SUB = "sub"
    MUL = "mul"
    DIV = "div"
    MOD = "mod"
    POWER = "power"
    LOG_BASE = "log_base"
    ROUND_TO = "round_to"  # round(x, n): n must be a literal
    CAST_DECIMAL = "cast_decimal"  # cast(x as decimal(p, s)): s a literal
    EQ = "eq"
    NEQ = "neq"
    LT = "lt"
    LTE = "lte"
    GT = "gt"
    GTE = "gte"


# String functions evaluate as dictionary side-table gathers (see
# expr/strings.py): CallVariadic("str:<fn>", (col, literal params...)).
STRING_FUNC_PREFIX = "str:"


def string_call(func: str, expr: "ScalarExpr", *params) -> "CallVariadic":
    return CallVariadic(
        STRING_FUNC_PREFIX + func, (expr,) + tuple(params)
    )


def _string_func_key(func: str, param_exprs) -> str:
    """Trace/render-time env key: literal params decoded to text."""
    from ..repr.schema import GLOBAL_DICT
    from . import strings

    vals = []
    for p in param_exprs:
        if not isinstance(p, Literal):
            raise NotImplementedError(
                f"{func}: non-literal string-function arguments are "
                "not supported (the mapping table is precomputed per "
                "distinct dictionary entry)"
            )
        if p.value is None:
            raise NotImplementedError(
                f"{func}: NULL parameters are not supported"
            )
        if p.ctype is ColumnType.STRING:
            vals.append(GLOBAL_DICT.decode(int(p.value)))
        else:
            vals.append(p.value)
    return strings.env_key(func, *vals)


class VariadicFunc:
    AND = "and"
    OR = "or"
    COALESCE = "coalesce"
    GREATEST = "greatest"
    LEAST = "least"
    # (expr, months, days, ms) with literal interval parts; subtraction
    # negates the parts at plan time
    ADD_INTERVAL = "add_interval"


@dataclass(frozen=True)
class CallUnary(ScalarExpr):
    func: str
    expr: ScalarExpr

    def typ(self, schema):
        inner = self.expr.typ(schema)
        f = self.func
        if f in (UnaryFunc.NOT,):
            return Column("f", ColumnType.BOOL, inner.nullable)
        if f == UnaryFunc.IS_NULL:
            return Column("f", ColumnType.BOOL, False)
        if f == UnaryFunc.CAST_INT64:
            return Column("f", ColumnType.INT64, inner.nullable)
        if f == UnaryFunc.CAST_INT32:
            return Column("f", ColumnType.INT32, inner.nullable)
        if f == UnaryFunc.CAST_FLOAT64:
            return Column("f", ColumnType.FLOAT64, inner.nullable)
        if f == UnaryFunc.CAST_BOOL:
            return Column("f", ColumnType.BOOL, inner.nullable)
        if f == UnaryFunc.CAST_DATE:
            return Column("f", ColumnType.DATE, inner.nullable)
        if f == UnaryFunc.CAST_TIMESTAMP:
            return Column("f", ColumnType.TIMESTAMP, inner.nullable)
        if f in _EXTRACT_INT_FUNCS:
            return Column("f", ColumnType.INT64, inner.nullable)
        if f in (UnaryFunc.EXTRACT_EPOCH, UnaryFunc.EXTRACT_SECOND):
            return Column("f", ColumnType.FLOAT64, inner.nullable)
        if f in (UnaryFunc.FLOOR, UnaryFunc.CEIL, UnaryFunc.TRUNC,
                 UnaryFunc.ROUND):
            # type-preserving on numerics (floor(numeric) is numeric)
            return inner
        if f in _FLOAT_UNARY_FUNCS:
            # domain errors (sqrt of negative, ln of nonpositive) yield
            # NULL here where the reference raises an EvalError
            nullable = inner.nullable or f in (
                UnaryFunc.SQRT, UnaryFunc.LN, UnaryFunc.LOG10,
                UnaryFunc.LOG2, UnaryFunc.ASIN, UnaryFunc.ACOS,
            )
            return Column("f", ColumnType.FLOAT64, nullable)
        if f == UnaryFunc.SIGN:
            return Column("f", ColumnType.INT64, inner.nullable)
        if f in UnaryFunc.DATE_TRUNCS.values():
            return Column("f", inner.ctype, inner.nullable)
        return inner  # NEG, ABS preserve type


@dataclass(frozen=True)
class CallBinary(ScalarExpr):
    func: str
    left: ScalarExpr
    right: ScalarExpr

    def typ(self, schema):
        lt_, rt = self.left.typ(schema), self.right.typ(schema)
        nullable = lt_.nullable or rt.nullable
        if self.func in (
            BinaryFunc.EQ,
            BinaryFunc.NEQ,
            BinaryFunc.LT,
            BinaryFunc.LTE,
            BinaryFunc.GT,
            BinaryFunc.GTE,
        ):
            return Column("f", ColumnType.BOOL, nullable)
        if self.func in (BinaryFunc.POWER, BinaryFunc.LOG_BASE):
            return Column("f", ColumnType.FLOAT64, True)
        if self.func == BinaryFunc.ROUND_TO:
            return Column("f", lt_.ctype, nullable, lt_.scale)
        if self.func == BinaryFunc.CAST_DECIMAL:
            assert isinstance(self.right, Literal)
            return Column(
                "f", ColumnType.DECIMAL, lt_.nullable, int(self.right.value)
            )
        if self.func == BinaryFunc.DIV:
            # SQL: division may produce NULL (div by zero -> error in MZ;
            # we produce NULL for now). int/int is INTEGER division
            # truncating toward zero (pg int4div/int8div); decimals keep
            # the left scale; anything float goes float.
            if lt_.ctype is ColumnType.DECIMAL:
                return Column("f", ColumnType.DECIMAL, True, lt_.scale)
            if lt_.ctype in (
                ColumnType.INT32, ColumnType.INT64
            ) and rt.ctype in (ColumnType.INT32, ColumnType.INT64):
                return Column("f", ColumnType.INT64, True)
            return Column("f", ColumnType.FLOAT64, True)
        # arithmetic: unify types
        ctype, scale = _unify_arith(lt_, rt, self.func)
        return Column("f", ctype, nullable, scale)


@dataclass(frozen=True)
class CallVariadic(ScalarExpr):
    func: str
    exprs: tuple

    def __init__(self, func, exprs):
        object.__setattr__(self, "func", func)
        object.__setattr__(self, "exprs", tuple(exprs))

    def typ(self, schema):
        if self.func.startswith(STRING_FUNC_PREFIX):
            from . import strings

            kind = strings.RESULT_KINDS[
                self.func[len(STRING_FUNC_PREFIX):]
            ]
            inner = self.exprs[0].typ(schema)
            if inner.ctype is not ColumnType.STRING:
                # gathering a non-code column through a dictionary
                # table would silently produce unrelated strings
                raise TypeError(
                    f"{self.func} requires a text operand, got "
                    f"{inner.ctype.value}"
                )
            ctype = {
                "str": ColumnType.STRING,
                "int": ColumnType.INT64,
                "bool": ColumnType.BOOL,
            }[kind]
            return Column("f", ctype, inner.nullable)
        if self.func in (VariadicFunc.AND, VariadicFunc.OR):
            nullable = any(e.typ(schema).nullable for e in self.exprs)
            return Column("f", ColumnType.BOOL, nullable)
        if self.func == VariadicFunc.COALESCE:
            first = self.exprs[0].typ(schema)
            nullable = all(e.typ(schema).nullable for e in self.exprs)
            return Column("f", first.ctype, nullable, first.scale)
        if self.func == VariadicFunc.ADD_INTERVAL:
            x = self.exprs[0].typ(schema)
            ms = self.exprs[3]
            has_ms = not (isinstance(ms, Literal) and ms.value == 0)
            if x.ctype is ColumnType.DATE and not has_ms:
                return Column("f", ColumnType.DATE, x.nullable)
            return Column("f", ColumnType.TIMESTAMP, x.nullable)
        if self.func in (VariadicFunc.GREATEST, VariadicFunc.LEAST):
            # unified numeric type; NULL inputs are skipped (pg semantics)
            typs = [e.typ(schema) for e in self.exprs]
            out = typs[0]
            for t in typs[1:]:
                ctype, scale = _unify_arith(out, t, BinaryFunc.ADD)
                out = Column("f", ctype, False, scale)
            nullable = all(t.nullable for t in typs)
            return Column("f", out.ctype, nullable, out.scale)
        raise NotImplementedError(self.func)


@dataclass(frozen=True)
class If(ScalarExpr):
    cond: ScalarExpr
    then: ScalarExpr
    els: ScalarExpr

    def _principal(self) -> str:
        """Which branch determines the result type: an untyped NULL
        literal defers to the other branch (CASE WHEN c THEN NULL
        ELSE 1.5 END is float, not int)."""
        if (
            isinstance(self.then, Literal)
            and self.then.value is None
            and not (
                isinstance(self.els, Literal) and self.els.value is None
            )
        ):
            return "els"
        return "then"

    def typ(self, schema):
        t = self.then.typ(schema)
        e = self.els.typ(schema)
        p = t if self._principal() == "then" else e
        return Column("f", p.ctype, t.nullable or e.nullable, p.scale)


def _unify_arith(lt_: Column, rt: Column, func: str) -> tuple[ColumnType, int]:
    a, b = lt_.ctype, rt.ctype
    if ColumnType.FLOAT64 in (a, b):
        return ColumnType.FLOAT64, 0
    if a is ColumnType.DECIMAL or b is ColumnType.DECIMAL:
        if func == BinaryFunc.MUL:
            return ColumnType.DECIMAL, lt_.scale + rt.scale
        scale = max(lt_.scale, rt.scale)
        return ColumnType.DECIMAL, scale
    if a is ColumnType.DATE and b in (ColumnType.INT32, ColumnType.INT64):
        return ColumnType.DATE, 0
    if ColumnType.INT64 in (a, b):
        return ColumnType.INT64, 0
    return a, 0


# ---------------------------------------------------------------------------
# Evaluation: trace-time construction of fused XLA ops


@dataclass
class Evaled:
    """An evaluated intermediate: column values + optional null mask."""

    values: jnp.ndarray
    nulls: jnp.ndarray | None
    col: Column  # type info

    def null_mask(self) -> jnp.ndarray:
        if self.nulls is None:
            return jnp.zeros(self.values.shape, dtype=bool)
        return self.nulls


def _to_decimal_scale(e: Evaled, scale: int) -> jnp.ndarray:
    """Rescale a decimal (or int) value array to the given decimal scale."""
    if e.col.ctype is ColumnType.DECIMAL:
        shift = scale - e.col.scale
    else:
        shift = scale
    v = e.values.astype(jnp.int64)
    if shift > 0:
        return v * (10**shift)
    if shift < 0:
        return v // (10 ** (-shift))
    return v


def eval_expr(expr: ScalarExpr, batch: Batch, time=None) -> Evaled:
    """Recursively build the XLA computation for `expr` over `batch`.

    ``time`` is the step's virtual timestamp, consumed by MzNow (the
    CallUnmaterializable mz_now() of expr/src/scalar.rs) — None outside
    a timed step, where MzNow is an error."""
    schema = batch.schema
    cap = batch.capacity

    if isinstance(expr, MzNow):
        if time is None:
            raise ValueError(
                "mz_now() evaluated outside a timed dataflow step"
            )
        vals = jnp.full(cap, time, dtype=jnp.int64)
        return Evaled(vals, None, expr.typ(schema))

    if isinstance(expr, ColumnRef):
        return Evaled(
            batch.cols[expr.index], batch.nulls[expr.index], schema[expr.index]
        )

    if isinstance(expr, Literal):
        col = expr.typ(schema)
        if expr.value is None:
            vals = jnp.zeros(cap, dtype=col.dtype)
            return Evaled(vals, jnp.ones(cap, dtype=bool), col)
        vals = jnp.full(cap, expr.value, dtype=col.dtype)
        return Evaled(vals, None, col)

    if isinstance(expr, CallUnary):
        e = eval_expr(expr.expr, batch, time)
        col = expr.typ(schema)
        f = expr.func
        if f == UnaryFunc.NOT:
            return Evaled(jnp.logical_not(e.values), e.nulls, col)
        if f == UnaryFunc.NEG:
            return Evaled(-e.values, e.nulls, col)
        if f == UnaryFunc.ABS:
            return Evaled(jnp.abs(e.values), e.nulls, col)
        if f == UnaryFunc.IS_NULL:
            return Evaled(e.null_mask(), None, col)
        if f == UnaryFunc.CAST_INT64:
            if e.col.ctype is ColumnType.DECIMAL:
                v = e.values // (10**e.col.scale)
            elif e.col.ctype is ColumnType.FLOAT64:
                from . import errors as _err

                x = e.values
                # asymmetric bounds: -2^63 is exactly representable
                bad = (
                    jnp.isnan(x)
                    | (x >= float(2**63))
                    | (x < -float(2**63))
                )
                _err.emit(
                    _err.NUMERIC_OUT_OF_RANGE,
                    jnp.logical_and(bad, jnp.logical_not(e.null_mask())),
                )
                v = jnp.where(bad, 0.0, x).astype(jnp.int64)
                return Evaled(v, _or_nulls(e.nulls, bad), col)
            else:
                v = e.values.astype(jnp.int64)
            return Evaled(v, e.nulls, col)
        if f == UnaryFunc.CAST_FLOAT64:
            if e.col.ctype is ColumnType.DECIMAL:
                v = e.values.astype(jnp.float64) / (10.0**e.col.scale)
            else:
                v = e.values.astype(jnp.float64)
            return Evaled(v, e.nulls, col)
        if f == UnaryFunc.CAST_INT32:
            if e.col.ctype is ColumnType.DECIMAL:
                v = (e.values // (10**e.col.scale)).astype(jnp.int32)
            else:
                from . import errors as _err

                x = e.values
                if e.col.ctype is ColumnType.FLOAT64:
                    bad = (
                        jnp.isnan(x)
                        | (x >= float(2**31))
                        | (x < -float(2**31))
                    )
                    x = jnp.where(bad, 0.0, x)
                else:
                    xi = x.astype(jnp.int64)
                    bad = jnp.logical_or(
                        xi >= 2**31, xi < -(2**31)
                    )
                _err.emit(
                    _err.NUMERIC_OUT_OF_RANGE,
                    jnp.logical_and(bad, jnp.logical_not(e.null_mask())),
                )
                v = x.astype(jnp.int32)
                v = jnp.where(bad, 0, v)
                return Evaled(v, _or_nulls(e.nulls, bad), col)
            return Evaled(v, e.nulls, col)
        if f == UnaryFunc.CAST_BOOL:
            return Evaled(e.values != 0, e.nulls, col)
        if f == UnaryFunc.CAST_DATE:
            if e.col.ctype is ColumnType.TIMESTAMP:
                v = (e.values.astype(jnp.int64) // _MS_PER_DAY).astype(
                    jnp.int32
                )
            else:
                v = e.values.astype(jnp.int32)
            return Evaled(v, e.nulls, col)
        if f == UnaryFunc.CAST_TIMESTAMP:
            if e.col.ctype is ColumnType.DATE:
                v = e.values.astype(jnp.int64) * _MS_PER_DAY
            else:
                v = e.values.astype(jnp.int64)
            return Evaled(v, e.nulls, col)
        if f in _EXTRACT_INT_FUNCS or f in (
            UnaryFunc.EXTRACT_EPOCH,
            UnaryFunc.EXTRACT_SECOND,
        ):
            return _eval_extract(f, e, col)
        if f in UnaryFunc.DATE_TRUNCS.values():
            return _eval_date_trunc(f, e, col)
        if f in (UnaryFunc.FLOOR, UnaryFunc.CEIL, UnaryFunc.TRUNC,
                 UnaryFunc.ROUND):
            return _eval_round_family(f, e, col)
        if f in _FLOAT_UNARY_FUNCS:
            x = _as_float(e)
            if f == UnaryFunc.SQRT:
                bad = x < 0.0
                v = jnp.sqrt(jnp.where(bad, 0.0, x))
                return Evaled(v, _or_nulls(e.nulls, bad), col)
            if f in (UnaryFunc.LN, UnaryFunc.LOG10, UnaryFunc.LOG2):
                bad = x <= 0.0
                safe = jnp.where(bad, 1.0, x)
                v = {
                    UnaryFunc.LN: jnp.log,
                    UnaryFunc.LOG10: lambda a: jnp.log(a)
                    / jnp.log(10.0),
                    UnaryFunc.LOG2: jnp.log2,
                }[f](safe)
                return Evaled(v, _or_nulls(e.nulls, bad), col)
            if f in (UnaryFunc.ASIN, UnaryFunc.ACOS):
                bad = jnp.abs(x) > 1.0
                safe = jnp.where(bad, 0.0, x)
                op = jnp.arcsin if f == UnaryFunc.ASIN else jnp.arccos
                return Evaled(op(safe), _or_nulls(e.nulls, bad), col)
            op = {
                UnaryFunc.CBRT: jnp.cbrt,
                UnaryFunc.EXP: jnp.exp,
                UnaryFunc.SIN: jnp.sin,
                UnaryFunc.COS: jnp.cos,
                UnaryFunc.TAN: jnp.tan,
                UnaryFunc.ATAN: jnp.arctan,
                UnaryFunc.RADIANS: jnp.radians,
                UnaryFunc.DEGREES: jnp.degrees,
            }[f]
            return Evaled(op(x), e.nulls, col)
        if f == UnaryFunc.SIGN:
            v = jnp.sign(
                _as_float(e) if e.col.ctype is ColumnType.FLOAT64
                else e.values
            ).astype(jnp.int64)
            return Evaled(v, e.nulls, col)
        raise NotImplementedError(f)

    if isinstance(expr, CallBinary):
        l = eval_expr(expr.left, batch, time)
        r = eval_expr(expr.right, batch, time)
        col = expr.typ(schema)
        nulls = _merge_nulls(l, r)
        f = expr.func
        if f in (
            BinaryFunc.EQ,
            BinaryFunc.NEQ,
            BinaryFunc.LT,
            BinaryFunc.LTE,
            BinaryFunc.GT,
            BinaryFunc.GTE,
        ):
            # Strings compare directly: dictionary codes are
            # order-preserving labels (repr/schema.py StringDictionary),
            # so integer comparison == lexicographic comparison.
            lv, rv = _coerce_comparable(l, r)
            op = {
                BinaryFunc.EQ: jnp.equal,
                BinaryFunc.NEQ: jnp.not_equal,
                BinaryFunc.LT: jnp.less,
                BinaryFunc.LTE: jnp.less_equal,
                BinaryFunc.GT: jnp.greater,
                BinaryFunc.GTE: jnp.greater_equal,
            }[f]
            return Evaled(op(lv, rv), nulls, col)
        if col.ctype is ColumnType.DECIMAL:
            if f == BinaryFunc.MUL:
                v = l.values.astype(jnp.int64) * r.values.astype(jnp.int64)
                return Evaled(v, nulls, col)
            lv = _to_decimal_scale(l, col.scale)
            rv = _to_decimal_scale(r, col.scale)
            if f == BinaryFunc.ADD:
                return Evaled(lv + rv, nulls, col)
            if f == BinaryFunc.SUB:
                return Evaled(lv - rv, nulls, col)
            if f == BinaryFunc.DIV:
                # decimal / decimal at left scale; the zero-divisor rows
                # become NULL here and surface through the error stream
                # (render.rs ok/err trees) when a collector is active
                zero = rv == 0
                from . import errors as _err

                _err.emit(
                    _err.DIVISION_BY_ZERO,
                    # pg: NULL numerator or divisor yields NULL, no error
                    jnp.logical_and(
                        zero,
                        jnp.logical_not(
                            jnp.logical_or(r.null_mask(), l.null_mask())
                        ),
                    ),
                )
                safe = jnp.where(zero, 1, rv)
                # Both operands are at col.scale after rescaling, so
                # the scale-preserving quotient multiplies by
                # 10^col.scale (NOT the divisor's original scale —
                # decimal/int division like avg's sum/count would
                # otherwise come out 10^scale too small).
                v = (lv * (10**col.scale)) // safe
                nulls = _or_nulls(nulls, zero)
                return Evaled(v, nulls, col)
        if f == BinaryFunc.ADD:
            return Evaled(l.values + r.values, nulls, col)
        if f == BinaryFunc.SUB:
            return Evaled(l.values - r.values, nulls, col)
        if f == BinaryFunc.MUL:
            return Evaled(l.values * r.values, nulls, col)
        if f == BinaryFunc.DIV:
            from . import errors as _err

            if col.ctype is ColumnType.INT64:
                # integer division truncates toward zero (pg int8div;
                # jnp // floors, wrong for mixed signs)
                li = l.values.astype(jnp.int64)
                ri = r.values.astype(jnp.int64)
                zero = ri == 0
                _err.emit(
                    _err.DIVISION_BY_ZERO,
                    jnp.logical_and(
                        zero,
                        jnp.logical_not(
                            jnp.logical_or(r.null_mask(), l.null_mask())
                        ),
                    ),
                )
                safe = jnp.where(zero, 1, ri)
                q = jnp.abs(li) // jnp.abs(safe)
                v = jnp.where(jnp.sign(li) == jnp.sign(safe), q, -q)
                return Evaled(v, _or_nulls(nulls, zero), col)
            lv = _as_float(l)
            rv = _as_float(r)
            zero = rv == 0.0
            _err.emit(
                _err.DIVISION_BY_ZERO,
                # pg: NULL numerator or divisor yields NULL, no error
                jnp.logical_and(
                    zero,
                    jnp.logical_not(
                        jnp.logical_or(r.null_mask(), l.null_mask())
                    ),
                ),
            )
            v = lv / jnp.where(zero, 1.0, rv)
            return Evaled(v, _or_nulls(nulls, zero), col)
        if f == BinaryFunc.MOD:
            from . import errors as _err

            zero = r.values == 0
            _err.emit(
                _err.DIVISION_BY_ZERO,
                # pg: NULL numerator or divisor yields NULL, no error
                jnp.logical_and(
                    zero,
                    jnp.logical_not(
                        jnp.logical_or(r.null_mask(), l.null_mask())
                    ),
                ),
            )
            # pg mod truncates toward zero: result takes the DIVIDEND's
            # sign (jnp % floors, giving the divisor's sign). Floats use
            # fmod (already truncating); the integer path also covers
            # DECIMAL (scaled-int mod IS decimal mod at that scale).
            if col.ctype is ColumnType.FLOAT64:
                lv, rv = _as_float(l), _as_float(r)
                v = jnp.fmod(lv, jnp.where(zero, 1.0, rv))
                return Evaled(
                    jnp.where(zero, 0.0, v), _or_nulls(nulls, zero), col
                )
            li = l.values.astype(jnp.int64)
            ri = jnp.where(zero, 1, r.values.astype(jnp.int64))
            q = jnp.abs(li) // jnp.abs(ri)
            tq = jnp.where(jnp.sign(li) == jnp.sign(ri), q, -q)
            v = jnp.where(zero, 0, li - tq * ri)
            if l.values.dtype != jnp.int64:
                v = v.astype(l.values.dtype)
            return Evaled(v, _or_nulls(nulls, zero), col)
        if f == BinaryFunc.POWER:
            lv, rv = _as_float(l), _as_float(r)
            v = jnp.power(lv, rv)
            bad = jnp.isnan(v) | jnp.isinf(v)
            return Evaled(
                jnp.where(bad, 0.0, v), _or_nulls(nulls, bad), col
            )
        if f == BinaryFunc.LOG_BASE:
            b, x = _as_float(l), _as_float(r)
            bad = (b <= 0.0) | (b == 1.0) | (x <= 0.0)
            v = jnp.log(jnp.where(bad, 2.0, x)) / jnp.log(
                jnp.where(bad, 2.0, b)
            )
            return Evaled(v, _or_nulls(nulls, bad), col)
        if f == BinaryFunc.CAST_DECIMAL:
            scale = col.scale
            if l.col.ctype is ColumnType.FLOAT64:
                v = jnp.round(l.values * (10.0**scale)).astype(jnp.int64)
            elif (
                l.col.ctype is ColumnType.DECIMAL and l.col.scale > scale
            ):
                # narrowing rescale rounds half away from zero (pg numeric)
                v = _round_half_away(
                    l.values, 10 ** (l.col.scale - scale), rescale=True
                )
            else:
                v = _to_decimal_scale(l, scale)
            return Evaled(v, l.nulls, col)
        if f == BinaryFunc.ROUND_TO:
            if not isinstance(expr.right, Literal):
                raise NotImplementedError("round(x, n): n must be a literal")
            n = int(expr.right.value)
            if l.col.ctype is ColumnType.FLOAT64:
                factor = 10.0**n
                v = jnp.round(l.values * factor) / factor
                return Evaled(v, nulls, col)
            if l.col.ctype is ColumnType.DECIMAL:
                drop = l.col.scale - n
                if drop <= 0:
                    return Evaled(l.values, nulls, col)
                v = _round_half_away(l.values, 10**drop)
                return Evaled(v, nulls, col)
            if n < 0:  # integers: round(123, -1) = 120 (pg numeric)
                v = _round_half_away(
                    l.values.astype(jnp.int64), 10 ** (-n)
                ).astype(l.values.dtype)
                return Evaled(v, nulls, col)
            return Evaled(l.values, nulls, col)
        raise NotImplementedError(f)

    if isinstance(expr, CallVariadic):
        col = expr.typ(schema)
        if expr.func.startswith(STRING_FUNC_PREFIX):
            from . import strings

            fn = expr.func[len(STRING_FUNC_PREFIX):]
            key = _string_func_key(fn, expr.exprs[1:])
            e = eval_expr(expr.exprs[0], batch, time)
            vals = strings.lookup(strings.trace_env()[key], e.values)
            return Evaled(vals, e.nulls, col)
        if expr.func == VariadicFunc.COALESCE:
            # pg evaluates COALESCE arguments in order until the first
            # non-NULL: an argument's evaluation errors only count for
            # rows that actually REACH it (all earlier args NULL).
            from . import errors as _err

            evaled, masksets = [], []
            for x in expr.exprs:
                with _err.collect() as m:
                    evaled.append(eval_expr(x, batch, time))
                masksets.append(m)
            reached = jnp.ones(cap, dtype=bool)
            for p, ms_ in zip(evaled, masksets):
                for code, mask in ms_:
                    _err.emit(code, jnp.logical_and(mask, reached))
                reached = jnp.logical_and(reached, p.null_mask())
            out_v = evaled[-1].values
            out_n = evaled[-1].null_mask()
            for p in reversed(evaled[:-1]):
                take = jnp.logical_not(p.null_mask())
                out_v = jnp.where(take, p.values, out_v)
                out_n = jnp.where(take, jnp.zeros_like(out_n), out_n)
            return Evaled(out_v, out_n, col)
        parts = [eval_expr(e, batch, time) for e in expr.exprs]
        if expr.func == VariadicFunc.AND:
            # SQL 3VL: FALSE dominates NULL
            val = jnp.ones(cap, dtype=bool)
            known_false = jnp.zeros(cap, dtype=bool)
            any_null = jnp.zeros(cap, dtype=bool)
            for p in parts:
                val = jnp.logical_and(val, p.values)
                known_false = jnp.logical_or(
                    known_false,
                    jnp.logical_and(
                        jnp.logical_not(p.values),
                        jnp.logical_not(p.null_mask()),
                    ),
                )
                any_null = jnp.logical_or(any_null, p.null_mask())
            nulls = jnp.logical_and(any_null, jnp.logical_not(known_false))
            return Evaled(
                jnp.logical_and(val, jnp.logical_not(known_false)), nulls, col
            )
        if expr.func == VariadicFunc.OR:
            val = jnp.zeros(cap, dtype=bool)
            known_true = jnp.zeros(cap, dtype=bool)
            any_null = jnp.zeros(cap, dtype=bool)
            for p in parts:
                val = jnp.logical_or(val, p.values)
                known_true = jnp.logical_or(
                    known_true,
                    jnp.logical_and(p.values, jnp.logical_not(p.null_mask())),
                )
                any_null = jnp.logical_or(any_null, p.null_mask())
            nulls = jnp.logical_and(any_null, jnp.logical_not(known_true))
            return Evaled(val, nulls, col)
        if expr.func == VariadicFunc.ADD_INTERVAL:
            e = parts[0]
            months, days, ms = (
                int(x.value) for x in expr.exprs[1:]  # plan-time literals
            )
            dd, msod = _days_and_ms(e)
            if months:
                y, m, d = _civil_from_days(dd)
                m0 = m - 1 + months
                y2 = y + m0 // 12
                m2 = m0 % 12 + 1
                # clamp to the target month's last day (pg semantics)
                next_month_start = _days_from_civil(
                    y2 + (m2 == 12), jnp.where(m2 == 12, 1, m2 + 1),
                    jnp.ones_like(m2),
                )
                month_len = next_month_start - _days_from_civil(
                    y2, m2, jnp.ones_like(m2)
                )
                d2 = jnp.minimum(d, month_len)
                dd = _days_from_civil(y2, m2, d2)
            dd = dd + days
            if col.ctype is ColumnType.DATE:
                return Evaled(dd.astype(col.dtype), e.nulls, col)
            return Evaled(dd * _MS_PER_DAY + msod + ms, e.nulls, col)
        if expr.func in (VariadicFunc.GREATEST, VariadicFunc.LEAST):
            # pg semantics: NULL arguments are ignored; result is NULL
            # only when every argument is NULL
            if col.ctype is ColumnType.FLOAT64:
                coerced = [_as_float(p) for p in parts]
            elif col.ctype is ColumnType.DECIMAL:
                coerced = [_to_decimal_scale(p, col.scale) for p in parts]
            else:
                coerced = [p.values.astype(col.dtype) for p in parts]
            better = (
                jnp.greater
                if expr.func == VariadicFunc.GREATEST
                else jnp.less
            )
            acc_v = coerced[0]
            acc_n = parts[0].null_mask()
            for p, v in zip(parts[1:], coerced[1:]):
                pn = p.null_mask()
                take = jnp.logical_and(
                    jnp.logical_not(pn),
                    jnp.logical_or(acc_n, better(v, acc_v)),
                )
                acc_v = jnp.where(take, v, acc_v)
                acc_n = jnp.logical_and(acc_n, pn)
            return Evaled(acc_v, acc_n, col)
        raise NotImplementedError(expr.func)

    if isinstance(expr, If):
        from . import errors as _err

        c = eval_expr(expr.cond, batch, time)
        # CASE/If is SQL's error guard: both branches evaluate
        # vectorized, but a branch's evaluation errors only count for
        # rows that actually SELECT that branch (the reference's MfpPlan
        # evaluates per-row lazily; here the masks are filtered).
        cond_sel = jnp.logical_and(
            c.values, jnp.logical_not(c.null_mask())
        )
        with _err.collect() as t_masks:
            t = eval_expr(expr.then, batch, time)
        with _err.collect() as e_masks:
            e = eval_expr(expr.els, batch, time)
        for code, m in t_masks:
            _err.emit(code, jnp.logical_and(m, cond_sel))
        for code, m in e_masks:
            _err.emit(
                code, jnp.logical_and(m, jnp.logical_not(cond_sel))
            )
        col = expr.typ(schema)
        cond = cond_sel
        tv, ev = t.values, e.values
        # branches of different device dtypes (an untyped NULL literal):
        # the principal branch (If.typ) defines the type; the NULL
        # branch's zeros are cast to it (values there are masked anyway)
        if ev.dtype != tv.dtype:
            if expr._principal() == "then":
                ev = ev.astype(tv.dtype)
            else:
                tv = tv.astype(ev.dtype)
        vals = jnp.where(cond, tv, ev)
        nulls = jnp.where(cond, t.null_mask(), e.null_mask())
        return Evaled(vals, nulls, col)

    raise NotImplementedError(type(expr))


def _merge_nulls(l: Evaled, r: Evaled):
    if l.nulls is None and r.nulls is None:
        return None
    return jnp.logical_or(l.null_mask(), r.null_mask())


def _or_nulls(nulls, extra):
    if nulls is None:
        return extra
    return jnp.logical_or(nulls, extra)


def _round_half_away(v: jnp.ndarray, step: int, rescale: bool = False):
    """Round a scaled integer to a multiple of ``step``, half away from
    zero (pg numeric). ``rescale`` divides the result by step (narrowing
    a decimal's scale) instead of keeping the original scale."""
    mag = (jnp.abs(v) + step // 2) // step
    if not rescale:
        mag = mag * step
    return jnp.sign(v) * mag


def _as_float(e: Evaled) -> jnp.ndarray:
    if e.col.ctype is ColumnType.DECIMAL:
        return e.values.astype(jnp.float64) / (10.0**e.col.scale)
    return e.values.astype(jnp.float64)


def _coerce_comparable(l: Evaled, r: Evaled):
    """Align decimal scales / numeric types for comparison."""
    if (
        l.col.ctype is ColumnType.DECIMAL
        or r.col.ctype is ColumnType.DECIMAL
    ) and ColumnType.FLOAT64 not in (l.col.ctype, r.col.ctype):
        scale = max(l.col.scale, r.col.scale)
        return _to_decimal_scale(l, scale), _to_decimal_scale(r, scale)
    if ColumnType.FLOAT64 in (l.col.ctype, r.col.ctype):
        return _as_float(l), _as_float(r)
    return l.values, r.values


def _civil_from_days(days: jnp.ndarray):
    """Howard Hinnant's civil_from_days, vectorized: (year, month, day)
    int64 arrays from days-since-epoch (proleptic Gregorian)."""
    z = days + 719468
    era = jnp.where(z >= 0, z, z - 146096) // 146097
    doe = z - era * 146097
    yoe = (doe - doe // 1460 + doe // 36524 - doe // 146096) // 365
    y = yoe + era * 400
    doy = doe - (365 * yoe + yoe // 4 - yoe // 100)
    mp = (5 * doy + 2) // 153
    d = doy - (153 * mp + 2) // 5 + 1
    m = jnp.where(mp < 10, mp + 3, mp - 9)
    return jnp.where(m <= 2, y + 1, y), m, d


def _days_from_civil(y, m, d):
    """Inverse of _civil_from_days, vectorized (proleptic Gregorian)."""
    y = y - (m <= 2)
    era = jnp.where(y >= 0, y, y - 399) // 400
    yoe = y - era * 400
    mp = jnp.where(m > 2, m - 3, m + 9)
    doy = (153 * mp + 2) // 5 + d - 1
    doe = yoe * 365 + yoe // 4 - yoe // 100 + doy
    return era * 146097 + doe - 719468


_EXTRACT_INT_FUNCS = frozenset(
    {
        UnaryFunc.EXTRACT_YEAR,
        UnaryFunc.EXTRACT_MONTH,
        UnaryFunc.EXTRACT_DAY,
        UnaryFunc.EXTRACT_QUARTER,
        UnaryFunc.EXTRACT_DOW,
        UnaryFunc.EXTRACT_ISODOW,
        UnaryFunc.EXTRACT_DOY,
        UnaryFunc.EXTRACT_WEEK,
        UnaryFunc.EXTRACT_HOUR,
        UnaryFunc.EXTRACT_MINUTE,
        UnaryFunc.EXTRACT_MILLENNIUM,
        UnaryFunc.EXTRACT_CENTURY,
        UnaryFunc.EXTRACT_DECADE,
    }
)

_FLOAT_UNARY_FUNCS = frozenset(
    {
        UnaryFunc.SQRT,
        UnaryFunc.CBRT,
        UnaryFunc.EXP,
        UnaryFunc.LN,
        UnaryFunc.LOG10,
        UnaryFunc.LOG2,
        UnaryFunc.SIN,
        UnaryFunc.COS,
        UnaryFunc.TAN,
        UnaryFunc.ASIN,
        UnaryFunc.ACOS,
        UnaryFunc.ATAN,
        UnaryFunc.RADIANS,
        UnaryFunc.DEGREES,
    }
)

_MS_PER_DAY = 86_400_000


def _days_and_ms(e: Evaled):
    """(days-since-epoch, ms-of-day) for a DATE or TIMESTAMP input."""
    if e.col.ctype is ColumnType.TIMESTAMP:
        ms = e.values.astype(jnp.int64)
        return ms // _MS_PER_DAY, ms % _MS_PER_DAY
    return e.values.astype(jnp.int64), jnp.zeros_like(
        e.values, dtype=jnp.int64
    )


def _eval_extract(f: str, e: Evaled, col: Column) -> Evaled:
    days, msod = _days_and_ms(e)
    if f == UnaryFunc.EXTRACT_EPOCH:
        if e.col.ctype is ColumnType.TIMESTAMP:
            v = e.values.astype(jnp.float64) / 1000.0
        else:
            v = days.astype(jnp.float64) * 86400.0
        return Evaled(v, e.nulls, col)
    if f == UnaryFunc.EXTRACT_HOUR:
        return Evaled(msod // 3_600_000, e.nulls, col)
    if f == UnaryFunc.EXTRACT_MINUTE:
        return Evaled((msod // 60_000) % 60, e.nulls, col)
    if f == UnaryFunc.EXTRACT_SECOND:
        v = (msod % 60_000).astype(jnp.float64) / 1000.0
        return Evaled(v, e.nulls, col)
    if f == UnaryFunc.EXTRACT_DOW:
        # pg: Sunday=0..Saturday=6; 1970-01-01 was a Thursday
        return Evaled((days + 4) % 7, e.nulls, col)
    if f == UnaryFunc.EXTRACT_ISODOW:
        return Evaled((days + 3) % 7 + 1, e.nulls, col)
    y, m, d = _civil_from_days(days)
    if f == UnaryFunc.EXTRACT_YEAR:
        return Evaled(y, e.nulls, col)
    if f == UnaryFunc.EXTRACT_MONTH:
        return Evaled(m, e.nulls, col)
    if f == UnaryFunc.EXTRACT_DAY:
        return Evaled(d, e.nulls, col)
    if f == UnaryFunc.EXTRACT_QUARTER:
        return Evaled((m + 2) // 3, e.nulls, col)
    if f == UnaryFunc.EXTRACT_DOY:
        return Evaled(days - _days_from_civil(y, 1, 1) + 1, e.nulls, col)
    if f == UnaryFunc.EXTRACT_WEEK:
        # ISO 8601 week: the week containing this date's Thursday
        thursday = days + (3 - (days + 3) % 7)
        ty, _, _ = _civil_from_days(thursday)
        week = (thursday - _days_from_civil(ty, 1, 1)) // 7 + 1
        return Evaled(week, e.nulls, col)
    if f == UnaryFunc.EXTRACT_MILLENNIUM:
        return Evaled((y - 1) // 1000 + 1, e.nulls, col)
    if f == UnaryFunc.EXTRACT_CENTURY:
        return Evaled((y - 1) // 100 + 1, e.nulls, col)
    if f == UnaryFunc.EXTRACT_DECADE:
        return Evaled(y // 10, e.nulls, col)
    raise NotImplementedError(f)


def _eval_date_trunc(f: str, e: Evaled, col: Column) -> Evaled:
    days, msod = _days_and_ms(e)
    T = UnaryFunc
    if f in (T.DATE_TRUNC_YEAR, T.DATE_TRUNC_QUARTER, T.DATE_TRUNC_MONTH):
        y, m, _ = _civil_from_days(days)
        if f == T.DATE_TRUNC_YEAR:
            tdays = _days_from_civil(y, jnp.ones_like(m), jnp.ones_like(m))
        elif f == T.DATE_TRUNC_QUARTER:
            qm = 3 * ((m - 1) // 3) + 1
            tdays = _days_from_civil(y, qm, jnp.ones_like(m))
        else:
            tdays = _days_from_civil(y, m, jnp.ones_like(m))
        tmsod = jnp.zeros_like(msod)
    elif f == T.DATE_TRUNC_WEEK:
        tdays = days - (days + 3) % 7  # back to Monday
        tmsod = jnp.zeros_like(msod)
    elif f == T.DATE_TRUNC_DAY:
        tdays, tmsod = days, jnp.zeros_like(msod)
    else:
        step = {
            T.DATE_TRUNC_HOUR: 3_600_000,
            T.DATE_TRUNC_MINUTE: 60_000,
            T.DATE_TRUNC_SECOND: 1_000,
        }[f]
        tdays, tmsod = days, msod - msod % step
    if e.col.ctype is ColumnType.TIMESTAMP:
        return Evaled(tdays * _MS_PER_DAY + tmsod, e.nulls, col)
    return Evaled(tdays.astype(e.values.dtype), e.nulls, col)


def _eval_round_family(f: str, e: Evaled, col: Column) -> Evaled:
    T = UnaryFunc
    if e.col.ctype is ColumnType.FLOAT64:
        op = {
            T.FLOOR: jnp.floor,
            T.CEIL: jnp.ceil,
            T.TRUNC: jnp.trunc,
            T.ROUND: jnp.round,  # half-even, like pg float8
        }[f]
        return Evaled(op(e.values), e.nulls, col)
    if e.col.ctype is ColumnType.DECIMAL and e.col.scale > 0:
        step = 10**e.col.scale
        v = e.values
        if f == T.FLOOR:
            out = (v // step) * step
        elif f == T.CEIL:
            out = -((-v) // step) * step
        elif f == T.TRUNC:
            out = jnp.where(v >= 0, v // step, -((-v) // step)) * step
        else:  # ROUND: half away from zero, like pg numeric
            out = _round_half_away(v, step)
        return Evaled(out, e.nulls, col)
    return Evaled(e.values, e.nulls, col)  # integers unchanged


# Convenience helpers for building expressions in tests/plans.
def col(i: int) -> ColumnRef:
    return ColumnRef(i)


def lit(value, ctype: ColumnType | None = None, scale: int = 0) -> Literal:
    if ctype is None:
        return _lift(value)
    return Literal(value, ctype, scale)


def and_(*exprs) -> CallVariadic:
    return CallVariadic(VariadicFunc.AND, [_lift(e) for e in exprs])


def or_(*exprs) -> CallVariadic:
    return CallVariadic(VariadicFunc.OR, [_lift(e) for e in exprs])
