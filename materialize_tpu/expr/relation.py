"""Relation expressions (MIR).

Analog of the reference's ``MirRelationExpr`` — all 15 variants
(src/expr/src/relation.rs:100): Constant, Get, Let, LetRec, Project, Map,
FlatMap, Filter, Join, Reduce, TopK, Negate, Threshold, Union, ArrangeBy —
plus the aggregate function vocabulary (src/expr/src/relation/func.rs:1878
``AggregateFunc``). The optimizer (materialize_tpu.transform) rewrites
these; plan.lowering lowers them to LIR for rendering.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field
from typing import Any, Sequence

from ..repr.schema import Column, ColumnType, Schema
from .scalar import ScalarExpr


class AggregateFunc(enum.Enum):
    """Aggregates; accumulable ones fold into the diff field
    (render/reduce.rs:1357 Accum), hierarchical ones need tournament
    trees (render/reduce.rs:850)."""

    COUNT = "count"        # accumulable
    SUM_INT = "sum_int"    # accumulable (int64/decimal)
    SUM_FLOAT = "sum_float"  # accumulable (f64; non-deterministic order OK)
    MIN = "min"            # hierarchical
    MAX = "max"            # hierarchical
    ANY = "any"            # accumulable over bools (true count > 0)
    ALL = "all"            # accumulable (false count == 0)
    # Basic (collection) aggregates — the analog of the reference's
    # build_basic_aggregate tier (compute/src/render/reduce.rs:369;
    # StringAgg / ArrayConcat / ListConcat in expr/src/relation/
    # func.rs:1878). The maintained device state is the sorted
    # (group key, value) multiset plus an order-insensitive digest
    # accumulator for change detection; the variable-width result is
    # produced at the serving edge (Dataflow.peek) where a host
    # readback happens anyway — variable-width concatenation per step
    # would break the zero-readback hot loop. Values order by
    # dictionary code == lexicographic order, so the output is
    # deterministic (pg leaves un-ORDER BY'd aggs unspecified).
    STRING_AGG = "string_agg"  # basic: join with separator
    ARRAY_AGG = "array_agg"    # basic: pg-style {a,b,c} text rendering
    LIST_AGG = "list_agg"      # basic: mz list, same rendering

    @property
    def is_accumulable(self) -> bool:
        return self in (
            AggregateFunc.COUNT,
            AggregateFunc.SUM_INT,
            AggregateFunc.SUM_FLOAT,
            AggregateFunc.ANY,
            AggregateFunc.ALL,
        )

    @property
    def is_hierarchical(self) -> bool:
        return self in (AggregateFunc.MIN, AggregateFunc.MAX)

    @property
    def is_basic(self) -> bool:
        return self in (
            AggregateFunc.STRING_AGG,
            AggregateFunc.ARRAY_AGG,
            AggregateFunc.LIST_AGG,
        )

    @property
    def preserves_nulls(self) -> bool:
        """array_agg/list_agg keep NULL elements (pg semantics; the
        reference's SQL layer wraps each value in ArrayCreate before
        ArrayConcat so NULLs survive, sql/src/func.rs:3668).
        string_agg drops them."""
        return self in (AggregateFunc.ARRAY_AGG, AggregateFunc.LIST_AGG)


@dataclass(frozen=True)
class AggregateExpr:
    """func applied to a scalar expression over the group
    (reference: expr AggregateExpr {func, expr, distinct})."""

    func: AggregateFunc
    expr: ScalarExpr
    distinct: bool = False
    # Host-side parameters (e.g. string_agg's separator TEXT). Part of
    # the plan, not a scalar input: basic-aggregate finalization runs at
    # the serving edge on the host.
    params: tuple = ()

    def output_col(self, input_schema: Schema) -> Column:
        inner = self.expr.typ(input_schema)
        if self.func is AggregateFunc.COUNT:
            return Column("count", ColumnType.INT64, False)
        if self.func is AggregateFunc.SUM_INT:
            return Column("sum", inner.ctype, True, inner.scale)
        if self.func is AggregateFunc.SUM_FLOAT:
            return Column("sum", ColumnType.FLOAT64, True)
        if self.func in (AggregateFunc.MIN, AggregateFunc.MAX):
            return Column(
                self.func.value, inner.ctype, True, inner.scale
            )
        if self.func in (AggregateFunc.ANY, AggregateFunc.ALL):
            return Column(self.func.value, ColumnType.BOOL, True)
        if self.func.is_basic:
            # The device column carries an opaque change-detection
            # digest until edge finalization substitutes the encoded
            # result string (ops/reduce.py basic tier).
            return Column(self.func.value, ColumnType.STRING, True)
        raise NotImplementedError(self.func)


class RelationExpr:
    """Base class for MIR relation expressions."""

    def schema(self) -> Schema:
        raise NotImplementedError

    def children(self) -> list["RelationExpr"]:
        return []

    # builder sugar
    def project(self, outputs: Sequence[int]) -> "Project":
        return Project(self, tuple(outputs))

    def map(self, exprs: Sequence[ScalarExpr]) -> "Map":
        return Map(self, tuple(exprs))

    def filter(self, preds: Sequence[ScalarExpr]) -> "Filter":
        return Filter(self, tuple(preds))

    def reduce(self, group_key, aggregates) -> "Reduce":
        return Reduce(self, tuple(group_key), tuple(aggregates))

    def distinct(self) -> "Reduce":
        return Reduce(
            self, tuple(range(self.schema().arity)), ()
        )

    def negate(self) -> "Negate":
        return Negate(self)

    def threshold(self) -> "Threshold":
        return Threshold(self)

    def union(self, *others) -> "Union":
        return Union((self, *others))

    def arrange_by(self, key) -> "ArrangeBy":
        return ArrangeBy(self, tuple(key))


@dataclass(frozen=True)
class Constant(RelationExpr):
    """Literal collection: rows with diffs (relation.rs Constant)."""

    rows: tuple  # tuple of (row_tuple, diff)
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class Get(RelationExpr):
    """Reference to a named collection (source, index, or let binding)."""

    name: str
    _schema: Schema

    def schema(self):
        return self._schema


@dataclass(frozen=True)
class Let(RelationExpr):
    name: str
    value: RelationExpr
    body: RelationExpr

    def schema(self):
        return self.body.schema()

    def children(self):
        return [self.value, self.body]


@dataclass(frozen=True)
class LetRec(RelationExpr):
    """WITH MUTUALLY RECURSIVE: bindings may reference each other and
    themselves; semantics are per-binding fixpoint iteration
    (relation.rs LetRec, rendered at compute render.rs:887)."""

    names: tuple  # binding names
    values: tuple  # RelationExpr per binding (may Get any binding name)
    value_schemas: tuple  # declared schema per binding
    body: RelationExpr
    # Iteration cap (reference LetRecLimit / RETURN AT RECURSION LIMIT,
    # expr/src/relation.rs LetRec limits). None = run to fixpoint.
    max_iters: int | None = None

    def schema(self):
        return self.body.schema()

    def children(self):
        return list(self.values) + [self.body]


@dataclass(frozen=True)
class Project(RelationExpr):
    input: RelationExpr
    outputs: tuple

    def schema(self):
        return self.input.schema().project(self.outputs)

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Map(RelationExpr):
    input: RelationExpr
    scalars: tuple

    def schema(self):
        cols = list(self.input.schema().columns)
        for e in self.scalars:
            c = e.typ(Schema(cols))
            cols.append(Column(f"c{len(cols)}", c.ctype, c.nullable, c.scale))
        return Schema(cols)

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class FlatMap(RelationExpr):
    """Table function application (unnest, generate_series...)."""

    input: RelationExpr
    func: str
    exprs: tuple
    output_cols: tuple  # Columns appended by the table function

    def schema(self):
        return Schema(
            tuple(self.input.schema().columns) + tuple(self.output_cols)
        )

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Filter(RelationExpr):
    input: RelationExpr
    predicates: tuple

    def schema(self):
        return self.input.schema()

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Join(RelationExpr):
    """Multiway equi-join. equivalences: classes of scalar expressions
    (over the concatenated columns of all inputs) asserted equal
    (relation.rs Join; the optimizer picks Linear vs Delta plans,
    transform/src/join_implementation.rs)."""

    inputs: tuple
    equivalences: tuple  # tuple of tuples of ScalarExpr
    # "auto" | "linear" | "delta" — JoinImplementation's decision
    # (transform/src/join_implementation.rs). auto: delta for >=3 inputs
    # (the delta join's sweet spot; delta_join.rs:10-12), linear for 2.
    implementation: str = "auto"

    def schema(self):
        cols = []
        for inp in self.inputs:
            cols.extend(inp.schema().columns)
        return Schema(cols)

    def children(self):
        return list(self.inputs)


@dataclass(frozen=True)
class Reduce(RelationExpr):
    input: RelationExpr
    group_key: tuple  # column indices (simple keys; exprs pre-mapped)
    aggregates: tuple  # AggregateExpr

    def schema(self):
        in_schema = self.input.schema()
        cols = [in_schema[i] for i in self.group_key]
        for j, agg in enumerate(self.aggregates):
            c = agg.output_col(in_schema)
            cols.append(Column(f"{c.name}_{j}", c.ctype, c.nullable, c.scale))
        return Schema(cols)

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class TopK(RelationExpr):
    """Per-group top-k by ordering (relation.rs TopK; plans at
    compute-types/src/plan/top_k.rs:28)."""

    input: RelationExpr
    group_key: tuple
    order_by: tuple  # (col_index, desc: bool, nulls_last: bool)
    limit: int | None
    offset: int = 0

    def schema(self):
        return self.input.schema()

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Negate(RelationExpr):
    input: RelationExpr

    def schema(self):
        return self.input.schema()

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Threshold(RelationExpr):
    """Keep rows with positive multiplicity (render/threshold.rs)."""

    input: RelationExpr

    def schema(self):
        return self.input.schema()

    def children(self):
        return [self.input]


@dataclass(frozen=True)
class Union(RelationExpr):
    inputs: tuple

    def schema(self):
        # Names/ctypes/scales come from branch 0; NULLABILITY is the
        # least upper bound across branches. Outer-join and
        # scalar-subquery lowerings build unions whose NULL-padding
        # branch is nullable while branch 0 is not — deriving the
        # schema from branch 0 alone claimed non-nullable columns that
        # carry NULLs, which let column_knowledge fold IS_NULL(col) to
        # false unsoundly (found by analysis/typecheck.py T-SCHEMA
        # over the SLT corpus). Memoized: the lub walks EVERY branch,
        # and lowerings nest union towers whose repeated schema() calls
        # would otherwise be quadratic in the tower depth. The node is
        # frozen/immutable, so the cache can never go stale.
        memo = self.__dict__.get("_schema_memo")
        if memo is not None:
            return memo
        base = self.inputs[0].schema()
        cols = list(base.columns)
        for inp in self.inputs[1:]:
            for i, c in enumerate(inp.schema().columns):
                if i < len(cols) and c.nullable and not cols[i].nullable:
                    old = cols[i]
                    cols[i] = Column(old.name, old.ctype, True, old.scale)
        sch = Schema(tuple(cols))
        object.__setattr__(self, "_schema_memo", sch)
        return sch

    def __getstate__(self):
        # The memo must not leak into pickled state:
        # DataflowDescription.fingerprint() pickles the expr, and
        # replica reconciliation compares fingerprints byte-for-byte —
        # a cache populated on one side but not the other would make an
        # unchanged dataflow look changed and trigger a full rebuild.
        d = dict(self.__dict__)
        d.pop("_schema_memo", None)
        return d

    def children(self):
        return list(self.inputs)


@dataclass(frozen=True)
class ArrangeBy(RelationExpr):
    """Assert arrangement by key (relation.rs ArrangeBy)."""

    input: RelationExpr
    key: tuple  # column indices

    def schema(self):
        return self.input.schema()

    def children(self):
        return [self.input]
