"""Rewrite string-dictionary codes embedded in MIR after a rebalance.

A ``StringDictionary.rebalance()`` (repr/schema.py) relabels every code.
Installed ``DataflowDescription``s hold MIR whose string ``Literal``s and
``Constant`` rows carry OLD codes; before rebuilding dataflows from those
descriptions, the codes must be remapped. Durable state needs no rewrite
(persist parts store actual strings, storage/persist/codec.py) — this is
purely a host-side fixup of in-memory plans.
"""

from __future__ import annotations

import dataclasses

from ..repr.schema import ColumnType
from . import relation as mir
from . import scalar as ms


def remap_scalar(e, remap: dict):
    if isinstance(e, ms.Literal):
        if (
            e.ctype is ColumnType.STRING
            and e.value is not None
            and int(e.value) in remap
        ):
            return ms.Literal(remap[int(e.value)], e.ctype, e.scale)
        return e
    if not dataclasses.is_dataclass(e):
        return e
    changes = {}
    for f in dataclasses.fields(e):
        v = getattr(e, f.name)
        if isinstance(v, ms.ScalarExpr):
            nv = remap_scalar(v, remap)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple) and any(
            isinstance(x, ms.ScalarExpr) for x in v
        ):
            nv = tuple(
                remap_scalar(x, remap)
                if isinstance(x, ms.ScalarExpr)
                else x
                for x in v
            )
            if nv != v:
                changes[f.name] = nv
    return dataclasses.replace(e, **changes) if changes else e


def _remap_aggregate(a, remap: dict):
    ne = remap_scalar(a.expr, remap)
    return dataclasses.replace(a, expr=ne) if ne is not a.expr else a


def remap_relation(expr, remap: dict):
    """Return ``expr`` with every embedded string code remapped."""
    if isinstance(expr, mir.Constant):
        str_cols = [
            i
            for i, c in enumerate(expr._schema.columns)
            if c.ctype is ColumnType.STRING
        ]
        if not str_cols or not expr.rows:
            return expr
        new_rows = []
        for vals, diff in expr.rows:
            vals = tuple(
                remap.get(int(v), v)
                if i in str_cols and v is not None
                else v
                for i, v in enumerate(vals)
            )
            new_rows.append((vals, diff))
        return mir.Constant(tuple(new_rows), expr._schema)
    if not dataclasses.is_dataclass(expr):
        return expr
    changes = {}
    for f in dataclasses.fields(expr):
        v = getattr(expr, f.name)
        if isinstance(v, mir.RelationExpr):
            nv = remap_relation(v, remap)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, mir.AggregateExpr):
            nv = _remap_aggregate(v, remap)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, ms.ScalarExpr):
            nv = remap_scalar(v, remap)
            if nv is not v:
                changes[f.name] = nv
        elif isinstance(v, tuple):
            nv = tuple(
                remap_relation(x, remap)
                if isinstance(x, mir.RelationExpr)
                else _remap_aggregate(x, remap)
                if isinstance(x, mir.AggregateExpr)
                else remap_scalar(x, remap)
                if isinstance(x, ms.ScalarExpr)
                else tuple(
                    remap_scalar(y, remap)
                    if isinstance(y, ms.ScalarExpr)
                    else y
                    for y in x
                )
                if isinstance(x, tuple)
                else x
                for x in v
            )
            if nv != v:
                changes[f.name] = nv
    return dataclasses.replace(expr, **changes) if changes else expr
