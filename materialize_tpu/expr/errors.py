"""Error streams: the ok/err collection pair, TPU-cast.

The reference renders every collection as parallel ok/err trees
(compute/src/render.rs:12-101): a division by zero inside a maintained
view produces an error VALUE in the err collection, surfaced as a SQL
error on read, and retracts when the offending row is deleted.

TPU re-cast: scalar evaluation sites (ops on data-dependent domains:
division, casts) publish per-row error masks into a trace-scoped
collector; the step function unions them into error update rows
``(err_code, time, diff)`` maintained in a SECOND output arrangement next
to the data output. Reads consult it first: nonempty => SQL error (the
reference "picks an arbitrary error if errs nonempty"). Deleting the
offending row feeds the same mask with diff=-1, retracting the error.

Scope (documented): errors are detected inside MFP evaluation (Map /
Filter / Project sites in render) — the places SQL expressions run over
arbitrary data. Aggregate-internal expression errors are future work.
"""

from __future__ import annotations

import contextlib
import threading

import jax.numpy as jnp

# error codes (repr: EvalError analog, expr/src/scalar.rs EvalError)
DIVISION_BY_ZERO = 1
NUMERIC_OUT_OF_RANGE = 2

MESSAGES = {
    DIVISION_BY_ZERO: "division by zero",
    NUMERIC_OUT_OF_RANGE: "numeric field overflow",
}

# Fixed-shape error-count vectors (LetRec carries one through its
# while_loop) are indexed by code; codes must stay small and dense.
N_CODES = max(MESSAGES) + 1


_tls = threading.local()


def _sinks() -> list:
    if not hasattr(_tls, "sinks"):
        _tls.sinks = []
    return _tls.sinks


@contextlib.contextmanager
def collect():
    """Activate an error sink for the dynamic extent (trace time): eval
    sites inside publish (code, mask) pairs via :func:`emit`. Yields the
    sink list of (code, mask) tuples."""
    sink: list = []
    _sinks().append(sink)
    try:
        yield sink
    finally:
        _sinks().pop()


def emit(code: int, mask) -> None:
    """Publish a per-row error mask (True where the row's evaluation
    errored). No-op when no sink is active — evaluation outside a
    collecting step (tests, oracles) keeps the historical
    NULL-on-error behavior."""
    s = _sinks()
    if s:
        s[-1].append((code, jnp.asarray(mask)))


def active() -> bool:
    return bool(_sinks())


# -- step-level error-batch sink ---------------------------------------------
# apply_mfp converts (code, mask) pairs into error update batches and
# pushes them here; the step function unions + consolidates them into
# the dataflow's error output arrangement.


def _step_sinks() -> list:
    if not hasattr(_tls, "step_sinks"):
        _tls.step_sinks = []
    return _tls.step_sinks


@contextlib.contextmanager
def step_scope():
    sink: list = []
    _step_sinks().append(sink)
    try:
        yield sink
    finally:
        _step_sinks().pop()


def push_step(err_batch) -> None:
    s = _step_sinks()
    if s:
        s[-1].append(err_batch)


def step_active() -> bool:
    return bool(_step_sinks())
