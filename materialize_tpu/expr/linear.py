"""MapFilterProject: the fused linear operator.

Analog of the reference's ``MapFilterProject`` / ``MfpPlan``
(src/expr/src/linear.rs:45,1724): a sequence of scalar expressions appended
as new columns (map), predicates that drop rows (filter), and a final
column selection (project). MFPs are pushed into sources, joins, and every
render node; on TPU the whole MFP fuses into one XLA computation over the
batch, ending in a scatter compaction for the filter.

Temporal predicates on ``mz_now()`` (linear.rs:404-408) live in
ops/temporal.py (TemporalFilterOp): the render layer splits them out of
Filter nodes; plain (non-comparison) mz_now() uses evaluate here via the
``time`` argument.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax.numpy as jnp

from ..repr.batch import Batch
from ..repr.schema import Column, Schema
from ..ops.sort import compact
from .scalar import ColumnRef, Evaled, ScalarExpr, eval_expr


@dataclass(frozen=True)
class MapFilterProject:
    """input_arity -> map expressions -> predicates -> projection.

    Expressions may reference input columns and previously mapped columns
    (by position input_arity + i), exactly like the reference
    (linear.rs MapFilterProject docs)."""

    input_arity: int
    expressions: tuple = ()
    predicates: tuple = ()
    projection: tuple | None = None  # None = identity over all columns

    def __init__(self, input_arity, expressions=(), predicates=(), projection=None):
        object.__setattr__(self, "input_arity", input_arity)
        object.__setattr__(self, "expressions", tuple(expressions))
        object.__setattr__(self, "predicates", tuple(predicates))
        object.__setattr__(
            self,
            "projection",
            tuple(projection) if projection is not None else None,
        )

    @property
    def is_identity(self) -> bool:
        return (
            not self.expressions
            and not self.predicates
            and (
                self.projection is None
                or self.projection == tuple(range(self.input_arity))
            )
        )

    def output_schema(self, schema: Schema) -> Schema:
        full = list(schema.columns)
        for e in self.expressions:
            full.append(e.typ(Schema(full)))
        proj = (
            self.projection
            if self.projection is not None
            else range(len(full))
        )
        cols = []
        for i, j in enumerate(proj):
            c = full[j]
            cols.append(Column(f"c{i}" if c.name == "f" else c.name,
                               c.ctype, c.nullable, c.scale))
        return Schema(cols)


def apply_mfp(mfp: MapFilterProject, batch: Batch, time=None) -> Batch:
    """Evaluate the MFP over a batch: fused map+filter+project, compacted.
    ``time`` is the step timestamp for mz_now() (non-temporal uses).

    Scalar evaluation errors (division by zero, cast overflow) are
    published as error update rows to the active error sink (the step's
    err collection — expr/errors.py, the render.rs ok/err analog); with
    no sink active, erroring rows keep the historical NULL result."""
    assert batch.schema.arity == mfp.input_arity, (
        f"mfp arity {mfp.input_arity} != batch arity {batch.schema.arity}"
    )
    if mfp.is_identity:
        return batch
    from . import errors as _errors

    with _errors.collect() as masks:
        out = _apply_mfp_inner(mfp, batch, time)
    if masks and _errors.step_active():
        valid = batch.valid_mask()
        for code, mask in masks:
            _errors.push_step(
                _err_batch(code, jnp.logical_and(mask, valid), batch)
            )
    return out


def _err_batch(code: int, mask, batch: Batch) -> Batch:
    """Error update rows: (err_code, time, diff) for masked rows."""
    from ..repr.schema import ERR_SCHEMA

    cap = batch.capacity
    return Batch(
        cols=(jnp.full(cap, code, dtype=jnp.int64),),
        nulls=(None,),
        time=batch.time,
        diff=jnp.where(mask, batch.diff, 0),
        count=batch.count,
        schema=ERR_SCHEMA,
    )


def _apply_mfp_inner(mfp: MapFilterProject, batch: Batch, time=None) -> Batch:

    # Working set: input columns + mapped columns, with growing schema.
    work_cols = list(batch.cols)
    work_nulls = list(batch.nulls)
    work_schema = list(batch.schema.columns)
    for e in mfp.expressions:
        tmp = Batch(
            cols=tuple(work_cols),
            nulls=tuple(work_nulls),
            time=batch.time,
            diff=batch.diff,
            count=batch.count,
            schema=Schema(work_schema),
        )
        ev = eval_expr(e, tmp, time)
        work_cols.append(ev.values)
        work_nulls.append(ev.nulls)
        work_schema.append(ev.col)

    full = Batch(
        cols=tuple(work_cols),
        nulls=tuple(work_nulls),
        time=batch.time,
        diff=batch.diff,
        count=batch.count,
        schema=Schema(work_schema),
    )

    # Filter: predicate TRUE (not false, not NULL) keeps the row.
    # Predicates short-circuit left-to-right for ERRORS (the reference's
    # MfpPlan stops at the first false predicate per row): a predicate's
    # evaluation errors only count for rows every EARLIER predicate
    # kept. Map expressions above evaluated unconditionally, as in the
    # reference.
    from . import errors as _errors

    keep = None
    for p in mfp.predicates:
        with _errors.collect() as pmasks:
            ev = eval_expr(p, full, time)
        for code, mask in pmasks:
            _errors.emit(
                code,
                mask if keep is None else jnp.logical_and(mask, keep),
            )
        ok = jnp.logical_and(ev.values, jnp.logical_not(ev.null_mask()))
        keep = ok if keep is None else jnp.logical_and(keep, ok)

    # Project.
    proj = (
        mfp.projection
        if mfp.projection is not None
        else tuple(range(len(work_schema)))
    )
    out_schema = mfp.output_schema(batch.schema)
    projected = Batch(
        cols=tuple(work_cols[j] for j in proj),
        nulls=tuple(work_nulls[j] for j in proj),
        time=batch.time,
        diff=batch.diff,
        count=batch.count,
        schema=out_schema,
    )
    if keep is None:
        return projected
    return compact(projected, keep)
