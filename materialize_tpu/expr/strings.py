"""String functions as dictionary side-tables.

Strings live host-side in the GLOBAL_DICT; device columns hold int32
codes (repr/schema.py). A string function over a column therefore
becomes a GATHER through a precomputed mapping array: for ``upper``,
``map[code] = encode(upper(decode(code)))`` — the function is applied
once per distinct string on the host, and the device does an O(n)
gather. This is the TPU-native analog of the reference's row-at-a-time
string function library (expr/src/scalar/func/impls/string.rs): the
dictionary IS the loop.

Mechanics: rendering collects the set of (func, params) keys used by a
dataflow's expressions; each step passes an ``env`` of mapping arrays
(one per key, padded to a power-of-two tier of the dictionary size) as
jit inputs, so arrays grow with the dictionary without retracing until
the tier changes. Inside the traced step, eval_expr reads the current
env through a trace-scope contextvar.

Ordering: dictionary codes are order-preserving labels
(repr/schema.py StringDictionary), so string comparisons, ORDER BY,
MIN/MAX, and TopK all operate on codes directly — no rank table.
"""

from __future__ import annotations

import contextlib
import contextvars
import re

import jax.numpy as jnp
import numpy as np

from ..repr.batch import capacity_tier
from ..repr.schema import GLOBAL_DICT

_TRACE_ENV: contextvars.ContextVar = contextvars.ContextVar(
    "mt_string_env", default=None
)


@contextlib.contextmanager
def trace_scope(env: dict):
    tok = _TRACE_ENV.set(env)
    try:
        yield
    finally:
        _TRACE_ENV.reset(tok)


def trace_env() -> dict:
    env = _TRACE_ENV.get()
    if env is None:
        raise RuntimeError(
            "string function evaluated outside a dataflow step with a "
            "string env (Dataflow passes it; direct eval_expr callers "
            "must wrap in strings.trace_scope(strings.build_env(keys)))"
        )
    return env


def env_key(func: str, *params) -> str:
    return "\x00".join([func] + [str(p) for p in params])


# -- host-side table computation ---------------------------------------------


def _like_regex(pattern: str, case_insensitive: bool) -> "re.Pattern":
    out = []
    i = 0
    while i < len(pattern):
        ch = pattern[i]
        if ch == "%":
            out.append(".*")
        elif ch == "_":
            out.append(".")
        elif ch == "\\" and i + 1 < len(pattern):
            out.append(re.escape(pattern[i + 1]))
            i += 1
        else:
            out.append(re.escape(ch))
        i += 1
    return re.compile(
        "(?s)" + "".join(out) + r"\Z",
        re.IGNORECASE if case_insensitive else 0,
    )


def _apply(func: str, params: tuple, s: str):
    """One string through one function (the host-side scalar kernel)."""
    if func == "upper":
        return s.upper()
    if func == "lower":
        return s.lower()
    if func == "trim":
        return s.strip(params[0]) if params else s.strip()
    if func == "ltrim":
        return s.lstrip(params[0]) if params else s.lstrip()
    if func == "rtrim":
        return s.rstrip(params[0]) if params else s.rstrip()
    if func == "initcap":
        return re.sub(
            r"[a-zA-Z0-9]+", lambda m: m.group(0).capitalize(), s
        )
    if func == "reverse":
        return s[::-1]
    if func == "length":
        return len(s)
    if func == "ascii":
        return ord(s[0]) if s else 0
    if func == "bit_length":
        return 8 * len(s.encode())
    if func == "octet_length":
        return len(s.encode())
    if func == "substr":
        start = int(params[0])
        # SQL substr is 1-based; start may be <= 0 (pg semantics)
        if len(params) > 1:
            n = int(params[1])
            end = start + n
            return s[max(start - 1, 0) : max(end - 1, 0)]
        return s[max(start - 1, 0) :]
    if func == "left":
        n = int(params[0])
        return s[:n] if n >= 0 else s[: len(s) + n]
    if func == "right":
        n = int(params[0])
        if n >= 0:
            return s[max(len(s) - n, 0) :] if n else ""
        return s[-n:]
    if func == "replace":
        return s.replace(params[0], params[1])
    if func == "concat_r":  # col || literal
        return s + params[0]
    if func == "concat_l":  # literal || col
        return params[0] + s
    if func == "lpad":
        n = int(params[0])
        fill = params[1] if len(params) > 1 else " "
        if len(s) >= n:
            return s[:n]
        pad = (fill * n)[: n - len(s)]
        return pad + s
    if func == "rpad":
        n = int(params[0])
        fill = params[1] if len(params) > 1 else " "
        if len(s) >= n:
            return s[:n]
        return s + (fill * n)[: n - len(s)]
    if func == "like":
        return bool(_like_regex(params[0], False).match(s))
    if func == "ilike":
        return bool(_like_regex(params[0], True).match(s))
    if func == "regex":
        return re.search(params[0], s) is not None
    if func == "position":
        return s.find(params[0]) + 1  # 0 when absent (pg)
    if func == "split_part":
        parts = s.split(params[0])
        i = int(params[1])
        return parts[i - 1] if 1 <= i <= len(parts) else ""
    raise NotImplementedError(func)


# result kind per function: code->code ("str"), ->int64, ->bool
RESULT_KINDS = {
    "upper": "str", "lower": "str", "trim": "str", "ltrim": "str",
    "rtrim": "str", "initcap": "str", "reverse": "str", "substr": "str",
    "left": "str", "right": "str", "replace": "str", "concat_r": "str",
    "concat_l": "str", "lpad": "str", "rpad": "str", "split_part": "str",
    "length": "int", "ascii": "int", "bit_length": "int",
    "octet_length": "int", "position": "int",
    "like": "bool", "ilike": "bool", "regex": "bool",
}


class _EnvCache:
    """Host cache: key -> (labels, values) np arrays, padded to a
    power-of-two tier of the dictionary size. Codes are SPARSE
    order-preserving labels (StringDictionary), so a table is a sorted
    label array + parallel values; the device lookup is
    searchsorted(labels, code) -> gather. Rebuilt when the dictionary
    version moves (growth only appends pairs, but label order is not
    insertion order, so the sorted arrays are rebuilt wholesale —
    dictionary sizes are host-trivial)."""

    def __init__(self):
        self._tables: dict[str, tuple] = {}
        self._version: dict[str, int] = {}
        # per-key computed results: label -> value. _apply (the Python
        # scalar kernel, possibly regex) runs ONCE per (key, string)
        # ever; dictionary growth only computes the NEW strings and
        # re-sorts arrays with numpy (streaming workloads stay
        # O(new strings) Python work per step, not O(dict)).
        self._done: dict[str, dict] = {}
        self._epoch = 0

    def table(self, key: str) -> tuple:
        # Optimistic epoch validation: the build runs UNLOCKED (it can
        # hold O(dict) Python regex work — taking the dictionary lock
        # for its duration would stall every concurrent decode/encode),
        # then re-checks the epoch under the lock. A rebalance that
        # interleaved with the build (epoch moved) would have produced
        # tables mixing old and new labels against device arrays still
        # holding old codes (garbage gathers) — those are discarded and
        # the build retried under the new labeling.
        while True:
            built = self._table_once(key)
            with GLOBAL_DICT.lock():
                if self._epoch == GLOBAL_DICT.epoch:
                    return built
            # epoch moved mid-build: reset and retry
            self._tables.clear()
            self._version.clear()
            self._done.clear()
            self._epoch = GLOBAL_DICT.epoch

    def _table_once(self, key: str) -> tuple:
        # A rebalance relabeled every code: tables (label arrays) and
        # done maps (keyed by label, str-kind values are labels too)
        # are all garbage. Full reset.
        if self._epoch != GLOBAL_DICT.epoch:
            self._tables.clear()
            self._version.clear()
            self._done.clear()
            self._epoch = GLOBAL_DICT.epoch
        parts = key.split("\x00")
        func, params = parts[0], tuple(parts[1:])
        kind = RESULT_KINDS[func]
        dtype = {
            "str": np.int64, "int": np.int64, "bool": np.bool_
        }[kind]
        cached = self._tables.get(key)
        if cached is not None and self._version.get(key) == (
            GLOBAL_DICT.version
        ):
            return cached
        done = self._done.setdefault(key, {})
        # Version BEFORE the build: encoding 'str'-kind results below
        # grows the dictionary, and the table only covers the pre-build
        # snapshot — stamping the post-build version would make the next
        # build_env pass treat this stale table as current (self-nested
        # calls like upper(upper(x)) then gather garbage).
        pre_version = GLOBAL_DICT.version
        pairs = GLOBAL_DICT.items_sorted()  # snapshot
        todo = [(c, s) for c, s in pairs if c not in done]
        if kind == "str":
            # Two-phase: compute every result first, BULK-insert the
            # new strings (positional gap division — one-at-a-time
            # content interpolation packs long-common-prefix result
            # families into slivers and exhausts gaps; encode_bulk
            # divides each gap evenly by run length), then map.
            results = [
                (c, _apply(func, params, s)) for c, s in todo
            ]
            GLOBAL_DICT.encode_bulk([v for _, v in results])
            for c, v in results:
                done[c] = GLOBAL_DICT.encode(v)
        else:
            for c, s in todo:
                done[c] = _apply(func, params, s)
        n = len(pairs)
        tier = capacity_tier(max(n, 1))
        labels = np.full(tier, GLOBAL_DICT.MAX_LABEL, dtype=np.int64)
        labels[:n] = [c for c, _ in pairs]
        values = np.zeros(tier, dtype=dtype)
        values[:n] = [done[c] for c, _ in pairs]
        self._tables[key] = (labels, values)
        self._version[key] = pre_version
        return self._tables[key]


_CACHE = _EnvCache()


def build_env(keys, depth: int = 1) -> dict:
    """Mapping tables for the given keys at the current dictionary
    state (device-transferred by the caller as jit inputs): each env
    entry is a (sorted_labels, values) pair.

    ``depth`` is the maximum nesting depth of string calls in the
    dataflow's expressions (collect_keys reports it): a chained
    upper(trim(x)) needs the ``upper`` table to cover ``trim``'s RESULT
    strings, so tables are rebuilt depth times. A dictionary-size
    fixpoint would NOT terminate — generative functions (concat) grow
    the dictionary on every pass when applied to their own outputs."""
    fn_keys = sorted(set(keys))
    tables: dict = {}
    for _ in range(max(1, depth)):
        tables = {k: _CACHE.table(k) for k in fn_keys}
    return {
        k: (jnp.asarray(l), jnp.asarray(v))
        for k, (l, v) in tables.items()
    }


def lookup(table: tuple, codes):
    """Device-side table lookup: searchsorted over the sorted label
    array, then gather. Valid codes always hit exactly (tables cover
    the whole dictionary); padding rows gather garbage that downstream
    validity masks drop."""
    labels, values = table
    idx = jnp.searchsorted(labels, codes)
    return values[jnp.clip(idx, 0, values.shape[0] - 1)]


# -- render-time key collection ----------------------------------------------


def collect_keys(rel) -> tuple:
    """(keys, depth) for a MIR relation tree's expressions: the
    'str:*' function keys and
    the maximum string-call nesting depth (build_env pass count).
    Called by the render layer so each Dataflow's step only carries the
    tables it uses."""
    from ..repr.schema import ColumnType
    from . import relation as mir
    from . import scalar as ms

    keys: set = set()
    max_depth = [0]

    def str_depth(e) -> int:
        d = 0
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, ms.ScalarExpr):
                d = max(d, str_depth(v))
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, ms.ScalarExpr):
                        d = max(d, str_depth(x))
        if isinstance(e, ms.CallVariadic) and e.func.startswith(
            ms.STRING_FUNC_PREFIX
        ):
            d += 1
        return d

    def walk_scalar(e, schema):
        if isinstance(e, ms.CallVariadic) and e.func.startswith(
            ms.STRING_FUNC_PREFIX
        ):
            fn = e.func[len(ms.STRING_FUNC_PREFIX):]
            keys.add(ms._string_func_key(fn, e.exprs[1:]))
            max_depth[0] = max(max_depth[0], str_depth(e))
        for f in getattr(e, "__dataclass_fields__", {}):
            v = getattr(e, f)
            if isinstance(v, ms.ScalarExpr):
                walk_scalar(v, schema)
            elif isinstance(v, tuple):
                for x in v:
                    if isinstance(x, ms.ScalarExpr):
                        walk_scalar(x, schema)

    def walk(node):
        for ch in node.children():
            walk(ch)
        if isinstance(node, mir.Map):
            sch = node.input.schema()
            for e in node.scalars:
                walk_scalar(e, sch)
        elif isinstance(node, mir.Filter):
            sch = node.input.schema()
            for e in node.predicates:
                walk_scalar(e, sch)
        elif isinstance(node, mir.Join):
            sch = node.schema()
            for cls in node.equivalences:
                for e in cls:
                    walk_scalar(e, sch)
        elif isinstance(node, mir.Reduce):
            sch = node.input.schema()
            for a in node.aggregates:
                walk_scalar(a.expr, sch)
        elif isinstance(node, mir.FlatMap):
            sch = node.input.schema()
            for f in getattr(node, "__dataclass_fields__", {}):
                v = getattr(node, f)
                if isinstance(v, tuple):
                    for x in v:
                        if isinstance(x, ms.ScalarExpr):
                            walk_scalar(x, sch)

    walk(rel)
    return keys, max_depth[0]
