"""Render: MIR relation expressions -> one jitted XLA step function.

Analog of the reference's render layer (compute/src/render.rs:202
``build_compute_dataflow``, :1155 ``render_plan_expr``), re-cast for TPU:
instead of building a graph of timely operators that run cooperatively,
rendering builds ONE pure function

    step(states, inputs, time) -> (output_delta, new_states, overflows)

that XLA compiles once per capacity signature and the host calls per
micro-batch (barrier-synchronous execution, SURVEY.md §7 design stance).
Stateful operators (Reduce, and later Join/TopK/Threshold) own slots in
the `states` tuple (Arrangements). Capacity overflow is detected on device
and resolved host-side by growing the state tier and retrying the step —
the compile-cache-per-capacity-tier scheme.

The ``Dataflow`` wrapper owns the host side: frontier/time advancement,
jit caching, overflow retries, and the output arrangement serving peeks
(the TraceManager + handle_peek analog, compute/src/compute_state.rs:744).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np

from ..arrangement.spine import Arrangement, arrange, insert
from ..expr import relation as mir
from ..expr.linear import MapFilterProject, apply_mfp
from ..ops.consolidate import consolidate
from ..ops.reduce import ReduceAccumulable
from ..repr.batch import Batch, capacity_tier
from ..repr.schema import Schema


def concat_batches(batches: list[Batch]) -> Batch:
    """Concatenate batches of the same schema (capacity = sum of caps).
    Valid rows are NOT contiguous across parts, so this compacts."""
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    cap = sum(b.capacity for b in batches)

    def cat(field):
        parts = [field(b) for b in batches]
        if any(p is None for p in parts):
            parts = [
                p
                if p is not None
                else jnp.zeros(b.capacity, dtype=bool)
                for p, b in zip(parts, batches)
            ]
        return jnp.concatenate(parts)

    keep = jnp.concatenate([b.valid_mask() for b in batches])
    out = Batch(
        cols=tuple(
            cat(lambda b, i=i: b.cols[i]) for i in range(schema.arity)
        ),
        nulls=tuple(
            (
                None
                if all(b.nulls[i] is None for b in batches)
                else cat(lambda b, i=i: b.nulls[i])
            )
            for i in range(schema.arity)
        ),
        time=cat(lambda b: b.time),
        diff=cat(lambda b: b.diff),
        count=jnp.asarray(cap, dtype=jnp.int32),
        schema=schema,
    )
    from ..ops.sort import compact

    return compact(out, keep)


@dataclass
class _StateSlot:
    index: int
    init: Arrangement


class _RenderContext:
    """Collects state slots while walking the MIR tree (one walk at trace
    time per compilation)."""

    def __init__(self, source_schemas: dict):
        self.source_schemas = source_schemas
        self.slots: list[_StateSlot] = []
        self.operators: list = []  # parallel to slots: op configs

    def new_slot(self, op, init: Arrangement) -> int:
        idx = len(self.slots)
        self.slots.append(_StateSlot(idx, init))
        self.operators.append(op)
        return idx


def _build(expr: mir.RelationExpr, ctx: _RenderContext):
    """Returns a closure (states, inputs, time) -> (delta_batch,
    state_updates: dict slot->new_state, overflow_flags: list)."""

    if isinstance(expr, mir.Get):
        name = expr.name

        def run(states, inputs, time):
            return inputs[name], {}, []

        return run

    if isinstance(expr, mir.Project):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, projection=expr.outputs
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Map):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, expressions=expr.scalars
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Filter):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, predicates=expr.predicates
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Negate):
        inner = _build(expr.input, ctx)

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return b.replace(diff=-b.diff), upd, ovf

        return run

    if isinstance(expr, mir.Union):
        inners = [_build(i, ctx) for i in expr.inputs]

        def run(states, inputs, time):
            parts, upd, ovf = [], {}, []
            for f in inners:
                b, u, o = f(states, inputs, time)
                parts.append(b)
                upd.update(u)
                ovf.extend(o)
            return concat_batches(parts), upd, ovf

        return run

    if isinstance(expr, mir.Reduce):
        op = ReduceAccumulable(
            expr.input.schema(), expr.group_key, expr.aggregates
        )
        slot = ctx.new_slot(op, op.init_state())
        inner = _build(expr.input, ctx)

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            state = states[slot]
            new_state, out, overflow = op.step(
                state, b, time, state.capacity
            )
            upd = dict(upd)
            upd[slot] = new_state
            return out, upd, ovf + [overflow]

        return run

    raise NotImplementedError(
        f"render: {type(expr).__name__} not supported in operator set v0"
    )


class Dataflow:
    """A maintained dataflow: install once, feed update batches, peek.

    The host-side analog of an installed DataflowDescription with an
    index export (compute-types/src/dataflows.rs:32): output deltas are
    merged into an output arrangement that serves peeks.
    """

    def __init__(self, expr: mir.RelationExpr, name: str = "df"):
        self.expr = expr
        self.name = name
        self.out_schema = expr.schema()
        ctx = _RenderContext({})
        self._run = _build(expr, ctx)
        self._ctx = ctx
        self.states = [s.init for s in ctx.slots]
        out_key = tuple(range(self.out_schema.arity))
        self.output = Arrangement.empty(self.out_schema, out_key)
        self.time = 0  # frontier: all steps < time are complete
        self._step_jit = jax.jit(self._step_core)
        self._insert_jit = jax.jit(insert, static_argnames=("out_capacity",))

    # pure, jitted once per capacity signature
    def _step_core(self, states, inputs, time):
        out, upd, ovf = self._run(states, inputs, time)
        out = consolidate(out)
        new_states = list(states)
        for k, v in upd.items():
            new_states[k] = v
        return out, tuple(new_states), ovf

    def step(self, inputs: dict) -> Batch:
        """Feed one micro-batch of updates per source; returns the output
        delta at this step's timestamp and advances the frontier."""
        t = jnp.asarray(self.time, dtype=jnp.uint64)
        while True:
            out, new_states, ovf = self._step_jit(
                tuple(self.states), inputs, t
            )
            if ovf and any(bool(o) for o in ovf):
                # Grow every overflowed state to the next tier and retry;
                # states were not committed, so the retry is idempotent.
                grown = []
                for s, o in zip(self.states, ovf):
                    if bool(o):
                        s = Arrangement(
                            s.batch.with_capacity(s.batch.capacity * 2),
                            s.key,
                        )
                    grown.append(s)
                self.states = grown
                continue
            break
        self.states = list(new_states)

        # Maintain the output arrangement (index on the MV).
        while True:
            new_out, ovf = self._insert_jit(
                self.output, out, out_capacity=self.output.capacity
            )
            if bool(ovf):
                self.output = Arrangement(
                    self.output.batch.with_capacity(
                        self.output.capacity * 2
                    ),
                    self.output.key,
                )
                continue
            break
        self.output = new_out
        self.time += 1
        return out

    def peek(self) -> list[tuple]:
        """Read the full maintained result (SELECT * FROM mv)."""
        return self.output.batch.to_rows()
