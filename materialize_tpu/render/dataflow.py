"""Render: MIR relation expressions -> one jitted XLA step function.

Analog of the reference's render layer (compute/src/render.rs:202
``build_compute_dataflow``, :1155 ``render_plan_expr``), re-cast for TPU:
instead of building a graph of timely operators that run cooperatively,
rendering builds ONE pure function

    step(states, inputs, time) -> (output_delta, new_states, overflows)

that XLA compiles once per capacity signature and the host calls per
micro-batch (barrier-synchronous execution, SURVEY.md §7 design stance).
Stateful operators (Reduce, Join, TopK, Threshold) own slots in the
`states` tuple (Arrangements). Capacity overflow is detected on device
and resolved host-side by growing the overflowed tier and retrying the
step — the compile-cache-per-capacity-tier scheme.

Two execution modes share the same render walk:

- ``Dataflow``: single device, no exchange (the one-worker replica).
- ``ShardedDataflow``: SPMD over a worker mesh via ``shard_map``; every
  stateful operator's input is routed to the key's owning worker with an
  all_to_all exchange first (timely's Exchange pact, SURVEY.md §2.4) —
  so each worker maintains a disjoint shard of every arrangement.

The wrappers own the host side: frontier/time advancement, jit caching,
overflow retries, and the output arrangement serving peeks (the
TraceManager + handle_peek analog, compute/src/compute_state.rs:744).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..arrangement.spine import Arrangement, arrange, insert
from ..expr import relation as mir
from ..expr.linear import MapFilterProject, apply_mfp
from ..ops.consolidate import consolidate
from ..ops.reduce import ReduceAccumulable
from ..parallel.exchange import exchange
from ..parallel.mesh import WORKER_AXIS, worker_sharding
from ..repr.batch import Batch, capacity_tier
from ..repr.schema import DIFF_DTYPE, TIME_DTYPE, Schema


def concat_batches(batches: list[Batch]) -> Batch:
    """Concatenate batches of the same schema (capacity = sum of caps).
    Valid rows are NOT contiguous across parts, so this compacts."""
    assert batches
    if len(batches) == 1:
        return batches[0]
    schema = batches[0].schema
    cap = sum(b.capacity for b in batches)

    def cat(field):
        parts = [field(b) for b in batches]
        if any(p is None for p in parts):
            parts = [
                p
                if p is not None
                else jnp.zeros(b.capacity, dtype=bool)
                for p, b in zip(parts, batches)
            ]
        return jnp.concatenate(parts)

    keep = jnp.concatenate([b.valid_mask() for b in batches])
    out = Batch(
        cols=tuple(
            cat(lambda b, i=i: b.cols[i]) for i in range(schema.arity)
        ),
        nulls=tuple(
            (
                None
                if all(b.nulls[i] is None for b in batches)
                else cat(lambda b, i=i: b.nulls[i])
            )
            for i in range(schema.arity)
        ),
        time=cat(lambda b: b.time),
        diff=cat(lambda b: b.diff),
        count=jnp.asarray(cap, dtype=jnp.int32),
        schema=schema,
    )
    from ..ops.sort import compact

    return compact(out, keep)


@dataclass
class _StateSlot:
    index: int
    init: Arrangement


class _RenderContext:
    """Collects state slots while walking the MIR tree (one walk at trace
    time per compilation). In sharded mode it also carries the mesh-axis
    facts every exchange site needs."""

    def __init__(self, source_schemas: dict, num_shards: int = 1,
                 axis_name: str = WORKER_AXIS, slot_cap: int = 256):
        self.source_schemas = source_schemas
        self.slots: list[_StateSlot] = []
        self.operators: list = []  # parallel to slots: op configs
        self.num_shards = num_shards
        self.axis_name = axis_name
        # Per-destination send-slot capacity for exchanges; grown on
        # overflow (mutated by the host wrapper, read at trace time).
        self.slot_cap = slot_cap
        self.n_exchanges = 0

    @property
    def sharded(self) -> bool:
        return self.num_shards > 1

    def new_slot(self, op, init: Arrangement) -> int:
        idx = len(self.slots)
        self.slots.append(_StateSlot(idx, init))
        self.operators.append(op)
        return idx

    def new_exchange_site(self) -> int:
        idx = self.n_exchanges
        self.n_exchanges += 1
        return idx

    def maybe_exchange(self, batch: Batch, key, site: int, ovf: dict):
        """Route `batch` by `key` to owning workers (no-op single-shard)."""
        if not self.sharded:
            return batch, ovf
        routed, overflow = exchange(
            batch, key, self.axis_name, self.num_shards, self.slot_cap
        )
        ovf = dict(ovf)
        ovf[("x", site)] = overflow
        return routed, ovf


def _build(expr: mir.RelationExpr, ctx: _RenderContext):
    """Returns a closure (states, inputs, time) -> (delta_batch,
    state_updates: dict slot->new_state, overflow_flags: dict key->flag).

    Overflow keys: ("state", slot) for arrangement tiers, ("x", site)
    for exchange slot tiers.
    """

    if isinstance(expr, mir.Get):
        name = expr.name

        def run(states, inputs, time):
            return inputs[name], {}, {}

        return run

    if isinstance(expr, mir.Constant):
        schema = expr._schema
        rows = expr.rows

        def run(states, inputs, time):
            # Emit the constant collection exactly once: at time == 0
            # (the as_of), nothing afterwards (render.rs:1170-1212).
            n = len(rows)
            cap = capacity_tier(max(n, 1))
            cols = []
            for j, c in enumerate(schema.columns):
                vals = np.asarray(
                    [r[0][j] for r in rows], dtype=c.dtype
                ) if n else np.zeros(0, dtype=c.dtype)
                pad = np.zeros(cap, dtype=c.dtype)
                pad[:n] = vals
                cols.append(jnp.asarray(pad))
            diffs = np.zeros(cap, dtype=DIFF_DTYPE)
            diffs[:n] = [r[1] for r in rows]
            first = (time == 0).astype(jnp.int32)
            if ctx.sharded:
                # Exactly one worker emits the constant; the exchange in
                # front of any stateful consumer routes rows to owners.
                first = first * (
                    jax.lax.axis_index(ctx.axis_name) == 0
                ).astype(jnp.int32)
            return (
                Batch(
                    cols=tuple(cols),
                    nulls=tuple(None for _ in schema.columns),
                    time=jnp.full(cap, time, dtype=TIME_DTYPE),
                    diff=jnp.asarray(diffs),
                    count=first * n,
                    schema=schema,
                ),
                {},
                {},
            )

        return run

    if isinstance(expr, mir.Project):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, projection=expr.outputs
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Map):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, expressions=expr.scalars
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Filter):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, predicates=expr.predicates
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b), upd, ovf

        return run

    if isinstance(expr, mir.Negate):
        inner = _build(expr.input, ctx)

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return b.replace(diff=-b.diff), upd, ovf

        return run

    if isinstance(expr, mir.Union):
        inners = [_build(i, ctx) for i in expr.inputs]

        def run(states, inputs, time):
            parts, upd, ovf = [], {}, {}
            for f in inners:
                b, u, o = f(states, inputs, time)
                parts.append(b)
                upd.update(u)
                ovf.update(o)
            return concat_batches(parts), upd, ovf

        return run

    if isinstance(expr, mir.Reduce):
        op = ReduceAccumulable(
            expr.input.schema(), expr.group_key, expr.aggregates
        )
        slot = ctx.new_slot(op, op.init_state())
        site = ctx.new_exchange_site()
        inner = _build(expr.input, ctx)
        group_key = expr.group_key

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            b, ovf = ctx.maybe_exchange(b, group_key, site, ovf)
            state = states[slot]
            new_state, out, overflow = op.step(
                state, b, time, state.capacity
            )
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            ovf[("state", slot)] = overflow
            return out, upd, ovf

        return run

    raise NotImplementedError(
        f"render: {type(expr).__name__} not supported in operator set v0"
    )


class _DataflowBase:
    """Shared host-side machinery: output arrangement + peeks."""

    def _init_output(self):
        out_key = tuple(range(self.out_schema.arity))
        self.output = Arrangement.empty(self.out_schema, out_key)
        self._insert_jit = jax.jit(insert, static_argnames=("out_capacity",))

    def _absorb_output(self, out: Batch):
        """Merge an output delta into the output arrangement (the index
        export: TraceManager arrangement, render.rs:357)."""
        while True:
            new_out, ovf = self._insert_jit(
                self.output, out, out_capacity=self.output.capacity
            )
            if bool(ovf):
                self.output = Arrangement(
                    self.output.batch.with_capacity(self.output.capacity * 2),
                    self.output.key,
                )
                continue
            break
        self.output = new_out

    def peek(self) -> list[tuple]:
        """Read the full maintained result (SELECT * FROM mv)."""
        return self.output.batch.to_rows()


class Dataflow(_DataflowBase):
    """A maintained dataflow on one device: install once, feed update
    batches, peek.

    The host-side analog of an installed DataflowDescription with an
    index export (compute-types/src/dataflows.rs:32).
    """

    def __init__(self, expr: mir.RelationExpr, name: str = "df"):
        self.expr = expr
        self.name = name
        self.out_schema = expr.schema()
        ctx = _RenderContext({})
        self._run = _build(expr, ctx)
        self._ctx = ctx
        self.states = [s.init for s in ctx.slots]
        self._init_output()
        self.time = 0  # frontier: all steps < time are complete
        self._step_jit = jax.jit(self._step_core)

    # pure, jitted once per capacity signature
    def _step_core(self, states, inputs, time):
        out, upd, ovf = self._run(states, inputs, time)
        out = consolidate(out)
        new_states = list(states)
        for k, v in upd.items():
            new_states[k] = v
        return out, tuple(new_states), ovf

    def step(self, inputs: dict) -> Batch:
        """Feed one micro-batch of updates per source; returns the output
        delta at this step's timestamp and advances the frontier."""
        t = jnp.asarray(self.time, dtype=jnp.uint64)
        while True:
            out, new_states, ovf = self._step_jit(
                tuple(self.states), inputs, t
            )
            grown = False
            for (kind, idx), flag in ovf.items():
                if kind == "state" and bool(flag):
                    s = self.states[idx]
                    self.states[idx] = Arrangement(
                        s.batch.with_capacity(s.batch.capacity * 2), s.key
                    )
                    grown = True
            if grown:
                # States were not committed; the retry is idempotent.
                continue
            break
        self.states = list(new_states)
        self._absorb_output(out)
        self.time += 1
        return out


def _shard_rows(arrays, n: int, num_shards: int, shard_cap: int):
    """Deal host rows round-robin across shards; returns per-field
    [num_shards * shard_cap] arrays + [num_shards] counts. Ingestion
    balance only — exchange re-routes by key inside the step."""
    base, extra = divmod(n, num_shards)
    counts = np.full(num_shards, base, dtype=np.int32)
    counts[:extra] += 1

    def pack(a):
        if a is None:
            return None
        out = np.zeros(num_shards * shard_cap, dtype=a.dtype)
        for s in range(num_shards):
            rows = a[s::num_shards]
            out[s * shard_cap : s * shard_cap + len(rows)] = rows
        return out

    return [pack(a) for a in arrays], counts


class ShardedDataflow(_DataflowBase):
    """A maintained dataflow SPMD over a worker mesh.

    Worker = device; every stateful operator's state is sharded by key
    hash; inputs are dealt across workers and exchanged on key inside the
    step (the timely model, SURVEY.md §2.4 row 1). One ``shard_map``-ped
    jitted step per capacity signature.
    """

    def __init__(self, expr: mir.RelationExpr, mesh, name: str = "df",
                 slot_cap: int = 256, input_shard_cap: int = 1024):
        self.expr = expr
        self.mesh = mesh
        self.name = name
        if len(mesh.axis_names) != 1:
            raise ValueError(
                "ShardedDataflow wants a 1-D worker mesh (make_mesh); "
                f"got axes {mesh.axis_names}"
            )
        self.axis_name = mesh.axis_names[0]
        self.num_shards = int(mesh.shape[self.axis_name])
        self.out_schema = expr.schema()
        ctx = _RenderContext(
            {}, num_shards=self.num_shards, axis_name=self.axis_name,
            slot_cap=slot_cap,
        )
        self._run = _build(expr, ctx)
        self._ctx = ctx
        self.input_shard_cap = input_shard_cap
        self._sharding = worker_sharding(mesh, self.axis_name)
        # Per-shard states, stored as global arrays [P * cap] / counts [P].
        self.states = [
            self._replicate_empty(s.init) for s in ctx.slots
        ]
        self._init_output()
        self.time = 0
        self._make_jit()

    # -- sharded state layout ----------------------------------------------
    def _replicate_empty(self, arr: Arrangement) -> Arrangement:
        """Each worker starts with an empty shard of this arrangement."""
        P_ = self.num_shards

        def rep(a):
            if a is None:
                return None
            return jax.device_put(
                np.zeros(P_ * a.shape[0], dtype=a.dtype), self._sharding
            )

        b = arr.batch
        gb = Batch(
            cols=tuple(rep(c) for c in b.cols),
            nulls=tuple(rep(n) for n in b.nulls),
            time=rep(b.time),
            diff=rep(b.diff),
            count=jax.device_put(
                np.zeros(P_, dtype=np.int32), self._sharding
            ),
            schema=b.schema,
        )
        return Arrangement(gb, arr.key)

    def _grow_state(self, arr: Arrangement) -> Arrangement:
        """Double every shard's capacity ([P, cap] -> [P, 2cap])."""
        P_ = self.num_shards
        b = arr.batch
        cap = b.capacity // P_

        def grow(a):
            if a is None:
                return None
            h = np.asarray(a).reshape(P_, cap)
            out = np.zeros((P_, 2 * cap), dtype=h.dtype)
            out[:, :cap] = h
            return jax.device_put(
                out.reshape(P_ * 2 * cap), self._sharding
            )

        gb = Batch(
            cols=tuple(grow(c) for c in b.cols),
            nulls=tuple(grow(n) for n in b.nulls),
            time=grow(b.time),
            diff=grow(b.diff),
            count=b.count,
            schema=b.schema,
        )
        return Arrangement(gb, arr.key)

    # -- the SPMD step ------------------------------------------------------
    def _make_jit(self):
        axis = self.axis_name

        def per_worker(states, inputs, time):
            # Leaves arrive rank-preserved: counts are [1]; make scalar.
            states = [
                Arrangement(
                    s.batch.replace(count=s.batch.count.reshape(())), s.key
                )
                for s in states
            ]
            inputs = {
                k: b.replace(count=b.count.reshape(()))
                for k, b in inputs.items()
            }
            out, upd, ovf = self._run(states, inputs, time)
            out = consolidate(out)
            new_states = list(states)
            for k, v in upd.items():
                new_states[k] = v
            # Rank-1 everything for the shard_map boundary.
            out = out.replace(count=out.count.reshape((1,)))
            new_states = tuple(
                Arrangement(
                    s.batch.replace(count=s.batch.count.reshape((1,))),
                    s.key,
                )
                for s in new_states
            )
            # Overflow anywhere aborts the step on every worker.
            ovf = {
                k: (jax.lax.psum(v.astype(jnp.int32), axis) > 0).reshape(
                    (1,)
                )
                for k, v in ovf.items()
            }
            return out, new_states, ovf

        def step(states, inputs, time):
            return jax.shard_map(
                per_worker,
                mesh=self.mesh,
                in_specs=(P(self.axis_name), P(self.axis_name), P()),
                out_specs=(P(self.axis_name), P(self.axis_name),
                           P(self.axis_name)),
                check_vma=False,
            )(states, inputs, time)

        self._step_jit = jax.jit(step)

    def _pack_inputs(self, inputs: dict) -> dict:
        packed = {}
        for name, b in inputs.items():
            if isinstance(b, Batch) and b.count.ndim == 0:
                # Host-global batch: deal rows across workers.
                n = int(b.count)
                cols = [np.asarray(c)[:n] for c in b.cols]
                nulls = [
                    None if nl is None else np.asarray(nl)[:n]
                    for nl in b.nulls
                ]
                time = np.asarray(b.time)[:n]
                diff = np.asarray(b.diff)[:n]
                cap = self.input_shard_cap
                while cap * self.num_shards < n or capacity_tier(
                    max((n + self.num_shards - 1) // self.num_shards, 1)
                ) > cap:
                    cap *= 2
                fields, counts = _shard_rows(
                    cols + nulls + [time, diff], n, self.num_shards, cap
                )
                k = len(cols)
                put = lambda a: (
                    None
                    if a is None
                    else jax.device_put(a, self._sharding)
                )
                packed[name] = Batch(
                    cols=tuple(put(a) for a in fields[:k]),
                    nulls=tuple(put(a) for a in fields[k : 2 * k]),
                    time=put(fields[2 * k]),
                    diff=put(fields[2 * k + 1]),
                    count=jax.device_put(counts, self._sharding),
                    schema=b.schema,
                )
            else:
                packed[name] = b
        return packed

    def _gather_output(self, out: Batch) -> Batch:
        """Concatenate every worker's output delta into one host batch."""
        P_ = self.num_shards
        counts = np.asarray(out.count)
        cap = out.diff.shape[0] // P_
        sel = np.concatenate(
            [
                np.arange(p * cap, p * cap + counts[p])
                for p in range(P_)
            ]
        ).astype(np.int64) if counts.sum() else np.zeros(0, dtype=np.int64)
        cols = [np.asarray(c)[sel] for c in out.cols]
        nulls = [
            None if nl is None else np.asarray(nl)[sel] for nl in out.nulls
        ]
        return Batch.from_numpy(
            out.schema,
            cols,
            np.asarray(out.time)[sel],
            np.asarray(out.diff)[sel],
            nulls=nulls,
        )

    def step(self, inputs: dict) -> Batch:
        """Feed one micro-batch (host batches are dealt across workers);
        returns the gathered output delta and advances the frontier."""
        t = jnp.asarray(self.time, dtype=jnp.uint64)
        packed = self._pack_inputs(inputs)
        while True:
            out, new_states, ovf = self._step_jit(
                tuple(self.states), packed, t
            )
            grown = False
            for (kind, idx), flag in ovf.items():
                if not bool(np.any(np.asarray(flag))):
                    continue
                if kind == "state":
                    self.states[idx] = self._grow_state(self.states[idx])
                    grown = True
                elif kind == "x":
                    self._ctx.slot_cap *= 2
                    self._make_jit()
                    grown = True
            if grown:
                continue
            break
        self.states = list(new_states)
        host_out = self._gather_output(out)
        self._absorb_output(host_out)
        self.time += 1
        return host_out
