"""Render: MIR relation expressions -> one jitted XLA step function.

Analog of the reference's render layer (compute/src/render.rs:202
``build_compute_dataflow``, :1155 ``render_plan_expr``), re-cast for TPU:
instead of building a graph of timely operators that run cooperatively,
rendering builds ONE pure function

    step(states, inputs, time) -> (output_delta, new_states, overflows)

that XLA compiles once per capacity signature and the host calls per
micro-batch (barrier-synchronous execution, SURVEY.md §7 design stance).
Stateful operators (Reduce, Join, TopK, Threshold) own slots in the
`states` tuple (Arrangements). Capacity overflow is detected on device
and resolved host-side by growing the overflowed tier and retrying the
step — the compile-cache-per-capacity-tier scheme.

Two execution modes share the same render walk:

- ``Dataflow``: single device, no exchange (the one-worker replica).
- ``ShardedDataflow``: SPMD over a worker mesh via ``shard_map``; every
  stateful operator's input is routed to the key's owning worker with an
  all_to_all exchange first (timely's Exchange pact, SURVEY.md §2.4) —
  so each worker maintains a disjoint shard of every arrangement.

The wrappers own the host side: frontier/time advancement, jit caching,
overflow retries, and the output arrangement serving peeks (the
TraceManager + handle_peek analog, compute/src/compute_state.rs:744).
"""

from __future__ import annotations

from dataclasses import dataclass

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..arrangement.spine import (
    Arrangement,
    Spine,
    arrange,
    compact_level,
    compact_spine,
    insert,
    insert_tail,
)
from ..expr import relation as mir
from ..expr.errors import N_CODES as _N_ERR_CODES
from ..expr.linear import MapFilterProject, apply_mfp
from ..ops.consolidate import consolidate
from ..ops.delta_join import DeltaJoinOp
from ..ops.flat_map import flat_map
from ..ops.join import JoinOp
from ..ops.reduce import ReduceOp
from ..ops.temporal import TemporalFilterOp, canonicalize_temporal
from ..ops.threshold import ThresholdOp
from ..ops.topk import TopKOp
from ..ops.sort import concat_batches, shrink
from ..parallel.compat import require_shard_map
from ..parallel.exchange import exchange
from ..parallel.mesh import WORKER_AXIS, worker_sharding
from ..repr.batch import Batch, capacity_tier
from ..repr.schema import DIFF_DTYPE, TIME_DTYPE, Schema


# The span program nests cumulative scans (reduce-window lowerings)
# inside lax.scan; at big run capacities the default 16MiB scoped-vmem
# budget overflows at compile time ("Ran out of memory in memory space
# vmem ... scoped"). Raise it for span compiles only (v5e has 128MiB
# VMEM; 64MiB scoped leaves ample room). TPU-only option — CPU/other
# backends reject it.


def _span_compiler_options():
    if jax.default_backend() in ("tpu", "axon"):
        return {"xla_tpu_scoped_vmem_limit_kib": 65536}
    return None


def _donation_supported() -> bool:
    """Whether the backend honors ``donate_argnums``. CPU ignores
    donation (warning per buffer) — and jaxlib 0.4.37 has been
    observed to SEGFAULT lowering large donated span programs under
    the forced multi-device host platform the test suite uses — so
    the argnums are wired only where they do something. The
    donation-SAFETY contract (cloned rollback checkpoint, span-
    boundary read barrier) stays backend-independent: callers request
    donation, the clone always happens, the argnums follow the
    backend."""
    return jax.default_backend() in ("tpu", "axon")


@dataclass
class _StateSlot:
    index: int
    init: Arrangement


class _RenderContext:
    """Collects state slots while walking the MIR tree (one walk at trace
    time per compilation). In sharded mode it also carries the mesh-axis
    facts every exchange site needs."""

    def __init__(self, source_schemas: dict, num_shards: int = 1,
                 axis_name: str = WORKER_AXIS, slot_cap: int = 256,
                 join_cap: int = 1024, state_cap: int = 256,
                 spmd_safe=None, force_merge_ingest: bool = False):
        self.source_schemas = source_schemas
        # Initial capacity tier for every stateful operator's
        # arrangements. Overflow growth doubles tiers as needed; callers
        # that know their steady-state size pass a larger tier up front
        # to skip the overflow->grow->recompile ladder (each rung is a
        # fresh XLA compile of the step program). Caps snap to the pow2
        # quantization menu (ISSUE 16): size-only DDL differences must
        # not mint new program-bank keys.
        from ..plan.decisions import quantize_cap

        state_cap = quantize_cap(state_cap)
        slot_cap = quantize_cap(slot_cap)
        join_cap = quantize_cap(join_cap)
        self.state_cap = state_cap
        # Ingest-mode decision for operator-state spines
        # (plan/decisions.py state_ingest_mode, the EXPLAIN-visible
        # source of truth): the number of append slots spine states
        # are built with, 0 = merge ingest. Under SPMD the slot cursor
        # rides the shard_map boundary as a per-device [P] vector,
        # gated on the shard-spec prover's verdict (ISSUE 9):
        # ``spmd_safe`` is True only for a render whose cursor the
        # prover has verdicted (or is about to verdict — the trial
        # render) shard-local; None/False resolve to merge.
        from ..plan.decisions import INGEST_RING_SLOTS, state_ingest_mode

        self.spmd_safe = spmd_safe
        # force_merge_ingest (ISSUE 16 async compile): the GENERIC
        # program family — merge ingest regardless of the dyncfg/auto
        # decision, so a fresh DDL's immediately-installed dataflow is
        # the cheapest-to-have-banked program while the specialized
        # one compiles in the background.
        self.ingest_slots = (
            INGEST_RING_SLOTS
            if not force_merge_ingest
            and state_ingest_mode(
                state_cap, spmd=num_shards > 1, spmd_safe=spmd_safe
            )
            == "append_slot"
            else 0
        )
        self.slots: list[_StateSlot] = []
        self.operators: list = []  # parallel to slots: op configs
        self.num_shards = num_shards
        self.axis_name = axis_name
        # Per-destination send-slot capacity for exchanges; grown on
        # overflow (mutated by the host wrapper, read at trace time).
        self.slot_cap = slot_cap
        self.n_exchanges = 0
        # Per-join-site output capacity tier (match fan-out is
        # data-dependent); grown on overflow, read at trace time.
        self.join_caps: list[int] = []
        self.default_join_cap = join_cap
        # Per-LetRec-site binding-delta capacity tier.
        self.letrec_caps: list[int] = []
        self.default_letrec_cap = 2048
        # Output deltas are shrunk to this tier before the output
        # arrangement insert, so the insert's sorts compile at a small
        # capacity regardless of input batch size.
        self.out_delta_cap = 4096
        # The dataflow's first processed timestamp (its as_of): set by
        # the host wrapper before the first step, read at trace time.
        # Constants emit exactly once, AT this time (render.rs:1170
        # "rows advanced to as_of") — not at literal time 0, which a
        # hydrated dataflow never processes.
        self.first_time = 0
        # Reduce sites with basic (collection) aggregates: (mir node id,
        # state slot, ReduceOp). The dataflow resolves these against its
        # top-level expression to build edge finalizers (ops/reduce.py
        # basic tier — render/reduce.rs:369 analog).
        self.basic_sites: list = []

    @property
    def sharded(self) -> bool:
        return self.num_shards > 1

    def new_slot(self, op, init: Arrangement) -> int:
        idx = len(self.slots)
        self.slots.append(_StateSlot(idx, init))
        self.operators.append(op)
        return idx

    def new_exchange_site(self) -> int:
        idx = self.n_exchanges
        self.n_exchanges += 1
        return idx

    def new_join_site(self) -> int:
        self.join_caps.append(self.default_join_cap)
        return len(self.join_caps) - 1

    def maybe_exchange(self, batch: Batch, key, site: int, ovf: dict,
                       null_aware: bool = True):
        """Route `batch` by `key` to owning workers (no-op single-shard)."""
        if not self.sharded:
            return batch, ovf
        routed, overflow = exchange(
            batch, key, self.axis_name, self.num_shards, self.slot_cap,
            null_aware,
        )
        ovf = dict(ovf)
        ovf[("x", site)] = overflow
        return routed, ovf


def _build(expr: mir.RelationExpr, ctx: _RenderContext):
    """Returns a closure (states, inputs, time) -> (delta_batch,
    state_updates: dict slot->new_state, overflow_flags: dict key->flag).

    Overflow keys: ("state", slot) for arrangement tiers, ("x", site)
    for exchange slot tiers.
    """

    if isinstance(expr, mir.Get):
        name = expr.name

        def run(states, inputs, time):
            return inputs[name], {}, {}

        return run

    if isinstance(expr, mir.Constant):
        schema = expr._schema
        rows = expr.rows

        def run(states, inputs, time):
            # Emit the constant collection exactly once: at the
            # dataflow's as_of, nothing afterwards (render.rs:1170-1212).
            n = len(rows)
            cap = capacity_tier(max(n, 1))
            cols = []
            for j, c in enumerate(schema.columns):
                vals = np.asarray(
                    [r[0][j] for r in rows], dtype=c.dtype
                ) if n else np.zeros(0, dtype=c.dtype)
                pad = np.zeros(cap, dtype=c.dtype)
                pad[:n] = vals
                cols.append(jnp.asarray(pad))
            diffs = np.zeros(cap, dtype=DIFF_DTYPE)
            diffs[:n] = [r[1] for r in rows]
            first = (time == ctx.first_time).astype(jnp.int32)
            if ctx.sharded:
                # Exactly one worker emits the constant; the exchange in
                # front of any stateful consumer routes rows to owners.
                first = first * (
                    jax.lax.axis_index(ctx.axis_name) == 0
                ).astype(jnp.int32)
            return (
                Batch(
                    cols=tuple(cols),
                    nulls=tuple(None for _ in schema.columns),
                    time=jnp.full(cap, time, dtype=TIME_DTYPE),
                    diff=jnp.asarray(diffs),
                    count=first * n,
                    schema=schema,
                ),
                {},
                {},
            )

        return run

    if isinstance(expr, mir.Project):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, projection=expr.outputs
        )

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return apply_mfp(mfp, b, time), upd, ovf

        return run

    if isinstance(expr, mir.Map):
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, expressions=expr.scalars
        )
        out_schema = expr.schema()  # MIR's naming (c{i}) is authoritative

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return (
                apply_mfp(mfp, b, time).replace(schema=out_schema),
                upd,
                ovf,
            )

        return run

    if isinstance(expr, mir.Filter):
        from ..expr.scalar import contains_mz_now

        temporal = [p for p in expr.predicates if contains_mz_now(p)]
        plain = [p for p in expr.predicates if not contains_mz_now(p)]
        inner = _build(expr.input, ctx)
        mfp = MapFilterProject(
            expr.input.schema().arity, predicates=plain
        )
        if not temporal:

            def run(states, inputs, time):
                b, upd, ovf = inner(states, inputs, time)
                return apply_mfp(mfp, b, time), upd, ovf

            return run

        # Temporal predicates: plain filter first, then the scheduled
        # window operator (expr/src/linear.rs:1724 MfpPlan). No exchange:
        # each worker schedules its own rows' futures.
        from ..utils.dyncfg import (
            COMPUTE_CONFIGS,
            ENABLE_TEMPORAL_FILTERS,
        )

        if not ENABLE_TEMPORAL_FILTERS(COMPUTE_CONFIGS):
            raise NotImplementedError(
                "temporal filters disabled by dyncfg "
                "enable_temporal_filters"
            )
        lo_exprs, hi_exprs = canonicalize_temporal(temporal)
        op = TemporalFilterOp(
            expr.input.schema(), tuple(lo_exprs), tuple(hi_exprs)
        )
        slot = ctx.new_slot(op, op.init_state(ctx.state_cap))
        osite = ctx.new_join_site()  # output-capacity tier

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            b = apply_mfp(mfp, b, time)
            new_state, out, overflow, out_ovf = op.step(
                states[slot], b, time, ctx.join_caps[osite]
            )
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            for part, flag in overflow.items():
                ovf[("state", slot, part)] = flag
            ovf[("join", osite)] = out_ovf
            return out, upd, ovf

        return run

    if isinstance(expr, mir.Negate):
        inner = _build(expr.input, ctx)

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            return b.replace(diff=-b.diff), upd, ovf

        return run

    if isinstance(expr, mir.Union):
        inners = [_build(i, ctx) for i in expr.inputs]

        def run(states, inputs, time):
            parts, upd, ovf = [], {}, {}
            for f in inners:
                b, u, o = f(states, inputs, time)
                parts.append(b)
                upd.update(u)
                ovf.update(o)
            return concat_batches(parts), upd, ovf

        return run

    if isinstance(expr, mir.Reduce):
        op = ReduceOp(
            expr.input.schema(), expr.group_key, expr.aggregates
        )
        slot = ctx.new_slot(op, op.init_state(ctx.state_cap))
        if op.basic_aggs:
            ctx.basic_sites.append((id(expr), slot, op))
        site = ctx.new_exchange_site()
        inner = _build(expr.input, ctx)
        group_key = expr.group_key

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            b, ovf = ctx.maybe_exchange(b, group_key, site, ovf)
            new_state, out, overflow = op.step(states[slot], b, time)
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            for part, flag in overflow.items():
                ovf[("state", slot, part)] = flag
            return out, upd, ovf

        return run

    if isinstance(expr, mir.Let):
        val = _build(expr.value, ctx)
        body = _build(expr.body, ctx)
        name = expr.name

        def run(states, inputs, time):
            vb, upd, ovf = val(states, inputs, time)
            # The binding's delta is computed ONCE and shared by every
            # Get (arrangement sharing analog: NormalizeLets + the
            # TraceManager let bindings, render_plan.rs bind stages).
            inner_inputs = dict(inputs)
            inner_inputs[name] = vb
            ob, u2, o2 = body(states, inner_inputs, time)
            return ob, {**upd, **u2}, {**ovf, **o2}

        return run

    if isinstance(expr, mir.Join):
        return _build_join(expr, ctx)

    if isinstance(expr, mir.LetRec):
        return _build_letrec(expr, ctx)

    if isinstance(expr, mir.Threshold):
        op = ThresholdOp(expr.input.schema())
        slot = ctx.new_slot(op, op.init_state(ctx.state_cap))
        site = ctx.new_exchange_site()
        inner = _build(expr.input, ctx)
        all_cols = tuple(range(expr.input.schema().arity))

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            b, ovf = ctx.maybe_exchange(b, all_cols, site, ovf)
            new_state, out, overflow = op.step(states[slot], b, time)
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            for part, flag in overflow.items():
                ovf[("state", slot, part)] = flag
            return out, upd, ovf

        return run

    if isinstance(expr, mir.TopK):
        op = TopKOp(
            expr.input.schema(), expr.group_key, expr.order_by,
            expr.limit, expr.offset,
        )
        slot = ctx.new_slot(op, op.init_state(ctx.state_cap))
        site = ctx.new_exchange_site()
        inner = _build(expr.input, ctx)
        group_key = expr.group_key

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            b, ovf = ctx.maybe_exchange(b, group_key, site, ovf)
            new_state, out, overflow = op.step(states[slot], b, time)
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            for part, flag in overflow.items():
                ovf[("state", slot, part)] = flag
            return out, upd, ovf

        return run

    if isinstance(expr, mir.FlatMap):
        inner = _build(expr.input, ctx)
        fsite = ctx.new_join_site()  # fan-out capacity tier, like a join
        out_schema = expr.schema()
        func, exprs = expr.func, expr.exprs

        def run(states, inputs, time):
            b, upd, ovf = inner(states, inputs, time)
            out, overflow = flat_map(
                b, func, exprs, out_schema, time, ctx.join_caps[fsite]
            )
            ovf = dict(ovf)
            ovf[("join", fsite)] = overflow
            return out, upd, ovf

        return run

    if isinstance(expr, mir.ArrangeBy):
        # Arrangement sharing across operators is implicit (Let bindings
        # compute each delta once); ArrangeBy is a planner hint here.
        return _build(expr.input, ctx)

    raise NotImplementedError(
        f"render: {type(expr).__name__} not supported in operator set v0"
    )


def _build_join(expr: mir.Join, ctx: _RenderContext):
    # The linear-vs-delta decision and stage keys come from the plan
    # layer (materialize_tpu/plan/decisions.py) so EXPLAIN PHYSICAL PLAN
    # prints exactly what renders.
    from ..plan import join_implementation

    if join_implementation(expr) == "delta":
        return _build_join_delta(expr, ctx)
    return _build_join_linear(expr, ctx)


def _build_join_delta(expr: mir.Join, ctx: _RenderContext):
    """Delta join plan: per-input update pipelines over shared arrangements
    (JoinPlan::Delta, compute-types/src/plan/join.rs; delta_join.rs:51).
    In SPMD mode every arrangement insert and every probe is preceded by
    an all_to_all on the relevant key (the half_join exchange)."""
    schemas = [i.schema() for i in expr.inputs]
    op = DeltaJoinOp(tuple(schemas), expr.equivalences)
    slot = ctx.new_slot(
        op,
        op.init_state(ctx.state_cap, ingest_slots=ctx.ingest_slots),
    )
    jsite = ctx.new_join_site()
    inners = [_build(i, ctx) for i in expr.inputs]
    ex_sites = {}
    for p in range(len(op.arr_specs)):
        ex_sites[("ins", p)] = ctx.new_exchange_site()
    for i, (steps, _) in enumerate(op.pipelines):
        for j, acc_key, j_key, ap in steps:
            ex_sites[("probe", i, ap)] = ctx.new_exchange_site()

    def run(states, inputs, time):
        deltas, upd, ovf = [], {}, {}
        for f in inners:
            b, u, o = f(states, inputs, time)
            deltas.append(b)
            upd.update(u)
            ovf.update(o)

        ovf_box = {"d": dict(ovf)}

        def exchange_fn(b, key, tag):
            b2, ovf_box["d"] = ctx.maybe_exchange(
                b, key, ex_sites[tag], ovf_box["d"], null_aware=False
            )
            return b2

        new_state, out, st_ovf, j_ovf = op.step(
            states[slot],
            deltas,
            time,
            ctx.join_caps[jsite],
            exchange_fn if ctx.sharded else None,
        )
        upd = dict(upd)
        upd[slot] = new_state
        ovf = dict(ovf_box["d"])
        for part, flag in st_ovf.items():
            ovf[("state", slot, part)] = flag
        ovf[("join", jsite)] = j_ovf
        return out, upd, ovf

    return run


def _build_join_linear(expr: mir.Join, ctx: _RenderContext):
    """Linear join plan: left-fold binary JoinOp stages, each with both
    sides exchanged on the stage key (JoinPlan::Linear,
    compute-types/src/plan/join.rs:46; rendering linear_join.rs:204)."""
    schemas = [i.schema() for i in expr.inputs]
    offsets = [0]
    for s in schemas:
        offsets.append(offsets[-1] + s.arity)
    inners = [_build(i, ctx) for i in expr.inputs]

    stages = []
    acc_schema = schemas[0]
    all_consumed: set = set()
    for i in range(1, len(expr.inputs)):
        from ..plan import join_stage_keys

        left_key, right_key, consumed = join_stage_keys(expr, offsets, i)
        all_consumed.update(consumed)
        op = JoinOp(acc_schema, schemas[i], left_key, right_key)
        slot = ctx.new_slot(
            op,
            op.init_state(
                ctx.state_cap, ingest_slots=ctx.ingest_slots
            ),
        )
        jsite = ctx.new_join_site()
        lsite = ctx.new_exchange_site()
        rsite = ctx.new_exchange_site()
        stages.append((op, slot, jsite, lsite, rsite, left_key, right_key))
        acc_schema = op.out_schema
    if len(all_consumed) != len(expr.equivalences):
        # An intra-input equality (all members in one input) would be
        # silently unenforced — the optimizer should have rewritten it
        # into a Filter; refuse rather than emit wrong rows.
        raise NotImplementedError(
            "equivalence class not consumable as a join key "
            "(intra-input equality: rewrite as Filter)"
        )

    def run(states, inputs, time):
        deltas, upd, ovf = [], {}, {}
        for f in inners:
            b, u, o = f(states, inputs, time)
            deltas.append(b)
            upd.update(u)
            ovf.update(o)
        acc = deltas[0]
        for (op, slot, jsite, lsite, rsite, lkey, rkey), d_right in zip(
            stages, deltas[1:]
        ):
            acc, ovf = ctx.maybe_exchange(
                acc, lkey, lsite, ovf, null_aware=False
            )
            d_right, ovf = ctx.maybe_exchange(
                d_right, rkey, rsite, ovf, null_aware=False
            )
            new_state, out, st_ovf, j_ovf = op.step(
                states[slot], acc, d_right, time, ctx.join_caps[jsite]
            )
            upd = dict(upd)
            upd[slot] = new_state
            ovf = dict(ovf)
            for part, flag in st_ovf.items():
                ovf[("state", slot, part)] = flag
            ovf[("join", jsite)] = j_ovf
            acc = out
        return acc, upd, ovf

    return run


def _build_letrec(expr: mir.LetRec, ctx: _RenderContext):
    """WITH MUTUALLY RECURSIVE: device-resident fixpoint iteration.

    Analog of the reference's iterative scopes (compute/src/render.rs:887
    ``render_recursive_plan``; differential ``Variable`` + PointStamp
    timestamps). The TPU re-cast is a ``jax.lax.while_loop`` of semi-naive
    (Jacobi) iterations — compiled once, running entirely on device:

      iter 0: binding values see the step's real source deltas and empty
              binding deltas;
      iter k: values see empty source deltas and iteration k-1's binding
              deltas; stateful operators inside the values carry their
              arrangements through the loop (the converged state at outer
              time t is the correct starting state for t+1, exactly the
              effect of differential's full logical compaction).

    Convergence = every binding's consolidated delta is empty (psum'd
    across workers in SPMD mode, so the loop condition is mesh-uniform);
    ``max_iters`` caps divergent or float-asymptotic recursions
    (LetRecLimit / RETURN AT RECURSION LIMIT analog). The body sees the
    per-step total (telescoped) binding deltas.

    Known limitation (documented, as in SURVEY.md §7 hard part re:
    determinism/recursion): retraction propagation uses derivation
    counting, which matches the reference's semantics for monotone and
    acyclic-derivation recursions; cyclic derivations with retractions
    would need iteration-indexed state (differential's nested timestamps).
    """
    names = expr.names
    schemas = expr.value_schemas
    value_fns = [_build(v, ctx) for v in expr.values]
    body_fn = _build(expr.body, ctx)
    site = len(ctx.letrec_caps)
    ctx.letrec_caps.append(ctx.default_letrec_cap)
    max_iters = expr.max_iters if expr.max_iters is not None else 100_000

    def run(states, inputs, time):
        cap = ctx.letrec_caps[site]

        def canon_states(states_l):
            """Null-mask presence must be loop-invariant (pytree aux of
            the while_loop carry): canonicalize every arrangement (or
            spine-run) batch."""
            out = []
            for s in states_l:
                if isinstance(s, tuple):
                    out.append(
                        tuple(
                            a.map_batches(
                                lambda b: b.canonicalize_nulls()
                            )
                            for a in s
                        )
                    )
                else:
                    out.append(s)
            return out

        def run_values(states_l, it_inputs):
            """One iteration: returns (new_states_list, deltas, ovf
            dict, err-count vector [N_ERR_CODES]).

            Error-stream batches raised INSIDE the fixpoint cannot ride
            the outer step's Python-list err sink (values created in
            the while_loop trace would escape the loop as leaked
            tracers). Instead they fold into a fixed-shape per-code
            count vector that RIDES THE LOOP CARRY; the outer run()
            converts the final counts into err update rows
            (render.rs:12-101 — LetRec-internal errors reach the err
            collection, and retract: a deletion re-evaluates the site
            with diff=-1)."""
            from ..expr import errors as _errors

            with _errors.step_scope() as sink:
                sts, deltas_i, ovf_i = _run_values_inner(
                    states_l, it_inputs
                )
            errs = jnp.zeros((_N_ERR_CODES,), jnp.int64)
            for eb in sink:
                errs = errs.at[eb.cols[0]].add(eb.diff)
            return sts, deltas_i, ovf_i, errs

        def _run_values_inner(states_l, it_inputs):
            states_l = list(states_l)
            ovf = {}
            deltas = []
            for i, fn in enumerate(value_fns):
                d, upd, o = fn(states_l, it_inputs, time)
                for k, v in upd.items():
                    states_l[k] = v
                ovf.update(o)
                d = consolidate(d, include_time=False)
                d, so = shrink(d, cap)
                if d.capacity != cap:
                    # Loop-carry invariant: binding deltas/accums must
                    # sit at EXACTLY the site cap — a value expr whose
                    # output tier is below cap would otherwise make
                    # iteration-0 accums smaller than the body's
                    # concat+shrink output (while_loop type mismatch).
                    d = d.with_capacity(cap)
                ovf[("lr", site, i)] = so
                # Rebrand to the DECLARED binding schema (value exprs may
                # produce equivalent columns under different names).
                deltas.append(
                    d.replace(schema=schemas[i]).canonicalize_nulls()
                )
            return canon_states(states_l), deltas, ovf

        # Iteration 0: real inputs, empty binding deltas.
        it0_inputs = dict(inputs)
        for nm, sch in zip(names, schemas):
            it0_inputs[nm] = Batch.empty(sch, cap)
        states_l, deltas, ovf, errs0 = run_values(
            list(states), it0_inputs
        )
        accums = list(deltas)

        ovf_keys = sorted(ovf.keys())

        def pack(o):
            if not ovf_keys:
                return jnp.zeros((0,), jnp.bool_)
            return jnp.stack(
                [jnp.asarray(o[k]).astype(jnp.bool_).reshape(()) for k in ovf_keys]
            )

        empty_inputs = {
            k: b.replace(count=jnp.zeros_like(b.count))
            for k, b in inputs.items()
        }

        def cond(carry):
            _, deltas_c, _, it, _, _ = carry
            pending = jnp.asarray(0, jnp.int32)
            for d in deltas_c:
                pending = pending + d.count.reshape(()).astype(jnp.int32)
            if ctx.sharded:
                pending = jax.lax.psum(pending, ctx.axis_name)
            return jnp.logical_and(it < max_iters, pending > 0)

        def body(carry):
            states_c, deltas_c, accums_c, it, ovf_c, errs_c = carry
            it_inputs = dict(empty_inputs)
            for nm, d in zip(names, deltas_c):
                it_inputs[nm] = d
            states_n, new_deltas, o, errs_n = run_values(
                list(states_c), it_inputs
            )
            new_accums = []
            for i, (a, d) in enumerate(zip(accums_c, new_deltas)):
                m = consolidate(
                    concat_batches([a, d]), include_time=False
                )
                m, so = shrink(m, cap)
                o[("lr", site, i)] = jnp.logical_or(o[("lr", site, i)], so)
                new_accums.append(m.canonicalize_nulls())
            assert sorted(o.keys()) == ovf_keys, "ovf keys drifted"
            return (
                tuple(states_n),
                tuple(new_deltas),
                tuple(new_accums),
                it + 1,
                jnp.logical_or(ovf_c, pack(o)),
                errs_c + errs_n,
            )

        carry0 = (
            tuple(states_l),
            tuple(deltas),
            tuple(accums),
            jnp.asarray(1, jnp.int32),
            pack(ovf),
            errs0,
        )
        states_f, _, accums_f, _, ovf_f, errs_f = jax.lax.while_loop(
            cond, body, carry0
        )
        # Surface the fixpoint's accumulated per-code error counts into
        # the OUTER step's err collection (zero-diff rows consolidate
        # away downstream).
        from ..expr import errors as _errors
        from ..repr.schema import ERR_SCHEMA

        if _errors.step_active():
            _errors.push_step(
                Batch(
                    cols=(
                        jnp.arange(_N_ERR_CODES, dtype=jnp.int64),
                    ),
                    nulls=(None,),
                    time=jnp.full(
                        _N_ERR_CODES, time, dtype=jnp.uint64
                    ),
                    diff=errs_f,
                    count=jnp.asarray(_N_ERR_CODES, jnp.int32),
                    schema=ERR_SCHEMA,
                )
            )

        # Body consumes real inputs + the per-step total binding deltas.
        body_inputs = dict(inputs)
        for nm, a in zip(names, accums_f):
            body_inputs[nm] = a
        states_l = list(states_f)
        out, upd_b, ovf_b = body_fn(states_l, body_inputs, time)

        upd = {i: s for i, s in enumerate(states_l)}
        upd.update(upd_b)
        ovf_out = {k: ovf_f[i] for i, k in enumerate(ovf_keys)}
        ovf_out.update(ovf_b)
        return out, upd, ovf_out

    return run



def _scalar_col_refs(e, out: set) -> None:
    from ..expr import scalar as ms

    if isinstance(e, ms.ColumnRef):
        out.add(e.index)
        return
    for f in getattr(e, "__dataclass_fields__", {}):
        v = getattr(e, f)
        if isinstance(v, ms.ScalarExpr):
            _scalar_col_refs(v, out)
        elif isinstance(v, tuple):
            for x in v:
                if isinstance(x, ms.ScalarExpr):
                    _scalar_col_refs(x, out)


def _resolve_basic_sites(expr: mir.RelationExpr, ctx) -> list:
    """Resolve basic-aggregate Reduce sites against the dataflow's
    top-level expression.

    A basic aggregate's device column is an opaque digest; its real
    (variable-width) value exists only at the serving edge. The digest
    may flow to the output through Project/Map/Filter layers that do
    not COMPUTE on it; anything else would leak digests into real
    operators, so it raises. Returns
    [(output col, state slot, state part, AggregateExpr, value Column,
      key_out_cols)] where key_out_cols maps each group-key column to
    its position in the OUTPUT schema (None if any key column was
    projected away — finalization then falls back to digest-only
    lookup).
    """
    if not ctx.basic_sites:
        return []
    chain = []
    node = expr
    while isinstance(node, (mir.Project, mir.Map, mir.Filter)):
        chain.append(node)
        node = node.input
    sites = {nid: (slot, op) for (nid, slot, op) in ctx.basic_sites}
    finalizers: list = []
    if id(node) in sites:
        slot, op = sites.pop(id(node))
        pos: dict = {}
        # Track the group-key columns through the chain too: when they
        # all survive to the output, finalization keys its lookup by
        # group key (digest demoted to a consistency check) — a 64-bit
        # digest collision between two groups then raises instead of
        # silently serving one group's result for the other.
        keypos: dict = {k: k for k in range(op.n_key)}
        for b, (j, agg) in enumerate(op.basic_aggs):
            part = 1 + len(op.hier_aggs) + b
            vcol = agg.expr.typ(op.input_schema)
            pos[op.n_key + j] = (slot, part, agg, vcol)
        for layer in reversed(chain):
            if isinstance(layer, (mir.Map, mir.Filter)):
                exprs = (
                    layer.scalars
                    if isinstance(layer, mir.Map)
                    else layer.predicates
                )
                refs: set = set()
                for e in exprs:
                    _scalar_col_refs(e, refs)
                if refs & set(pos):
                    raise NotImplementedError(
                        "string_agg/array_agg/list_agg results cannot "
                        "feed scalar expressions or filters: the "
                        "maintained device column is a digest, "
                        "finalized only at the serving edge"
                    )
            else:  # Project
                pos = {
                    o: pos[srcidx]
                    for o, srcidx in enumerate(layer.outputs)
                    if srcidx in pos
                }
                inv = {}
                for o, srcidx in enumerate(layer.outputs):
                    for k, p in keypos.items():
                        if p == srcidx and k not in inv:
                            inv[k] = o
                keypos = inv
        key_out = (
            tuple(keypos[k] for k in range(op.n_key))
            if len(keypos) == op.n_key
            else None
        )
        finalizers = [(o, *v, key_out) for o, v in pos.items()]
    if sites:
        raise NotImplementedError(
            "string_agg/array_agg/list_agg must sit at the dataflow "
            "output (optionally under Project/Map/Filter); composing "
            "them into joins, further reduces, or other operators is "
            "not supported"
        )
    return finalizers


def _finalize_basic_value(agg, vcol, values, vnulls, mults, gdict) -> str:
    """Materialize one group's basic-aggregate result from its sorted
    multiset (host side). ``vnulls`` marks NULL elements (array_agg/
    list_agg preserve them; rendered as pg's array NULL literal).
    ``gdict`` is the caller's epoch-coherent dictionary snapshot."""
    from ..expr.relation import AggregateFunc
    from ..repr.schema import ColumnType

    def render(v) -> str:
        if vcol.ctype is ColumnType.STRING:
            return gdict.decode(int(v))
        if vcol.ctype is ColumnType.BOOL:
            return "t" if v else "f"
        if vcol.ctype is ColumnType.DECIMAL and vcol.scale:
            q = 10 ** vcol.scale
            sign = "-" if v < 0 else ""
            v = abs(int(v))
            return f"{sign}{v // q}.{v % q:0{vcol.scale}d}"
        if vcol.ctype is ColumnType.DATE:
            from ..repr.schema import days_to_date

            return str(days_to_date(v))
        if vcol.ctype is ColumnType.TIMESTAMP:
            from ..repr.schema import ms_to_ts

            return str(ms_to_ts(v))
        return str(int(v))

    parts: list = []
    for i, (v, m) in enumerate(zip(values, mults)):
        s = (
            "NULL"
            if vnulls is not None and bool(vnulls[i])
            else render(v)
        )
        parts.extend([s] * int(m))
    if agg.func is AggregateFunc.STRING_AGG:
        sep = agg.params[0] if agg.params else ""
        return sep.join(parts)
    return "{" + ",".join(parts) + "}"



class _DataflowBase:
    """Shared host-side machinery: pipelined stepping, overflow-driven
    capacity growth with rollback/replay, peeks.

    The output arrangement (the index export: TraceManager arrangement,
    render.rs:357) lives ON DEVICE as part of the step state; per-step
    host traffic is one packed overflow-flag readback, checked once per
    pipelined run (device->host transfers through the TPU tunnel are the
    latency cost center, so the hot loop never reads data back)."""

    def _init_output(
        self, capacity: int = 256, levels: int = 2, slots: int = 0
    ):
        from ..repr.schema import ERR_SCHEMA

        out_key = tuple(range(self.out_schema.arity))
        # The output index is a two-run Spine: per-step inserts touch
        # only the tail; scheduled compactions fold the tail into the
        # base (so an index over a 2^20-row collection costs O(tail)
        # per step, not O(state)). HASH order: the output index serves
        # consolidation and full scans, never in-range value order, so
        # it rides the 2-lane hash order that keeps state-scale merges
        # sort-free and search-cheap (spine.py order modes).
        self.output = Spine.empty(
            self.out_schema, out_key, capacity,
            tail_capacity=self._ctx.out_delta_cap,
            order="hash",
            levels=levels,
            ingest_slots=slots,
        )
        # The err collection: scalar-evaluation errors maintained next
        # to the data output (ok/err pair, render.rs:12-101). Reads
        # consult it first; deleting the offending row retracts the
        # error.
        self.err_output = Arrangement.empty(ERR_SCHEMA, (0,), 256)
        self._ovf_keys: list = []
        # Device-resident logical time: created once, then carried as a
        # step output -> next step input. Feeding time from the host
        # would cost one h2d transfer per step — measured ~8 ms through
        # the remote-TPU tunnel, which was the dominant per-step cost in
        # round 1 (PERF_NOTES.md).
        self._time_dev = None
        # Deferred-overflow-check bookkeeping (see run_steps/check_flags).
        # Flags accumulate as a running ON-DEVICE logical_or — one tiny
        # array regardless of how many steps are deferred. (Keeping a
        # per-step list and stacking at check time built a program with
        # one operand PER DEFERRED STEP; at ~500 steps that program took
        # tens of minutes to build+run through the remote-TPU tunnel —
        # the actual cause of rounds 3/4's driver bench timeouts.)
        self._defer_ck = None
        self._defer_log: list = []
        self._defer_flags = None
        self._defer_cflags = None
        # Donation bookkeeping for the CURRENT defer window (ISSUE 8):
        # which carry parts ride donated dispatches (the provenance
        # prover's unsound-donation check reads this), and whether the
        # window checkpoint is a fresh-buffer clone (a donated window
        # with a plain reference checkpoint would resurrect dead
        # buffers on rollback).
        self._defer_donated: tuple = ()
        self._defer_ck_cloned = False
        # Spine-compaction schedule (differential's geometric spine-
        # merge budget): every `_compact_every` steps, fold level 0 of
        # every spine into level 1; every `_compact_every *
        # _compact_ratio^l` steps, also fold level l. Deterministic —
        # driven by a host tick counter that is part of the rollback
        # checkpoint, so overflow replays reproduce the same schedule.
        self._compact_every = 8
        self._compact_ratio = 8
        self._compact_tick = 0
        self._compact_jits: dict = {}
        self._covf_keys = self._compact_keys()
        # Pipelined-control-plane bookkeeping (ISSUE 7): d2h readback
        # census (every flags transfer increments it — the trace's
        # readbacks-per-span counter reads deltas of this), and the
        # span executor attached to this dataflow, if any (reads of
        # dataflow state sequence against its span boundaries).
        self._readbacks = 0
        self._span_exec = None

    # Back-compat shim for callers that poked the old counter directly.
    @property
    def _steps_since_compact(self) -> int:
        return self._compact_tick % self._compact_every

    def _compact_keys(self) -> list:
        """Overflow-flag keys of the compact program (per-target-run
        growth across every spine level), in the deterministic order
        every compact variant packs them (variants that do not touch a
        level pack False for it — flag shape is uniform). A slotted
        spine's level-0 flush targets run 0, so its keys start at run
        index 0; slotless spines' first target is run 1."""
        from ..arrangement.spine import compact_depth

        keys = []
        for slot, parts in enumerate(self.states):
            for p, s in enumerate(parts):
                if isinstance(s, Spine):
                    first = 0 if s.slots else 1
                    for lvl in range(compact_depth(s)):
                        keys.append(("state", slot, (p, first + lvl)))
        first = 0 if self.output.slots else 1
        for lvl in range(compact_depth(self.output)):
            keys.append(("out", first + lvl))
        return keys

    def _due_levels(self, tick: int) -> int:
        """Highest spine level due for folding at compaction tick
        `tick` (tick counts steps; called when tick %
        _compact_every == 0). Level l folds every
        _compact_every * _compact_ratio^l steps."""
        lvl = 0
        period = self._compact_every * self._compact_ratio
        while tick % period == 0:
            lvl += 1
            period *= self._compact_ratio
        return lvl

    def _pack_flags(self, ovf: dict) -> jnp.ndarray:
        """Deterministically order overflow flags into one tiny array.
        Captures the key order at trace time (the dict's keys are a
        static property of the rendered plan)."""
        keys = sorted(ovf.keys())
        self._ovf_keys = keys
        if not keys:
            return jnp.zeros((0,), jnp.bool_)
        return jnp.stack(
            [jnp.asarray(ovf[k]).astype(jnp.bool_).reshape(()) for k in keys]
        )

    def _grow_for(self, key, target: int | None = None) -> None:
        """Grow the capacity tier behind an overflowed key — one
        doubling by default, or straight to ``target`` in a single pad
        (callers applying known steady-state tiers up front skip the
        doubling ladder, whose per-rung pad programs each cost a compile
        + dispatch through the TPU tunnel). Explicit targets snap to
        the pow2 quantization menu (ISSUE 16) so applied bench tiers
        land on bankable program keys; doubling from a quantized base
        stays on the menu by construction."""
        if target is not None:
            from ..plan.decisions import quantize_cap

            target = quantize_cap(target)
        if key[0] == "state":
            _, slot, part = key
            parts = list(self.states[slot])
            if isinstance(part, tuple):  # spine sub-run: (part, which)
                p, which = part
                parts[p] = self._grow_spine(parts[p], which, target)
            else:
                parts[part] = self._grow_arrangement(parts[part], target)
            self.states[slot] = tuple(parts)
        elif key[0] == "out":
            self.output = self._grow_spine(self.output, key[1], target)
        elif key[0] == "join":
            self._ctx.join_caps[key[1]] *= 2
            self._remake_jit()
        elif key[0] == "x":
            self._ctx.slot_cap *= 2
            self._remake_jit()
        elif key[0] == "lr":
            self._ctx.letrec_caps[key[1]] *= 2
            self._remake_jit()
        elif key[0] == "outd":
            self._ctx.out_delta_cap *= 2
            self._remake_jit()
        elif key[0] == "errout":
            self.err_output = self._grow_arrangement(
                self.err_output, target
            )
        else:
            raise AssertionError(f"unknown overflow key {key}")

    def _grow_arrangement(
        self, arr: Arrangement, target: int | None = None
    ) -> Arrangement:
        return arr.map_batches(lambda b: self._grow_batch(b, target))

    @staticmethod
    def _pad_lanes(lanes, new_cap: int):
        """Zero-pad a cached ``[cap, L]`` lane array to a grown run
        capacity. Pad rows' lanes are garbage either way (every lane
        consumer bounds itself by the run's count), so no recompute."""
        if lanes.shape[0] >= new_cap:
            return lanes
        return (
            jnp.zeros((new_cap, lanes.shape[1]), lanes.dtype)
            .at[: lanes.shape[0]]
            .set(lanes)
        )

    def _grow_spine(
        self, spine: Spine, which, target: int | None = None
    ) -> Spine:
        """Grow one run of a spine. `which` is a run index, or the
        aliases "base" (largest run) / "tail" (the ingest tier: the
        slot ring when present, else run 0). Cached lanes are padded
        alongside their run."""
        if which == "tail" and spine.slots:
            new_slots = tuple(
                self._grow_batch(s, target) for s in spine.slots
            )
            slot_lanes = spine.slot_lanes
            if slot_lanes:
                slot_lanes = tuple(
                    self._pad_lanes(l, nb.capacity)
                    for l, nb in zip(slot_lanes, new_slots)
                )
            return Spine(
                spine.runs_b,
                spine.key,
                spine.order,
                new_slots,
                spine.cursor,
                spine.lanes,
                slot_lanes,
            )
        if which == "base":
            which = spine.levels - 1
        elif which == "tail":
            which = 0
        grown = self._grow_batch(spine.runs_b[which], target)
        lanes = None
        if spine.lanes:
            lanes = self._pad_lanes(spine.lanes[which], grown.capacity)
        return spine.with_run(which, grown, lanes)

    def _check_slot_ring(self) -> None:
        """The append-slot ring must hold every insert between level-0
        flushes: a ring smaller than _compact_every would silently
        overwrite unflushed slots (the cursor wraps; no overflow flag
        can catch it)."""
        for sp in [self.output] + [
            s
            for parts in self.states
            for s in parts
            if isinstance(s, Spine)
        ]:
            if sp.slots and len(sp.slots) < self._compact_every:
                raise ValueError(
                    f"ingest slot ring ({len(sp.slots)}) smaller than "
                    f"compact_every ({self._compact_every}): inserts "
                    "would overwrite unflushed slots"
                )

    def step(self, inputs: dict) -> Batch:
        """Feed one micro-batch of updates per source; returns the output
        delta (device-resident) and advances the frontier."""
        return self.run_steps([inputs])[-1]

    def gather_delta(self, out: Batch) -> Batch:
        """Host view of a step's output delta. Single-device dataflows
        are already host-readable; ShardedDataflow overrides this to
        gather per-worker shards. Callers (MaintainedView) use this
        uniformly instead of duck-typing on the dataflow class."""
        return out

    @property
    def time(self) -> int:
        """Host mirror of the dataflow frontier (all steps < time are
        complete)."""
        return self._time

    @time.setter
    def time(self, v: int) -> None:
        # External time assignment (e.g. MaintainedView aligning the
        # dataflow to a shard as_of) must invalidate the device-resident
        # time carry, or steps would run at a stale timestamp. The hot
        # loop (_dispatch_span) advances self._time directly so the
        # carry survives normal stepping.
        self._time = v
        if getattr(self, "_time_dev", None) is not None:
            self._time_dev = None

    def _apply_err_delta(self, err_output, err_parts, ovf: dict):
        """Fold a step's collected error batches into the err
        arrangement (shared by single-device and sharded step bodies).
        Returns the new err arrangement; mutates ovf and records the
        trace-time fact of whether this dataflow CAN produce errors
        (peek_errors shortcuts when it can't)."""
        self._has_errors = bool(err_parts)
        if not err_parts:
            return err_output
        errs = consolidate(
            concat_batches(err_parts), include_time=False
        )
        errs, err_shrink = shrink(errs, 2048)
        new_err, err_ovf = insert(
            err_output, errs, out_capacity=err_output.capacity
        )
        ovf[("errout",)] = jnp.logical_or(err_shrink, err_ovf)
        return new_err

    def _accumulate_errors(self, rows) -> list[tuple]:
        acc: dict = {}
        for r in rows:
            acc[r[0]] = acc.get(r[0], 0) + r[-1]
        return sorted((c, n) for c, n in acc.items() if n != 0)

    # -- basic-aggregate edge finalization ---------------------------------
    # Shared by single-device and sharded dataflows (sharded overrides
    # _basic_multiset_host with a per-worker gather — the reduce input
    # exchange keys groups to one worker, so shards concatenate into a
    # group-contiguous multiset). render/reduce.rs:369 analog.

    def _basic_multiset_host(self, arr) -> dict:
        """Host view of one basic-aggregate multiset arrangement."""
        b = arr.batch
        n = int(b.count)
        return {
            "n": n,
            "cols": [np.asarray(c)[:n] for c in b.cols],
            "nulls": [
                None if x is None else np.asarray(x)[:n]
                for x in b.nulls
            ],
            "diff": np.asarray(b.diff)[:n],
        }

    def capture_basic_multisets(self) -> dict:
        """Pre-step host snapshot of every basic multiset part: the
        persist-sink delta path finalizes RETRACTION rows against the
        state their digests describe (the post-step multiset no longer
        holds it)."""
        out: dict = {}
        for fi, (_oc, slot, part, *_rest) in enumerate(
            self._basic_finalizers
        ):
            out[fi] = self._basic_multiset_host(
                self.states[slot][part]
            )
        return out

    def _basic_group_maps(self, multisets: dict | None = None) -> list:
        """Per-finalizer (by_digest, by_key) result-lookup maps built
        from the multiset state (or from pre-captured host views)."""
        from ..ops.reduce import _NULL_DIGEST, _mix64_host
        from ..repr.schema import GLOBAL_DICT

        gdict = GLOBAL_DICT.snapshot()
        maps: list = []
        for fi, (
            out_col, slot, part, agg, vcol, key_out
        ) in enumerate(self._basic_finalizers):
            arr = self.states[slot][part]
            b = (
                multisets[fi]
                if multisets is not None
                else self._basic_multiset_host(arr)
            )
            bcols, bnulls, diffs = b["cols"], b["nulls"], b["diff"]
            keep = diffs != 0
            n_key = len(arr.key)
            vals = bcols[n_key][keep].astype(np.int64)
            vnl = bnulls[n_key]
            vnl = vnl[keep] if vnl is not None else None
            mult = diffs[keep]
            by_digest: dict = {}
            by_key: dict = {}
            if len(vals):
                # Masked key columns, computed ONCE (the per-group loop
                # below only indexes them — re-masking per group made
                # finalization O(groups * rows)).
                kcols = [bcols[ki][keep] for ki in range(n_key)]
                knulls = [
                    None if bnulls[ki] is None else bnulls[ki][keep]
                    for ki in range(n_key)
                ]
                # Group boundaries: multiset rows sort by (key, value)
                # with NULL keys canonicalized first, so groups are
                # contiguous; compare raw values gated on null flags.
                change = np.zeros(len(vals), dtype=bool)
                change[0] = True
                for kc, nl in zip(kcols, knulls):
                    if nl is None:
                        change[1:] |= kc[1:] != kc[:-1]
                    else:
                        both = ~nl[1:] & ~nl[:-1]
                        change[1:] |= (nl[1:] != nl[:-1]) | (
                            both & (kc[1:] != kc[:-1])
                        )
                starts = np.flatnonzero(change)
                ends = np.append(starts[1:], len(vals))
                m = _mix64_host(vals).astype(np.uint64)
                if vnl is not None:
                    m = np.where(
                        vnl,
                        np.uint64(np.int64(_NULL_DIGEST)),
                        m,
                    )
                m = m * mult.astype(np.uint64)
                for s0, e0 in zip(starts, ends):
                    dig = int(
                        m[s0:e0].sum(dtype=np.uint64).astype(np.int64)
                    )
                    res = _finalize_basic_value(
                        agg, vcol, vals[s0:e0],
                        vnl[s0:e0] if vnl is not None else None,
                        mult[s0:e0], gdict,
                    )
                    by_digest[dig] = res
                    if key_out is not None:
                        kt = tuple(
                            None
                            if knulls[ki] is not None
                            and bool(knulls[ki][s0])
                            else kcols[ki][s0].item()
                            for ki in range(n_key)
                        )
                        by_key[kt] = (dig, res)
            maps.append((by_digest, by_key))
        return maps

    def finalize_basic_columns(
        self, cols, nulls, diffs=None, old_multisets=None
    ) -> list:
        """Edge finalization of basic aggregates: replace each digest
        value in the host output columns with the group's materialized
        result STRING (object-dtype column; decode_result_rows passes
        pre-decoded columns through — results never round-trip the
        global dictionary, which peeks under churn would otherwise grow
        without bound), computed from the maintained (key, value)
        multiset state.

        When every group-key column survives to the output, the lookup
        is keyed by group key with the digest as a consistency check (a
        64-bit digest collision between groups raises instead of
        serving the wrong group's result); digest-only lookup is the
        fallback for outputs that project keys away.

        With ``diffs`` + ``old_multisets`` (the persist-sink delta
        path), RETRACTION rows (diff < 0) resolve against the pre-step
        maps — their digests describe group states the current multiset
        no longer holds."""
        if not self._basic_finalizers:
            return list(cols)
        new_maps = self._basic_group_maps()
        old_maps = (
            self._basic_group_maps(old_multisets)
            if old_multisets is not None
            else None
        )
        cols = list(cols)
        for fi, (
            out_col, slot, part, agg, vcol, key_out
        ) in enumerate(self._basic_finalizers):
            src = np.asarray(cols[out_col])
            out = np.empty(len(src), dtype=object)
            nl = nulls[out_col] if nulls else None
            key_src = (
                [np.asarray(cols[ko]) for ko in key_out]
                if key_out is not None
                else None
            )
            for i in range(len(src)):
                if nl is not None and nl[i]:
                    out[i] = None
                    continue
                retract = (
                    diffs is not None
                    and old_maps is not None
                    and diffs[i] < 0
                )
                by_digest, by_key = (
                    old_maps[fi] if retract else new_maps[fi]
                )
                d = int(src[i])
                if key_out is not None:
                    kt = tuple(
                        None
                        if nulls[ko] is not None and bool(nulls[ko][i])
                        else key_src[kk][i].item()
                        for kk, ko in enumerate(key_out)
                    )
                    hit = by_key.get(kt)
                    if hit is None:
                        raise RuntimeError(
                            "basic-aggregate group has no multiset "
                            "entry (state divergence)"
                        )
                    dig, res = hit
                    if dig != d:
                        raise RuntimeError(
                            "basic-aggregate digest mismatch for group "
                            f"{kt!r} (digest/multiset divergence)"
                        )
                    out[i] = res
                else:
                    if d not in by_digest:
                        raise RuntimeError(
                            "basic-aggregate digest has no multiset "
                            "group (digest/multiset divergence)"
                        )
                    out[i] = by_digest[d]
            cols[out_col] = out
        return cols

    def _build_env(self):
        if getattr(self, "_str_keys", None):
            # dictionary side-tables for string functions: built once
            # per span (inputs are already encoded, so the dictionary
            # is stable across the span's steps)
            from ..expr import strings

            return strings.build_env(
                self._str_keys, getattr(self, "_str_depth", 1)
            )
        return None

    def _checkpoint(self):
        return (
            list(self.states),
            self.output,
            self.err_output,
            self.time,
            self._time_dev,
            self._compact_tick,
        )

    def _restore(self, ck):
        (
            self.states,
            self.output,
            self.err_output,
            self.time,
            self._time_dev,
            self._compact_tick,
        ) = ck

    def _dispatch_compact(self, max_level: int = 10**9):
        """Dispatch one spine-compaction program folding levels
        [0, max_level] of every spine (clamped per spine; the default
        is a full cascade). Async like steps; returns its packed
        per-target-run overflow flags (key order: self._covf_keys —
        uniform across variants; untouched levels pack False)."""
        from ..utils.lockcheck import device_dispatch

        device_dispatch("_dispatch_compact")
        jitfn = self._compact_jits.get(max_level)
        if jitfn is None:
            jitfn = self._make_compact_jit(max_level)
            self._compact_jits[max_level] = jitfn
        new_states, new_output, cfl = jitfn(
            tuple(self.states), self.output
        )
        self.states = list(new_states)
        self.output = new_output
        return cfl

    def _compact_core_single(self, states, output, max_level: int = 10**9):
        """Trace body of the compact program (single-device layout).
        Walks the static state layout; only Spine parts are touched —
        levels [0, max_level] of each (clamped to the spine's depth)."""
        from ..arrangement.spine import compact_depth

        flags = {}
        new_states = []
        for slot, parts in enumerate(states):
            ps = list(parts)
            for p, s in enumerate(ps):
                if isinstance(s, Spine):
                    sp = s
                    first = 0 if sp.slots else 1
                    for lvl in range(
                        min(max_level + 1, compact_depth(sp))
                    ):
                        sp, ovf = compact_level(sp, lvl)
                        flags[("state", slot, (p, first + lvl))] = ovf
                    ps[p] = sp
            new_states.append(tuple(ps))
        new_out = output
        first = 0 if output.slots else 1
        for lvl in range(min(max_level + 1, compact_depth(output))):
            new_out, ovf = compact_level(new_out, lvl)
            flags[("out", first + lvl)] = ovf
        packed = jnp.stack(
            [
                jnp.asarray(
                    flags.get(k, jnp.asarray(False))
                ).astype(jnp.bool_).reshape(())
                for k in self._covf_keys
            ]
        )
        return tuple(new_states), new_out, packed

    @staticmethod
    def _or_acc(acc, fl):
        """Fold one packed flag array into the running on-device OR."""
        if acc is None:
            return fl
        return jnp.logical_or(acc, fl)

    def _dispatch_span(self, packed: list, env, donate: tuple = ()):
        """Asynchronously dispatch one step per packed input, plus the
        scheduled spine compactions. ZERO host transfers: time rides as
        a device scalar (created once per dataflow), overflow flags
        accumulate as a running on-device logical_or for the caller to
        check. Returns (deltas, step-flag OR, compaction-flag OR).

        ``donate`` names the carry parts handed to the step program's
        ``donate_argnums`` (the prover-approved subset): each step then
        writes its output carry into the previous step's buffers
        instead of allocating state-sized arrays per dispatch. The
        killed leaves are recorded in the sanitizer ledger — dead the
        moment the dispatch returns."""
        from ..utils.lockcheck import device_dispatch

        device_dispatch("_dispatch_span")
        if self._time_dev is None:
            self._time_dev = jnp.asarray(self.time, dtype=jnp.uint64)
        step_fn = self._step_jit
        record = None
        if donate:
            from ..analysis.donation import (
                LEDGER,
                STEP_ARGNUM as part_arg,
                sanitizer_enabled,
            )

            if _donation_supported():
                step_fn = self._donated_step_program(tuple(donate))
            # Resolve the sanitizer ONCE per dispatch train: with it
            # off (the production default) the hot loop must not pay
            # per-tick ledger-argument construction or a dyncfg-lock
            # read. The contract still holds on any backend when on.
            record = LEDGER.record if sanitizer_enabled() else None
        deltas, flags_or, cflags_or = [], None, None
        for p in packed:
            args = (
                tuple(self.states),
                self.output,
                self.err_output,
                p,
                self._time_dev,
            )
            if env is not None:
                out, new_states, new_output, new_err, new_t, fl = (
                    step_fn(*args, env)
                )
            else:
                out, new_states, new_output, new_err, new_t, fl = (
                    step_fn(*args)
                )
            self.states = list(new_states)
            self.output = new_output
            self.err_output = new_err
            self._time_dev = new_t
            self._time += 1  # direct: keep the device carry live
            if record is not None:
                record(
                    tuple(args[part_arg[part]] for part in donate),
                    f"{self.name}.run_steps step t={self._time - 1} "
                    f"(donated {','.join(donate)})",
                )
            deltas.append(out)
            flags_or = self._or_acc(flags_or, fl)
            self._compact_tick += 1
            if self._compact_tick % self._compact_every == 0:
                cflags_or = self._or_acc(
                    cflags_or,
                    self._dispatch_compact(
                        min(
                            self._due_levels(self._compact_tick),
                            self._max_compact_level(),
                        )
                    ),
                )
        return deltas, flags_or, cflags_or

    def _read_flags(self, flags_or, keys: list) -> np.ndarray:
        """One tiny d2h readback of the OR-accumulated overflow flags.
        NOTE: through the remote-TPU tunnel, the FIRST d2h readback in a
        process permanently switches dispatch from pipelined-async to
        synchronous round-trips (~10 ms/dispatch; measured, see
        PERF_NOTES.md). Latency-critical paths defer this via
        run_steps(defer_check=True) + check_flags()."""
        if flags_or is not None and keys:
            self._readbacks += 1
            fh = np.asarray(flags_or)  # [nkeys] or [nkeys, P]
            return fh.reshape(len(keys), -1).any(axis=1)
        return np.zeros(len(keys) if keys else 0, dtype=bool)

    def _overflowed_keys(self, flags_or, cflags_or) -> list:
        """Read both flag groups (steps + compactions); returns the list
        of overflowed tier keys."""
        out = []
        for i in np.nonzero(self._read_flags(flags_or, self._ovf_keys))[0]:
            out.append(self._ovf_keys[i])
        for i in np.nonzero(
            self._read_flags(cflags_or, self._covf_keys)
        )[0]:
            out.append(self._covf_keys[i])
        return out

    def _compact_now(self) -> None:
        """Synchronously compact every spine (full cascade into the
        base): peeks and snapshots read the base run as THE
        consolidated state. Grows run tiers on overflow and retries."""
        while True:
            ck = self._checkpoint()
            cfl = self._dispatch_compact()
            self._compact_tick = 0
            over = self._read_flags(cfl, self._covf_keys)
            if not over.any():
                return
            self._restore(ck)
            for i in np.nonzero(over)[0]:
                self._grow_for(self._covf_keys[i])

    def output_batch(self) -> Batch:
        """Consolidated single-run view of the maintained output index
        (device-resident). Forces a spine compaction first — peeks are
        off the hot path (compute_state.rs:744 handle_peek reads a
        trace cursor; here the compacted base run IS the cursor)."""
        self.span_barrier()
        self.check_flags()
        self._compact_now()
        return self.output.base

    def output_records(self) -> int:
        """Approximate maintained row count (sum over all runs and
        ingest slots; may overcount rows whose diffs cancel across
        runs until the next compaction). Introspection only — one
        small d2h read. Deliberately NOT span-barriered: the replica
        reports records alongside every frontier change, and syncing
        there would serialize the span double-buffer once per loop;
        counts may include rows an in-flight span is still inserting
        (the refs are that span's OUTPUT buffers — always valid, even
        under donation)."""
        return int(
            sum(
                np.asarray(b.count).sum()
                for b in self.output.runs_b + self.output.slots
            )
        )

    def run_steps(
        self,
        inputs_list: list,
        defer_check: bool = False,
        donate=False,
    ) -> list:
        """Feed several micro-batches with deferred overflow handling:
        all steps are submitted asynchronously and the packed overflow
        flags are read once at the end of the span; on overflow the
        whole span is rolled back (states are immutable device values),
        tiers grown, and the span replayed — steps are pure, so the
        replay is idempotent. This keeps the hot loop free of per-step
        syncs.

        With ``defer_check=True`` even the end-of-span readback is
        skipped: flags are stashed on device and only read when the
        caller invokes :meth:`check_flags` (or a later synchronous
        ``run_steps``). Until then the span's inputs stay referenced so
        an overflow discovered later can still roll back and replay.

        ``donate`` hands carry parts to the step program's
        ``donate_argnums`` — True for the whole carry, or a tuple of
        part names from ``analysis.provenance.CARRY_PARTS`` (the
        prover's per-argnum verdict). The donation CONTRACT (cloned
        window checkpoint, ledger record of the killed leaves) engages
        whenever donation is REQUESTED; the argnums themselves narrow
        to backends that honor donation (_donation_supported — the one
        shared predicate). Callers must decide donation at a fresh
        defer window: a window that started with a plain-reference
        checkpoint keeps its spans un-donated (rollback would
        resurrect dead buffers otherwise).

        CAVEAT: deltas returned from a deferred span are PROVISIONAL —
        if a tier overflowed they were computed against truncated
        arrangements. Do not feed them to a sink until
        :meth:`check_flags` returns False; when it returns True, the
        corrected per-step deltas of the replay are available on
        ``self.replayed_deltas`` (in dispatch order)."""
        from ..analysis.provenance import CARRY_PARTS

        self.span_barrier()
        if getattr(self, "_first_time", None) is None:
            # The dataflow's as_of: the first processed timestamp
            # (constants fire exactly here; baked at trace time).
            self._first_time = int(self.time)
            self._ctx.first_time = self._first_time
        self._check_slot_ring()
        parts = (
            tuple(CARRY_PARTS)
            if donate is True
            else tuple(donate or ())
        )
        packed = [self._pack_inputs(i) for i in inputs_list]
        env = self._build_env()
        if parts:
            from ..analysis.donation import guard_read

            # Re-dispatching a donated buffer as an operand is itself
            # a use-after-donate (sanitizer-gated, no-op when off).
            guard_read(packed, f"{self.name}.run_steps operands")
        if defer_check:
            if self._defer_ck is None:
                self._defer_ck = (
                    self._clone_checkpoint()
                    if parts
                    else self._checkpoint()
                )
                self._defer_ck_cloned = bool(parts)
            elif parts and not self._defer_ck_cloned:
                # Mid-window donation flip with a plain reference
                # checkpoint: donating now could resurrect dead
                # buffers on rollback. Stay un-donated until the
                # window turns over (the view re-decides there).
                parts = ()
            if parts:
                self._defer_donated = tuple(
                    sorted(set(self._defer_donated) | set(parts))
                )
            deltas, flags_or, cflags_or = self._dispatch_span(
                packed, env, donate=parts
            )
            self._defer_log.append((packed, env))
            if flags_or is not None:
                self._defer_flags = self._or_acc(
                    self._defer_flags, flags_or
                )
            if cflags_or is not None:
                self._defer_cflags = self._or_acc(
                    self._defer_cflags, cflags_or
                )
            return deltas
        self.check_flags()
        while True:
            ck = (
                self._clone_checkpoint() if parts else self._checkpoint()
            )
            deltas, flags, cflags = self._dispatch_span(
                packed, env, donate=parts
            )
            over = self._overflowed_keys(flags, cflags)
            if over:
                self._restore(ck)
                for k in over:
                    self._grow_for(k)
                continue
            return deltas

    # -- span-scan execution ------------------------------------------------
    #
    # Through the remote-TPU tunnel every dispatch+block round trip
    # costs ~96ms, paid serially (PERF_NOTES.md round 5). A per-step
    # host loop is therefore RTT-bound at ~10 steps/s regardless of
    # device speed. run_span executes K steps as ONE device program —
    # lax.scan chunks of `_compact_every` steps with the spine
    # compaction traced BETWEEN chunks — so a span pays one RTT total.
    # This is also the TPU-native shape independent of the tunnel: the
    # micro-batch loop is control flow, and control flow belongs on
    # device (lax.scan), not in Python.

    def _stack_packed(self, packed_list: list) -> dict:
        """Stack K per-step input dicts into one dict of batches with
        [K, ...] leaves (the scan's xs)."""
        out = {}
        for name in packed_list[0]:
            bs = [p[name] for p in packed_list]
            leaves0, treedef = jax.tree_util.tree_flatten(bs[0])
            leavess = [jax.tree_util.tree_flatten(b)[0] for b in bs]
            stacked = [
                jnp.stack([lv[i] for lv in leavess])
                for i in range(len(leaves0))
            ]
            out[name] = jax.tree_util.tree_unflatten(treedef, stacked)
        return out

    def _max_compact_level(self) -> int:
        """Deepest fold index any spine in this dataflow can take."""
        from ..arrangement.spine import compact_depth

        deepest = compact_depth(self.output) - 1
        for parts in self.states:
            for s in parts:
                if isinstance(s, Spine):
                    deepest = max(deepest, compact_depth(s) - 1)
        return deepest

    def _make_span_jit(self, with_env: bool, donate: bool = False):
        """ONE program for every span shape: an outer lax.scan over
        chunks whose xs carry (chunk inputs, compaction level) — the
        geometric cadence is RUNTIME DATA dispatched with lax.switch,
        so the pattern never forces a recompile (the unrolled-chunk
        form compiled one ~3-minute variant per distinct pattern).

        ``donate`` donates the carry argnums (states, output spine,
        err arrangement, device time) so XLA writes each span's output
        state into the input state's buffers instead of allocating and
        copying state-sized arrays per dispatch (the h2d/HBM traffic
        saver of the pipelined control plane). Donated inputs are DEAD
        after the call — see _clone_checkpoint for the rollback
        contract; backends without donation support (CPU) silently
        ignore it."""
        ce = self._compact_every
        n_branches = self._max_compact_level() + 1

        def span(states, output, err_output, time_dev, chunks, levels,
                 *env_a):
            env = env_a[0] if env_a else None

            def chunk_body(carry, xs):
                chunk, lvl = xs
                st, o, e, t = carry
                # Only the spine's INGEST tier rides the inner scan
                # carry (the slot ring + cursor when present, else run
                # 0 — each WITH its cached lanes, which the insert
                # rewrites every step); every other run (and its
                # lanes) is chunk-invariant (the step never touches
                # it) and rejoins only for the compaction.
                if o.slots:
                    invariant = o.runs_b
                    inv_lanes = o.lanes

                    if o.lanes:

                        def rebuild(carried):
                            slots, slot_lanes, cursor = carried
                            return Spine(
                                invariant, o.key, o.order, slots,
                                cursor, inv_lanes, slot_lanes,
                            )

                        def extract(sp):
                            return (sp.slots, sp.slot_lanes, sp.cursor)

                        carried0 = (o.slots, o.slot_lanes, o.cursor)
                    else:

                        def rebuild(carried):
                            slots, cursor = carried
                            return Spine(
                                invariant, o.key, o.order, slots, cursor
                            )

                        def extract(sp):
                            return (sp.slots, sp.cursor)

                        carried0 = (o.slots, o.cursor)
                else:
                    invariant = o.runs_b[1:]
                    inv_lanes = o.lanes[1:] if o.lanes else ()

                    if o.lanes:

                        def rebuild(carried):
                            r0, l0 = carried
                            return Spine(
                                (r0,) + invariant, o.key, o.order,
                                (), None, (l0,) + inv_lanes, (),
                            )

                        def extract(sp):
                            return (sp.runs_b[0], sp.lanes[0])

                        carried0 = (o.runs_b[0], o.lanes[0])
                    else:

                        def rebuild(carried):
                            return Spine(
                                (carried,) + invariant, o.key, o.order
                            )

                        def extract(sp):
                            return sp.runs_b[0]

                        carried0 = o.runs_b[0]

                def step_body(c2, x):
                    st2, ingest, e2, t2 = c2
                    o2 = rebuild(ingest)
                    if env is not None:
                        out, ns, no, ne, nt, fl = self._step_core(
                            st2, o2, e2, x, t2, env
                        )
                    else:
                        out, ns, no, ne, nt, fl = self._step_core(
                            st2, o2, e2, x, t2
                        )
                    return (ns, extract(no), ne, nt), (out, fl)

                (st, ingest, e, t), (deltas, fls) = jax.lax.scan(
                    step_body, (st, carried0, e, t), chunk
                )
                o = rebuild(ingest)
                branches = [
                    (lambda s_, o_, m=m: self._compact_core_single(
                        s_, o_, m
                    ))
                    for m in range(n_branches)
                ]
                st, o, cfl = jax.lax.switch(lvl, branches, st, o)
                return (st, o, e, t), (deltas, fls.any(axis=0), cfl)

            carry = (tuple(states), output, err_output, time_dev)
            carry, (deltas, sfls, cfls) = jax.lax.scan(
                chunk_body, carry, (chunks, levels)
            )
            # deltas leaves: [n_chunks, ce, ...] -> [K, ...]
            deltas_all = jax.tree_util.tree_map(
                lambda a: a.reshape((-1,) + a.shape[2:]), deltas
            )
            return carry, deltas_all, sfls.any(axis=0), cfls.any(axis=0)

        from ..utils.compile_ledger import ledger_jit

        return ledger_jit(
            jax.jit(
                span,
                compiler_options=_span_compiler_options(),
                donate_argnums=(0, 1, 2, 3) if donate else (),
            ),
            "span_donated" if donate else "span",
            self.name,
            getattr(self, "_fingerprint", self.name),
        )

    def run_span(self, inputs_list: list, donate: bool = False):
        """Feed a span of micro-batches as ONE device dispatch (deferred
        overflow checks — see run_steps). The span length must be a
        multiple of ``_compact_every``; spine compaction runs on device
        between scan chunks. Returns the stacked per-step output deltas
        (leaves shaped [K, ...], device-resident, PROVISIONAL until
        check_flags). ``donate`` hands the carry's buffers to the span
        program (see _make_span_jit); the defer checkpoint is then a
        fresh-buffer clone."""
        from ..utils.lockcheck import device_dispatch

        self.span_barrier()
        device_dispatch("run_span")
        ce = self._compact_every
        if len(inputs_list) % ce != 0:
            raise ValueError(
                f"span length {len(inputs_list)} must be a multiple of "
                f"compact_every={ce}"
            )
        if getattr(self, "_first_time", None) is None:
            self._first_time = int(self.time)
            self._ctx.first_time = self._first_time
        self._check_slot_ring()
        # Checkpoint BEFORE any dispatch (including the flush
        # compaction below): an overflow discovered at check_flags
        # time must be able to roll all of it back. Donated spans
        # clone the checkpoint to fresh buffers — the live carry's
        # buffers die at dispatch.
        if self._defer_ck is None:
            self._defer_ck = (
                self._clone_checkpoint() if donate else self._checkpoint()
            )
            self._defer_ck_cloned = bool(donate)
        elif donate and not self._defer_ck_cloned:
            # A window that started with a plain reference checkpoint
            # cannot start donating mid-window: rollback would
            # resurrect buffers a donated dispatch killed.
            donate = False
        if self._compact_tick % ce:
            # Flush (full cascade) so the span's internal compaction
            # schedule starts from a clean counter.
            cfl = self._dispatch_compact()
            self._defer_cflags = self._or_acc(self._defer_cflags, cfl)
            self._compact_tick = 0
        packed = [self._pack_inputs(i) for i in inputs_list]
        env = self._build_env()
        if self._time_dev is None:
            self._time_dev = jnp.asarray(self.time, dtype=jnp.uint64)
        n_chunks = len(inputs_list) // ce
        levels = jnp.asarray(
            [
                min(
                    self._due_levels(self._compact_tick + (j + 1) * ce),
                    self._max_compact_level(),
                )
                for j in range(n_chunks)
            ],
            dtype=jnp.int32,
        )
        if not hasattr(self, "_span_jits"):
            self._span_jits = {}
        requested = bool(donate)
        donate = donate and _donation_supported()
        key = (ce, n_chunks, env is not None, donate)
        jitfn = self._span_jits.get(key)
        if jitfn is None:
            jitfn = self._make_span_jit(env is not None, donate=donate)
            self._span_jits[key] = jitfn
        stacked = self._stack_packed(packed)
        chunks = jax.tree_util.tree_map(
            lambda a: a.reshape((n_chunks, ce) + a.shape[1:]), stacked
        )
        args = (
            tuple(self.states), self.output, self.err_output,
            self._time_dev, chunks, levels,
        )
        if env is not None:
            args = args + (env,)
        # No donation-warning suppression needed: `donate` was
        # narrowed above to backends that honor donate_argnums, so
        # the CPU "donated buffers were not usable" warning is
        # unreachable here by construction.
        carry, deltas, sfl, cfl = jitfn(*args)
        if requested:
            # The donation CONTRACT is backend-independent: whenever a
            # span is dispatched with donation requested, the old
            # carry is dead — record it so the sanitizer catches any
            # holder that reads it (even on backends where the argnums
            # were not wired and the buffers happen to survive).
            from ..analysis.donation import record_donated
            from ..analysis.provenance import CARRY_PARTS

            record_donated(
                args[:4],
                f"{self.name}.run_span span@t={self.time} (donated "
                "carry)",
            )
            self._defer_donated = tuple(CARRY_PARTS)
        st, o, e, t = carry
        self.states = list(st)
        self.output = o
        self.err_output = e
        self._time_dev = t
        self._time += len(inputs_list)
        self._compact_tick += len(inputs_list)
        # Rollback/replay bookkeeping: replays reuse the ordinary
        # per-step path (compaction timing differs, which is
        # semantically transparent — compaction never changes content).
        self._defer_log.append((packed, env))
        if sfl is not None:
            self._defer_flags = self._or_acc(self._defer_flags, sfl)
        if cfl is not None:
            self._defer_cflags = self._or_acc(self._defer_cflags, cfl)
        return deltas

    def check_flags(self) -> bool:
        """Resolve deferred overflow checks: one flags readback covering
        every span dispatched with ``defer_check=True``. On overflow,
        rolls back to the pre-defer checkpoint, grows the flagged tiers,
        and replays the logged spans synchronously. Returns whether any
        overflow occurred (callers timing the deferred spans use this to
        invalidate their measurement)."""
        if self._defer_flags is None and self._defer_cflags is None:
            self._defer_ck = None
            self._defer_log = []
            self._defer_donated = ()
            self._defer_ck_cloned = False
            return False
        over = self._overflowed_keys(self._defer_flags, self._defer_cflags)
        log = self._defer_log
        ck = self._defer_ck
        self._defer_log = []
        self._defer_flags, self._defer_cflags = None, None
        self._defer_ck = None
        self._defer_donated = ()
        self._defer_ck_cloned = False
        if not over:
            return False
        self._restore(ck)
        for k in over:
            self._grow_for(k)
        # The deltas handed out during the deferred window were computed
        # against truncated state; the replay's corrected deltas are
        # published for callers that forward deltas to sinks.
        self.replayed_deltas = []
        for packed, env in log:
            while True:
                ck2 = self._checkpoint()
                deltas, flags, cflags = self._dispatch_span(packed, env)
                ovf = self._overflowed_keys(flags, cflags)
                if not ovf:
                    self.replayed_deltas.extend(deltas)
                    break
                self._restore(ck2)
                for k in ovf:
                    self._grow_for(k)
        return True

    # -- pipelined span boundaries (ISSUE 7) --------------------------------
    #
    # The double-buffered executor protocol: dispatch span K+1, THEN
    # read span K's accumulated overflow flags — the readback blocks
    # exactly until span K's program finished (all of a dispatch's
    # outputs become ready together), while span K+1 is already queued
    # behind it on device. One snapshot readback per span is the
    # span's entire d2h traffic.

    def flags_snapshot(self):
        """Reference the OR-accumulated deferred overflow flags AS OF
        NOW. Flags accumulate monotonically (logical_or), so a
        snapshot taken after dispatching span K covers every span
        <= K and nothing after — reading it is the span-boundary
        commit check."""
        return (self._defer_flags, self._defer_cflags)

    def read_flags_snapshot(self, snap) -> bool:
        """ONE fused d2h readback of a flags snapshot; True if any
        overflow occurred up to the snapshot point (the caller then
        runs :meth:`check_flags` for the rollback+replay). Blocks
        until the snapshot's producing span has finished executing —
        this is the pipelined executor's per-span sync point."""
        f, c = snap
        parts = []
        if f is not None:
            parts.append(jnp.ravel(jnp.asarray(f)).astype(jnp.uint8))
        if c is not None:
            parts.append(jnp.ravel(jnp.asarray(c)).astype(jnp.uint8))
        if not parts:
            return False
        fused = parts[0] if len(parts) == 1 else jnp.concatenate(parts)
        self._readbacks += 1
        return bool(
            np.asarray(fused).any()  # host-sync: ok(the ONE boundary readback per span)
        )

    def span_barrier(self) -> None:
        """Sequence a state read against span boundaries: when a
        pipelined span executor is attached, an in-flight span's carry
        may hold donated (dead) buffers and a provisional frontier —
        complete and commit it before reading dataflow state. No-op
        without an executor or from the executor's own dispatch."""
        ex = self._span_exec
        if ex is not None and not ex.in_dispatch:
            ex.sync()

    def _clone_checkpoint(self):
        """A rollback checkpoint whose device leaves are FRESH buffer
        copies — required before the first DONATED span dispatch of a
        defer window: donation hands the live carry's buffers to XLA,
        so a plain reference checkpoint would resurrect dead buffers
        on rollback."""
        from ..arrangement.spine import clone_state_tree

        st, out, err, tdev = clone_state_tree(
            (
                tuple(self.states),
                self.output,
                self.err_output,
                self._time_dev,
            )
        )
        return (list(st), out, err, self.time, tdev, self._compact_tick)


class Dataflow(_DataflowBase):
    """A maintained dataflow on one device: install once, feed update
    batches, peek.

    The host-side analog of an installed DataflowDescription with an
    index export (compute-types/src/dataflows.rs:32).
    """

    def __init__(self, expr: mir.RelationExpr, name: str = "df",
                 state_cap: int = 256, out_levels: int = 2,
                 out_slots: int | None = None,
                 force_merge_ingest: bool = False):
        from ..expr import strings

        self.expr = expr
        self.name = name
        self.out_schema = expr.schema()
        # Stable render identity for the compile ledger (ISSUE 12):
        # pickled-MIR fingerprints are deterministic across installs
        # and processes (PR 1), so a re-CREATE of the same definition
        # ledgers its compiles as HITS — the program-bank opportunity.
        from ..utils.compile_ledger import expr_fingerprint

        self._fingerprint = expr_fingerprint(expr)
        self._str_keys, self._str_depth = strings.collect_keys(expr)
        # Tier quantization (ISSUE 16): a requested state_cap snaps to
        # its pow2 menu rung so two DDLs differing only in size render
        # byte-identical programs and share one program-bank key.
        from ..plan.decisions import quantize_cap

        state_cap = quantize_cap(state_cap)
        ctx = _RenderContext(
            {}, state_cap=state_cap,
            force_merge_ingest=force_merge_ingest,
        )
        if force_merge_ingest:
            out_slots = 0
        if out_slots is None:
            # Ingest-mode decision for the output index (plan layer —
            # same source of truth EXPLAIN prints): append-slot ring
            # for big-state outputs, every-step run-0 merge otherwise.
            from ..plan.decisions import INGEST_RING_SLOTS, ingest_mode

            out_slots = (
                INGEST_RING_SLOTS
                if ingest_mode(state_cap, ctx.out_delta_cap)
                == "append_slot"
                else 0
            )
        self._run = _build(expr, ctx)
        self._ctx = ctx
        self._basic_finalizers = _resolve_basic_sites(expr, ctx)
        self.states = [s.init for s in ctx.slots]
        # Big output indexes run a deeper geometric run ladder
        # (out_levels=3-4) so base-scale merges amortize to every
        # ratio^(levels-1) steps, plus an append-slot ingest ring
        # (out_slots=compact_every) for O(delta) per-step inserts
        # (spine.py).
        self._init_output(levels=out_levels, slots=out_slots)
        self.time = 0  # frontier: all steps < time are complete
        self._remake_jit()

    def _remake_jit(self):
        # A fresh jit wrapper so trace-time reads of mutable ctx tiers
        # (join_caps, slot_cap) take effect after growth. Dataflows
        # whose expressions use string functions carry the dictionary
        # side-tables as an extra jit input (expr/strings.py); others
        # keep the 4-argument signature (and their compile-cache
        # entries).
        from ..utils.compile_ledger import ledger_jit

        fp = getattr(self, "_fingerprint", self.name)
        self._span_jits = {}
        self._donated_step_jits = {}
        if self._str_keys:
            self._step_jit = ledger_jit(
                jax.jit(
                    lambda s, o, eo, i, t, env: self._step_core(
                        s, o, eo, i, t, env
                    )
                ),
                "step", self.name, fp,
            )
        else:
            self._step_jit = ledger_jit(
                jax.jit(
                    lambda s, o, eo, i, t: self._step_core(
                        s, o, eo, i, t
                    )
                ),
                "step", self.name, fp,
            )

    def _donated_step_program(self, parts: tuple):
        """The step jit with ``donate_argnums`` on the prover-approved
        carry parts (the replica's donated ``run_steps`` span train,
        ISSUE 8): each step's output carry reuses the previous step's
        buffers instead of allocating state-sized arrays per tick.
        Cached per part subset; inputs (argnum 3) are never donated —
        the defer log replays them on overflow."""
        from ..analysis.donation import STEP_ARGNUM

        parts = tuple(sorted(parts))
        jitfn = self._donated_step_jits.get(parts)
        if jitfn is None:
            from ..utils.compile_ledger import ledger_jit

            argnums = tuple(
                sorted(STEP_ARGNUM[p] for p in parts)
            )
            if self._str_keys:
                jitfn = jax.jit(
                    lambda s, o, eo, i, t, env: self._step_core(
                        s, o, eo, i, t, env
                    ),
                    donate_argnums=argnums,
                )
            else:
                jitfn = jax.jit(
                    lambda s, o, eo, i, t: self._step_core(
                        s, o, eo, i, t
                    ),
                    donate_argnums=argnums,
                )
            jitfn = ledger_jit(
                jitfn, "step_donated", self.name,
                getattr(self, "_fingerprint", self.name),
            )
            self._donated_step_jits[parts] = jitfn
        return jitfn

    def _grow_batch(self, b: Batch, target: int | None = None) -> Batch:
        cap = target if target is not None else b.capacity * 2
        return b.with_capacity(cap) if cap > b.capacity else b

    def _make_compact_jit(self, max_level: int = 10**9):
        from ..utils.compile_ledger import ledger_jit

        return ledger_jit(
            jax.jit(
                lambda s, o: self._compact_core_single(s, o, max_level)
            ),
            "compact", self.name,
            getattr(self, "_fingerprint", self.name),
        )

    def _pack_inputs(self, inputs: dict) -> dict:
        return inputs

    # pure, jitted once per capacity signature
    def _step_core(self, states, output, err_output, inputs, time,
                   env=None):
        from ..expr import strings

        with strings.trace_scope(env if env is not None else {}):
            return self._step_core_inner(
                states, output, err_output, inputs, time
            )

    def _step_core_inner(self, states, output, err_output, inputs, time):
        from ..expr import errors as _errors

        with _errors.step_scope() as err_parts:
            out, upd, ovf = self._run(states, inputs, time)
        new_states = list(states)
        for k, v in upd.items():
            new_states[k] = v
        # The delta is what sinks/subscribers see: consolidate so
        # union-produced +/- pairs at the same time cancel.
        out = consolidate(out, include_time=True)
        out, shrink_ovf = shrink(out, self._ctx.out_delta_cap)
        new_output, out_ovf = insert_tail(output, out)
        ovf = dict(ovf)
        ovf[("outd",)] = shrink_ovf
        ovf[("out", "tail")] = out_ovf
        # The err collection delta (scalar-eval errors published by
        # apply_mfp sites during the _run trace above).
        new_err = self._apply_err_delta(err_output, err_parts, ovf)
        # time+1 rides back to the host loop as a device scalar so the
        # next step needs no h2d transfer (see _dispatch_span).
        return (
            out,
            tuple(new_states),
            new_output,
            new_err,
            time + jnp.asarray(1, dtype=time.dtype),
            self._pack_flags(ovf),
        )

    def peek(self) -> list[tuple]:
        """Read the full maintained result (SELECT * FROM mv)."""
        b = self.output_batch()
        if not self._basic_finalizers:
            return b.to_rows()
        n = int(b.count)
        cols = [np.asarray(c)[:n] for c in b.cols]
        nulls = [
            None if x is None else np.asarray(x)[:n] for x in b.nulls
        ]
        cols = self.finalize_basic_columns(cols, nulls)
        cols = cols + [
            np.asarray(b.time)[:n], np.asarray(b.diff)[:n]
        ]
        return [
            tuple(
                x.item() if isinstance(x, np.generic) else x
                for x in row
            )
            for row in zip(*cols)
        ]

    def peek_errors(self) -> list[tuple]:
        """The maintained err collection: [(err_code, count)] with
        count != 0. Nonempty means reads of this dataflow must raise
        (the reference picks an arbitrary error; render.rs:12-101).
        Dataflows whose step program has no error-emitting sites (a
        trace-time fact) skip the device readback entirely."""
        if not getattr(self, "_has_errors", False):
            return []
        self.span_barrier()
        self.check_flags()
        return self._accumulate_errors(self.err_output.batch.to_rows())


def _shard_rows(arrays, n: int, num_shards: int, shard_cap: int):
    """Deal host rows round-robin across shards; returns per-field
    [num_shards * shard_cap] arrays + [num_shards] counts. Ingestion
    balance only — exchange re-routes by key inside the step."""
    base, extra = divmod(n, num_shards)
    counts = np.full(num_shards, base, dtype=np.int32)
    counts[:extra] += 1

    def pack(a):
        if a is None:
            return None
        out = np.zeros(num_shards * shard_cap, dtype=a.dtype)
        for s in range(num_shards):
            rows = a[s::num_shards]
            out[s * shard_cap : s * shard_cap + len(rows)] = rows
        return out

    return [pack(a) for a in arrays], counts


class ShardedDataflow(_DataflowBase):
    """A maintained dataflow SPMD over a worker mesh.

    Worker = device; every stateful operator's state is sharded by key
    hash; inputs are dealt across workers and exchanged on key inside the
    step (the timely model, SURVEY.md §2.4 row 1). One ``shard_map``-ped
    jitted step per capacity signature. Each worker also maintains its
    own shard of the output arrangement; peeks gather + combine.

    Append-slot ingest under SPMD (ISSUE 9): the slot-ring cursor is
    carried as a PER-DEVICE ``[P]`` int32 vector riding the shard_map
    boundary specs like every other state leaf (reshaped to the
    per-worker scalar inside the step body), which is sound iff the
    cursor's dataflow is shard-local — worker p's cursor depends only
    on worker p's inputs. The shard-spec abstract interpreter
    (analysis/shard_prop.py) PROVES that property over the rendered
    step program; a refuted (or unprovable) cursor re-renders in
    merge-ingest mode, with the blame surfaced via
    ``sharding_report()`` / ``mz_sharding`` / EXPLAIN ANALYSIS.
    """

    def __init__(self, expr: mir.RelationExpr, mesh, name: str = "df",
                 slot_cap: int = 256, input_shard_cap: int = 1024,
                 output_cap: int = 256, state_cap: int = 256,
                 out_levels: int = 2, out_slots: int | None = None):
        from ..expr import strings

        self.expr = expr
        self.mesh = mesh
        self.name = name
        from ..utils.compile_ledger import expr_fingerprint

        self._fingerprint = expr_fingerprint(expr)
        self._str_keys, self._str_depth = strings.collect_keys(expr)
        if len(mesh.axis_names) != 1:
            raise ValueError(
                "ShardedDataflow wants a 1-D worker mesh (make_mesh); "
                f"got axes {mesh.axis_names}"
            )
        self.axis_name = mesh.axis_names[0]
        self.num_shards = int(mesh.shape[self.axis_name])
        self.out_schema = expr.schema()
        # Quantize every requested capacity to the pow2 menu
        # (ISSUE 16): size-only differences must share bank keys.
        from ..plan.decisions import quantize_cap

        self.input_shard_cap = quantize_cap(input_shard_cap)
        self._sharding = worker_sharding(mesh, self.axis_name)
        self._slot_cap0 = quantize_cap(slot_cap)
        self._output_cap = quantize_cap(output_cap)
        self._state_cap = quantize_cap(state_cap)
        self._out_levels = out_levels
        self._requested_out_slots = out_slots
        self._shard_prop_report: dict | None = None
        # TRIAL render, prover gate, fallback (ISSUE 9): render as if
        # the cursor proof will succeed; when any spine actually took
        # a slot ring, run the shard-spec prover over the rendered
        # step program and keep the ring only on a SAFE verdict —
        # otherwise re-render in merge mode. Dataflows whose ingest
        # decision is merge anyway (the common small-state case) never
        # pay the abstract trace.
        self._render(spmd_safe=True)
        from ..analysis.shard_prop import _has_slot_cursors

        if _has_slot_cursors(self):
            from ..analysis.shard_prop import sharded_step_report

            report = sharded_step_report(self)
            self._shard_prop_report = report
            if not report["safe"]:
                self._render(spmd_safe=False)
                self._shard_prop_report = dict(
                    report, ingest_mode="merge"
                )

    def _render(self, spmd_safe) -> None:
        """One full render at the given prover assumption (the ingest
        decisions consult ``spmd_safe`` through
        plan/decisions.state_ingest_mode — the EXPLAIN-visible source
        of truth)."""
        ctx = _RenderContext(
            {}, num_shards=self.num_shards, axis_name=self.axis_name,
            slot_cap=self._slot_cap0, state_cap=self._state_cap,
            spmd_safe=spmd_safe,
        )
        self._run = _build(self.expr, ctx)
        # Basic aggregates work sharded: the reduce input exchange keys
        # every group to exactly one worker, so the per-worker multiset
        # shards are group-disjoint and _basic_multiset_host's gather
        # yields a group-contiguous multiset for edge finalization.
        self._basic_finalizers = _resolve_basic_sites(self.expr, ctx)
        self._ctx = ctx
        out_slots = self._requested_out_slots
        if out_slots is None:
            from ..plan.decisions import INGEST_RING_SLOTS, ingest_mode

            out_slots = (
                INGEST_RING_SLOTS
                if ingest_mode(
                    self._state_cap,
                    ctx.out_delta_cap,
                    spmd=True,
                    spmd_safe=spmd_safe,
                )
                == "append_slot"
                else 0
            )
        elif out_slots and spmd_safe is not True:
            # An explicitly requested ring is still prover-gated under
            # SPMD: a refuted cursor falls back to merge (correctness
            # beats the request; sharding_report carries the blame).
            out_slots = 0
        # Per-shard states, stored as global arrays [P * cap] / counts [P].
        self.states = [
            self._replicate_empty(s.init) for s in ctx.slots
        ]
        self._init_output(
            self._output_cap, levels=self._out_levels, slots=out_slots
        )
        self.output = self._replicate_empty_one(self.output)
        self.err_output = self._replicate_empty_one(self.err_output)
        self.time = 0
        self._remake_jit()

    def sharding_report(self) -> dict:
        """The shard-spec prover's report over this dataflow's step
        program (ISSUE 9): communication census, per-cursor
        SPMD-safety verdicts, resolved ingest mode. Computed eagerly
        when a slot ring was requested (it gates the enablement),
        lazily for merge-mode dataflows; cached — surfaces
        (mz_sharding, EXPLAIN ANALYSIS, bench --multichip) read it
        for free after the first call."""
        if self._shard_prop_report is None:
            from ..analysis.shard_prop import sharded_step_report

            self._shard_prop_report = sharded_step_report(self)
        return self._shard_prop_report

    # -- sharded state layout ----------------------------------------------
    def _replicate_empty(self, parts: tuple) -> tuple:
        """Each worker starts with empty shards of every state part."""
        return tuple(self._replicate_empty_one(a) for a in parts)

    def _replicate_empty_one(self, obj):
        """Each worker starts with an empty shard of this arrangement
        (or of each run of a spine). A slot-ring cursor becomes a
        PER-DEVICE [P] vector (each worker owns a private ring cursor;
        the shard-spec prover guarantees it stays shard-local)."""
        out = obj.map_batches(self._rep_batch)
        if isinstance(out, Spine) and out.cursor is not None:
            out = out.with_cursor(
                jax.device_put(
                    np.zeros(self.num_shards, np.int32),
                    self._sharding,
                )
            )
        return out

    def _rep_batch(self, b: Batch) -> Batch:
        P_ = self.num_shards

        def rep(a):
            if a is None:
                return None
            return jax.device_put(
                np.zeros(P_ * a.shape[0], dtype=a.dtype), self._sharding
            )

        return Batch(
            cols=tuple(rep(c) for c in b.cols),
            nulls=tuple(rep(n) for n in b.nulls),
            time=rep(b.time),
            diff=rep(b.diff),
            count=jax.device_put(
                np.zeros(P_, dtype=np.int32), self._sharding
            ),
            schema=b.schema,
        )

    def _grow_batch(self, b: Batch, target: int | None = None) -> Batch:
        """Grow every shard's capacity ([P, cap] -> [P, new_cap]):
        doubled by default, or straight to a GLOBAL ``target`` capacity
        (same units as b.capacity, i.e. P * per-shard)."""
        P_ = self.num_shards
        cap = b.capacity // P_
        new_cap = (
            -(-target // P_) if target is not None else cap * 2
        )
        if new_cap <= cap:
            return b

        def grow(a):
            if a is None:
                return None
            h = np.asarray(a).reshape(P_, cap)
            out = np.zeros((P_, new_cap), dtype=h.dtype)
            out[:, :cap] = h
            return jax.device_put(
                out.reshape(P_ * new_cap), self._sharding
            )

        return Batch(
            cols=tuple(grow(c) for c in b.cols),
            nulls=tuple(grow(n) for n in b.nulls),
            time=grow(b.time),
            diff=grow(b.diff),
            count=b.count,
            schema=b.schema,
        )

    # -- the SPMD step ------------------------------------------------------
    # Boundary rank adjustment: counts (and the slot cursor) cross the
    # shard_map boundary rank-1 ([1] per worker from the global [P])
    # and run the step body as scalars.
    @staticmethod
    def _scalar_counts(s: tuple) -> tuple:
        def fix(o):
            o = o.map_batches(
                lambda b: b.replace(count=b.count.reshape(()))
            )
            if isinstance(o, Spine) and o.cursor is not None:
                o = o.with_cursor(o.cursor.reshape(()))
            return o

        return tuple(fix(o) for o in s)

    @staticmethod
    def _vec_counts(s: tuple) -> tuple:
        def fix(o):
            o = o.map_batches(
                lambda b: b.replace(count=b.count.reshape((1,)))
            )
            if isinstance(o, Spine) and o.cursor is not None:
                o = o.with_cursor(o.cursor.reshape((1,)))
            return o

        return tuple(fix(o) for o in s)

    def _remake_jit(self):
        axis = self.axis_name
        scalar_counts = self._scalar_counts
        vec_counts = self._vec_counts

        def body(states, output, err_output, inputs, time):
            from ..expr import errors as _errors

            with _errors.step_scope() as err_parts:
                out, upd, ovf = self._run(states, inputs, time)
            new_states = list(states)
            for k, v in upd.items():
                new_states[k] = v
            out = consolidate(out, include_time=True)
            out, shrink_ovf = shrink(out, self._ctx.out_delta_cap)
            new_output, out_ovf = insert_tail(output, out)
            ovf = dict(ovf)
            ovf[("outd",)] = shrink_ovf
            ovf[("out", "tail")] = out_ovf
            # Each worker maintains its own err shard (errors stay
            # where computed; peek_errors gathers).
            new_err = self._apply_err_delta(err_output, err_parts, ovf)
            # Overflow anywhere aborts the span on every worker.
            flags = self._pack_flags(ovf)
            flags = (
                jax.lax.psum(flags.astype(jnp.int32), axis) > 0
            ).reshape(-1, 1)
            # Rank-1 counts for the shard_map boundary.
            out = out.replace(count=out.count.reshape((1,)))
            new_states = tuple(vec_counts(s) for s in new_states)
            (new_output,) = vec_counts((new_output,))
            (new_err,) = vec_counts((new_err,))
            new_time = time + jnp.asarray(1, dtype=time.dtype)
            return out, new_states, new_output, new_err, new_time, flags

        def per_worker(states, output, err_output, inputs, time, env=None):
            from ..expr import strings

            # Leaves arrive rank-preserved: counts are [1]; make scalar.
            states = [scalar_counts(s) for s in states]
            (output,) = scalar_counts((output,))
            (err_output,) = scalar_counts((err_output,))
            inputs = {
                k: b.replace(count=b.count.reshape(()))
                for k, b in inputs.items()
            }
            with strings.trace_scope(env if env is not None else {}):
                return body(states, output, err_output, inputs, time)

        shard_map = require_shard_map()
        if self._str_keys:
            # env (the string side-tables) rides along REPLICATED: every
            # worker gathers through identical dictionaries
            def step(states, output, err_output, inputs, time, env):
                return shard_map(
                    per_worker,
                    mesh=self.mesh,
                    in_specs=(P(self.axis_name), P(self.axis_name),
                              P(self.axis_name), P(self.axis_name),
                              P(), P()),
                    out_specs=(P(self.axis_name), P(self.axis_name),
                               P(self.axis_name), P(self.axis_name),
                               P(), P(None, self.axis_name)),
                    check_vma=False,
                )(states, output, err_output, inputs, time, env)
        else:
            def step(states, output, err_output, inputs, time):
                return shard_map(
                    lambda s, o, eo, i, t: per_worker(s, o, eo, i, t),
                    mesh=self.mesh,
                    in_specs=(P(self.axis_name), P(self.axis_name),
                              P(self.axis_name), P(self.axis_name),
                              P()),
                    out_specs=(P(self.axis_name), P(self.axis_name),
                               P(self.axis_name), P(self.axis_name),
                               P(), P(None, self.axis_name)),
                    check_vma=False,
                )(states, output, err_output, inputs, time)

        # The raw (un-jitted) step: the shard-spec abstract
        # interpreter traces it to reach the shard_map eqn's boundary
        # specs (analysis/shard_prop.trace_sharded_step).
        from ..utils.compile_ledger import ledger_jit

        self._step_fn = step
        self._step_jit = ledger_jit(
            jax.jit(step), "step_spmd", self.name,
            getattr(self, "_fingerprint", self.name),
        )

    def run_span(self, inputs_list: list, donate: bool = False):
        raise NotImplementedError(
            "span-scan execution is single-device for now; sharded "
            "dataflows pipeline through run_steps(defer_check=True) + "
            "flags snapshots instead (the shard_map step is already "
            "one dispatch per step, and its packed flags ride the "
            "same deferred logical_or accumulator) — with slot-ring "
            "ingest now prover-gated under SPMD (ISSUE 9), the "
            "remaining span work is the scan-over-chunks program, "
            "see ROADMAP item 2"
        )

    def _donated_step_program(self, parts: tuple):
        raise NotImplementedError(
            "SPMD dataflows do not donate their carry: the per-worker "
            "shard layout rides shard_map boundary specs that "
            "donate_argnums cannot alias through — the view layer "
            "routes SPMD views to the un-donated per-tick path. (The "
            "old second blocker — SPMD forcing merge ingest — is "
            "gone: the shard-spec prover now gates a per-device "
            "slot ring, ISSUE 9.)"
        )

    def _make_compact_jit(self, max_level: int = 10**9):
        axis = self.axis_name
        scalar_counts = self._scalar_counts
        vec_counts = self._vec_counts

        def per_worker(states, output):
            states = [scalar_counts(s) for s in states]
            (output,) = scalar_counts((output,))
            new_states, new_out, fl = self._compact_core_single(
                states, output, max_level
            )
            new_states = tuple(vec_counts(s) for s in new_states)
            (new_out,) = vec_counts((new_out,))
            fl = (jax.lax.psum(fl.astype(jnp.int32), axis) > 0).reshape(
                -1, 1
            )
            return new_states, new_out, fl

        shard_map = require_shard_map()

        def compact(states, output):
            return shard_map(
                per_worker,
                mesh=self.mesh,
                in_specs=(P(self.axis_name), P(self.axis_name)),
                out_specs=(
                    P(self.axis_name),
                    P(self.axis_name),
                    P(None, self.axis_name),
                ),
                check_vma=False,
            )(states, output)

        from ..utils.compile_ledger import ledger_jit

        return ledger_jit(
            jax.jit(compact), "compact_spmd", self.name,
            getattr(self, "_fingerprint", self.name),
        )

    def _pack_inputs(self, inputs: dict) -> dict:
        packed = {}
        for name, b in inputs.items():
            if isinstance(b, Batch) and b.count.ndim == 0:
                # Host-global batch: deal rows across workers.
                n = int(b.count)
                cols = [np.asarray(c)[:n] for c in b.cols]
                nulls = [
                    None if nl is None else np.asarray(nl)[:n]
                    for nl in b.nulls
                ]
                time = np.asarray(b.time)[:n]
                diff = np.asarray(b.diff)[:n]
                cap = self.input_shard_cap
                while cap * self.num_shards < n or capacity_tier(
                    max((n + self.num_shards - 1) // self.num_shards, 1)
                ) > cap:
                    cap *= 2
                fields, counts = _shard_rows(
                    cols + nulls + [time, diff], n, self.num_shards, cap
                )
                k = len(cols)
                put = lambda a: (
                    None
                    if a is None
                    else jax.device_put(a, self._sharding)
                )
                packed[name] = Batch(
                    cols=tuple(put(a) for a in fields[:k]),
                    nulls=tuple(put(a) for a in fields[k : 2 * k]),
                    time=put(fields[2 * k]),
                    diff=put(fields[2 * k + 1]),
                    count=jax.device_put(counts, self._sharding),
                    schema=b.schema,
                )
            else:
                packed[name] = b
        return packed

    def _gather_batch(self, out: Batch) -> Batch:
        """Concatenate every worker's shard rows into one host batch."""
        P_ = self.num_shards
        counts = np.asarray(out.count)
        cap = out.diff.shape[0] // P_
        sel = np.concatenate(
            [
                np.arange(p * cap, p * cap + counts[p])
                for p in range(P_)
            ]
        ).astype(np.int64) if counts.sum() else np.zeros(0, dtype=np.int64)
        cols = [np.asarray(c)[sel] for c in out.cols]
        nulls = [
            None if nl is None else np.asarray(nl)[sel] for nl in out.nulls
        ]
        return Batch.from_numpy(
            out.schema,
            cols,
            np.asarray(out.time)[sel],
            np.asarray(out.diff)[sel],
            nulls=nulls,
        )

    def gather_delta(self, out: Batch) -> Batch:
        """Host view of a per-worker output delta from step()."""
        return self._gather_batch(out)

    def _basic_multiset_host(self, arr) -> dict:
        """Host view of a SHARDED basic multiset: concatenate each
        worker's valid rows. Groups are worker-disjoint (reduce's keyed
        exchange), so the concatenation is group-contiguous — exactly
        what the group-boundary scan in _basic_group_maps needs."""
        b = self._gather_batch(arr.batch)
        n = int(b.count)
        return {
            "n": n,
            "cols": [np.asarray(c)[:n] for c in b.cols],
            "nulls": [
                None if x is None else np.asarray(x)[:n]
                for x in b.nulls
            ],
            "diff": np.asarray(b.diff)[:n],
        }

    def peek_errors(self) -> list[tuple]:
        """Gather every worker's err shard: [(err_code, count)]."""
        if not getattr(self, "_has_errors", False):
            return []
        self.span_barrier()
        self.check_flags()
        return self._accumulate_errors(
            self._gather_batch(self.err_output.batch).to_rows()
        )

    def peek(self) -> list[tuple]:
        """Gather and combine every worker's output-arrangement shard.
        Different workers may hold the same row value (outputs stay where
        they were computed), so diffs are summed host-side."""
        b = self._gather_batch(self.output_batch())
        if self._basic_finalizers:
            n = int(b.count)
            cols = [np.asarray(c)[:n] for c in b.cols]
            nulls = [
                None if x is None else np.asarray(x)[:n]
                for x in b.nulls
            ]
            cols = self.finalize_basic_columns(cols, nulls)
            cols = cols + [
                np.asarray(b.time)[:n], np.asarray(b.diff)[:n]
            ]
            rows = [
                tuple(
                    x.item() if isinstance(x, np.generic) else x
                    for x in row
                )
                for row in zip(*cols)
            ]
        else:
            rows = b.to_rows()
        acc: dict = {}
        for r in rows:
            key = r[:-2]  # value columns only: shards may hold the same
            acc[key] = acc.get(key, 0) + r[-1]  # row at different times
        return [k + (0, d) for k, d in acc.items() if d != 0]
