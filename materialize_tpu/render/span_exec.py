"""Pipelined span executor: the double-buffered control plane.

ISSUE 7 / ROADMAP item 4: through the remote-TPU tunnel every
dispatch+block round trip costs ~96ms (PERF_NOTES round 5), and the
serial span protocol — dispatch span K, BLOCK on its readback, think,
dispatch span K+1 — leaves the device idle for the whole host-side
inter-span gap. The timely-dataflow discipline (Differential Dataflow,
PAPERS.md) is to keep the workers saturated and coordinate only at
frontier boundaries; this executor is that discipline for the render
layer's span programs:

    stage span K+1's inputs     (h2d upload, ~615 MB/s — overlaps
                                 span K executing on device)
    dispatch span K+1           (queues behind K; device never drains)
    read span K's flags         (ONE tiny d2h readback per span: the
                                 OR-accumulated overflow flags; it
                                 blocks exactly until K finished while
                                 K+1 is already executing)
    commit span K               (frontier advance, trace record)

At most ONE span is in flight ahead of the committed boundary (double
buffering): the host is always preparing exactly the next span, and
every span's entire device→host traffic is the single flags readback
(``readbacks == 1`` in the timeline trace — the bench gate).

Buffer donation (``span_donation`` dyncfg): the span program's carry —
operator states, the output spine, the err arrangement, the device
time scalar — is donated to XLA (``donate_argnums``), so each span
writes its output state into the previous span's buffers instead of
allocating and copying state-sized arrays per dispatch. Donated
buffers are DEAD after dispatch; the rollback checkpoint is therefore
a fresh-buffer clone (``_clone_checkpoint``), and every read of
dataflow state sequences through :meth:`sync` (the span barrier wired
into ``output_batch``/``peek_errors``/``run_steps``) — no donated
buffer is ever read after handoff.

Overflow keeps the existing rollback/replay contract: flags accumulate
as a monotone on-device OR, so the span whose boundary readback first
reports an overflow triggers ``check_flags`` — roll back to the
window checkpoint, grow the flagged tiers, replay the window's logged
inputs — and the pipeline refills. Windows are bounded
(``span_window_spans``) so the defer log cannot grow without bound in
a long-running serving loop.
"""

from __future__ import annotations

import time as _time

import jax
import numpy as np


def resolve_donation(mode=None) -> bool:
    """Resolve the span-carry donation mode: explicit bool wins, then
    the ``span_donation`` dyncfg ('on'/'off'/'auto'); 'auto' donates
    only where the backend implements donation (TPU — CPU ignores it
    with a warning per buffer)."""
    if isinstance(mode, bool):
        return mode
    if mode is None:
        from ..utils.dyncfg import COMPUTE_CONFIGS, SPAN_DONATION

        mode = SPAN_DONATION(COMPUTE_CONFIGS)
    if mode in ("on", "true", True):
        return True
    if mode in ("off", "false", False):
        return False
    from .dataflow import _donation_supported

    return _donation_supported()


class SpanExecutor:
    """Double-buffered pipelined execution of a ``Dataflow``'s span
    program. One executor per dataflow; attaching sets the dataflow's
    span barrier so state reads sequence against span boundaries."""

    def __init__(self, df, donate=None, trace: bool = True):
        from .dataflow import _donation_supported

        self.df = df
        # `donate` is the REQUEST (dyncfg policy); `self.donate` is
        # what actually wires — run_span narrows to supporting
        # backends, and everything this executor reports (stats,
        # bench span_trace "donated") must reflect the effective
        # value, or an A/B comparison on an unsupported backend would
        # read two identical un-donated runs as donated-vs-not.
        self.donate_requested = resolve_donation(donate)
        self.donate = self.donate_requested and _donation_supported()
        # Reentrancy guard: the dataflow's span_barrier() must no-op
        # for reads issued by this executor's own dispatch/sync path.
        self.in_dispatch = False
        # (flags snapshot, trace rec, deltas, arrival monotonic stamp)
        self._inflight = None
        self.trace: list[dict] = [] if trace else None
        # Freshness identity: bench sets the label to the config name
        # so --measure/--trace lag summaries key per config; the
        # replica path records through MaintainedView instead.
        self.freshness_label = getattr(df, "name", "") or "span"
        self.freshness_replica = "local"
        self.spans_submitted = 0
        self.spans_committed = 0
        self.boundary_syncs = 0  # reads that forced a span boundary
        self.overflows = 0
        self._last_host_free: float | None = None
        df._span_exec = self

    # -- the pipeline -------------------------------------------------------
    def submit(self, inputs_list: list):
        """Stage + dispatch one span, then complete the PREVIOUS
        span's boundary (its one readback) — the readback waits for
        the previous span while this one is already queued on device.
        Returns the previous span's committed (validated) stacked
        deltas, or None when there was no previous span or its window
        was replayed."""
        from ..utils.dyncfg import COMPUTE_CONFIGS, SPAN_WINDOW_SPANS

        t0 = _time.perf_counter()
        arrived = _time.monotonic()  # freshness clock (lag_ms)
        gap_ms = (
            0.0
            if self._last_host_free is None
            else (t0 - self._last_host_free) * 1e3
        )
        prev_deltas = None
        self.in_dispatch = True
        try:
            window_sync_ms = 0.0
            if (
                len(self.df._defer_log)
                >= int(SPAN_WINDOW_SPANS(COMPUTE_CONFIGS))
            ):
                # Window boundary: validate + clear the defer log so
                # replay memory stays bounded. One extra sync point,
                # amortized over the window; the pipeline refills on
                # the next submit. Timed SEPARATELY — its blocking
                # readbacks are device wait, not upload/host work, and
                # must not inflate the overlap accounting.
                self._sync_locked()
                self.df.check_flags()
                window_sync_ms = (_time.perf_counter() - t0) * 1e3
            t_up = _time.perf_counter()
            staged = self._stage(inputs_list)
            t1 = _time.perf_counter()
            # Pass the REQUEST: run_span clones the rollback
            # checkpoint whenever donation is requested (cheap safety,
            # keeps the clone path covered on CPU) and narrows the
            # actual argnums to supporting backends itself.
            deltas = self.df.run_span(
                staged, donate=self.donate_requested
            )
            snap = self.df.flags_snapshot()
            t2 = _time.perf_counter()
            rec = {
                "span": self.spans_submitted,
                "ticks": len(inputs_list),
                "host_gap_ms": round(gap_ms, 3),
                "window_sync_ms": round(window_sync_ms, 3),
                "upload_ms": round((t1 - t_up) * 1e3, 3),
                "dispatch_ms": round((t2 - t1) * 1e3, 3),
                "readback_wait_ms": None,
                "readbacks": None,
                "overflow": False,
                # The EFFECTIVE per-span donation fact (narrowed to
                # supporting backends): bench --trace reports it per
                # span so an A/B trace can prove which mode ran.
                "donated": self.donate,
            }
            self.spans_submitted += 1
            # Arrival stamp for freshness: the span's inputs were in
            # hand when submit() was entered (t0 on the same clock).
            prev, self._inflight = (
                self._inflight,
                (snap, rec, deltas, arrived),
            )
            if prev is not None:
                prev_deltas = self._complete(prev)
        finally:
            self.in_dispatch = False
            self._last_host_free = _time.perf_counter()
        return prev_deltas

    def _stage(self, inputs_list: list) -> list:
        """h2d prefetch: upload every input batch's host leaves NOW so
        the transfer (~615 MB/s through the tunnel, PERF_NOTES fact 5)
        overlaps the in-flight span's device compute instead of
        happening lazily inside the next dispatch. The upload is
        input-sized (the delta), never state-sized. On CPU backends
        there is no transfer to hide — host and 'device' share cores —
        so staging passes through (same accelerator predicate as
        donation: a backend with a real h2d transfer)."""
        from .dataflow import _donation_supported

        if not _donation_supported():
            return inputs_list
        return [
            {
                name: jax.device_put(b)  # h2d: prefetch staging
                for name, b in inputs.items()
            }
            for inputs in inputs_list
        ]

    def _complete(self, handle):
        """The span boundary: ONE fused flags readback (blocks until
        the span's program finished), then commit — or, on overflow,
        roll back and replay the whole window through check_flags."""
        snap, rec, deltas, arrived = handle
        r0 = self.df._readbacks
        t0 = _time.perf_counter()
        overflow = self.df.read_flags_snapshot(snap)
        rec["readback_wait_ms"] = round(
            (_time.perf_counter() - t0) * 1e3, 3
        )
        rec["readbacks"] = self.df._readbacks - r0
        if overflow:
            # The flagged span (and everything after it, including the
            # span still in flight) replays against grown tiers; the
            # replay commits synchronously, so the in-flight handle is
            # already absorbed.
            rec["overflow"] = True
            self.overflows += 1
            self.df.check_flags()
            absorbed, self._inflight = self._inflight, None
            if absorbed is not None:
                arec = absorbed[1]
                arec["readbacks"] = 0
                arec["readback_wait_ms"] = 0.0
                arec["absorbed_by_replay"] = True
                if self.trace is not None:
                    self.trace.append(arec)
                self.spans_committed += 1
            deltas = None
        if self.trace is not None:
            self.trace.append(rec)
        self.spans_committed += 1
        # Span-boundary freshness: lag since the committed span's
        # inputs were submitted (pure host bookkeeping; this function
        # is RECORDER_PATH-linted, so a d2h sync here fails CI). The
        # frontier is the monotone committed-span counter — bench
        # dataflows have no tick timestamps of their own.
        from ..coord.freshness import FRESHNESS, lag_ms

        FRESHNESS.record(
            self.freshness_label,
            self.freshness_replica,
            self.spans_committed,
            lag_ms(arrived),
        )
        from ..utils.trace import TRACER

        if TRACER.enabled("debug"):
            # Ring-buffer record of the committed span (ISSUE 12):
            # DEBUG level so the default trace_level keeps the span
            # boundary recorder-free; attrs mirror the bench --trace
            # span schema so mz_trace_spans and the perfetto export
            # see the same stage/dispatch/readback-wait decomposition.
            TRACER.record(
                "span_exec.commit",
                _time.time(),  # host-sync: ok(pure host clock read)
                (rec["readback_wait_ms"] or 0.0) / 1e3,
                level="debug",
                span=rec["span"],
                ticks=rec["ticks"],
                upload_ms=rec["upload_ms"],
                dispatch_ms=rec["dispatch_ms"],
                host_gap_ms=rec["host_gap_ms"],
                donated=rec["donated"],
                overflow=rec["overflow"],
            )
        return deltas

    def sync(self):
        """Complete + commit the in-flight span — the read barrier
        every dataflow-state read sequences through. Peeks admitted
        while a span is in flight therefore always observe a committed
        span boundary, never a half-applied (or donated) carry."""
        if self._inflight is None:
            return
        self.boundary_syncs += 1
        self.in_dispatch = True
        try:
            self._sync_locked()
        finally:
            self.in_dispatch = False
            self._last_host_free = _time.perf_counter()

    def _sync_locked(self):
        if self._inflight is None:
            return
        handle, self._inflight = self._inflight, None
        self._complete(handle)

    def close(self):
        """Drain the pipeline, validate the window, and detach."""
        self.sync()
        self.df.check_flags()
        if self.df._span_exec is self:
            self.df._span_exec = None

    # -- observability ------------------------------------------------------
    def stats(self) -> dict:
        committed = [
            r for r in (self.trace or []) if r["readbacks"] is not None
        ]
        readbacks = [
            r["readbacks"]
            for r in committed
            if not r.get("absorbed_by_replay")
        ]
        return {
            "spans_submitted": self.spans_submitted,
            "spans_committed": self.spans_committed,
            "overflows": self.overflows,
            "boundary_syncs": self.boundary_syncs,
            "donated": self.donate,
            "readbacks_per_span": (
                float(np.mean(readbacks)) if readbacks else 0.0
            ),
        }
