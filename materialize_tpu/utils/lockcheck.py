"""Lock-order sanitizer over the coordination-plane locks.

The coord layer holds a small zoo of locks — the coordinator's
sequencing RLock, the controller's state lock, the PeekBatcher's queue
lock, the replica's remap lock, the dyncfg/metrics registry locks —
acquired from many threads (session threads, the response absorber,
the peek flusher, replica worker loops). Two hazards this sanitizer
catches at test time, before they deadlock a production serving loop:

1. **Order cycles**: thread A acquires X then Y while thread B
   acquires Y then X. The sanitizer records every observed
   acquisition edge (X held while Y acquired ⇒ X→Y) into one global
   order graph; an acquisition that would close a cycle is recorded
   as a finding with both paths named.
2. **Sequencing lock across a device dispatch**: a dispatch (XLA
   compile + execute, potentially seconds cold) while holding a lock
   marked ``sequencing`` starves every other session — the exact
   regression `Coordinator._unlocked` exists to prevent. Dispatch
   sites call :func:`device_dispatch`; intentionally-held sites (the
   coordinator's tiny introspection-constant step) wrap themselves in
   :func:`allow_dispatch`.

Recording is OFF by default (one module-bool check per acquire — the
wrappers cost nothing in production); the ``pytest -m analysis`` lane
and ``scripts/check_plans.py --bench`` enable it, drive the ordinary
serving/span paths, and assert zero findings. Findings are RECORDED,
never raised: a sanitizer must not turn a would-be deadlock into a
crash mid-test — the assertion at the end reads the ledger.
"""

from __future__ import annotations

import threading
from dataclasses import dataclass

# Module-level switch: read unsynchronized on the hot acquire path (a
# torn read just misses one edge during enable/disable — benign).
_ENABLED = False

# The happens-before race detector (analysis/racecheck.py) layers on
# these hook slots: one module-global read per acquire/release/shared-
# access when it is off (None), so production pays nothing. The
# detector installs itself via set_racecheck() on enable. Keeping the
# slots HERE (not in analysis/) lets every hot module instrument its
# declared shared state through the already-imported lockcheck module
# without pulling the heavyweight analysis package onto the hot path.
_RACECHECK = None

# The order graph + findings, guarded by a LEAF lock that is itself
# never tracked (no recursion, no ordering constraints against it).
_graph_lock = threading.Lock()
_edges: dict = {}  # name -> set(names acquired while name held)
_edge_example: dict = {}  # (a, b) -> where string
_findings: list = []
_state = threading.local()  # per-thread held-lock stack
# Epoch versioning for the per-thread held stacks: a lock acquired
# while recording was on but released while it was OFF never runs
# _record_release, leaking a phantom held entry into the thread's
# stack. clear() bumps the epoch, so every thread's stale stack is
# discarded at its next acquisition instead of poisoning the next
# enable() window with spurious nesting.
_epoch = 0


@dataclass
class LockFinding:
    kind: str  # "lock-cycle" | "dispatch-under-lock"
    message: str

    def __str__(self):
        return f"[{self.kind}] {self.message}"


def enable(reset: bool = True) -> None:
    global _ENABLED
    if reset:
        clear()
    _ENABLED = True


def disable() -> None:
    global _ENABLED
    _ENABLED = False


def enabled() -> bool:
    return _ENABLED


def clear() -> None:
    global _epoch
    with _graph_lock:
        _edges.clear()
        _edge_example.clear()
        del _findings[:]
        _epoch += 1


def findings() -> list:
    with _graph_lock:
        return list(_findings)


def edges() -> dict:
    with _graph_lock:
        return {k: set(v) for k, v in _edges.items()}


def _held() -> list:
    h = getattr(_state, "held", None)
    if h is None or getattr(_state, "epoch", -1) != _epoch:
        h = []
        _state.held = h
        _state.epoch = _epoch
    return h


def _path(src: str, dst: str) -> list | None:
    """A path src -> ... -> dst in the observed-order graph (DFS)."""
    stack = [(src, [src])]
    seen = {src}
    while stack:
        node, path = stack.pop()
        if node == dst:
            return path
        for nxt in _edges.get(node, ()):
            if nxt not in seen:
                seen.add(nxt)
                stack.append((nxt, path + [nxt]))
    return None


def _record_acquire(name: str) -> None:
    held = _held()
    for i, entry in enumerate(held):
        if entry[0] == name:
            entry[1] += 1  # RLock re-entry: no new ordering fact
            return
    for hname, _depth in held:
        with _graph_lock:
            if name in _edges.get(hname, ()):
                continue
            cycle = _path(name, hname)
            if cycle is not None:
                _findings.append(
                    LockFinding(
                        "lock-cycle",
                        f"acquiring {name!r} while holding {hname!r} "
                        f"closes the cycle {' -> '.join(cycle)} -> "
                        f"{name} (reverse order first seen at "
                        f"{_edge_example.get((cycle[0], cycle[1]), '?')}"
                        ") — two threads interleaving these orders "
                        "deadlock",
                    )
                )
            _edges.setdefault(hname, set()).add(name)
            _edge_example[(hname, name)] = _caller()
    held.append([name, 1])


def _record_release(name: str) -> None:
    held = _held()
    for i in range(len(held) - 1, -1, -1):
        if held[i][0] == name:
            held[i][1] -= 1
            if held[i][1] <= 0:
                del held[i]
            return


def _caller() -> str:
    import inspect

    for fr in inspect.stack()[2:8]:
        fn = fr.filename
        if "lockcheck" not in fn and "threading" not in fn:
            return f"{fn.rsplit('/', 1)[-1]}:{fr.lineno}"
    return "?"


def held_names() -> tuple:
    return tuple(n for n, _ in _held())


# -- race-detector hook slots -------------------------------------------------


def set_racecheck(hooks) -> None:
    """Install (or remove, with None) the happens-before race detector.
    ``hooks`` is any object with on_acquire/on_release/on_read/on_write
    (analysis/racecheck installs its own module)."""
    global _RACECHECK
    _RACECHECK = hooks


def shared_read(name: str) -> None:
    """Instrumentation shim for a READ of declared shared state. One
    global load + None check when the race detector is off."""
    rc = _RACECHECK
    if rc is not None:
        rc.on_read(name)


def shared_write(name: str) -> None:
    """Instrumentation shim for a WRITE of declared shared state."""
    rc = _RACECHECK
    if rc is not None:
        rc.on_write(name)


def registered_names() -> set:
    """Every tracked-lock name ever constructed in this process — the
    tracked-object registry the interleaving explorer keys its DPOR
    independence relation on (analysis/interleave.py) and the race
    detector uses to seed lock clocks."""
    with _graph_lock:
        return set(_REGISTRY)


# -- tracked lock wrappers ---------------------------------------------------


class TrackedLock:
    """A threading.Lock with acquisition-order recording. Drop-in:
    context manager, acquire/release with the stdlib signatures, and
    ``locked()``. ``sequencing=True`` marks the lock for the
    dispatch-under-lock rule."""

    _factory = staticmethod(threading.Lock)

    def __init__(self, name: str, sequencing: bool = False):
        self.name = name
        self.sequencing = sequencing
        if sequencing:
            _SEQUENCING_NAMES.add(name)
        with _graph_lock:
            _REGISTRY.add(name)
        self._lock = self._factory()

    def acquire(self, blocking: bool = True, timeout: float = -1):
        got = self._lock.acquire(blocking, timeout)
        if got:
            if _ENABLED:
                _record_acquire(self.name)
            rc = _RACECHECK
            if rc is not None:
                rc.on_acquire(self.name)
        return got

    def release(self) -> None:
        # The race detector snapshots the releasing thread's vector
        # clock while the lock is STILL held (the release publishes
        # everything this thread did under it).
        rc = _RACECHECK
        if rc is not None:
            rc.on_release(self.name)
        if _ENABLED:
            _record_release(self.name)
        self._lock.release()

    def locked(self) -> bool:
        return self._lock.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class TrackedRLock(TrackedLock):
    _factory = staticmethod(threading.RLock)

    def _is_owned(self) -> bool:
        # The coordinator's _unlocked() helper asks the RLock whether
        # THIS thread holds it before releasing around a blocking wait.
        return self._lock._is_owned()

    def locked(self) -> bool:  # RLock has no .locked() before 3.12
        return self._lock._is_owned()


def tracked_lock(name: str, sequencing: bool = False) -> TrackedLock:
    return TrackedLock(name, sequencing)


def tracked_rlock(name: str, sequencing: bool = False) -> TrackedRLock:
    return TrackedRLock(name, sequencing)


# -- the dispatch-under-sequencing-lock rule ---------------------------------


def allow_dispatch(why: str):
    """Context manager sanctioning a device dispatch under a
    sequencing lock (e.g. the coordinator's introspection-constant
    step: a handful of rows, no source waits, bounded work)."""
    import contextlib

    @contextlib.contextmanager
    def cm():
        prev = getattr(_state, "dispatch_ok", 0)
        _state.dispatch_ok = prev + 1
        try:
            yield
        finally:
            _state.dispatch_ok = prev

    return cm()


def device_dispatch(where: str) -> None:
    """Called from render-layer dispatch sites: records a finding when
    a sequencing-marked lock is held by this thread (unless inside
    allow_dispatch). No-op (one bool check) when disabled."""
    if not _ENABLED or getattr(_state, "dispatch_ok", 0):
        return
    seq = [
        n
        for n, _ in _held()
        if n in _SEQUENCING_NAMES
    ]
    if seq:
        with _graph_lock:
            _findings.append(
                LockFinding(
                    "dispatch-under-lock",
                    f"device dispatch at {where} while holding "
                    f"sequencing lock(s) {seq}: an XLA compile here "
                    "stalls every other session on the lock — release "
                    "it around the dispatch (Coordinator._unlocked) "
                    "or sanction a bounded site with "
                    "lockcheck.allow_dispatch(<why>)",
                )
            )


# Names the dispatch rule treats as sequencing locks: seeded with the
# known coordinator lock (deterministic even before any Coordinator is
# constructed) and extended by every tracked lock built with
# sequencing=True.
_SEQUENCING_NAMES = {"coord.sequencing"}

# Every tracked-lock name ever constructed (see registered_names()).
_REGISTRY: set = set()
