"""Compile ledger: every XLA compile, anywhere, becomes a record.

ISSUE 12 tentpole (b), feeding ROADMAP item 4 (the ahead-of-time
program bank): cold XLA compiles are the worst real-hardware numbers
we have (~26s index step, 112s 4-operand sort, 227s q9 planning —
PERF_NOTES), yet nothing attributed wall-clock to them. This module
makes compilation a COUNTED surface: every jit program the system
builds (dataflow step/span/compact programs, donated variants, peek
gather programs) is wrapped with :func:`ledger_jit`, and each actual
XLA compile records ``(program kind, dataflow name, dataflow
fingerprint, tier vector, wall seconds, hit|miss)`` into a bounded
per-process ring.

Hit/miss semantics are the PROGRAM-BANK question, not jax's: a
``miss`` means this (kind, fingerprint, tier) was never compiled in
this process before; a ``hit`` means the same program was compiled
AGAIN (a re-install, a restart re-render, a fresh jit wrapper after
tier growth re-deriving an identical program). The total seconds spent
on hits is exactly the wall-clock a cross-process program bank keyed
by (fingerprint, tier) would recover.

Detection rides ``jax.jit``'s own per-signature cache
(``fn._cache_size()``): a call that grows the cache paid a trace +
compile, and only then does the wrapper touch the ledger — the
steady-state dispatch path pays two C attribute calls and a
perf_counter read, no tree flattening, no device sync (the wrapper is
registered with the host-sync linter).

Replica processes piggyback their records on Frontiers responses (the
span/verdict pattern); the controller ingests them, deduping by pid so
in-process replicas (which share this ledger) never double-report.
Surfaces: the ``mz_compile_log`` introspection relation, the
``mz_compile_*`` /metrics families, EXPLAIN ANALYSIS's ``compiles:``
block, and ``bench.py --trace``'s ``compiles`` summary.

With a program bank configured (ISSUE 16, compile/bank.py) every
``ledger_jit`` site becomes a bank lookup point. First sight of a
``(kind, fingerprint, tier)`` in this process consults the bank: a
usable entry deserializes in milliseconds and records ``bank_hit``
(attrs carry the compile seconds the hit recovered); a bank miss
compiles AHEAD-OF-TIME (``fn.lower(...).compile()`` — one trace, one
compile, and the executable in hand) and writes the entry back. The
resolved executable is routed directly on subsequent calls. Bank-off
dispatch is byte-identical to the pre-bank hot path.
"""

from __future__ import annotations

import hashlib
import os
import time as _time
from collections import deque
from dataclasses import dataclass, field

from . import lockcheck


@dataclass
class CompileRecord:
    kind: str  # step | step_donated | span | compact | peek_* | ...
    name: str  # dataflow (or program owner) name
    fingerprint: str  # stable identity of the rendered program family
    tier: str  # tier vector: capacity/shape signature of this compile
    seconds: float
    # "miss" (first sight, compiled) | "hit" (recompiled a known key)
    # | "bank_hit" (served from the program bank — no XLA compile;
    # seconds is the deserialize wall, attrs["recovered_seconds"] the
    # compile wall it skipped)
    cache: str
    when: float = 0.0  # wall-clock stamp
    pid: int = 0
    process: str = ""
    attrs: dict = field(default_factory=dict)

    def to_wire(self) -> tuple:
        return (
            self.kind, self.name, self.fingerprint, self.tier,
            self.seconds, self.cache, self.when, self.pid,
            self.process, dict(self.attrs),
        )

    @classmethod
    def from_wire(cls, t: tuple) -> "CompileRecord":
        return cls(*t[:9], attrs=t[9])


class CompileLedger:
    # Hit/miss memory: one entry per distinct (kind, fingerprint,
    # tier) ever compiled, bounded so a long-lived deployment serving
    # endless distinct ad-hoc programs cannot leak (oldest keys evict
    # first; an evicted key's recompile re-classifies as "miss", which
    # only UNDERSTATES the bankable wall).
    SEEN_CAP = 32768

    def __init__(self, capacity: int = 4096):
        # Tracked (ISSUE 17): every ledger_jit site in any thread takes
        # this lock; the race detector also watches the _seen memory
        # through the lockcheck shared-state shims.
        from .lockcheck import tracked_lock

        self._lock = tracked_lock("compile.ledger")
        self._buf: deque[CompileRecord] = deque(maxlen=capacity)
        self._ingested: deque[CompileRecord] = deque(maxlen=capacity)
        self._seen: dict = {}  # insertion-ordered: FIFO eviction
        self._ship: deque | None = None
        self._pid = os.getpid()
        self._metrics = None

    def _metric_handles(self):
        if self._metrics is None:
            from .metrics import REGISTRY

            self._metrics = (
                REGISTRY.get_or_create(
                    "counter", "mz_compile_total",
                    "XLA program compiles observed by the ledger",
                ),
                REGISTRY.get_or_create(
                    "counter", "mz_compile_misses_total",
                    "compiles of a never-before-seen "
                    "(kind, fingerprint, tier) key",
                ),
                REGISTRY.get_or_create(
                    "counter", "mz_compile_hits_total",
                    "recompiles of an already-seen key — the wall "
                    "the program bank (ROADMAP 4) would recover",
                ),
                REGISTRY.get_or_create(
                    "histogram", "mz_compile_seconds",
                    "wall seconds per observed compile",
                    buckets=(
                        0.01, 0.05, 0.1, 0.5, 1, 5, 10, 30, 60, 120,
                        300,
                    ),
                ),
                REGISTRY.get_or_create(
                    "counter", "mz_compile_bank_hits_total",
                    "programs served from the persistent AOT bank "
                    "(deserialized, no XLA compile)",
                ),
                REGISTRY.get_or_create(
                    "counter", "mz_compile_bank_misses_total",
                    "compiles whose key was absent from the bank "
                    "(entry written back after the compile)",
                ),
            )
        return self._metrics

    # -- recording ------------------------------------------------------------
    def record(
        self,
        kind: str,
        name: str,
        fingerprint: str,
        tier: str,
        seconds: float,
        cache: str | None = None,
        **attrs,
    ) -> CompileRecord:
        key = (kind, fingerprint, tier)
        with self._lock:
            lockcheck.shared_write("compile_ledger.seen")
            if cache is None:
                if key in self._seen:
                    cache = "hit"
                else:
                    # Bounded-_seen misclassification fix (ISSUE 16
                    # satellite): an evicted key's recompile used to
                    # re-classify as "miss" — harmless while hit/miss
                    # was pure measurement, wrong once the bank serves
                    # the key. The bank's on-disk entry is the durable
                    # _seen: if it holds the key, this compile is a
                    # re-compile of a known program, never a cold miss.
                    cache = "hit" if self._bank_has(key) else "miss"
            self._seen[key] = True
            while len(self._seen) > self.SEEN_CAP:
                self._seen.pop(next(iter(self._seen)))
            rec = CompileRecord(
                kind, name, fingerprint, tier, seconds, cache,
                when=_time.time(), pid=os.getpid(),
                process=f"pid{os.getpid()}", attrs=attrs,
            )
            self._buf.append(rec)
            if self._ship is not None:
                self._ship.append(rec)
        handles = self._metric_handles()
        total, misses, hits, hist = handles[:4]
        bank_hits, bank_misses = handles[4:]
        if cache == "bank_hit":
            bank_hits.inc()
        else:
            total.inc()
            (misses if cache == "miss" else hits).inc()
            hist.observe(seconds)
            if attrs.get("bank") == "miss":
                bank_misses.inc()
        return rec

    @staticmethod
    def _bank_has(key: tuple) -> bool:
        """Durable seen-check against the program bank; never raises
        (called under the ledger lock on the compile path)."""
        try:
            from ..compile import bank as _bank

            b = _bank.get_bank()
            return b is not None and b.has(*key)
        except Exception:
            return False

    # -- cross-process shipping (Frontiers piggyback) ------------------------
    def enable_ship(self, capacity: int = 4096) -> None:
        with self._lock:
            if self._ship is None:
                self._ship = deque(maxlen=capacity)

    def drain_shippable(self) -> list[tuple]:
        if self._ship is None or not self._ship:
            return []
        with self._lock:
            out = [r.to_wire() for r in self._ship]
            self._ship.clear()
        return out

    def ingest(self, wire_records: list, process: str = "") -> None:
        me = os.getpid()
        with self._lock:
            for t in wire_records:
                rec = CompileRecord.from_wire(t)
                if rec.pid == me:
                    continue  # in-process replica: already in _buf
                if process:
                    rec.process = process
                self._ingested.append(rec)

    # -- introspection --------------------------------------------------------
    def records(self) -> list[CompileRecord]:
        with self._lock:
            return list(self._buf) + list(self._ingested)

    def summary(self, names: set | None = None) -> dict:
        """Totals (optionally scoped to dataflow ``names``): the
        EXPLAIN ANALYSIS / bench.py surface. ``bank_hit`` records are
        NOT compiles — they count separately (``bank_hits``,
        ``bank_seconds_recovered`` = the compile wall they skipped),
        so ``compiles``/``misses``/``hits`` keep their pre-bank
        meaning."""
        recs = self.records()
        if names is not None:
            recs = [r for r in recs if r.name in names]
        banked = [r for r in recs if r.cache == "bank_hit"]
        recs = [r for r in recs if r.cache != "bank_hit"]
        out = {
            "compiles": len(recs),
            "misses": sum(1 for r in recs if r.cache == "miss"),
            "hits": sum(1 for r in recs if r.cache == "hit"),
            "seconds": round(sum(r.seconds for r in recs), 3),
            "hit_seconds": round(
                sum(r.seconds for r in recs if r.cache == "hit"), 3
            ),
            "bank_hits": len(banked),
            "bank_misses": sum(
                1 for r in recs if r.attrs.get("bank") == "miss"
            ),
            "bank_seconds_recovered": round(
                sum(
                    float(r.attrs.get("recovered_seconds", 0.0))
                    for r in banked
                ),
                3,
            ),
            "by_kind": {},
        }
        for r in recs:
            k = out["by_kind"].setdefault(
                r.kind, {"compiles": 0, "seconds": 0.0}
            )
            k["compiles"] += 1
            k["seconds"] = round(k["seconds"] + r.seconds, 3)
        return out

    def clear(self) -> None:
        with self._lock:
            lockcheck.shared_write("compile_ledger.seen")
            self._buf.clear()
            self._ingested.clear()
            self._seen.clear()
            if self._ship is not None:
                self._ship.clear()


LEDGER = CompileLedger()


def expr_fingerprint(obj) -> str:
    """Stable short fingerprint of a rendered expression (the PR 1
    fingerprint-stability work makes pickled MIR deterministic across
    processes and installs — the program-bank key's first half)."""
    import pickle

    try:
        payload = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    except Exception:
        payload = repr(obj).encode()
    return hashlib.blake2b(payload, digest_size=8).hexdigest()


def tier_vector(args: tuple) -> str:
    """Tier vector of one call signature: a digest of every array
    leaf's (shape, dtype) plus the total operand bytes — the program
    bank key's second half. Computed ONLY when a compile actually
    happened (never on the steady-state dispatch path)."""
    import jax

    h = hashlib.blake2b(digest_size=6)
    total = 0
    for leaf in jax.tree_util.tree_leaves(args):
        shape = getattr(leaf, "shape", None)
        if shape is None:
            h.update(repr(leaf)[:32].encode())
            continue
        dt = getattr(leaf, "dtype", None)
        h.update(str((tuple(shape), str(dt))).encode())
        try:
            total += leaf.size * leaf.dtype.itemsize
        except (AttributeError, TypeError):
            pass
    return f"{h.hexdigest()}:{total}"


class LedgeredJit:
    """A ``jax.jit`` wrapper that records actual compiles. With no
    bank configured (the default) the hot path costs two C attribute
    reads and a perf_counter call; ledger work happens only on the
    (seconds-long) compile itself. With a bank, dispatch routes
    through per-tier resolved executables (one dict probe + a
    tier_vector digest — microseconds against the ~ms device step),
    and first sight of a tier goes bank-lookup-then-AOT-compile."""

    __slots__ = (
        "fn", "kind", "name", "fingerprint", "ledger", "_routes",
    )

    def __init__(self, fn, kind, name, fingerprint, ledger=None):
        self.fn = fn
        self.kind = kind
        self.name = name
        self.fingerprint = fingerprint
        self.ledger = ledger if ledger is not None else LEDGER
        self._routes = {}

    def __call__(self, *args, **kwargs):
        from ..compile import bank as _bank

        b = _bank.BANK if _bank._resolved else _bank.get_bank()
        if b is not None:
            return self._banked_call(b, args, kwargs)
        return self._plain_call(args, kwargs)

    def _plain_call(self, args, kwargs):
        fn = self.fn
        try:
            n0 = fn._cache_size()
        except (AttributeError, TypeError):  # jax without the API
            return fn(*args, **kwargs)
        t0 = _time.perf_counter()
        out = fn(*args, **kwargs)
        if fn._cache_size() > n0:
            self.ledger.record(
                self.kind,
                self.name,
                self.fingerprint,
                tier_vector(args),
                _time.perf_counter() - t0,
            )
        return out

    # -- program-bank dispatch (ISSUE 16) ---------------------------------
    def _banked_call(self, b, args, kwargs):
        tier = tier_vector(args)
        route = self._routes.get(tier)
        if route is None:
            route = self._resolve_route(b, tier, args, kwargs)
            self._routes[tier] = route
        if route is False:
            # Unbankable program (serializer/lowering limits): the
            # plain jit path, with normal ledger accounting.
            return self._plain_call(args, kwargs)
        try:
            return route(*args, **kwargs)
        except Exception:
            # A resolved executable the runtime won't accept (layout
            # or structure drift) must degrade to a recompile, never
            # to an error or a wrong result.
            self._routes[tier] = False
            return self._plain_call(args, kwargs)

    def _resolve_route(self, b, tier, args, kwargs):
        key = (self.kind, self.fingerprint, tier)
        t0 = _time.perf_counter()
        loaded = b.lookup(*key)
        if loaded is not None:
            compiled, meta = loaded
            self.ledger.record(
                self.kind, self.name, self.fingerprint, tier,
                _time.perf_counter() - t0,
                cache="bank_hit",
                recovered_seconds=float(meta.get("seconds", 0.0)),
            )
            return compiled
        # Bank miss: compile ahead-of-time so the executable is in
        # hand for both dispatch and the write-back (calling the jit
        # would compile internally and keep the Compiled out of
        # reach).
        try:
            compiled = self.fn.lower(*args, **kwargs).compile()
        except Exception:
            return False
        secs = _time.perf_counter() - t0
        self.ledger.record(
            self.kind, self.name, self.fingerprint, tier, secs,
            bank="miss",
        )
        b.store(
            self.kind, self.fingerprint, tier, compiled,
            seconds=secs, name=self.name,
        )
        return compiled

    def lower(self, *args, **kwargs):
        return self.fn.lower(*args, **kwargs)

    def _cache_size(self):
        return self.fn._cache_size()


def ledger_jit(fn, kind: str, name: str, fingerprint: str,
               ledger=None) -> LedgeredJit:
    """Wrap an already-jitted callable so its compiles hit the ledger."""
    return LedgeredJit(fn, kind, name, fingerprint, ledger)
