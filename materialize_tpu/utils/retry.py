"""Unified retry/timeout/backoff policy (the ore::retry analog).

One module owns every control-plane wait: the reconnect backoff, the
durability-layer retry, hydration build retries, install/frontier poll
loops, and peek budgets. Before ISSUE 10 these were scattered ad-hoc
constants (``backoff = 0.05`` in the replica client, ``timeout=5.0``
socket connects, 30s install waits, 2–5ms poll sleeps); now each
*surface* resolves a :class:`RetryPolicy` through a dyncfg spec string,
so operators can retune a single surface at runtime::

    SET retry_policy_reconnect = 'base=10ms,max=500ms,mult=2,jitter=0.2'

A policy spec is ``key=value`` pairs separated by commas. Durations
accept ``ms``/``s`` suffixes (bare numbers are seconds):

    base    initial backoff                 (default 50ms)
    max     backoff ceiling                 (default 2s)
    mult    backoff multiplier              (default 2.0; 1 = fixed poll)
    jitter  +/- fraction of each sleep      (default 0.2)
    attempts  max attempts, 0 = unbounded   (default 0)
    budget    total wall-clock budget, 0 = unbounded (default 0).
              Surfaces that replaced a legacy hard cap treat 0 as
              that cap instead, never as an infinite wait: peek
              180s, install_wait/frontier_wait 30s, shutdown 5s.

Jitter is deterministic per :class:`RetryStream` when a seed is given
(the chaos harness replays fault schedules exactly); without a seed it
draws from a process-global PRNG, which breaks retry synchronization
between active-active replicas (the epoch ping-pong the jitter exists
to break).
"""

from __future__ import annotations

import random
import threading
import time as _time
from dataclasses import dataclass

from .dyncfg import COMPUTE_CONFIGS, Config


def _dur(s: str) -> float:
    s = s.strip().lower()
    if s.endswith("ms"):
        return float(s[:-2]) / 1000.0
    if s.endswith("s"):
        return float(s[:-1])
    return float(s)


@dataclass(frozen=True)
class RetryPolicy:
    """Backoff shape + budget for one retry surface."""

    base: float = 0.05
    max: float = 2.0
    mult: float = 2.0
    jitter: float = 0.2
    attempts: int = 0  # 0 = unbounded
    budget: float = 0.0  # seconds; 0 = unbounded

    _KEYS = frozenset(
        ("base", "max", "mult", "jitter", "attempts", "budget")
    )

    @classmethod
    def parse(cls, spec: str) -> "RetryPolicy":
        kv = {}
        for part in str(spec).split(","):
            part = part.strip()
            if not part:
                continue
            k, _, v = part.partition("=")
            kv[k.strip()] = v.strip()
        unknown = set(kv) - cls._KEYS
        if unknown:
            raise ValueError(
                f"unknown retry-policy key(s) {sorted(unknown)}; "
                f"valid: {sorted(cls._KEYS)}"
            )
        return cls(
            base=_dur(kv.get("base", "50ms")),
            max=_dur(kv.get("max", "2s")),
            mult=float(kv.get("mult", 2.0)),
            jitter=float(kv.get("jitter", 0.2)),
            attempts=int(kv.get("attempts", 0)),
            budget=_dur(kv.get("budget", "0")),
        )

    def stream(self, seed: int | None = None) -> "RetryStream":
        return RetryStream(self, seed=seed)

    def deadline(self, now: float | None = None) -> float:
        """Absolute monotonic deadline for this policy's budget
        (+inf when unbounded)."""
        if self.budget <= 0:
            return float("inf")
        return (_time.monotonic() if now is None else now) + self.budget

    def retry(self, f, retryable: tuple = (Exception,),
              seed: int | None = None):
        """Call ``f`` until it succeeds or the policy is exhausted;
        re-raises the last retryable error on exhaustion."""
        stream = self.stream(seed=seed)
        while True:
            try:
                return f()
            except retryable:
                if not stream.sleep():
                    raise


class RetryStream:
    """One retry sequence: tracks attempts, budget, and the jittered
    backoff. ``sleep()`` returns False when the policy is exhausted
    (the caller gives up); ``next_sleep()`` exposes the duration
    without sleeping for select-style waits."""

    def __init__(self, policy: RetryPolicy, seed: int | None = None):
        self.policy = policy
        self.attempt = 0
        self._backoff = policy.base
        self._deadline = policy.deadline()
        self._rng = random.Random(seed) if seed is not None else _RNG

    def expired(self) -> bool:
        if self.policy.attempts and self.attempt >= self.policy.attempts:
            return True
        return _time.monotonic() >= self._deadline

    def _jittered(self) -> float:
        d = self._backoff
        j = self.policy.jitter
        if j:
            d *= 1.0 + self._rng.uniform(-j, j)
        return d

    def next_sleep(self) -> float:
        remaining = self._deadline - _time.monotonic()
        return max(min(self._jittered(), remaining), 0.0)

    def next_sleep_unbounded(self) -> float:
        """Jittered backoff with attempts/budget IGNORED — for
        surfaces that must never give up (the reconnect loop keeps
        trying at the backoff ceiling forever; a 0.0 sleep from an
        expired budget would busy-spin it at full CPU)."""
        return max(self._jittered(), 0.0)

    def advance(self) -> None:
        self.attempt += 1
        self._backoff = min(
            self._backoff * self.policy.mult, self.policy.max
        )

    def sleep(self) -> bool:
        """One jittered backoff sleep. Returns False (without
        sleeping) when attempts or budget are exhausted."""
        self.advance()
        if self.expired():
            return False
        d = self.next_sleep()
        if d > 0:
            _time.sleep(d)
        return True

    def reset(self) -> None:
        """Back to the initial backoff (a successful session resets
        the reconnect stream)."""
        self.attempt = 0
        self._backoff = self.policy.base


class _SeededGlobal:
    """Process-global jitter source (thread-safe)."""

    def __init__(self):
        self._rng = random.Random()
        self._lock = threading.Lock()

    def uniform(self, a: float, b: float) -> float:
        with self._lock:
            return self._rng.uniform(a, b)


_RNG = _SeededGlobal()


# -- per-surface dyncfg specs -------------------------------------------------
#
# Each surface is ONE string config so SET/SHOW work on it whole. The
# defaults reproduce the constants they replaced (documented per
# surface) — consolidation first, retuning second.

RETRY_RECONNECT = Config(
    "retry_policy_reconnect",
    "base=50ms,max=2s,mult=2,jitter=0.2",
    "controller -> replica reconnect backoff (was the hardcoded "
    "0.05 -> 2.0 doubling loop in ReplicaClient)",
).register(COMPUTE_CONFIGS)

RETRY_DURABILITY = Config(
    "retry_policy_durability",
    "base=10ms,max=2s,mult=2,jitter=0.2,attempts=8",
    "blob/consensus transient-failure retry (was retry_external's "
    "8 attempts at 10ms doubling)",
).register(COMPUTE_CONFIGS)

RETRY_HYDRATION = Config(
    "retry_policy_hydration",
    "base=10ms,max=500ms,mult=2,jitter=0.2,attempts=5",
    "replica dataflow build/hydration retry against transient "
    "SinkConflict/Fenced/compaction races (was 5 attempts at 10ms)",
).register(COMPUTE_CONFIGS)

RETRY_INSTALL_WAIT = Config(
    "retry_policy_install_wait",
    "base=5ms,max=5ms,mult=1,jitter=0,budget=30s",
    "coordinator wait for a replica install ack (was a 5ms poll with "
    "a 30s budget)",
).register(COMPUTE_CONFIGS)

RETRY_FRONTIER_WAIT = Config(
    "retry_policy_frontier_wait",
    "base=5ms,max=5ms,mult=1,jitter=0,budget=30s",
    "controller frontier-advance poll (was a 5ms poll with a 30s "
    "default budget; explicit caller timeouts still override the "
    "budget)",
).register(COMPUTE_CONFIGS)

RETRY_PEEK = Config(
    "retry_policy_peek",
    "budget=180s",
    "peek/batched-gather response budget; on exhaustion the read is "
    "shed with the retryable ServerBusy signal (SQLSTATE 53400 / "
    "HTTP 503), never a generic error",
).register(COMPUTE_CONFIGS)

RETRY_SHUTDOWN = Config(
    "retry_policy_shutdown",
    "budget=5s",
    "per-replica graceful-exit budget before Environment.shutdown "
    "escalates terminate -> kill",
).register(COMPUTE_CONFIGS)

RETRY_FAILOVER = Config(
    "retry_policy_failover",
    "base=1s,max=1s,mult=1,jitter=0,attempts=3,budget=10s",
    "routed-read failover (ISSUE 19): `base` is the per-target stall "
    "budget before an unanswered routed peek re-dispatches to the "
    "next least-lagged candidate (disconnects re-dispatch "
    "immediately, not on this timer); `attempts` caps how many "
    "routed targets are tried before the terminal one-shot broadcast "
    "fallback; `budget` bounds drain_replica's wait for in-flight "
    "reads to move off a draining replica",
).register(COMPUTE_CONFIGS)

_SURFACES = {
    "reconnect": RETRY_RECONNECT,
    "durability": RETRY_DURABILITY,
    "hydration": RETRY_HYDRATION,
    "install_wait": RETRY_INSTALL_WAIT,
    "frontier_wait": RETRY_FRONTIER_WAIT,
    "peek": RETRY_PEEK,
    "shutdown": RETRY_SHUTDOWN,
    "failover": RETRY_FAILOVER,
}

_PARSE_CACHE: dict[str, RetryPolicy] = {}


def policy(surface: str) -> RetryPolicy:
    """The current policy for one surface, resolved through dyncfg
    (parse results memoized by spec string — the hot poll loops read
    this per wait, not per sleep). A malformed spec falls back to the
    surface's registered default: SET validates specs up front, but a
    bad record already in a durable catalog must degrade to defaults,
    not raise inside a reconnect daemon thread on every boot."""
    cfg = _SURFACES[surface]
    spec = str(cfg(COMPUTE_CONFIGS))
    got = _PARSE_CACHE.get(spec)
    if got is None:
        try:
            got = RetryPolicy.parse(spec)
        except ValueError:
            got = RetryPolicy.parse(cfg.default)
        _PARSE_CACHE[spec] = got
    return got


# -- recovery metrics ---------------------------------------------------------
#
# Counters every retry surface and the recovery paths feed; surfaced
# through /metrics, the mz_recovery introspection relation, and
# EXPLAIN ANALYSIS's `recovery:` block. Get-or-create: multiple
# controllers in one process (tests) share the process counters.

def _counter(name: str, help_: str):
    from .metrics import REGISTRY

    got = REGISTRY.get(name)
    if got is None:
        got = REGISTRY.counter(name, help_)
    return got


def _gauge(name: str, help_: str):
    from .metrics import REGISTRY

    got = REGISTRY.get(name)
    if got is None:
        got = REGISTRY.gauge(name, help_)
    return got


def reconnects_total():
    return _counter(
        "mz_controller_reconnects_total",
        "replica sessions re-established after a connection loss",
    )


def fenced_epochs_total():
    return _counter(
        "mz_fenced_epochs_total",
        "HelloReject responses observed (a newer controller owns the "
        "replica's epoch)",
    )


def recovery_seconds():
    return _gauge(
        "mz_recovery_seconds",
        "wall-clock seconds the last coordinator bootstrap spent "
        "replaying the durable catalog",
    )


def catalog_replayed_total():
    return _counter(
        "mz_catalog_replayed_total",
        "durable catalog records replayed across coordinator boots "
        "in this process",
    )
