"""Metrics: Prometheus-style registry.

Analog of the reference's ``ore::metrics::MetricsRegistry`` (every
process registers counters/gauges/histograms and serves them in the
Prometheus text exposition format; SURVEY.md §5 metrics/observability).
No external client library — the text format is trivial and this keeps
the zero-dependency rule.

Deployment-wide scraping (ISSUE 12): replica processes piggyback their
sample snapshots on Frontiers responses; the controller keeps the
latest per replica, and :func:`cluster_exposition` merges them with
the local registry into ONE conformant exposition — every remote
sample gains a ``replica`` label, families repeated across processes
share a single ``# TYPE`` header, and one scrape of the coordinator's
``/metrics`` covers the cluster.
"""

from __future__ import annotations

import threading
from bisect import bisect_right


class _Metric:
    def __init__(self, name: str, help_: str, registry: "MetricsRegistry"):
        from .lockcheck import tracked_lock

        self.name = name
        self.help = help_
        self._lock = tracked_lock("metrics.metric")
        if registry is not None:
            registry._register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        with self._lock:
            return [(self.name, {}, self._value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        with self._lock:
            return [(self.name, {}, self._value)]


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BUCKETS = (
        0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10,
    )

    def __init__(self, name, help_="", buckets=None, registry=None):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._total += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts: the upper bound of
        the bucket containing the q-th observation. Edge contract
        (ISSUE 12 satellite): empty histogram -> 0.0; q <= 0 -> the
        first NONEMPTY bucket's bound (never an empty leading bucket);
        q >= 1 -> the last nonempty bucket's bound (+Inf only when
        observations actually landed past the last finite bucket)."""
        with self._lock:
            if self._total == 0:
                return 0.0
            q = min(max(q, 0.0), 1.0)
            if q <= 0.0:
                for i, c in enumerate(self._counts[:-1]):
                    if c > 0:
                        return self.buckets[i]
                return float("inf")  # everything in the overflow bucket
            target = q * self._total
            acc = 0
            for i, c in enumerate(self._counts[:-1]):
                acc += c
                # `c > 0` skips empty leading buckets a tiny target
                # (q*total < 1) would otherwise select.
                if c > 0 and acc >= target:
                    return self.buckets[i]
            return float("inf")

    def samples(self):
        with self._lock:  # consistent with observe(): no torn scrapes
            counts = list(self._counts)
            total, sum_ = self._total, self._sum
        out = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append((self.name + "_bucket", {"le": str(b)}, acc))
        out.append(
            (self.name + "_bucket", {"le": "+Inf"}, acc + counts[-1])
        )
        out.append((self.name + "_sum", {}, sum_))
        out.append((self.name + "_count", {}, total))
        return out


def _escape_help(s: str) -> str:
    return s.replace("\\", "\\\\").replace("\n", "\\n")


def _escape_label(s: str) -> str:
    return (
        s.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")
    )


def _fmt_value(v) -> str:
    """Prometheus sample value: integers render without a trailing
    `.0` (cumulative bucket counts MUST parse as integers)."""
    f = float(v)
    if f == int(f) and abs(f) < 1e15:
        return str(int(f))
    return repr(f)


def sample_line(name: str, labels: dict, value) -> str:
    if labels:
        lbl = ",".join(
            f'{k}="{_escape_label(str(v))}"'
            for k, v in sorted(labels.items())
        )
        return f"{name}{{{lbl}}} {_fmt_value(value)}"
    return f"{name} {_fmt_value(value)}"


def exposition(families: list) -> str:
    """Render [(name, kind, help, [(sample_name, labels, value)...])]
    to the text exposition format. Families sharing a name (the same
    metric observed in several processes) merge under ONE header."""
    lines = []
    seen_headers = set()
    for name, kind, help_, samples in families:
        if name not in seen_headers:
            seen_headers.add(name)
            if help_:
                lines.append(f"# HELP {name} {_escape_help(help_)}")
            lines.append(f"# TYPE {name} {kind}")
        for sname, labels, value in samples:
            lines.append(sample_line(sname, labels, value))
    return "\n".join(lines) + "\n"


class MetricsRegistry:
    """Register-and-scrape: the per-process metrics authority."""

    def __init__(self):
        from .lockcheck import tracked_lock

        self._metrics: dict[str, _Metric] = {}
        self._lock = tracked_lock("metrics.registry")

    def _register(self, m: _Metric) -> None:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(f"metric {m.name!r} already registered")
            self._metrics[m.name] = m

    def get_or_create(self, kind: str, name: str, help_: str = "",
                      **kwargs) -> _Metric:
        """Idempotent registration: return the existing metric or
        create it, tolerating a first-registration race (shared
        metrics registered lazily from several threads — the compile
        ledger, the coordinator's statement counter)."""
        m = self.get(name)
        if m is not None:
            return m
        try:
            return getattr(self, kind)(name, help_, **kwargs)
        except ValueError:
            return self.get(name)

    def counter(self, name, help_="") -> Counter:
        return Counter(name, help_, registry=self)

    def gauge(self, name, help_="") -> Gauge:
        return Gauge(name, help_, registry=self)

    def histogram(self, name, help_="", buckets=None) -> Histogram:
        return Histogram(name, help_, buckets=buckets, registry=self)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def families(self, extra_labels: dict | None = None) -> list:
        """[(name, kind, help, samples)] — the mergeable form replicas
        piggyback on Frontiers (``extra_labels`` stamped on every
        sample, e.g. {"replica": "r0"})."""
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        out = []
        for m in metrics:
            samples = m.samples()
            if extra_labels:
                samples = [
                    (sn, {**lb, **extra_labels}, v)
                    for sn, lb, v in samples
                ]
            out.append((m.name, m.kind, m.help, samples))
        return out

    def expose_text(self) -> str:
        """Prometheus text exposition format (this process only)."""
        return exposition(self.families())


def cluster_exposition(registry: "MetricsRegistry",
                       remote: dict | None) -> str:
    """One exposition covering the deployment: the local registry's
    families plus every replica's last piggybacked snapshot, remote
    samples labeled ``replica="<name>"``. Families are merged by name
    so a metric observed in N processes exposes one TYPE header and
    N+... labeled series."""
    merged: dict[str, tuple] = {}
    order: list[str] = []

    def absorb(families, extra_labels=None):
        for name, kind, help_, samples in families:
            if extra_labels:
                samples = [
                    (sn, {**lb, **extra_labels}, v)
                    for sn, lb, v in samples
                ]
            if name in merged:
                k0, h0, s0 = merged[name]
                merged[name] = (k0, h0 or help_, s0 + list(samples))
            else:
                merged[name] = (kind, help_, list(samples))
                order.append(name)

    absorb(registry.families())
    for rep_name in sorted(remote or ()):
        absorb(remote[rep_name], {"replica": rep_name})
    return exposition(
        [(n,) + merged[n][:2] + (merged[n][2],) for n in order]
    )


# Per-process default registry (ore::metrics global analog).
REGISTRY = MetricsRegistry()
