"""Metrics: Prometheus-style registry.

Analog of the reference's ``ore::metrics::MetricsRegistry`` (every
process registers counters/gauges/histograms and serves them in the
Prometheus text exposition format; SURVEY.md §5 metrics/observability).
No external client library — the text format is trivial and this keeps
the zero-dependency rule.
"""

from __future__ import annotations

import threading
from bisect import bisect_right


class _Metric:
    def __init__(self, name: str, help_: str, registry: "MetricsRegistry"):
        from .lockcheck import tracked_lock

        self.name = name
        self.help = help_
        self._lock = tracked_lock("metrics.metric")
        if registry is not None:
            registry._register(self)


class Counter(_Metric):
    kind = "counter"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._value = 0.0

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        return [(self.name, {}, self._value)]


class Gauge(_Metric):
    kind = "gauge"

    def __init__(self, name, help_="", registry=None):
        super().__init__(name, help_, registry)
        self._value = 0.0

    def set(self, v: float) -> None:
        self._value = float(v)

    def inc(self, amount: float = 1.0) -> None:
        with self._lock:
            self._value += amount

    def dec(self, amount: float = 1.0) -> None:
        self.inc(-amount)

    @property
    def value(self) -> float:
        return self._value

    def samples(self):
        return [(self.name, {}, self._value)]


class Histogram(_Metric):
    kind = "histogram"

    DEFAULT_BUCKETS = (
        0.0001, 0.0005, 0.001, 0.005, 0.01, 0.05, 0.1, 0.5, 1, 5, 10,
    )

    def __init__(self, name, help_="", buckets=None, registry=None):
        super().__init__(name, help_, registry)
        self.buckets = tuple(buckets or self.DEFAULT_BUCKETS)
        self._counts = [0] * (len(self.buckets) + 1)
        self._sum = 0.0
        self._total = 0

    def observe(self, v: float) -> None:
        with self._lock:
            self._counts[bisect_right(self.buckets, v)] += 1
            self._sum += v
            self._total += 1

    def quantile(self, q: float) -> float:
        """Approximate quantile from bucket counts (upper bound)."""
        with self._lock:
            if self._total == 0:
                return 0.0
            target = q * self._total
            acc = 0
            for i, c in enumerate(self._counts[:-1]):
                acc += c
                if acc >= target:
                    return self.buckets[i]
            return float("inf")

    def samples(self):
        with self._lock:  # consistent with observe(): no torn scrapes
            counts = list(self._counts)
            total, sum_ = self._total, self._sum
        out = []
        acc = 0
        for b, c in zip(self.buckets, counts):
            acc += c
            out.append((self.name + "_bucket", {"le": str(b)}, acc))
        out.append(
            (self.name + "_bucket", {"le": "+Inf"}, acc + counts[-1])
        )
        out.append((self.name + "_sum", {}, sum_))
        out.append((self.name + "_count", {}, total))
        return out


class MetricsRegistry:
    """Register-and-scrape: the per-process metrics authority."""

    def __init__(self):
        from .lockcheck import tracked_lock

        self._metrics: dict[str, _Metric] = {}
        self._lock = tracked_lock("metrics.registry")

    def _register(self, m: _Metric) -> None:
        with self._lock:
            if m.name in self._metrics:
                raise ValueError(f"metric {m.name!r} already registered")
            self._metrics[m.name] = m

    def counter(self, name, help_="") -> Counter:
        return Counter(name, help_, registry=self)

    def gauge(self, name, help_="") -> Gauge:
        return Gauge(name, help_, registry=self)

    def histogram(self, name, help_="", buckets=None) -> Histogram:
        return Histogram(name, help_, buckets=buckets, registry=self)

    def get(self, name: str) -> _Metric | None:
        return self._metrics.get(name)

    def expose_text(self) -> str:
        """Prometheus text exposition format."""
        lines = []
        with self._lock:
            metrics = sorted(self._metrics.values(), key=lambda m: m.name)
        for m in metrics:
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            for name, labels, value in m.samples():
                if labels:
                    lbl = ",".join(
                        f'{k}="{v}"' for k, v in sorted(labels.items())
                    )
                    lines.append(f"{name}{{{lbl}}} {value}")
                else:
                    lines.append(f"{name} {value}")
        return "\n".join(lines) + "\n"


# Per-process default registry (ore::metrics global analog).
REGISTRY = MetricsRegistry()
