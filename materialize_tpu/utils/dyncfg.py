"""dyncfg: typed dynamic configuration flags.

Analog of the reference's ``mz_dyncfg`` (``dyncfg/src/lib.rs:10-30``):
typed ``Config``s registered into a shared ``ConfigSet``; values can be
updated at runtime (from a file, SQL, or the controller) and every
component reads the current value at use sites. Updates propagate to
replicas IN COMMAND-STREAM ORDER via ``UpdateConfiguration`` (see
coord/protocol.py), so all workers flip a flag at the same point in the
update stream (compute_state.rs:46-59 discipline).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

from . import lockcheck


@dataclass
class Config:
    """One typed flag: name, default, help. Bind into a ConfigSet to
    read values."""

    name: str
    default: Any
    help: str = ""

    def __call__(self, config_set: "ConfigSet"):
        return config_set.get(self.name)

    def register(self, config_set: "ConfigSet") -> "Config":
        config_set.add(self)
        return self


class ConfigSet:
    def __init__(self):
        from .lockcheck import tracked_lock

        self._configs: dict[str, Config] = {}
        self._values: dict[str, Any] = {}
        self._lock = tracked_lock("dyncfg")

    def add(self, cfg: Config) -> None:
        with self._lock:
            existing = self._configs.get(cfg.name)
            if existing is not None and existing.default != cfg.default:
                raise ValueError(
                    f"config {cfg.name!r} re-registered with a "
                    "different default"
                )
            self._configs[cfg.name] = cfg

    def get(self, name: str):
        with self._lock:
            lockcheck.shared_read("dyncfg.values")
            if name in self._values:
                return self._values[name]
            return self._configs[name].default

    def update(self, values: dict) -> dict:
        """Apply updates (unknown keys are kept — a newer process may
        know them); returns the full current value map for shipping to
        replicas."""
        with self._lock:
            lockcheck.shared_write("dyncfg.values")
            for k, v in values.items():
                if v is None:
                    # None RESETS to the default (a stored None would
                    # permanently mask it).
                    self._values.pop(k, None)
                    continue
                cfg = self._configs.get(k)
                if cfg is not None:
                    # Coerce to the default's type (flags arrive as
                    # strings from SQL/files).
                    t = type(cfg.default)
                    if t is bool and isinstance(v, str):
                        v = v.lower() in ("true", "on", "1", "yes")
                    elif not isinstance(v, t):
                        v = t(v)
                self._values[k] = v
            return dict(self._values)

    def current(self) -> dict:
        with self._lock:
            lockcheck.shared_read("dyncfg.values")
            out = {n: c.default for n, c in self._configs.items()}
            out.update(self._values)
            return out


# The compute-layer flag set (compute-types/src/dyncfgs.rs analog).
COMPUTE_CONFIGS = ConfigSet()

ENABLE_TEMPORAL_FILTERS = Config(
    "enable_temporal_filters", True,
    "render mz_now() predicates as scheduled temporal filters",
).register(COMPUTE_CONFIGS)

DELTA_JOIN_MIN_INPUTS = Config(
    "delta_join_min_inputs", 3,
    "minimum join breadth for the delta-join plan (vs linear)",
).register(COMPUTE_CONFIGS)

ARRANGEMENT_COMPACTION_BATCHES = Config(
    "arrangement_compaction_batches", 8,
    "shard spine length that triggers background compaction",
).register(COMPUTE_CONFIGS)

COMPACTION_MODE = Config(
    "compaction_mode", "background",
    "where shard compaction runs when a writer's append grows the "
    "spine past arrangement_compaction_batches: 'background' enqueues "
    "to the leased compactor service (storage/persist/compactor.py; "
    "the tick path's entire cost is the O(1) request), 'inline' merges "
    "synchronously on the writer's path (the pre-ISSUE-20 behavior, "
    "kept as the bench comparison baseline), 'off' never triggers "
    "(manual maybe_compact only)",
).register(COMPUTE_CONFIGS)

COMPACTION_LEASE_S = Config(
    "compaction_lease_s", 5.0,
    "compaction lease duration: a crashed compactor's shard is "
    "reclaimable by a successor after this long; the holder renews "
    "before every swap, and epoch fencing rejects a stale holder that "
    "outlived its lease",
).register(COMPUTE_CONFIGS)

PART_TIERING = Config(
    "part_tiering", "auto",
    "batch-part hot/cold tiering: 'auto' keeps recently "
    "written/read decoded parts host-resident up to part_hot_bytes "
    "(LRU eviction to blob-only cold tier, lazy rehydration on first "
    "read), 'all_hot' never evicts, 'all_cold' caches nothing (every "
    "read rehydrates from blob — the worst-case latency baseline)",
).register(COMPUTE_CONFIGS)

PART_HOT_BYTES = Config(
    "part_hot_bytes", 64 << 20,
    "hot-tier budget in encoded part bytes per process (part_tiering="
    "auto); mz_arrangement_sizes' hot/cold byte split reports the "
    "resulting boundary per dataflow",
).register(COMPUTE_CONFIGS)

OPTIMIZER_TYPECHECK = Config(
    "optimizer_typecheck", False,
    "run the MIR typechecker (analysis/typecheck.py) between optimizer "
    "transforms so an invalid plan is blamed on the transform that "
    "produced it (transform/src/typecheck.rs analog); default-on in "
    "the test suite via tests/conftest.py",
).register(COMPUTE_CONFIGS)

FUSED_MERGE = Config(
    "fused_merge", "auto",
    "sorted-merge position kernel: 'auto' picks the Pallas kernel on "
    "TPU when both runs' lanes fit VMEM and the pure-lax fused binary "
    "search elsewhere; 'lax' forces the fused lax path; 'pallas' "
    "forces the Pallas kernel (interpret mode off-TPU — CPU tests and "
    "the TPU path share semantics); 'unfused' keeps the legacy "
    "per-lane gather search (comparison baseline)",
).register(COMPUTE_CONFIGS)

CACHED_RUN_LANES = Config(
    "cached_run_lanes", True,
    "carry each frozen spine run's stacked sort lanes in the spine "
    "state, computed at fold time and maintained by the merge's own "
    "row-gather — per-step probes and folds then never re-derive "
    "lanes from columns of unchanged runs (round-6 O(delta) work)",
).register(COMPUTE_CONFIGS)

ARRANGEMENT_INGEST_MODE = Config(
    "arrangement_ingest_mode", "auto",
    "spine hot-path ingest: 'append_slot' lands each arranged delta "
    "in a run-0 append slot (O(delta) per step; the ladder's level-0 "
    "fold absorbs the ring on its amortized cadence), 'merge' merges "
    "into run 0 every step (O(run0)); 'auto' picks append_slot for "
    "big-state arrangements (plan/decisions.ingest_mode)",
).register(COMPUTE_CONFIGS)

COMPUTE_RETAIN_HISTORY = Config(
    "compute_retain_history", 32,
    "multiversion window: per-dataflow output-delta history retained "
    "for AS OF reads, in virtual timestamps (the read-policy lag "
    "analog, adapter/src/coord/read_policy.rs)",
).register(COMPUTE_CONFIGS)

# -- the O(result) serving plane (ISSUE 6 / ROADMAP item 3) -------------------

PEEK_FAST_PATH = Config(
    "peek_fast_path", True,
    "serve key-equality lookups and full scans over peekable "
    "(indexed/materialized) relations by row-gathering directly from "
    "the maintained spine (coord/peek.py) instead of rendering a "
    "transient dataflow — O(result) reads, zero installs (the "
    "adapter-layer peek fast path, coord/peek.rs analog)",
).register(COMPUTE_CONFIGS)

PEEK_BATCHING = Config(
    "peek_batching", True,
    "fan concurrent sessions' fast-path lookups against the same "
    "index into ONE stacked device gather per batch window, so the "
    "dispatch round trip (~96ms through the TPU tunnel) is amortized "
    "across all waiting readers; off = one dispatch per peek",
).register(COMPUTE_CONFIGS)

PEEK_BATCH_WINDOW_MS = Config(
    "peek_batch_window_ms", 2.0,
    "batching span tick: how long queued fast-path lookups wait to be "
    "stacked into one device gather (latency floor of a batched read)",
).register(COMPUTE_CONFIGS)

PEEK_MAX_BATCH = Config(
    "peek_max_batch", 64,
    "max probes stacked into one gather dispatch (padded to a pow2 "
    "batch lane so the program compiles once per tier)",
).register(COMPUTE_CONFIGS)

PEEK_QUEUE_DEPTH = Config(
    "peek_queue_depth", 1024,
    "admission control: max fast-path lookups queued for batching; "
    "arrivals beyond this are shed with a clean 'server busy' error "
    "(SQLSTATE 53400 at pgwire, HTTP 503) instead of building an "
    "unbounded backlog",
).register(COMPUTE_CONFIGS)

PEEK_MAX_INFLIGHT = Config(
    "peek_max_inflight", 4,
    "admission control: max batched gather dispatches in flight; the "
    "flusher holds further batches (queue-depth shedding then "
    "backpressures arrivals)",
).register(COMPUTE_CONFIGS)

PEEK_TS_CACHE_MS = Config(
    "peek_ts_cache_ms", 0.0,
    "serving-mode timestamp selection: cache a peekable dataflow's "
    "selected read timestamp for this many milliseconds (invalidated "
    "by writes through this coordinator). 0 = strict (one consensus "
    "read per peek); >0 trades bounded staleness w.r.t. out-of-band "
    "source ticks for not paying a consensus read per peek under "
    "concurrency (reads within one serving tick share a timestamp)",
).register(COMPUTE_CONFIGS)

# -- the async pipelined control plane (ISSUE 7 / ROADMAP item 4) ------------

SPAN_PIPELINING = Config(
    "span_pipelining", True,
    "replica worker loop: step maintained views in SPANS of up to "
    "span_max_ticks ready micro-batches with deferred overflow checks "
    "— the span's ticks dispatch asynchronously and the span commits "
    "with ONE flags readback, overlapped with the NEXT span's ingest "
    "and dispatch (double-buffered: at most one span in flight ahead "
    "of the committed frontier). Off = the per-tick step loop (one "
    "readback per tick)",
).register(COMPUTE_CONFIGS)

SPAN_MAX_TICKS = Config(
    "span_max_ticks", 8,
    "max ready micro-batches dispatched per replica span; the span "
    "commit (frontier advance, subscriber publish, history record) "
    "happens once per span at the boundary readback",
).register(COMPUTE_CONFIGS)

SPAN_WINDOW_SPANS = Config(
    "span_window_spans", 16,
    "pipelined spans per rollback window: the deferred-overflow "
    "checkpoint and input log are retained across this many committed "
    "spans, then validated and cleared (bounds replay memory; the "
    "boundary validation is the window's one extra sync point)",
).register(COMPUTE_CONFIGS)

SPAN_DONATION = Config(
    "span_donation", "auto",
    "donate the span program's carry (operator states, output spine, "
    "err arrangement, device time) to XLA so each span's outputs "
    "reuse the previous span's state buffers instead of allocating + "
    "copying state-sized arrays per dispatch. 'auto' = on for TPU "
    "backends; 'off' forces off; 'on' forces on WHERE the backend "
    "honors donation (CPU ignores donate_argnums, and jaxlib crashes "
    "lowering large donated programs on the forced multi-device host "
    "platform; reported state always reflects the EFFECTIVE value). "
    "The rollback checkpoint is CLONED to fresh buffers before the "
    "first donated dispatch of a window — donated buffers are never "
    "read back",
).register(COMPUTE_CONFIGS)

# -- the persistent AOT program bank (ISSUE 16) ------------------------------

PROGRAM_BANK_PATH = Config(
    "program_bank_path", "",
    "directory of the persistent cross-process AOT program bank "
    "(compile/bank.py): every ledger_jit site looks serialized "
    "executables up by (kind, fingerprint, tier) before compiling "
    "and writes misses back. Empty = bank off (dispatch is "
    "byte-identical to the pre-bank hot path). environmentd sets "
    "this to <data-dir>/blob/program_bank; SET propagates it to "
    "replicas like every dyncfg",
).register(COMPUTE_CONFIGS)

ENABLE_ASYNC_COMPILE = Config(
    "enable_async_compile", False,
    "async DDL compile + hot-swap (requires a program bank): CREATE "
    "INDEX / CREATE MATERIALIZED VIEW installs its dataflow in "
    "generic merge mode immediately (correct results, O(run0) "
    "ingest) while a background worker pre-compiles the specialized "
    "program into the bank; the replica hot-swaps at a span boundary "
    "(sync_spans sequencing — no half-applied carry). Surfaced in "
    "EXPLAIN ANALYSIS compiles: pending_swap, the hydration board, "
    "and mz_program_bank",
).register(COMPUTE_CONFIGS)

# -- buffer-provenance / donation safety (ISSUE 8) ---------------------------

BUFFER_SANITIZER = Config(
    "buffer_sanitizer", False,
    "use-after-donate sanitizer: every donated span/step dispatch "
    "records the killed carry leaves in a ledger (weakrefs — never "
    "extends a buffer's lifetime), and guarded read sites "
    "(IndexSource snapshots, multiversion rewinds, operand packing) "
    "raise UseAfterDonateError with the provenance chain naming who "
    "still held the alias. The donation CONTRACT is backend-"
    "independent, so the sanitizer enforces it on CPU too — the test "
    "suite (default ON under `pytest -m analysis`) catches "
    "use-after-donate bugs on hosts where real donation is not even "
    "wired. Production default off (one ledger walk per donated "
    "dispatch)",
).register(COMPUTE_CONFIGS)

RACE_DETECTOR = Config(
    "race_detector", False,
    "happens-before race detector (analysis/racecheck.py): vector-"
    "clock instrumentation layered on lockcheck's tracked-lock "
    "acquire/release hooks plus the declared-shared-state registry "
    "(controller maps, hub session tables, freshness rings, "
    "compile-ledger memory, this dyncfg store), reporting "
    "unsynchronized read/write pairs with both stack chains. Default "
    "ON under `pytest -m analysis` (tests/conftest.py) and in the "
    "check_plans.py --bench race-free gate; production default off "
    "(one module-global None check per declared access, same "
    "discipline as buffer_sanitizer)",
).register(COMPUTE_CONFIGS)

# -- the push serving plane (ISSUE 11 / ROADMAP item 3) ----------------------

SUBSCRIBE_MAX_SESSIONS = Config(
    "subscribe_max_sessions", 10000,
    "admission control for the push plane: max live SUBSCRIBE "
    "sessions across the coordinator; arrivals beyond this are shed "
    "with 'server busy' (SQLSTATE 53400 at pgwire, HTTP 503) instead "
    "of degrading every existing stream",
).register(COMPUTE_CONFIGS)

SUBSCRIBE_QUEUE_DEPTH = Config(
    "subscribe_queue_depth", 8192,
    "per-session delivery queue bound, in rows: a consumer that "
    "cannot drain its deltas this far behind the shared tail is "
    "handled by subscribe_slow_policy instead of buffering without "
    "bound (the hub's queues are the only per-subscriber state)",
).register(COMPUTE_CONFIGS)

SUBSCRIBE_SLOW_POLICY = Config(
    "subscribe_slow_policy", "disconnect",
    "what happens to a subscriber whose queue exceeds "
    "subscribe_queue_depth: 'disconnect' terminates the session with "
    "a retryable error; 'coalesce' drops the queued deltas and "
    "re-delivers a collapsed snapshot at the current frontier (state "
    "transfer — correct for dashboard-class consumers that only need "
    "current state, at the cost of one extra shard read)",
).register(COMPUTE_CONFIGS)

SUBSCRIBE_TAIL_POLL_MS = Config(
    "subscribe_tail_poll_ms", 50.0,
    "shared-tail wait granularity: how long one listen cycle blocks "
    "for the sink shard's upper to advance before re-checking for "
    "retirement (bounds tail-thread teardown latency, NOT delivery "
    "latency — data wakes the listen immediately)",
).register(COMPUTE_CONFIGS)

# -- the observability plane (ISSUE 12) --------------------------------------

TRACE_LEVEL = Config(
    "trace_level", "info",
    "statement-trace recording level (the log_filter system var "
    "analog): 'off' disables span recording entirely, 'error' < "
    "'info' < 'debug'. Statement/command spans record at info; the "
    "per-span pipeline cadence (dispatch, readback-wait, commit, "
    "fold) records at debug so the default level keeps the hot path "
    "recorder-free. Propagates to replicas via UpdateConfiguration "
    "like every dyncfg",
).register(COMPUTE_CONFIGS)

SLOW_STATEMENT_MS = Config(
    "slow_statement_ms", 0.0,
    "slow-statement log threshold in milliseconds: statements whose "
    "end-to-end sequencing exceeds it are recorded (sql, wall ms, "
    "trace_id) in the mz_slow_statements ring and counted in "
    "/metrics. 0 disables (production default: opt in per deployment)",
).register(COMPUTE_CONFIGS)

METRICS_REPORT_MS = Config(
    "metrics_report_ms", 2000.0,
    "how often a replica piggybacks its /metrics sample snapshot on a "
    "Frontiers response (deployment-wide scrape cadence): snapshots "
    "ship at most once per interval and only when some value changed",
).register(COMPUTE_CONFIGS)

FRESHNESS_SLO_MS = Config(
    "freshness_slo_ms", 0.0,
    "per-object wallclock-lag SLO in milliseconds (the freshness "
    "plane, coord/freshness.py): a committed span boundary whose lag "
    "exceeds it increments mz_freshness_breaches_total, and breach "
    "ONSETS append to the bounded mz_freshness_events ring; /api/"
    "readyz reports not-ready while any durable dataflow's latest lag "
    "breaches. 0 disables (production default: opt in per deployment)",
).register(COMPUTE_CONFIGS)

TRANSIENT_PEEK_CACHE = Config(
    "transient_peek_cache", 8,
    "memoize slow-path SELECT dataflows by description fingerprint: "
    "a repeated identical SELECT reuses the installed transient "
    "dataflow (skipping re-render/re-compile) instead of installing a "
    "uniquely-named copy; LRU-capped at this many installs, 0 "
    "disables (PR 1's fingerprint stability exists for exactly this)",
).register(COMPUTE_CONFIGS)

PEEK_ROUTING = Config(
    "peek_routing", "route",
    "read-plane dispatch mode (ISSUE 19): 'route' sends each peek / "
    "batched lookup to the single least-lagged hydrated replica "
    "(duplicate dispatches avoided are counted in "
    "mz_peek_broadcast_avoided_total) and fails over to the next "
    "candidate on disconnect/stall via retry_policy_failover; "
    "'broadcast' restores the legacy fan-out-to-all/first-response-"
    "wins path",
).register(COMPUTE_CONFIGS)

AUTOSCALE_POLICY = Config(
    "autoscale_policy", "",
    "SLO-driven replica autoscaler spec (coord/autoscaler.py), e.g. "
    "'min=1,max=3,up_sustain=2s,down_sustain=10s,cooldown=5s,"
    "headroom=0.25,interval=250ms': sustained mz_freshness_events "
    "breaches spawn a replica (up to max), sustained lag headroom "
    "(every durable dataflow's latest lag under headroom*slo) drains "
    "the most-lagged one (down to min), with cooldown hysteresis; "
    "every decision lands in the mz_autoscale_events ledger. Empty "
    "disables (production default: opt in per deployment)",
).register(COMPUTE_CONFIGS)
