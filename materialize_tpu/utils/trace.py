"""Tracing: lightweight spans with a per-process ring buffer.

Analog of the reference's tracing stack (tracing + OpenTelemetry with
runtime-settable filters, SURVEY.md §5): spans record (name, start,
duration, attributes, parent) into a bounded ring buffer queryable as an
introspection relation; a dynamic level filter mirrors the ``log_filter``
system var. Span context propagates across the control protocol by
carrying the span id in command payloads (OpenTelemetryContext riding
PeekResponse in the reference).
"""

from __future__ import annotations

import itertools
import threading
import time as _time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

LEVELS = {"off": 0, "error": 1, "info": 2, "debug": 3}


@dataclass
class SpanRecord:
    span_id: int
    parent_id: int | None
    name: str
    start: float
    duration: float
    level: str
    attrs: dict = field(default_factory=dict)


class Tracer:
    def __init__(self, capacity: int = 4096):
        self._buf: deque[SpanRecord] = deque(maxlen=capacity)
        self._ids = itertools.count(1)
        self._level = LEVELS["info"]
        self._local = threading.local()
        self._lock = threading.Lock()

    # -- dynamic filter (log_filter system var analog) ----------------------
    def set_level(self, level: str) -> None:
        self._level = LEVELS[level]

    @property
    def level(self) -> str:
        for k, v in LEVELS.items():
            if v == self._level:
                return k
        return "info"

    # -- span API ------------------------------------------------------------
    @contextmanager
    def span(self, name: str, level: str = "info", **attrs):
        if LEVELS[level] > self._level:
            yield None
            return
        span_id = next(self._ids)
        parent = getattr(self._local, "current", None)
        self._local.current = span_id
        start = _time.perf_counter()
        wall = _time.time()
        try:
            yield span_id
        finally:
            dur = _time.perf_counter() - start
            self._local.current = parent
            with self._lock:
                self._buf.append(
                    SpanRecord(
                        span_id, parent, name, wall, dur, level, attrs
                    )
                )

    def current_span(self) -> int | None:
        """For protocol propagation: ship this with commands."""
        return getattr(self._local, "current", None)

    @contextmanager
    def remote_parent(self, parent_id: int | None):
        """Adopt a propagated remote span as the parent."""
        saved = getattr(self._local, "current", None)
        self._local.current = parent_id
        try:
            yield
        finally:
            self._local.current = saved

    # -- introspection --------------------------------------------------------
    def records(self, name_prefix: str = "") -> list[SpanRecord]:
        with self._lock:
            return [
                r for r in self._buf if r.name.startswith(name_prefix)
            ]

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()


TRACER = Tracer()
