"""Tracing: per-statement trace trees across processes (ISSUE 12).

Analog of the reference's tracing stack (tracing + OpenTelemetry with
runtime-settable filters, SURVEY.md §5): spans record (trace_id,
span_id, parent_id, process, name, start, duration, attributes) into a
bounded per-process ring buffer queryable as the ``mz_trace_spans``
introspection relation; a dynamic level filter mirrors the
``log_filter`` system var (the ``trace_level`` dyncfg).

Cross-process propagation follows the reference's
OpenTelemetryContext-riding-commands pattern: the front end (pgwire /
HTTP) MINTS a trace_id per statement and opens the root span; the
coordinator and controller open child spans on the same thread
(thread-local context stack); CTP commands carry ``{"t": trace_id,
"s": span_id}`` so the replica can :meth:`Tracer.adopt` the remote
parent; and completed replica spans ship back PIGGYBACKED on Frontiers
responses (the PR 5/6 verdict pattern — shipped only when present, so
steady state with tracing off pays nothing). The controller ingests
shipped spans into this process's tracer, so one ``mz_trace_spans``
query shows ONE coherent tree per statement across every process.

Span ids embed the process id (``(pid << 40) | counter``) so ids from
different processes never collide in a merged tree; ingest drops
records whose pid equals ours (an in-process replica shares this
tracer — its spans are already in the ring).

The recorder is pure host bookkeeping — no device reads, no syncs —
and is registered with the host-sync linter (analysis/host_sync.py) so
a d2h sync can never sneak into the hot recording path.
"""

from __future__ import annotations

import itertools
import os
import threading
import time as _time
from collections import deque
from contextlib import contextmanager
from dataclasses import dataclass, field

LEVELS = {"off": 0, "error": 1, "info": 2, "debug": 3}

# Span-id layout: the low 40 bits count, the bits above carry the pid.
_PID_SHIFT = 40


@dataclass
class SpanRecord:
    span_id: int
    parent_id: int | None
    name: str
    start: float
    duration: float
    level: str
    attrs: dict = field(default_factory=dict)
    trace_id: int = 0  # 0 = recorded outside any statement trace
    process: str = ""  # "" = this process (filled on ingest)
    pid: int = 0

    def to_wire(self) -> tuple:
        """Compact tuple for the Frontiers piggyback (attrs must be
        plain scalars/strings — enforced at record time by usage)."""
        return (
            self.span_id, self.parent_id, self.name, self.start,
            self.duration, self.level, dict(self.attrs), self.trace_id,
            self.process, self.pid,
        )

    @classmethod
    def from_wire(cls, t: tuple) -> "SpanRecord":
        (sid, parent, name, start, dur, level, attrs, trace_id,
         process, pid) = t
        return cls(
            sid, parent, name, start, dur, level, attrs, trace_id,
            process, pid,
        )


class Tracer:
    """Per-process span recorder with cross-process context handoff."""

    def __init__(self, capacity: int = 4096, process: str = ""):
        self.process = process or f"pid{os.getpid()}"
        self._pid = os.getpid()
        self._base = (self._pid & 0x3FFFFF) << _PID_SHIFT
        self._ids = itertools.count(1)
        self._level = LEVELS["info"]
        self._local = threading.local()
        self._lock = threading.Lock()
        self._buf: deque[SpanRecord] = deque(maxlen=capacity)
        # Ingested remote spans (piggybacked off Frontiers) live in
        # their own ring: clear() of local spans keeps remote history
        # and vice versa is not needed.
        self._ingested: deque[SpanRecord] = deque(maxlen=capacity)
        # Ship queue: records pending piggyback to a controller.
        # Bounded — an unreported replica must not grow without bound.
        self._ship: deque[SpanRecord] | None = None

    # -- dynamic filter (log_filter / trace_level dyncfg analog) ------------
    def set_level(self, level: str) -> None:
        self._level = LEVELS[level]

    @property
    def level(self) -> str:
        for k, v in LEVELS.items():
            if v == self._level:
                return k
        return "info"

    def enabled(self, level: str = "info") -> bool:
        return LEVELS[level] <= self._level

    # -- id minting ----------------------------------------------------------
    def _next_id(self) -> int:
        if os.getpid() != self._pid:
            # Forked child (subprocess replicas exec fresh interpreters,
            # but be safe): re-base so ids stay collision-free.
            self._pid = os.getpid()
            self._base = (self._pid & 0x3FFFFF) << _PID_SHIFT
            self.process = f"pid{self._pid}"
        return self._base | next(self._ids)

    def new_trace(self) -> int:
        """Mint a fresh statement trace id."""
        return self._next_id()

    # -- thread-local context stack ------------------------------------------
    def _stack(self) -> list:
        st = getattr(self._local, "stack", None)
        if st is None:
            st = []
            self._local.stack = st
        return st

    def current_span(self) -> int | None:
        """For protocol propagation: ship this with commands."""
        st = self._stack()
        return st[-1][1] if st else None

    def current_trace(self) -> int:
        st = self._stack()
        return st[-1][0] if st else 0

    def context(self) -> dict | None:
        """The wire form of the current context (rides CTP commands),
        or None when no span is open on this thread."""
        st = self._stack()
        if not st:
            return None
        trace_id, span_id = st[-1]
        return {"t": trace_id, "s": span_id}

    # -- span API ------------------------------------------------------------
    @contextmanager
    def span(self, name: str, level: str = "info", root: bool = False,
             **attrs):
        """Open a child span of the current thread context (or a fresh
        ROOT span minting a new trace_id when ``root=True`` or no
        context is open and the caller asks for one). Yields the span
        id, or None when filtered by level."""
        if LEVELS[level] > self._level:
            yield None
            return
        st = self._stack()
        if root:
            trace_id, parent = self.new_trace(), None
        elif st:
            trace_id, parent = st[-1]
        else:
            trace_id, parent = 0, None  # untraced orphan span
        span_id = self._next_id()
        st.append((trace_id, span_id))
        start = _time.perf_counter()
        wall = _time.time()
        try:
            yield span_id
        finally:
            dur = _time.perf_counter() - start
            st.pop()
            self._append(
                SpanRecord(
                    span_id, parent, name, wall, dur, level, attrs,
                    trace_id, self.process, self._pid,
                )
            )

    @contextmanager
    def statement(self, name: str, **attrs):
        """The front-end entry point: mint a trace and open its root
        span (one per SQL statement — pgwire/HTTP drive this)."""
        with self.span(name, root=True, **attrs) as sid:
            yield sid

    def record(
        self,
        name: str,
        start: float,
        duration: float,
        level: str = "info",
        parent: int | None = None,
        **attrs,
    ) -> int | None:
        """Retroactive span record (the pipelined span commit knows its
        timings only after the boundary readback). Parent defaults to
        the current thread context. Pure host bookkeeping."""
        if LEVELS[level] > self._level:
            return None
        st = self._stack()
        trace_id = st[-1][0] if st else 0
        if parent is None and st:
            parent = st[-1][1]
        span_id = self._next_id()
        self._append(
            SpanRecord(
                span_id, parent, name, start, duration, level, attrs,
                trace_id, self.process, self._pid,
            )
        )
        return span_id

    def _append(self, rec: SpanRecord) -> None:
        with self._lock:
            self._buf.append(rec)
            if self._ship is not None:
                self._ship.append(rec)

    @contextmanager
    def adopt(self, ctx: dict | None):
        """Adopt a PROPAGATED remote context as this thread's parent
        (the replica side of command propagation). ``None`` is a
        no-op pass-through."""
        if not ctx:
            yield
            return
        st = self._stack()
        st.append((int(ctx.get("t") or 0), int(ctx.get("s") or 0)))
        try:
            yield
        finally:
            st.pop()

    @contextmanager
    def remote_parent(self, parent_id: int | None):
        """Back-compat adoption by bare span id (no trace id)."""
        with self.adopt(
            None if parent_id is None else {"t": 0, "s": parent_id}
        ):
            yield

    # -- cross-process shipping (Frontiers piggyback) ------------------------
    def enable_ship(self, capacity: int = 4096) -> None:
        """Start queueing completed spans for piggyback (replica side)."""
        with self._lock:
            if self._ship is None:
                self._ship = deque(maxlen=capacity)

    def drain_shippable(self) -> list[tuple]:
        """Completed spans pending piggyback, as wire tuples (empty
        when shipping is off or nothing happened — the common case)."""
        if self._ship is None or not self._ship:
            return []
        with self._lock:
            out = [r.to_wire() for r in self._ship]
            self._ship.clear()
        return out

    def ingest(self, wire_records: list, process: str = "") -> None:
        """Absorb piggybacked spans from another process. Records from
        OUR pid are dropped (an in-process replica shares this tracer;
        its spans already sit in the local ring)."""
        me = os.getpid()
        with self._lock:
            for t in wire_records:
                rec = SpanRecord.from_wire(t)
                if rec.pid == me:
                    continue
                if process and (
                    not rec.process or rec.process.startswith("pid")
                ):
                    rec.process = process
                self._ingested.append(rec)

    # -- introspection --------------------------------------------------------
    def records(self, name_prefix: str = "") -> list[SpanRecord]:
        with self._lock:
            if not name_prefix:
                # list(deque) runs at C speed — keeps the critical
                # section short under writer pressure.
                return list(self._buf) + list(self._ingested)
            out = [
                r for r in self._buf if r.name.startswith(name_prefix)
            ]
            out.extend(
                r
                for r in self._ingested
                if r.name.startswith(name_prefix)
            )
        return out

    def trace_tree(self, trace_id: int) -> list[SpanRecord]:
        """All spans of one statement trace, roots first."""
        recs = [r for r in self.records() if r.trace_id == trace_id]
        recs.sort(key=lambda r: (r.parent_id is not None, r.start))
        return recs

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self._ingested.clear()
            if self._ship is not None:
                self._ship.clear()


TRACER = Tracer()
