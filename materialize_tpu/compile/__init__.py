"""The compile plane: the persistent AOT program bank (ISSUE 16).

PR 9's compile ledger made every XLA compile a counted record and
printed ``bankable_seconds`` — the wall a cross-process program bank
keyed by ``(kind, fingerprint, tier)`` would recover. This package IS
that bank:

- :mod:`bank` — blob-backed serialized-executable store. Every
  ``ledger_jit`` site becomes a bank lookup point when a bank is
  configured: first sight of a key loads the serialized executable
  (``bank_hit``, milliseconds) instead of recompiling (seconds to
  minutes), and misses are compiled ahead-of-time and written back.
- :mod:`worker` — the background compile worker behind async DDL:
  ``CREATE INDEX`` / ``CREATE MATERIALIZED VIEW`` serves immediately
  in generic merge mode while the worker pre-compiles the specialized
  program into the bank; the replica hot-swaps at a span boundary.
"""

from .bank import ProgramBank, configure_bank, get_bank  # noqa: F401
