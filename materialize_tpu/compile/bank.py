"""Persistent cross-process AOT program bank (ISSUE 16 tentpole a).

A directory of serialized compiled executables, keyed by the compile
ledger's ``(kind, dataflow fingerprint, tier vector)`` identity. The
payload is ``jax.experimental.serialize_executable.serialize`` output —
the PJRT *executable*, not just StableHLO — so a bank hit pays a
deserialize (tens of milliseconds) instead of an XLA compile (seconds
to minutes: ~26s index step, 112s 4-operand sort on real hardware,
PERF_NOTES facts 6).

Entries are environment-stamped (jax/jaxlib versions, backend
platform, device count): a stale-jaxlib or cross-platform entry is
skipped, never loaded — an executable serialized by a different
runtime is at best unloadable and at worst wrong. A truncated or
corrupt entry is unlinked best-effort and reported as a miss; the
caller falls back to a clean compile, so a damaged bank can degrade
recovery time but never correctness. Stores are load-verified before
export (see ``ProgramBank.store``), so a published entry is one this
runtime demonstrably deserializes.

Writes are atomic (tmp + rename into place) so concurrent processes
(replica subprocesses sharing the blob dir with environmentd) never
observe half-written entries. The bank lives under the deployment's
blob directory (``<data-dir>/blob/program_bank``) so
``environmentd --recover`` finds a warm bank exactly where the durable
state already is.
"""

from __future__ import annotations

import os
import pickle
import threading
import time as _time

# Bump when the entry layout changes: old-format entries are skipped.
BANK_FORMAT = 1

# Environment variable fallback: subprocess replicas inherit the bank
# location without threading a flag through every entry point.
BANK_ENV_VAR = "MZ_PROGRAM_BANK"


def _entry_filename(kind: str, fingerprint: str, tier: str) -> str:
    # tier vectors are "<hex>:<bytes>"; keep filenames shell-safe.
    safe = "".join(
        c if (c.isalnum() or c in "._-") else "_" for c in tier
    )
    return f"{kind}__{fingerprint}__{safe}.aot"


def _env_stamp() -> dict:
    import jax
    import jaxlib

    return {
        "format": BANK_FORMAT,
        "jax": jax.__version__,
        "jaxlib": jaxlib.version.__version__,
        "platform": jax.default_backend(),
        "devices": jax.device_count(),
    }


class ProgramBank:
    """One bank directory. Thread-safe; cheap to construct."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        # Tracked (ISSUE 17): the bank store lock is taken from every
        # jit site AND the async compile worker — it must stay a leaf
        # in the observed lock-order graph.
        from ..utils.lockcheck import tracked_lock

        self._lock = tracked_lock("compile.bank")
        self._stamp: dict | None = None
        # Counters for mz_program_bank / the recovery report.
        self.stats = {
            "hits": 0,       # entries deserialized and served
            "misses": 0,     # lookups that found no usable entry
            "stores": 0,     # entries written back
            "errors": 0,     # corrupt/skewed/unserializable entries
            "seconds_recovered": 0.0,  # compile wall the hits skipped
        }

    # -- key paths ---------------------------------------------------------
    def path_for(self, kind: str, fingerprint: str, tier: str) -> str:
        return os.path.join(
            self.root, _entry_filename(kind, fingerprint, tier)
        )

    def has(self, kind: str, fingerprint: str, tier: str) -> bool:
        """Existence only — no load, no environment check. Used by the
        ledger's ``_seen`` eviction fix: a key the bank holds was
        compiled SOMEWHERE, so its recompile is never a cold miss."""
        return os.path.exists(self.path_for(kind, fingerprint, tier))

    def _environment(self) -> dict:
        if self._stamp is None:
            self._stamp = _env_stamp()
        return self._stamp

    # -- lookup / store ----------------------------------------------------
    def lookup(self, kind: str, fingerprint: str, tier: str):
        """Load an entry's executable. Returns ``(compiled, meta)`` or
        ``None``. Never raises: corruption, version skew, and
        deserialize failures all resolve to a miss (the caller
        compiles cleanly); a provably corrupt file is unlinked so the
        next process doesn't re-pay the failed load."""
        path = self.path_for(kind, fingerprint, tier)
        try:
            with open(path, "rb") as f:
                entry = pickle.load(f)
        except FileNotFoundError:
            with self._lock:
                self.stats["misses"] += 1
            return None
        except Exception:
            # Truncated/corrupt pickle: drop the entry, fall back.
            self._damaged(path)
            return None
        meta = entry.get("meta") if isinstance(entry, dict) else None
        if meta is None or "payload" not in entry:
            self._damaged(path)
            return None
        env = self._environment()
        for k in ("format", "jax", "jaxlib", "platform", "devices"):
            if meta.get(k) != env[k]:
                # Version/platform skew: not corruption — another
                # deployment (or a future upgrade rollback) may still
                # want it. Skip, don't unlink.
                with self._lock:
                    self.stats["misses"] += 1
                    self.stats["errors"] += 1
                return None
        try:
            from jax.experimental import serialize_executable

            compiled = serialize_executable.deserialize_and_load(
                *entry["payload"]
            )
        except Exception:
            self._damaged(path)
            return None
        with self._lock:
            self.stats["hits"] += 1
            self.stats["seconds_recovered"] += float(
                meta.get("seconds", 0.0)
            )
        return compiled, meta

    def store(
        self,
        kind: str,
        fingerprint: str,
        tier: str,
        compiled,
        seconds: float = 0.0,
        name: str = "",
    ) -> bool:
        """Serialize an executable into the bank (atomic write).
        ``seconds`` is the compile wall this entry cost — what a
        future hit recovers (the recovery report's
        ``compile_seconds_recovered``). Returns False (and counts an
        error) if the program isn't serializable; the caller keeps
        its in-process compiled program either way."""
        try:
            from jax.experimental import serialize_executable

            payload = serialize_executable.serialize(compiled)
            # Verify the payload actually loads BEFORE exporting it:
            # some runtimes (observed on jaxlib CPU) serialize a
            # module whose compile was not the first in-process
            # instance into a payload that fails deserialization with
            # "Symbols not found". A bank must never publish an entry
            # a fresh process cannot serve — the ~tens-of-ms load here
            # guards the seconds-to-minutes compile it replaces.
            serialize_executable.deserialize_and_load(*payload)
            entry = {
                "meta": {
                    **self._environment(),
                    "kind": kind,
                    "fingerprint": fingerprint,
                    "tier": tier,
                    "name": name,
                    "seconds": float(seconds),
                    "stored_at": _time.time(),
                },
                "payload": payload,
            }
            blob = pickle.dumps(
                entry, protocol=pickle.HIGHEST_PROTOCOL
            )
            path = self.path_for(kind, fingerprint, tier)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "wb") as f:
                f.write(blob)
            os.replace(tmp, path)
        except Exception:
            with self._lock:
                self.stats["errors"] += 1
            return False
        with self._lock:
            self.stats["stores"] += 1
        return True

    def _damaged(self, path: str) -> None:
        with self._lock:
            self.stats["misses"] += 1
            self.stats["errors"] += 1
        try:
            os.unlink(path)
        except OSError:
            pass

    # -- introspection (mz_program_bank) -----------------------------------
    def entries(self) -> list[dict]:
        """Per-entry metadata without loading executables: parse the
        key back out of the filename, stat for size/mtime. Unreadable
        names are skipped (a foreign file in the dir is not an
        error)."""
        out = []
        try:
            names = sorted(os.listdir(self.root))
        except OSError:
            return out
        for fn in names:
            if not fn.endswith(".aot"):
                continue
            parts = fn[: -len(".aot")].split("__")
            if len(parts) != 3:
                continue
            kind, fingerprint, tier = parts
            try:
                st = os.stat(os.path.join(self.root, fn))
            except OSError:
                continue
            out.append(
                {
                    "kind": kind,
                    "fingerprint": fingerprint,
                    "tier": tier,
                    "bytes": int(st.st_size),
                    "stored_at": float(st.st_mtime),
                }
            )
        return out

    def snapshot(self) -> dict:
        """Counters + entry census: the recovery report / bench
        surface."""
        with self._lock:
            stats = dict(self.stats)
        ents = self.entries()
        stats["entries"] = len(ents)
        stats["bytes"] = sum(e["bytes"] for e in ents)
        stats["seconds_recovered"] = round(
            stats["seconds_recovered"], 3
        )
        return stats


# -- process-global bank -----------------------------------------------------
# `BANK` is read on the ledger_jit dispatch path: module attribute, no
# function call, None when the bank is off (the default — bank-off
# dispatch stays byte-identical to the pre-bank hot path).
BANK: ProgramBank | None = None
_resolved = False


def configure_bank(path: str | None) -> ProgramBank | None:
    """Point this process at a bank directory (None disables). Called
    by environmentd/replica boot, bench.py --bank, and tests."""
    global BANK, _resolved
    _resolved = True
    BANK = ProgramBank(path) if path else None
    return BANK


def get_bank() -> ProgramBank | None:
    """The configured bank, resolving the MZ_PROGRAM_BANK environment
    variable once on first use (subprocess replicas inherit it)."""
    global BANK, _resolved
    if not _resolved:
        _resolved = True
        path = os.environ.get(BANK_ENV_VAR)
        if path:
            BANK = ProgramBank(path)
    return BANK
