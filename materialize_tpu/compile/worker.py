"""Background compile worker (ISSUE 16 tentpole c).

``CREATE INDEX`` / ``CREATE MATERIALIZED VIEW`` should not serialize
the session behind a multi-second XLA compile. With async compile on
(dyncfg ``enable_async_compile``) and a program bank configured, the
replica installs a fresh DDL's dataflow in GENERIC MERGE MODE
(``out_slots=0`` — the every-step run-0 merge program, correct for any
state size, just O(run0) per step instead of O(delta)) and hands this
worker the description. The worker renders the SPECIALIZED dataflow
off-thread, drives one warm-up step so its step program compiles
through the banked ``ledger_jit`` path (the compile lands in the bank),
and marks the task done. The replica's worker loop notices at a span
boundary, drains in-flight spans (the PR 4 ``sync_spans`` sequencing —
no half-applied carry), and rebuilds the dataflow from durable state;
the rebuild's compiles come back as bank hits, so the swap costs a
re-hydration, not a compile wall.

The warm-up compiles the base-tier step program. Tiers the warm-up
cannot predict (post-hydration growth) compile at swap time and are
written back — the bank converges; the swap never blocks correctness
on warm-up completeness.
"""

from __future__ import annotations

import queue
import threading
import time as _time


class CompileTask:
    __slots__ = ("desc", "queued_at", "done_at", "error")

    def __init__(self, desc):
        self.desc = desc
        self.queued_at = _time.time()
        self.done_at: float | None = None
        self.error: str = ""

    @property
    def done(self) -> bool:
        return self.done_at is not None


class CompileWorker:
    """One daemon thread per replica process, started lazily on the
    first async install. Failures are recorded on the task, never
    raised — a warm-up that cannot compile (exotic expr, serializer
    limits) just means the swap pays the compile itself."""

    def __init__(self):
        self._q: queue.Queue = queue.Queue()
        self._thread: threading.Thread | None = None
        # Tracked (ISSUE 17): guards the task table shared between
        # replica worker loops (submit/poll at span boundaries) and
        # the compile thread.
        from ..utils.lockcheck import tracked_lock

        self._lock = tracked_lock("compile.worker")
        self.tasks: dict[str, CompileTask] = {}

    def submit(self, desc) -> CompileTask:
        task = CompileTask(desc)
        with self._lock:
            self.tasks[desc.name] = task
            if self._thread is None or not self._thread.is_alive():
                self._thread = threading.Thread(
                    target=self._run, daemon=True,
                    name="mz-compile-worker",
                )
                self._thread.start()
        self._q.put(task)
        return task

    def pop_ready(self) -> list[CompileTask]:
        """Completed tasks, removed — the replica loop's swap poll."""
        with self._lock:
            ready = [t for t in self.tasks.values() if t.done]
            for t in ready:
                self.tasks.pop(t.desc.name, None)
        return ready

    def pending(self) -> list[str]:
        with self._lock:
            return sorted(
                n for n, t in self.tasks.items() if not t.done
            )

    def _run(self) -> None:
        while True:
            try:
                task = self._q.get(timeout=30)
            except queue.Empty:
                return  # idle worker retires; next submit restarts
            try:
                warm_programs(task.desc)
            except Exception as e:
                task.error = repr(e)
            task.done_at = _time.time()


def warm_programs(desc) -> None:
    """Render the specialized dataflow for ``desc`` and compile its
    base-tier step program through the banked ledger_jit path. The
    shadow dataflow holds no durable state and is dropped on return —
    only the bank entry (and the ledger record) survive."""
    import numpy as np

    from ..render.dataflow import Dataflow
    from ..repr.batch import Batch
    from ..repr.schema import DIFF_DTYPE, TIME_DTYPE

    df = Dataflow(desc.expr, name=desc.name)
    inputs = {}
    for name, schema in _source_schemas(desc).items():
        inputs[name] = Batch.from_numpy(
            schema,
            [np.zeros(0, dtype=c.dtype) for c in schema.columns],
            np.zeros(0, dtype=TIME_DTYPE),
            np.zeros(0, dtype=DIFF_DTYPE),
        )
    if inputs:
        df.run_steps([inputs])


def _source_schemas(desc) -> dict:
    """name -> Schema for every input the step program reads. Source
    imports carry (shard_id, schema) pairs; index imports are skipped
    (the shadow dataflow has no publisher to subscribe to — their
    programs compile at swap time)."""
    out = {}
    for name, imp in getattr(desc, "source_imports", {}).items():
        schema = imp[1] if isinstance(imp, tuple) else getattr(
            imp, "schema", None
        )
        if schema is not None:
            out[name] = schema
    if getattr(desc, "index_imports", None):
        # A dataflow reading another index needs live IndexSources to
        # step; warm only pure-source dataflows.
        return {}
    return out
