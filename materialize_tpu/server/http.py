"""HTTP endpoints: SQL-over-HTTP, metrics, readiness, SSE SUBSCRIBE.

Analog of the reference's ``environmentd/src/http``: POST /api/sql
executes statements and returns JSON results; GET /metrics serves the
Prometheus registry; GET /api/readyz for probes; GET/POST
/api/subscribe streams a SUBSCRIBE as Server-Sent Events off the
fan-out hub (ISSUE 11). Stdlib http.server — the control plane is not
a throughput surface, but SSE sessions are hub-woken (event-driven),
so idle streams cost nothing between spans.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlparse

from ..utils.metrics import REGISTRY


def make_handler(coordinator):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _webhook(self, name: str) -> None:
            """POST /api/webhook/<source>: body {"rows": [[...], ...]},
            an array of rows [[...], ...], or one flat row [...]
            (webhook sources, adapter/src/webhook.rs analog)."""
            n = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
                if isinstance(body, dict) and "rows" in body:
                    rows = body["rows"]
                elif isinstance(body, list):
                    if not body:
                        rows = []  # empty batch: appended 0
                    elif isinstance(body[0], list):
                        rows = body
                    else:
                        rows = [body]  # one flat row
                else:
                    raise ValueError(
                        'expected {"rows": [[...], ...]}, an array of '
                        "rows, or one flat row array"
                    )
                count = coordinator.append_webhook(name, rows)
                self._reply(
                    200,
                    json.dumps({"appended": count}).encode(),
                    "application/json",
                )
            except Exception as e:
                from ..sql.hir import PlanError

                code = (
                    400
                    if isinstance(
                        e, (PlanError, ValueError, json.JSONDecodeError)
                    )
                    else 500
                )
                body = json.dumps({"error": str(e)}).encode()
                self._reply(code, body, "application/json")

        def _subscribe_sse(self, sql: str) -> None:
            """GET/POST /api/subscribe: stream a SUBSCRIBE as
            Server-Sent Events. Each hub chunk becomes one `data:`
            message `{"events": [[vals..., time, diff], ...],
            "progress": frontier}` (plus `"snapshot": true` for state
            transfers); keepalive comments flush every 15s so a dead
            client surfaces as a write failure. Admission sheds are
            503; slow-consumer disconnects end the stream with an
            `event: error` message."""
            from ..coord.peek import ServerBusy
            from ..coord.subscribe import SubscriptionLagging

            if not sql:
                self._reply(
                    400,
                    json.dumps(
                        {"error": "missing SUBSCRIBE query"}
                    ).encode(),
                    "application/json",
                )
                return
            # Validate BEFORE executing: /api/subscribe must never
            # run a non-SUBSCRIBE statement (a GET carrying an INSERT
            # would otherwise commit the write and then report 400 —
            # state-changing "errors" break retry semantics).
            try:
                from ..sql import ast as sqlast
                from ..sql import parser as sqlparser

                stmt = sqlparser.parse_statement(sql)
                if not isinstance(stmt, sqlast.Subscribe):
                    raise ValueError(
                        "/api/subscribe requires a SUBSCRIBE "
                        "statement"
                    )
            except Exception as e:
                self._reply(
                    400,
                    json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
                return
            try:
                res = coordinator.execute(sql)
            except ServerBusy as e:
                self._reply(
                    503,
                    json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
                return
            except Exception as e:
                self._reply(
                    400,
                    json.dumps({"error": str(e)}).encode(),
                    "application/json",
                )
                return
            if res.kind != "subscription":
                self._reply(
                    400,
                    json.dumps(
                        {
                            "error": "/api/subscribe requires a "
                            "SUBSCRIBE statement"
                        }
                    ).encode(),
                    "application/json",
                )
                return
            sub = res.subscription
            self.send_response(200)
            self.send_header("Content-Type", "text/event-stream")
            self.send_header("Cache-Control", "no-cache")
            self.end_headers()
            import select

            wake = sub.wake_socket()
            conn = self.connection
            try:
                self.wfile.write(
                    b": subscribed columns="
                    + ",".join(map(str, res.columns)).encode()
                    + b"\n\n"
                )
                self.wfile.flush()
                while True:
                    # Drain BEFORE selecting (chunks enqueued before
                    # the wake fd existed — the join snapshot — have
                    # no wake byte to select on) and BEFORE honoring
                    # `closed`: a hub-reaped lagging session still
                    # owes the client its error (raised by pop_ready),
                    # not a clean end-of-stream.
                    for kind, events, frontier, _st in sub.pop_ready():
                        payload = {
                            "events": [list(e) for e in events],
                            "progress": frontier,
                        }
                        if kind == "snapshot":
                            payload["snapshot"] = True
                        self.wfile.write(
                            b"data: "
                            + json.dumps(
                                payload, default=str
                            ).encode()
                            + b"\n\n"
                        )
                        self.wfile.flush()
                    if sub.closed:
                        return
                    # Event-driven, like the pgwire COPY-out loop: a
                    # committed span wakes via the session fd, a
                    # client close wakes via the connection (EOF —
                    # SSE clients never send mid-stream, so ANY
                    # inbound readability is teardown).
                    ready, _, _ = select.select(
                        [conn, wake], [], [], 15.0
                    )
                    if conn in ready:
                        return
                    if wake in ready:
                        try:
                            while wake.recv(4096):
                                pass
                        except (BlockingIOError, OSError):
                            pass
                    if not ready:
                        # Liveness probe: a half-open (unreachable,
                        # never-FIN'd) client fails this write.
                        self.wfile.write(b": keepalive\n\n")
                        self.wfile.flush()
            except SubscriptionLagging as e:
                try:
                    self.wfile.write(
                        b"event: error\ndata: "
                        + json.dumps({"error": str(e)}).encode()
                        + b"\n\n"
                    )
                    self.wfile.flush()
                except (BrokenPipeError, ConnectionError, OSError):
                    pass
            except (BrokenPipeError, ConnectionError, OSError):
                pass
            finally:
                sub.close()

        def do_GET(self):
            if self.path.startswith("/api/subscribe"):
                qs = parse_qs(urlparse(self.path).query)
                self._subscribe_sse(
                    (qs.get("query") or [""])[0].strip()
                )
                return
            if self.path == "/metrics":
                # ONE scrape covers the deployment (ISSUE 12): the
                # local registry merged with every replica's last
                # piggybacked snapshot, remote samples labeled
                # replica="<name>" (utils/metrics.cluster_exposition).
                from ..utils.metrics import cluster_exposition

                with coordinator.controller._lock:
                    remote = dict(
                        coordinator.controller.replica_metrics
                    )
                self._reply(
                    200,
                    cluster_exposition(REGISTRY, remote).encode(),
                    "text/plain; version=0.0.4",
                )
            elif self.path == "/api/livez":
                # Liveness: the process answers HTTP. Always 200 —
                # restarts are decided by readiness, not liveness.
                self._reply(200, b"live\n", "text/plain")
            elif self.path == "/api/readyz":
                # Readiness (the freshness plane, ISSUE 15): 200 only
                # when the coordinator's health verdict says catalog
                # replay succeeded, some replica is connected, every
                # durable dataflow hydrated, and lag is under the SLO;
                # otherwise 503 with the full JSON verdict — the
                # machine-checkable "ready" for `environmentd
                # --recover` drives and rolling restarts.
                verdict = coordinator.health()
                self._reply(
                    200 if verdict["ready"] else 503,
                    (json.dumps(verdict) + "\n").encode(),
                    "application/json",
                )
            else:
                self._reply(404, b"not found\n", "text/plain")

        def do_POST(self):
            if self.path.startswith("/api/webhook/"):
                self._webhook(self.path[len("/api/webhook/"):])
                return
            if self.path.startswith("/api/subscribe"):
                n = int(self.headers.get("Content-Length", 0))
                try:
                    body = json.loads(self.rfile.read(n) or b"{}")
                    sql = str(body.get("query", "")).strip()
                except Exception:
                    sql = ""
                self._subscribe_sse(sql)
                return
            if self.path != "/api/sql":
                self._reply(404, b"not found\n", "text/plain")
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                queries = req.get("query")
                if isinstance(queries, str):
                    from .pgwire import _split_statements

                    queries = [
                        q for q in _split_statements(queries)
                        if q.strip()
                    ]
                from ..utils.trace import TRACER

                results = []
                for q in queries or []:
                    with TRACER.statement("http.query", sql=q[:100]):
                        res = coordinator.execute(q)
                    if res.kind == "rows":
                        results.append(
                            {
                                "tag": f"SELECT {len(res.rows)}",
                                "columns": list(res.columns),
                                "rows": [list(r) for r in res.rows],
                            }
                        )
                    elif res.kind == "text":
                        results.append(
                            {"tag": "EXPLAIN", "text": res.text}
                        )
                    elif res.kind == "copy_in":
                        results.append(
                            {
                                "error": "COPY FROM STDIN is not "
                                "supported over HTTP; use pgwire"
                            }
                        )
                    elif res.kind == "subscription":
                        res.subscription.close()
                        results.append(
                            {
                                "error": "SUBSCRIBE over /api/sql "
                                "cannot stream; use the "
                                "/api/subscribe SSE endpoint"
                            }
                        )
                    else:
                        results.append({"tag": "OK"})
                # default=str: exact decimal.Decimal values serialize as
                # their text form (pg's numeric-over-json behavior)
                body = json.dumps(
                    {"results": results}, default=str
                ).encode()
                self._reply(200, body, "application/json")
            except Exception as e:
                from ..coord.peek import ServerBusy
                from ..sql.hir import PlanError
                from ..sql.parser import ParseError

                # Client mistakes are 400; admission-control sheds are
                # 503 (retryable overload); execution faults (peek
                # timeouts, internal errors) are the server's 500.
                if isinstance(e, ServerBusy):
                    code = 503
                elif isinstance(
                    e, (PlanError, ParseError, json.JSONDecodeError)
                ):
                    code = 400
                else:
                    code = 500
                body = json.dumps({"error": str(e)}).encode()
                self._reply(code, body, "application/json")

    return Handler


class HttpServer:
    def __init__(self, coordinator, host="127.0.0.1", port=0):
        self._srv = ThreadingHTTPServer(
            (host, port), make_handler(coordinator)
        )
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    def start(self) -> "HttpServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
