"""HTTP endpoints: SQL-over-HTTP, metrics, readiness.

Analog of the reference's ``environmentd/src/http``: POST /api/sql
executes statements and returns JSON results; GET /metrics serves the
Prometheus registry; GET /api/readyz for probes. Stdlib http.server —
the control plane is not a throughput surface.
"""

from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from ..utils.metrics import REGISTRY


def make_handler(coordinator):
    class Handler(BaseHTTPRequestHandler):
        def log_message(self, *args):  # quiet
            pass

        def _reply(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def _webhook(self, name: str) -> None:
            """POST /api/webhook/<source>: body {"rows": [[...], ...]},
            an array of rows [[...], ...], or one flat row [...]
            (webhook sources, adapter/src/webhook.rs analog)."""
            n = int(self.headers.get("Content-Length", 0))
            try:
                body = json.loads(self.rfile.read(n) or b"{}")
                if isinstance(body, dict) and "rows" in body:
                    rows = body["rows"]
                elif isinstance(body, list):
                    if not body:
                        rows = []  # empty batch: appended 0
                    elif isinstance(body[0], list):
                        rows = body
                    else:
                        rows = [body]  # one flat row
                else:
                    raise ValueError(
                        'expected {"rows": [[...], ...]}, an array of '
                        "rows, or one flat row array"
                    )
                count = coordinator.append_webhook(name, rows)
                self._reply(
                    200,
                    json.dumps({"appended": count}).encode(),
                    "application/json",
                )
            except Exception as e:
                from ..sql.hir import PlanError

                code = (
                    400
                    if isinstance(
                        e, (PlanError, ValueError, json.JSONDecodeError)
                    )
                    else 500
                )
                body = json.dumps({"error": str(e)}).encode()
                self._reply(code, body, "application/json")

        def do_GET(self):
            if self.path == "/metrics":
                self._reply(
                    200, REGISTRY.expose_text().encode(),
                    "text/plain; version=0.0.4",
                )
            elif self.path in ("/api/readyz", "/api/livez"):
                self._reply(200, b"ready\n", "text/plain")
            else:
                self._reply(404, b"not found\n", "text/plain")

        def do_POST(self):
            if self.path.startswith("/api/webhook/"):
                self._webhook(self.path[len("/api/webhook/"):])
                return
            if self.path != "/api/sql":
                self._reply(404, b"not found\n", "text/plain")
                return
            n = int(self.headers.get("Content-Length", 0))
            try:
                req = json.loads(self.rfile.read(n) or b"{}")
                queries = req.get("query")
                if isinstance(queries, str):
                    from .pgwire import _split_statements

                    queries = [
                        q for q in _split_statements(queries)
                        if q.strip()
                    ]
                results = []
                for q in queries or []:
                    res = coordinator.execute(q)
                    if res.kind == "rows":
                        results.append(
                            {
                                "tag": f"SELECT {len(res.rows)}",
                                "columns": list(res.columns),
                                "rows": [list(r) for r in res.rows],
                            }
                        )
                    elif res.kind == "text":
                        results.append(
                            {"tag": "EXPLAIN", "text": res.text}
                        )
                    elif res.kind == "copy_in":
                        results.append(
                            {
                                "error": "COPY FROM STDIN is not "
                                "supported over HTTP; use pgwire"
                            }
                        )
                    elif res.kind == "subscription":
                        res.subscription.close()
                        results.append(
                            {
                                "error": "SUBSCRIBE is not supported "
                                "over HTTP; use pgwire"
                            }
                        )
                    else:
                        results.append({"tag": "OK"})
                # default=str: exact decimal.Decimal values serialize as
                # their text form (pg's numeric-over-json behavior)
                body = json.dumps(
                    {"results": results}, default=str
                ).encode()
                self._reply(200, body, "application/json")
            except Exception as e:
                from ..coord.peek import ServerBusy
                from ..sql.hir import PlanError
                from ..sql.parser import ParseError

                # Client mistakes are 400; admission-control sheds are
                # 503 (retryable overload); execution faults (peek
                # timeouts, internal errors) are the server's 500.
                if isinstance(e, ServerBusy):
                    code = 503
                elif isinstance(
                    e, (PlanError, ParseError, json.JSONDecodeError)
                ):
                    code = 400
                else:
                    code = 500
                body = json.dumps({"error": str(e)}).encode()
                self._reply(code, body, "application/json")

    return Handler


class HttpServer:
    def __init__(self, coordinator, host="127.0.0.1", port=0):
        self._srv = ThreadingHTTPServer(
            (host, port), make_handler(coordinator)
        )
        self.port = self._srv.server_address[1]
        self._thread = threading.Thread(
            target=self._srv.serve_forever, daemon=True
        )

    def start(self) -> "HttpServer":
        self._thread.start()
        return self

    def stop(self) -> None:
        self._srv.shutdown()
        self._srv.server_close()
