"""PostgreSQL wire protocol v3: the SQL front door.

Analog of the reference's ``src/pgwire`` (``protocol.rs:145 run()``,
``:847 StateMachine``): startup handshake, the simple-query protocol
(Query -> RowDescription/DataRow*/CommandComplete/ReadyForQuery), error
responses, and SUBSCRIBE streamed via the COPY-out subprotocol (the
reference streams TAIL/SUBSCRIBE the same way). Text result format only
(the reference negotiates binary per column; text is always legal).
No TLS/SCRAM — SSLRequest is politely refused with 'N' (plaintext), as
the reference does when TLS is off.
"""

from __future__ import annotations

import socket
import struct
import threading
import traceback

from ..repr.schema import ColumnType
from ..utils.trace import TRACER

# PG type OIDs for the text protocol.
_OIDS = {
    ColumnType.BOOL: 16,
    ColumnType.INT32: 23,
    ColumnType.INT64: 20,
    ColumnType.FLOAT64: 701,
    ColumnType.DATE: 1082,
    ColumnType.TIMESTAMP: 20,  # virtual time: expose as int8
    ColumnType.DECIMAL: 1700,
    ColumnType.STRING: 25,
}

PROTOCOL_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class PgConnection:
    def __init__(self, sock: socket.socket, coordinator):
        self.sock = sock
        self.coord = coordinator
        self.alive = True

    # -- low-level ----------------------------------------------------------
    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client disconnected")
            buf += chunk
        return buf

    def _send(self, data: bytes) -> None:
        self.sock.sendall(data)

    # -- session ------------------------------------------------------------
    def run(self) -> None:
        try:
            if not self._startup():
                return
            self._ready()
            while self.alive:
                tag = self.sock.recv(1)
                if not tag:
                    return
                (length,) = struct.unpack("!I", self._recv_exact(4))
                payload = self._recv_exact(length - 4)
                if tag == b"Q":
                    self._handle_query(payload[:-1].decode())
                elif tag == b"X":
                    return
                elif tag in (b"P", b"B", b"D", b"E", b"S", b"C"):
                    # Extended protocol: not implemented; report cleanly
                    # once a Sync arrives.
                    if tag == b"S":
                        self._error(
                            "0A000",
                            "extended query protocol not supported; "
                            "use simple queries",
                        )
                        self._ready()
                else:
                    self._error("08P01", f"unknown message {tag!r}")
                    self._ready()
        except (ConnectionError, OSError):
            pass
        finally:
            self.sock.close()

    def _startup(self) -> bool:
        while True:
            (length,) = struct.unpack("!I", self._recv_exact(4))
            payload = self._recv_exact(length - 4)
            (code,) = struct.unpack("!I", payload[:4])
            if code == SSL_REQUEST:
                self._send(b"N")  # no TLS; client retries plaintext
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_V3:
                self._error("08P01", f"unsupported protocol {code}")
                return False
            break
        # AuthenticationOk + minimal parameters + key data.
        self._send(_msg(b"R", struct.pack("!I", 0)))
        for k, v in (
            ("server_version", "9.5.0"),
            ("server_name", "materialize_tpu"),
            ("client_encoding", "UTF8"),
            ("DateStyle", "ISO"),
            ("integer_datetimes", "on"),
        ):
            self._send(_msg(b"S", _cstr(k) + _cstr(v)))
        self._send(_msg(b"K", struct.pack("!II", 0, 0)))
        return True

    def _ready(self) -> None:
        self._send(_msg(b"Z", b"I"))

    def _error(self, code: str, message: str) -> None:
        payload = (
            b"S" + _cstr("ERROR")
            + b"C" + _cstr(code)
            + b"M" + _cstr(message)
            + b"\x00"
        )
        self._send(_msg(b"E", payload))

    # -- queries ------------------------------------------------------------
    def _handle_query(self, sql: str) -> None:
        with TRACER.span("pgwire.query", sql=sql[:100]):
            for stmt in _split_statements(sql):
                if not stmt.strip():
                    self._send(_msg(b"I", b""))  # EmptyQueryResponse
                    continue
                try:
                    res = self.coord.execute(stmt)
                except Exception as e:  # planning/execution error
                    self._error("XX000", str(e))
                    self._ready()
                    return
                try:
                    self._send_result(stmt, res)
                except BrokenPipeError:
                    raise
        self._ready()

    def _send_result(self, stmt: str, res) -> None:
        if res.kind == "rows":
            schema = self._result_schema(res)
            self._row_description(res.columns, schema)
            for row in res.rows:
                self._data_row(row, schema)
            self._complete(f"SELECT {len(res.rows)}")
        elif res.kind == "text":
            self._row_description(res.columns or ("explain",), None)
            for line in res.text.split("\n"):
                self._data_row((line,), None)
            self._complete("EXPLAIN")
        elif res.kind == "subscription":
            self._stream_subscription(res)
        else:
            verb = stmt.strip().split()[0].upper()
            self._complete(
                f"INSERT 0 {res.affected}" if verb == "INSERT" else verb
            )

    def _result_schema(self, res):
        # Column types: taken from the plan when available; text is a
        # safe fallback for the wire's text format.
        return getattr(res, "schema", None)

    def _row_description(self, columns, schema) -> None:
        parts = [struct.pack("!H", len(columns))]
        for i, name in enumerate(columns):
            oid = 25
            if schema is not None and i < len(schema.columns):
                oid = _OIDS.get(schema.columns[i].ctype, 25)
            parts.append(
                _cstr(str(name))
                + struct.pack("!IhIhih", 0, 0, oid, -1, -1, 0)
            )
        self._send(_msg(b"T", b"".join(parts)))

    def _data_row(self, row, schema) -> None:
        parts = [struct.pack("!H", len(row))]
        for v in row:
            if v is None:
                parts.append(struct.pack("!i", -1))
            else:
                if isinstance(v, bool):
                    s = "t" if v else "f"
                else:
                    s = str(v)
                b = s.encode()
                parts.append(struct.pack("!i", len(b)) + b)
        self._send(_msg(b"D", b"".join(parts)))

    def _complete(self, tag: str) -> None:
        self._send(_msg(b"C", _cstr(tag)))

    def _stream_subscription(self, res) -> None:
        """SUBSCRIBE over the COPY-out subprotocol: one text line per
        update '(time, diff, cols...)', until the client disconnects
        (the reference's SUBSCRIBE/TAIL wire behavior)."""
        sub = res.subscription
        # CopyOutResponse: text format, one column.
        self._send(_msg(b"H", struct.pack("!bh", 0, 0)))
        try:
            while True:
                got = sub.poll(timeout=1.0)
                if got is None:
                    # Heartbeat nothing; loop until client drops.
                    try:
                        self.sock.settimeout(0.001)
                        peek = self.sock.recv(1, socket.MSG_PEEK)
                        if peek == b"":
                            return
                    except socket.timeout:
                        pass
                    finally:
                        self.sock.settimeout(None)
                    continue
                events, frontier = got
                lines = []
                for ev in events:
                    *vals, t, d = ev
                    fields = "\t".join(
                        "\\N" if v is None else str(v) for v in vals
                    )
                    lines.append(f"{t}\t{d}\t{fields}\n")
                lines.append(f"{frontier}\t0\tprogress\n")
                self._send(
                    _msg(b"d", "".join(lines).encode())
                )
        except (BrokenPipeError, ConnectionError, OSError):
            pass
        finally:
            sub.close()


def _split_statements(sql: str) -> list[str]:
    """Split on ';' outside string literals (simple-query batches)."""
    out, cur, in_str = [], [], False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
            cur.append(ch)
        elif ch == ";" and not in_str:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        out.append("".join(cur))
    return out


class PgServer:
    """TCP acceptor: one thread per connection (server-core analog)."""

    def __init__(self, coordinator, host: str = "127.0.0.1", port: int = 0):
        self.coord = coordinator
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)

    def start(self) -> "PgServer":
        self._thread.start()
        return self

    def _accept(self) -> None:
        self.sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            PgConnection(conn, self.coord).run()
        except Exception:
            traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self.sock.close()
