"""PostgreSQL wire protocol v3: the SQL front door.

Analog of the reference's ``src/pgwire`` (``protocol.rs:145 run()``,
``:847 StateMachine``): startup handshake, the simple-query protocol
(Query -> RowDescription/DataRow*/CommandComplete/ReadyForQuery), error
responses, and SUBSCRIBE streamed via the COPY-out subprotocol (the
reference streams TAIL/SUBSCRIBE the same way). Text result format only
(the reference negotiates binary per column; text is always legal).
No TLS/SCRAM — SSLRequest is politely refused with 'N' (plaintext), as
the reference does when TLS is off.
"""

from __future__ import annotations

import socket
import struct
import threading
import traceback

from ..repr.schema import ColumnType
from ..utils.trace import TRACER

# PG type OIDs for the text protocol.
_OIDS = {
    ColumnType.BOOL: 16,
    ColumnType.INT32: 23,
    ColumnType.INT64: 20,
    ColumnType.FLOAT64: 701,
    ColumnType.DATE: 1082,
    ColumnType.TIMESTAMP: 20,  # virtual time: expose as int8
    ColumnType.DECIMAL: 1700,
    ColumnType.STRING: 25,
}

PROTOCOL_V3 = 196608
SSL_REQUEST = 80877103
CANCEL_REQUEST = 80877102


def _msg(tag: bytes, payload: bytes) -> bytes:
    return tag + struct.pack("!I", len(payload) + 4) + payload


def _error_code(e: Exception) -> str:
    """SQLSTATE for an execution error. Admission-control sheds
    (coord/peek.ServerBusy) map to 53400 (configuration_limit_exceeded
    family: insufficient resources, retryable) so clients can
    distinguish overload from query errors."""
    from ..coord.peek import ServerBusy

    return "53400" if isinstance(e, ServerBusy) else "XX000"


def _cstr(s: str) -> bytes:
    return s.encode() + b"\x00"


class _Prepared:
    """A named prepared statement (extended protocol Parse target)."""

    def __init__(self, sql: str, param_oids: tuple):
        self.sql = sql
        self.param_oids = param_oids
        self.nparams = _max_param(sql)


class _Portal:
    """A bound portal: statement + parameter values, partially
    executable with row limits (protocol.rs portal machinery)."""

    def __init__(self, sql: str):
        self.sql = sql
        self.result = None  # ExecuteResult once executed
        self.sent = 0  # rows already sent (Execute with maxrows)


def _max_param(sql: str) -> int:
    """Highest $N placeholder outside string literals."""
    import re

    n = 0
    in_str = False
    i = 0
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
        elif not in_str and ch == "$":
            m = re.match(r"\$(\d+)", sql[i:])
            if m:
                n = max(n, int(m.group(1)))
                i += len(m.group(0))
                continue
        i += 1
    return n


# OID families for parameter typing (Parse's declared param_oids)
_NUMERIC_OIDS = {20, 21, 23, 26, 700, 701, 1700}
_TEXT_OIDS = {25, 1042, 1043, 18, 19}
_BOOL_OID = 16


def _substitute_params(
    sql: str, values: list, param_oids: tuple = ()
) -> str:
    """Inline bound parameter values as SQL literals ($N -> literal).
    The reference carries typed Datums through portals; the text
    protocol's values are re-parsed here. A parameter whose Parse
    message declared an OID is typed by it; undeclared (OID 0/absent)
    parameters fall back to a numeric-looking heuristic — ambiguous for
    text columns holding digit strings, in which case clients should
    declare OIDs (drivers that prepare with types do)."""
    import re

    def lit(idx, v):
        if v is None:
            return "NULL"
        s = v if isinstance(v, str) else v.decode()
        oid = param_oids[idx] if idx < len(param_oids) else 0
        if oid in _TEXT_OIDS:
            return "'" + s.replace("'", "''") + "'"
        if oid in _NUMERIC_OIDS:
            return s
        if oid == _BOOL_OID:
            return "true" if s.strip().lower() in (
                "t", "true", "1", "yes", "on"
            ) else "false"
        if re.fullmatch(r"-?\d+(\.\d+)?([eE][+-]?\d+)?", s):
            return s
        if s.lower() in ("true", "false"):
            return s
        return "'" + s.replace("'", "''") + "'"

    out, i, in_str = [], 0, False
    while i < len(sql):
        ch = sql[i]
        if ch == "'":
            in_str = not in_str
            out.append(ch)
            i += 1
            continue
        if not in_str and ch == "$":
            m = re.match(r"\$(\d+)", sql[i:])
            if m:
                idx = int(m.group(1)) - 1
                if idx >= len(values):
                    raise ValueError(f"parameter ${idx + 1} not bound")
                out.append(lit(idx, values[idx]))
                i += len(m.group(0))
                continue
        out.append(ch)
        i += 1
    return "".join(out)


class PgConnection:
    def __init__(self, sock: socket.socket, coordinator):
        self.sock = sock
        self.coord = coordinator
        self.alive = True
        self.prepared: dict[str, _Prepared] = {}
        self.portals: dict[str, _Portal] = {}
        self._skip_until_sync = False

    # -- low-level ----------------------------------------------------------
    def _recv_exact(self, n: int) -> bytes:
        buf = b""
        while len(buf) < n:
            chunk = self.sock.recv(n - len(buf))
            if not chunk:
                raise ConnectionError("client disconnected")
            buf += chunk
        return buf

    def _send(self, data: bytes) -> None:
        self.sock.sendall(data)

    # -- session ------------------------------------------------------------
    def run(self) -> None:
        try:
            if not self._startup():
                return
            self._ready()
            while self.alive:
                tag = self.sock.recv(1)
                if not tag:
                    return
                (length,) = struct.unpack("!I", self._recv_exact(4))
                payload = self._recv_exact(length - 4)
                if tag == b"Q":
                    self._handle_query(payload[:-1].decode())
                elif tag == b"X":
                    return
                elif tag == b"S":  # Sync: end of extended batch
                    self._skip_until_sync = False
                    self._ready()
                elif self._skip_until_sync:
                    continue  # drop messages until Sync after an error
                elif tag == b"P":
                    self._handle_parse(payload)
                elif tag == b"B":
                    self._handle_bind(payload)
                elif tag == b"D":
                    self._handle_describe(payload)
                elif tag == b"E":
                    self._handle_execute(payload)
                elif tag == b"C":
                    self._handle_close(payload)
                elif tag == b"H":  # Flush: all responses sent eagerly
                    pass
                else:
                    self._error("08P01", f"unknown message {tag!r}")
                    self._ready()
        except (ConnectionError, OSError):
            pass
        finally:
            self.sock.close()

    def _startup(self) -> bool:
        while True:
            (length,) = struct.unpack("!I", self._recv_exact(4))
            payload = self._recv_exact(length - 4)
            (code,) = struct.unpack("!I", payload[:4])
            if code == SSL_REQUEST:
                self._send(b"N")  # no TLS; client retries plaintext
                continue
            if code == CANCEL_REQUEST:
                return False
            if code != PROTOCOL_V3:
                self._error("08P01", f"unsupported protocol {code}")
                return False
            break
        # AuthenticationOk + minimal parameters + key data.
        self._send(_msg(b"R", struct.pack("!I", 0)))
        for k, v in (
            ("server_version", "9.5.0"),
            ("server_name", "materialize_tpu"),
            ("client_encoding", "UTF8"),
            ("DateStyle", "ISO"),
            ("integer_datetimes", "on"),
        ):
            self._send(_msg(b"S", _cstr(k) + _cstr(v)))
        self._send(_msg(b"K", struct.pack("!II", 0, 0)))
        return True

    def _ready(self) -> None:
        self._send(_msg(b"Z", b"I"))

    def _error(self, code: str, message: str) -> None:
        payload = (
            b"S" + _cstr("ERROR")
            + b"C" + _cstr(code)
            + b"M" + _cstr(message)
            + b"\x00"
        )
        self._send(_msg(b"E", payload))

    # -- queries ------------------------------------------------------------
    def _handle_query(self, sql: str) -> None:
        # One trace per STATEMENT (ISSUE 12 drive-by: the statement
        # root uses the shared trace-context API, so coordinator /
        # controller / replica child spans all join this id space —
        # mz_trace_spans shows one tree per statement, not one blob
        # per simple-query batch).
        for stmt in _split_statements(sql):
            if not stmt.strip():
                self._send(_msg(b"I", b""))  # EmptyQueryResponse
                continue
            with TRACER.statement("pgwire.query", sql=stmt[:100]):
                try:
                    res = self.coord.execute(stmt)
                except Exception as e:  # planning/execution error
                    self._error(_error_code(e), str(e))
                    self._ready()
                    return
                try:
                    self._send_result(stmt, res)
                except BrokenPipeError:
                    raise
                except (ConnectionError, OSError):
                    raise
                except Exception as e:
                    # e.g. COPY parse failures / CopyFail: report and
                    # keep the session alive
                    self._error("XX000", str(e))
                    self._ready()
                    return
        self._ready()

    # -- extended protocol (protocol.rs extended-query state machine) ------
    def _ext_error(self, code: str, message: str) -> None:
        """Error inside an extended-protocol batch: report and discard
        messages until the Sync."""
        self._error(code, message)
        self._skip_until_sync = True

    def _handle_parse(self, payload: bytes) -> None:
        name, off = _read_cstr(payload, 0)
        sql, off = _read_cstr(payload, off)
        (noids,) = struct.unpack_from("!h", payload, off)
        off += 2
        oids = struct.unpack_from(f"!{noids}I", payload, off)
        try:
            stmts = [s for s in _split_statements(sql) if s.strip()]
            if len(stmts) > 1:
                raise ValueError(
                    "cannot prepare multiple statements at once"
                )
            self.prepared[name] = _Prepared(
                stmts[0] if stmts else "", tuple(oids)
            )
            self._send(_msg(b"1", b""))  # ParseComplete
        except Exception as e:
            self._ext_error("42601", str(e))

    def _handle_bind(self, payload: bytes) -> None:
        try:
            portal, off = _read_cstr(payload, 0)
            stmt_name, off = _read_cstr(payload, off)
            (nfmt,) = struct.unpack_from("!h", payload, off)
            off += 2
            fmts = struct.unpack_from(f"!{nfmt}h", payload, off)
            off += 2 * nfmt
            (nparams,) = struct.unpack_from("!h", payload, off)
            off += 2
            values = []
            for i in range(nparams):
                (ln,) = struct.unpack_from("!i", payload, off)
                off += 4
                if ln == -1:
                    values.append(None)
                else:
                    raw = payload[off : off + ln]
                    off += ln
                    fmt = fmts[i] if i < len(fmts) else (
                        fmts[0] if len(fmts) == 1 else 0
                    )
                    if fmt != 0:
                        raise ValueError(
                            "binary parameter format not supported"
                        )
                    values.append(raw.decode())
            # result formats: text (0) only
            (nrfmt,) = struct.unpack_from("!h", payload, off)
            off += 2
            rfmts = struct.unpack_from(f"!{nrfmt}h", payload, off)
            if any(f != 0 for f in rfmts):
                raise ValueError("binary result format not supported")
            ps = self.prepared.get(stmt_name)
            if ps is None:
                raise ValueError(
                    f"prepared statement {stmt_name!r} does not exist"
                )
            self.portals[portal] = _Portal(
                _substitute_params(ps.sql, values, ps.param_oids)
            )
            self._send(_msg(b"2", b""))  # BindComplete
        except Exception as e:
            self._ext_error("08P01", str(e))

    def _describe_results(self, sql: str) -> None:
        """RowDescription (or NoData) for a statement/portal by planning
        it without executing (Describe; the reference's describe path
        runs the planner's describe-only mode, sql/src/plan/statement.rs)."""
        from ..sql import parser as sqlparser
        from ..sql.plan import SelectPlan, plan_statement

        try:
            stmt = sqlparser.parse_statement(sql)
            plan = plan_statement(stmt, self.coord.catalog)
        except Exception:
            self._send(_msg(b"n", b""))  # NoData for unplannable here
            return
        if isinstance(plan, SelectPlan):
            self._row_description(plan.column_names, plan.expr.schema())
        else:
            self._send(_msg(b"n", b""))

    def _handle_describe(self, payload: bytes) -> None:
        kind = payload[0:1]
        name, _ = _read_cstr(payload, 1)
        if kind == b"S":
            ps = self.prepared.get(name)
            if ps is None:
                self._ext_error(
                    "26000", f"prepared statement {name!r} does not exist"
                )
                return
            # ParameterDescription: unknown params described as text
            oids = list(ps.param_oids) + [25] * (
                ps.nparams - len(ps.param_oids)
            )
            self._send(
                _msg(
                    b"t",
                    struct.pack("!h", len(oids))
                    + b"".join(struct.pack("!I", o) for o in oids),
                )
            )
            self._describe_results(
                _substitute_params(ps.sql, [None] * ps.nparams)
                if ps.nparams
                else ps.sql
            )
        elif kind == b"P":
            po = self.portals.get(name)
            if po is None:
                self._ext_error(
                    "34000", f"portal {name!r} does not exist"
                )
                return
            self._describe_results(po.sql)
        else:
            self._ext_error("08P01", f"bad describe kind {kind!r}")

    def _handle_execute(self, payload: bytes) -> None:
        name, off = _read_cstr(payload, 0)
        (maxrows,) = struct.unpack_from("!i", payload, off)
        po = self.portals.get(name)
        if po is None:
            self._ext_error("34000", f"portal {name!r} does not exist")
            return
        try:
            if po.result is None:
                if not po.sql.strip():
                    self._send(_msg(b"I", b""))  # EmptyQueryResponse
                    return
                with TRACER.statement(
                    "pgwire.execute", sql=po.sql[:100]
                ):
                    po.result = self.coord.execute(po.sql)
                po.sent = 0
            res = po.result
            if res.kind == "rows" and getattr(res, "copy_out", False):
                self._copy_out_rows(res)
            elif res.kind == "copy_in":
                self._copy_in(res)
            elif res.kind == "rows":
                schema = self._result_schema(res)
                rows = res.rows[po.sent :]
                if maxrows and maxrows > 0 and len(rows) > maxrows:
                    for row in rows[:maxrows]:
                        self._data_row(row, schema)
                    po.sent += maxrows
                    self._send(_msg(b"s", b""))  # PortalSuspended
                    return
                for row in rows:
                    self._data_row(row, schema)
                po.sent = len(res.rows)
                self._complete(f"SELECT {len(res.rows)}")
            elif res.kind == "subscription":
                res.subscription.close()
                self._ext_error(
                    "0A000",
                    "SUBSCRIBE requires the simple query protocol",
                )
            else:
                self._send_result(po.sql, res)
        except Exception as e:
            self._ext_error(_error_code(e), str(e))

    def _handle_close(self, payload: bytes) -> None:
        kind = payload[0:1]
        name, _ = _read_cstr(payload, 1)
        if kind == b"S":
            self.prepared.pop(name, None)
        else:
            self.portals.pop(name, None)
        self._send(_msg(b"3", b""))  # CloseComplete

    def _send_result(self, stmt: str, res) -> None:
        if res.kind == "rows" and getattr(res, "copy_out", False):
            self._copy_out_rows(res)
        elif res.kind == "rows":
            schema = self._result_schema(res)
            self._row_description(res.columns, schema)
            for row in res.rows:
                self._data_row(row, schema)
            self._complete(f"SELECT {len(res.rows)}")
        elif res.kind == "copy_in":
            self._copy_in(res)
        elif res.kind == "text":
            self._row_description(res.columns or ("explain",), None)
            for line in res.text.split("\n"):
                self._data_row((line,), None)
            self._complete("EXPLAIN")
        elif res.kind == "subscription":
            self._stream_subscription(res)
        else:
            verb = stmt.strip().split()[0].upper()
            self._complete(
                f"INSERT 0 {res.affected}" if verb == "INSERT" else verb
            )

    def _result_schema(self, res):
        # Column types: taken from the plan when available; text is a
        # safe fallback for the wire's text format.
        return getattr(res, "schema", None)

    def _row_description(self, columns, schema) -> None:
        parts = [struct.pack("!H", len(columns))]
        for i, name in enumerate(columns):
            oid = 25
            if schema is not None and i < len(schema.columns):
                oid = _OIDS.get(schema.columns[i].ctype, 25)
            parts.append(
                _cstr(str(name))
                + struct.pack("!IhIhih", 0, 0, oid, -1, -1, 0)
            )
        self._send(_msg(b"T", b"".join(parts)))

    def _data_row(self, row, schema) -> None:
        parts = [struct.pack("!H", len(row))]
        for v in row:
            if v is None:
                parts.append(struct.pack("!i", -1))
            else:
                if isinstance(v, bool):
                    s = "t" if v else "f"
                else:
                    s = str(v)
                b = s.encode()
                parts.append(struct.pack("!i", len(b)) + b)
        self._send(_msg(b"D", b"".join(parts)))

    def _complete(self, tag: str) -> None:
        self._send(_msg(b"C", _cstr(tag)))

    def _copy_out_rows(self, res) -> None:
        """COPY (query) TO STDOUT: rows in pg text format."""
        n = len(res.columns)
        self._send(
            _msg(b"H", struct.pack("!bh", 0, n) + b"\x00\x00" * n)
        )
        lines = []
        for row in res.rows:
            lines.append(
                "\t".join(_copy_text_field(v) for v in row) + "\n"
            )
        if lines:
            self._send(_msg(b"d", "".join(lines).encode()))
        self._send(_msg(b"c", b""))  # CopyDone
        self._complete(f"COPY {len(res.rows)}")

    def _copy_in(self, res) -> None:
        """COPY table FROM STDIN: CopyInResponse, then CopyData until
        CopyDone/CopyFail (text format)."""
        n = len(res.columns)
        self._send(
            _msg(b"G", struct.pack("!bh", 0, n) + b"\x00\x00" * n)
        )
        chunks: list = []
        saw_sync = False
        while True:
            tag = self._recv_exact(1)
            (length,) = struct.unpack("!I", self._recv_exact(4))
            payload = self._recv_exact(length - 4)
            if tag == b"d":
                chunks.append(payload)
            elif tag == b"c":  # CopyDone
                break
            elif tag == b"f":  # CopyFail
                raise ValueError(
                    "COPY aborted by client: "
                    + payload.rstrip(b"\x00").decode()
                )
            elif tag == b"S":
                # a pipelined Sync (extended-protocol batch) arrives
                # before the copy stream: owe its ReadyForQuery after
                # the copy completes
                saw_sync = True
            elif tag == b"H":  # Flush: no-op
                continue
            else:
                raise ValueError(
                    f"unexpected message {tag!r} during COPY"
                )
        rows = []
        text = b"".join(chunks).decode()
        lines = text.split("\n")
        if lines and lines[-1] == "":
            lines.pop()  # artifact of the terminating newline ONLY —
            # interior empty lines are real single-column empty strings
        for line in lines:
            if line == "\\.":
                continue
            rows.append(
                [
                    None if f == "\\N" else _copy_unescape(f)
                    for f in line.split("\t")
                ]
            )
        count = self.coord.copy_in_rows(res.table, res.columns, rows)
        self._complete(f"COPY {count}")
        if saw_sync:
            self._skip_until_sync = False
            self._ready()

    def _stream_subscription(self, res) -> None:
        """SUBSCRIBE over the COPY-out subprotocol: one text line per
        update '(time, diff, cols...)' plus a progress line per span
        window (the reference's SUBSCRIBE/TAIL wire behavior).

        Delivery is EVENT-DRIVEN (ISSUE 11): the loop selects on the
        client socket and the hub session's wake fd — a committed span
        wakes it to drain the shared tail's chunk, and a client
        half-close / CopyFail / Terminate wakes it to tear down. No
        polling heartbeat, no MSG_PEEK hack: thousands of idle
        subscribers cost zero CPU between spans."""
        import select

        from ..coord.subscribe import SubscriptionLagging

        sub = res.subscription
        # CopyOutResponse: text format, one column.
        self._send(_msg(b"H", struct.pack("!bh", 0, 0)))
        wake = sub.wake_socket()
        try:
            while True:
                # Drain BEFORE selecting (chunks enqueued before the
                # wake fd existed — the join snapshot — have no wake
                # byte to select on) and BEFORE honoring `closed`: a
                # hub-reaped lagging session still owes the client its
                # SubscriptionLagging error (raised by pop_ready), not
                # a clean end-of-stream.
                for kind, events, frontier, _stamp in sub.pop_ready():
                    lines = []
                    if kind == "snapshot":
                        # Coalesce-to-snapshot marker: the rows that
                        # follow REPLACE the consumer's accumulated
                        # state (subscribe_slow_policy = 'coalesce',
                        # or the join snapshot itself).
                        lines.append(f"{frontier}\t0\tsnapshot\n")
                    for ev in events:
                        *vals, t, d = ev
                        fields = "\t".join(
                            "\\N" if v is None else str(v)
                            for v in vals
                        )
                        lines.append(f"{t}\t{d}\t{fields}\n")
                    lines.append(f"{frontier}\t0\tprogress\n")
                    self._send(_msg(b"d", "".join(lines).encode()))
                if sub.closed:
                    return
                ready, _, _ = select.select(
                    [self.sock, wake], [], [], 30.0
                )
                if self.sock in ready:
                    # The client spoke mid-stream: CopyFail aborts,
                    # Terminate ends the session, a bare EOF is the
                    # half-close of a dead client (SIGKILL included —
                    # the kernel's FIN lands here).
                    try:
                        tag = self.sock.recv(1)
                    except OSError:
                        return
                    if not tag:
                        return  # half-close / client death
                    (length,) = struct.unpack(
                        "!I", self._recv_exact(4)
                    )
                    self._recv_exact(length - 4)
                    if tag == b"f":  # CopyFail: client aborted
                        return
                    if tag == b"c":  # CopyDone: clean client end
                        return
                    if tag == b"X":  # Terminate
                        self.alive = False
                        return
                    # Flush/Sync etc. during COPY-out: ignore.
                if wake in ready:
                    try:
                        while wake.recv(4096):
                            pass
                    except (BlockingIOError, OSError):
                        pass
        except SubscriptionLagging as e:
            # Slow-consumer disconnect policy: a retryable shed, like
            # admission control (the client may re-SUBSCRIBE).
            try:
                self._error("53400", str(e))
            except (ConnectionError, OSError):
                pass
        except (BrokenPipeError, ConnectionError, OSError):
            pass
        finally:
            sub.close()


_COPY_ESCAPES = {
    "\\": "\\",
    "t": "\t",
    "n": "\n",
    "r": "\r",
    "b": "\b",
    "f": "\f",
    "v": "\v",
}


def _copy_unescape(field: str) -> str:
    if "\\" not in field:
        return field
    out, i = [], 0
    while i < len(field):
        ch = field[i]
        if ch == "\\" and i + 1 < len(field):
            out.append(_COPY_ESCAPES.get(field[i + 1], field[i + 1]))
            i += 2
        else:
            out.append(ch)
            i += 1
    return "".join(out)


def _copy_text_field(v) -> str:
    if v is None:
        return "\\N"
    if isinstance(v, bool):
        return "t" if v else "f"
    s = str(v)
    return (
        s.replace("\\", "\\\\")
        .replace("\t", "\\t")
        .replace("\n", "\\n")
        .replace("\r", "\\r")
    )


def _read_cstr(buf: bytes, off: int) -> tuple:
    end = buf.index(b"\x00", off)
    return buf[off:end].decode(), end + 1


def _split_statements(sql: str) -> list[str]:
    """Split on ';' outside string literals (simple-query batches)."""
    out, cur, in_str = [], [], False
    for ch in sql:
        if ch == "'":
            in_str = not in_str
            cur.append(ch)
        elif ch == ";" and not in_str:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    if "".join(cur).strip():
        out.append("".join(cur))
    return out


class PgServer:
    """TCP acceptor: one thread per connection (server-core analog)."""

    def __init__(self, coordinator, host: str = "127.0.0.1", port: int = 0):
        self.coord = coordinator
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind((host, port))
        self.sock.listen(16)
        self.port = self.sock.getsockname()[1]
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._accept, daemon=True)

    def start(self) -> "PgServer":
        self._thread.start()
        return self

    def _accept(self) -> None:
        self.sock.settimeout(0.2)
        while not self._stop.is_set():
            try:
                conn, _ = self.sock.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._serve_conn, args=(conn,), daemon=True
            ).start()

    def _serve_conn(self, conn: socket.socket) -> None:
        try:
            PgConnection(conn, self.coord).run()
        except Exception:
            traceback.print_exc()

    def stop(self) -> None:
        self._stop.set()
        self.sock.close()
