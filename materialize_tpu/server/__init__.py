"""Server front end: pgwire protocol + HTTP endpoints + environmentd
(SURVEY.md L0: src/pgwire, environmentd/src/http, server-core)."""
