"""environmentd: the controller process.

Analog of the reference's ``environmentd`` (``Listeners::serve``,
``environmentd/src/lib.rs:361``): opens the durable catalog, boots the
coordinator + controllers, (optionally) spawns replica subprocesses, and
serves pgwire + HTTP. One command brings up a working deployment:

    python -m materialize_tpu.server.environmentd \
        --data-dir DIR [--pg-port P] [--http-port P] [--replicas N]
"""

from __future__ import annotations

import argparse
import atexit
import os
import signal
import socket
import subprocess
import sys
import time as _time

from ..coord.coordinator import Coordinator
from ..storage.persist import FileBlob, PersistClient, SqliteConsensus
from .http import HttpServer
from .pgwire import PgServer


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def bank_path(data_dir: str) -> str:
    """The deployment's program-bank directory (ISSUE 16): under the
    blob root, so the bank rides the same durable storage the shards
    do and ``--recover`` finds warm executables next to warm state."""
    return os.path.join(data_dir, "blob", "program_bank")


def spawn_replica(
    data_dir: str, port: int, rid: str, workers: int = 1
) -> subprocess.Popen:
    """One clusterd subprocess (orchestrator-process analog)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", ""))
    # Subprocess replicas share the deployment's program bank: the env
    # var is resolved once by compile.bank.get_bank() at first
    # ledger_jit dispatch — no flag threading through replica main.
    env.setdefault("MZ_PROGRAM_BANK", bank_path(data_dir))
    return subprocess.Popen(
        [
            sys.executable, "-m", "materialize_tpu.coord.replica",
            "--port", str(port),
            "--blob", os.path.join(data_dir, "blob"),
            "--consensus", os.path.join(data_dir, "consensus.db"),
            "--replica-id", rid,
            "--workers", str(workers),
        ],
        env=env,
    )


class Environment:
    """A running deployment: coordinator + replicas + listeners."""

    def __init__(
        self,
        data_dir: str,
        pg_port: int = 0,
        http_port: int = 0,
        n_replicas: int = 1,
        workers: int = 1,
        tick_interval: float | None = 0.05,
        in_process_replicas: bool = False,
    ):
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        # Every process in the deployment — this one (coordinator +
        # in-process replicas) and spawned subprocess replicas (via
        # MZ_PROGRAM_BANK in spawn_replica) — shares one bank under
        # the blob root. Recovery's re-renders become bank hits.
        from ..compile.bank import configure_bank

        configure_bank(bank_path(data_dir))
        self.procs: list[subprocess.Popen] = []
        self._threads = []
        replica_ports = []
        for i in range(n_replicas):
            port = _free_port()
            rid = f"r{i}"
            if in_process_replicas:
                import threading

                from ..coord.protocol import PersistLocation
                from ..coord.replica import serve_forever

                ready = threading.Event()
                t = threading.Thread(
                    target=serve_forever,
                    args=(
                        port,
                        PersistLocation(
                            os.path.join(data_dir, "blob"),
                            os.path.join(data_dir, "consensus.db"),
                        ),
                        rid,
                        ready,
                    ),
                    kwargs={"workers": workers},
                    daemon=True,
                )
                t.start()
                ready.wait(10)
                self._threads.append(t)
            else:
                self.procs.append(
                    spawn_replica(data_dir, port, rid, workers)
                )
            replica_ports.append((rid, port))
        self.coord = Coordinator(
            PersistClient(
                FileBlob(os.path.join(data_dir, "blob")),
                SqliteConsensus(os.path.join(data_dir, "consensus.db")),
            ),
            tick_interval=tick_interval,
        )
        for rid, port in replica_ports:
            self.coord.add_replica(rid, ("127.0.0.1", port))
        self.pg = PgServer(self.coord, port=pg_port).start()
        self.http = HttpServer(self.coord, port=http_port).start()
        self._down = False

    # -- restart recovery (ISSUE 10) ----------------------------------------
    def recovery_report(self) -> dict:
        """What this boot recovered: the coordinator's catalog replay
        counts and the controller's replica/dataflow recovery view
        (the programmatic face of `mz_recovery`)."""
        report = {"coordinator": dict(self.coord.recovery)}
        report.update(self.coord.controller.recovery_snapshot())
        # Compile breakdown (ISSUE 16): how much of this boot's
        # compile wall the program bank absorbed. A warm-bank recover
        # of unchanged fingerprints shows bank_misses == 0 — ZERO
        # fresh XLA compiles — with the skipped wall in
        # compile_seconds_recovered.
        from ..compile.bank import get_bank
        from ..utils.compile_ledger import LEDGER

        s = LEDGER.summary()
        compiles = {
            "bank_hits": s["bank_hits"],
            "bank_misses": s["bank_misses"],
            "compile_seconds_recovered": s["bank_seconds_recovered"],
            "fresh_compiles": s["misses"],
        }
        bank = get_bank()
        if bank is not None:
            compiles["bank"] = bank.snapshot()
        report["compiles"] = compiles
        return report

    def await_recovery(self, timeout: float = 120.0) -> dict:
        """Block until every durable dataflow (MV/index) the replayed
        catalog re-registered is installed on some replica, then
        return the recovery report — the --recover boot path's proof
        obligation: the catalog came back AND the dataflows re-rendered
        and re-hydrated (from input-shard snapshots at the persisted
        as_of; storage/persist/operators.py)."""
        import time as _t

        deadline = _t.monotonic() + timeout
        for name in sorted(set(self.coord.peekable.values())):
            self.coord.controller.wait_installed(
                name, timeout=max(deadline - _t.monotonic(), 0.1)
            )
        # Install-acked is not compile-counted: hydration is the phase
        # that consults the program bank, and subprocess replicas ship
        # their compile records on the same Frontiers report that
        # flips the hydration board. Wait for the readiness verdict
        # (every durable dataflow hydrated somewhere), then let the
        # piggybacked ledger settle, so the report's `compiles` block
        # describes this boot instead of racing it.
        while _t.monotonic() < deadline:
            if self.coord.health()["ready"]:
                break
            _t.sleep(0.05)
        from ..utils.compile_ledger import LEDGER

        settle_until = min(deadline, _t.monotonic() + 5.0)
        prev = LEDGER.summary()
        while _t.monotonic() < settle_until:
            _t.sleep(0.1)
            cur = LEDGER.summary()
            if cur == prev:
                break
            prev = cur
        return self.recovery_report()

    def shutdown(self) -> dict:
        """Stop listeners, coordinator, and replicas. Replica exits
        escalate terminate -> kill when the graceful budget
        (retry_policy_shutdown) expires — a wedged replica must never
        hang shutdown forever — and the exit report says exactly what
        happened to each process (ISSUE 10 satellite)."""
        report: dict = {"replicas": [], "escalations": 0}
        if self._down:
            return report
        self._down = True
        # Un-configure the process-global bank: the deployment owns
        # its bank directory; a later Environment (or a bankless
        # caller in the same process, e.g. the test suite) must not
        # keep writing into this one.
        from ..compile.bank import configure_bank

        configure_bank(None)
        self.pg.stop()
        self.http.stop()
        self.coord.shutdown()
        from ..utils.retry import policy as _retry_policy

        budget = _retry_policy("shutdown").budget or 5.0
        for p in self.procs:
            p.terminate()
        deadline = _time.monotonic() + budget
        for p in self.procs:
            entry = {"pid": p.pid, "escalated": False}
            try:
                entry["returncode"] = p.wait(
                    timeout=max(deadline - _time.monotonic(), 0.1)
                )
            except subprocess.TimeoutExpired:
                # Escalate: SIGKILL, then a short bounded reap. A
                # process that survives SIGKILL (unkillable D-state)
                # is reported, not waited on forever.
                entry["escalated"] = True
                report["escalations"] += 1
                p.kill()
                try:
                    entry["returncode"] = p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    entry["returncode"] = None
            report["replicas"].append(entry)
        return report


def main() -> None:
    # The axon TPU plugin ignores the JAX_PLATFORMS env var; honor it
    # via the config knob before any backend init (same as replica.py).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    ap = argparse.ArgumentParser(description="materialize_tpu environmentd")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--pg-port", type=int, default=6875)
    ap.add_argument("--http-port", type=int, default=6876)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument(
        "--workers", type=int, default=1,
        help="devices per replica (SPMD mesh size)",
    )
    ap.add_argument(
        "--tick-interval", type=float, default=0.05,
        help="load-generator tick seconds",
    )
    ap.add_argument(
        "--recover", action="store_true",
        help="restart-recovery boot: replay the durable catalog, "
        "re-render every dataflow, wait for replicas to re-hydrate "
        "from persist, and print the recovery report before serving",
    )
    args = ap.parse_args()
    env = Environment(
        args.data_dir,
        pg_port=args.pg_port,
        http_port=args.http_port,
        n_replicas=args.replicas,
        workers=args.workers,
        tick_interval=args.tick_interval,
    )
    atexit.register(env.shutdown)
    if args.recover:
        import json as _json

        report = env.await_recovery()
        print("recovery: " + _json.dumps(report, sort_keys=True),
              flush=True)
    print(
        f"materialize_tpu listening: pgwire=127.0.0.1:{env.pg.port} "
        f"http=127.0.0.1:{env.http.port} data={args.data_dir}",
        flush=True,
    )
    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        while True:
            _time.sleep(3600)


if __name__ == "__main__":
    main()
