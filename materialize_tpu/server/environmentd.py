"""environmentd: the controller process.

Analog of the reference's ``environmentd`` (``Listeners::serve``,
``environmentd/src/lib.rs:361``): opens the durable catalog, boots the
coordinator + controllers, (optionally) spawns replica subprocesses, and
serves pgwire + HTTP. One command brings up a working deployment:

    python -m materialize_tpu.server.environmentd \
        --data-dir DIR [--pg-port P] [--http-port P] [--replicas N]
"""

from __future__ import annotations

import argparse
import atexit
import os
import signal
import socket
import subprocess
import sys
import time as _time

from ..coord.coordinator import Coordinator
from ..storage.persist import FileBlob, PersistClient, SqliteConsensus
from .http import HttpServer
from .pgwire import PgServer


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def bank_path(data_dir: str) -> str:
    """The deployment's program-bank directory (ISSUE 16): under the
    blob root, so the bank rides the same durable storage the shards
    do and ``--recover`` finds warm executables next to warm state."""
    return os.path.join(data_dir, "blob", "program_bank")


def spawn_replica(
    data_dir: str, port: int, rid: str, workers: int = 1
) -> subprocess.Popen:
    """One clusterd subprocess (orchestrator-process analog)."""
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", os.environ.get("JAX_PLATFORMS", ""))
    # Subprocess replicas share the deployment's program bank: the env
    # var is resolved once by compile.bank.get_bank() at first
    # ledger_jit dispatch — no flag threading through replica main.
    env.setdefault("MZ_PROGRAM_BANK", bank_path(data_dir))
    return subprocess.Popen(
        [
            sys.executable, "-m", "materialize_tpu.coord.replica",
            "--port", str(port),
            "--blob", os.path.join(data_dir, "blob"),
            "--consensus", os.path.join(data_dir, "consensus.db"),
            "--replica-id", rid,
            "--workers", str(workers),
        ],
        env=env,
    )


class Environment:
    """A running deployment: coordinator + replicas + listeners."""

    def __init__(
        self,
        data_dir: str,
        pg_port: int = 0,
        http_port: int = 0,
        n_replicas: int = 1,
        workers: int = 1,
        tick_interval: float | None = 0.05,
        in_process_replicas: bool = False,
    ):
        os.makedirs(data_dir, exist_ok=True)
        self.data_dir = data_dir
        # Every process in the deployment — this one (coordinator +
        # in-process replicas) and spawned subprocess replicas (via
        # MZ_PROGRAM_BANK in spawn_replica) — shares one bank under
        # the blob root. Recovery's re-renders become bank hits.
        from ..compile.bank import configure_bank
        from ..utils.lockcheck import tracked_lock

        configure_bank(bank_path(data_dir))
        self.procs: list[subprocess.Popen] = []
        self._in_process = in_process_replicas
        self._default_workers = workers
        # Replica registry (ISSUE 19): rid -> {port, proc|worker+thread,
        # workers}. add/drop/rolling-restart/autoscale actions all
        # serialize on the scale lock — the interleave model
        # `autoscale-vs-restart` pins why (an unserialized check-then-
        # spawn can bust the replica band or drop the last server).
        self.replica_records: dict[str, dict] = {}
        self._scale_lock = tracked_lock("environment.scale")
        self._replica_seq = n_replicas
        for i in range(n_replicas):
            self._spawn_record(f"r{i}", workers=workers)
        self.coord = Coordinator(
            PersistClient(
                FileBlob(os.path.join(data_dir, "blob")),
                SqliteConsensus(os.path.join(data_dir, "consensus.db")),
                # Production client (ISSUE 20): table/catalog appends
                # request leased background compaction off the serving
                # path per the compaction_mode dyncfg.
                auto_compaction=True,
            ),
            tick_interval=tick_interval,
        )
        for rid, rec in self.replica_records.items():
            self.coord.add_replica(rid, ("127.0.0.1", rec["port"]))
        self.pg = PgServer(self.coord, port=pg_port).start()
        self.http = HttpServer(self.coord, port=http_port).start()
        self._down = False
        # The SLO-driven autoscaler (coord/autoscaler.py): the policy
        # thread always runs; it acts only while the autoscale_policy
        # dyncfg is non-empty, so SET enables/disables it live.
        from ..coord.autoscaler import Autoscaler

        self.autoscaler = Autoscaler(
            self.coord.controller,
            lambda: self.add_replica(),
            lambda rid: self.drop_replica(rid, drain=True),
        ).start()

    # -- replica lifecycle (ISSUE 19) ---------------------------------------
    def _spawn_record(
        self, rid: str, workers: int | None = None
    ) -> dict:
        """Start one replica (subprocess or in-process thread, matching
        the deployment mode) and register it in the records map. Does
        NOT touch the coordinator — callers pair this with
        coord.add_replica under the scale lock."""
        port = _free_port()
        w = self._default_workers if workers is None else workers
        if self._in_process:
            import threading

            from ..coord.protocol import PersistLocation
            from ..coord.replica import serve_forever

            ready = threading.Event()
            handle: list = []
            t = threading.Thread(
                target=serve_forever,
                args=(
                    port,
                    PersistLocation(
                        os.path.join(self.data_dir, "blob"),
                        os.path.join(self.data_dir, "consensus.db"),
                    ),
                    rid,
                    ready,
                ),
                kwargs={"workers": w, "handle": handle},
                daemon=True,
            )
            t.start()
            ready.wait(10)
            rec = {
                "port": port,
                "proc": None,
                "worker": handle[0] if handle else None,
                "thread": t,
                "workers": w,
            }
        else:
            p = spawn_replica(self.data_dir, port, rid, w)
            self.procs.append(p)
            rec = {
                "port": port, "proc": p, "worker": None,
                "thread": None, "workers": w,
            }
        self.replica_records[rid] = rec
        return rec

    def _stop_record(self, rec: dict) -> None:
        p = rec.get("proc")
        if p is not None:
            from ..utils.retry import policy as _retry_policy

            budget = _retry_policy("shutdown").budget or 5.0
            p.terminate()
            try:
                p.wait(timeout=budget)
            except subprocess.TimeoutExpired:
                p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass
            if p in self.procs:
                self.procs.remove(p)
        w = rec.get("worker")
        if w is not None:
            w.stop()
            t = rec.get("thread")
            if t is not None:
                t.join(2)

    def add_replica(
        self, rid: str | None = None, workers: int | None = None
    ) -> str:
        """Runtime scale-up (`CREATE CLUSTER REPLICA` analog): spawn,
        register with the controller (the nonce Hello fences it like
        any boot-time replica), and return the name. It hydrates from
        the shared program bank, so join time is seconds — it becomes
        a routing candidate once the hydration board flips."""
        with self._scale_lock:
            if self._down:
                raise RuntimeError("environment is shut down")
            if rid is None:
                rid = f"r{self._replica_seq}"
                self._replica_seq += 1
            if rid in self.replica_records:
                raise ValueError(f"replica {rid!r} already exists")
            rec = self._spawn_record(rid, workers=workers)
            self.coord.add_replica(rid, ("127.0.0.1", rec["port"]))
        return rid

    def drop_replica(self, rid: str, drain: bool = True) -> dict:
        """Runtime scale-down (`DROP CLUSTER REPLICA` analog): drain
        (stop routing, move in-flight reads, then drop) or hard-drop,
        then stop the process/thread."""
        with self._scale_lock:
            return self._drop_replica_locked(rid, drain)

    def _drop_replica_locked(self, rid: str, drain: bool) -> dict:
        rec = self.replica_records.pop(rid, None)
        if rec is None:
            return {"dropped": False, "reason": "unknown replica"}
        ctl = self.coord.controller
        if drain:
            out = dict(ctl.drain_replica(rid))
        else:
            ctl.drop_replica(rid)
            out = {"drained": False}
        self._stop_record(rec)
        out["dropped"] = True
        return out

    def rolling_restart(
        self, hydrate_timeout: float = 60.0
    ) -> dict:
        """Restart every replica, one at a time, under live ingest +
        serving. Per replica: wait until every durable dataflow has at
        least one OTHER serving replica, drain it (in-flight reads
        move immediately), stop it, respawn the SAME rid (fenced
        Hello, warm program bank -> seconds-scale rehydration), and
        wait until it serves again before touching the next one.

        The "at least one hydrated replica serves every durable
        dataflow at every instant" invariant is CHECKED, not assumed:
        a monitor thread samples `controller.serving_replicas` for
        every durable dataflow throughout and the report carries every
        violation (none = the restart was continuously served).
        `rebuilds` counts the restarted replicas' reported dataflow
        rebuilds — 0 on unchanged fingerprints (reconciliation +
        program bank)."""
        import threading
        import time as _t

        ctl = self.coord.controller
        dataflows = sorted(set(self.coord.peekable.values()))
        monitor_stop = threading.Event()
        violations: list = []
        samples = [0]

        def monitor():
            while not monitor_stop.is_set():
                samples[0] += 1
                for df in dataflows:
                    if not ctl.serving_replicas(df):
                        violations.append((df, samples[0]))
                monitor_stop.wait(0.02)

        mt = threading.Thread(target=monitor, daemon=True)
        mt.start()
        report: dict = {"replicas": [], "aborted": None}
        try:
            for rid in list(self.replica_records):
                with self._scale_lock:
                    if rid not in self.replica_records:
                        continue  # dropped while we iterated
                    entry: dict = {"replica": rid}
                    t0 = _t.monotonic()
                    deadline = t0 + hydrate_timeout
                    # Precondition: losing `rid` must leave every
                    # durable dataflow served by someone else.
                    uncovered = dataflows
                    while _t.monotonic() < deadline:
                        uncovered = [
                            df
                            for df in dataflows
                            if not [
                                r
                                for r in ctl.serving_replicas(df)
                                if r != rid
                            ]
                        ]
                        if not uncovered:
                            break
                        _t.sleep(0.05)
                    if uncovered:
                        entry["error"] = (
                            "no other serving replica for "
                            f"{uncovered}; restart aborted"
                        )
                        report["replicas"].append(entry)
                        report["aborted"] = rid
                        break
                    workers = self.replica_records[rid]["workers"]
                    drained = self._drop_replica_locked(
                        rid, drain=True
                    )
                    entry["moved_reads"] = drained.get("moved", 0)
                    rec = self._spawn_record(rid, workers=workers)
                    self.coord.add_replica(
                        rid, ("127.0.0.1", rec["port"])
                    )
                    while _t.monotonic() < deadline:
                        if all(
                            rid in ctl.serving_replicas(df)
                            for df in dataflows
                        ):
                            break
                        _t.sleep(0.05)
                    entry["seconds"] = round(_t.monotonic() - t0, 3)
                    entry["rehydrated"] = all(
                        rid in ctl.serving_replicas(df)
                        for df in dataflows
                    )
                report["replicas"].append(entry)
        finally:
            monitor_stop.set()
            mt.join(2)
        # The restarted replicas' own rebuild counts (piggybacked on
        # their frontier reports): 0 on unchanged fingerprints.
        restarted = {e["replica"] for e in report["replicas"]}
        rebuilds = 0
        snap = ctl.recovery_snapshot()["dataflows"]
        for df, per in snap.items():
            for rep, counters in per.items():
                if rep in restarted:
                    rebuilds += int(counters.get("rebuilds", 0))
        report["rebuilds"] = rebuilds
        report["invariant"] = {
            "samples": samples[0],
            "violations": violations[:20],
            "continuously_served": not violations,
        }
        return report

    # -- restart recovery (ISSUE 10) ----------------------------------------
    def recovery_report(self) -> dict:
        """What this boot recovered: the coordinator's catalog replay
        counts and the controller's replica/dataflow recovery view
        (the programmatic face of `mz_recovery`)."""
        report = {"coordinator": dict(self.coord.recovery)}
        report.update(self.coord.controller.recovery_snapshot())
        # Compile breakdown (ISSUE 16): how much of this boot's
        # compile wall the program bank absorbed. A warm-bank recover
        # of unchanged fingerprints shows bank_misses == 0 — ZERO
        # fresh XLA compiles — with the skipped wall in
        # compile_seconds_recovered.
        from ..compile.bank import get_bank
        from ..utils.compile_ledger import LEDGER

        s = LEDGER.summary()
        compiles = {
            "bank_hits": s["bank_hits"],
            "bank_misses": s["bank_misses"],
            "compile_seconds_recovered": s["bank_seconds_recovered"],
            "fresh_compiles": s["misses"],
        }
        bank = get_bank()
        if bank is not None:
            compiles["bank"] = bank.snapshot()
        report["compiles"] = compiles
        return report

    def await_recovery(self, timeout: float = 120.0) -> dict:
        """Block until every durable dataflow (MV/index) the replayed
        catalog re-registered is installed on some replica, then
        return the recovery report — the --recover boot path's proof
        obligation: the catalog came back AND the dataflows re-rendered
        and re-hydrated (from input-shard snapshots at the persisted
        as_of; storage/persist/operators.py)."""
        import time as _t

        deadline = _t.monotonic() + timeout
        for name in sorted(set(self.coord.peekable.values())):
            self.coord.controller.wait_installed(
                name, timeout=max(deadline - _t.monotonic(), 0.1)
            )
        # Install-acked is not compile-counted: hydration is the phase
        # that consults the program bank, and subprocess replicas ship
        # their compile records on the same Frontiers report that
        # flips the hydration board. Wait for the readiness verdict
        # (every durable dataflow hydrated somewhere), then let the
        # piggybacked ledger settle, so the report's `compiles` block
        # describes this boot instead of racing it.
        while _t.monotonic() < deadline:
            if self.coord.health()["ready"]:
                break
            _t.sleep(0.05)
        from ..utils.compile_ledger import LEDGER

        settle_until = min(deadline, _t.monotonic() + 5.0)
        prev = LEDGER.summary()
        while _t.monotonic() < settle_until:
            _t.sleep(0.1)
            cur = LEDGER.summary()
            if cur == prev:
                break
            prev = cur
        return self.recovery_report()

    def shutdown(self) -> dict:
        """Stop listeners, coordinator, and replicas. Replica exits
        escalate terminate -> kill when the graceful budget
        (retry_policy_shutdown) expires — a wedged replica must never
        hang shutdown forever — and the exit report says exactly what
        happened to each process (ISSUE 10 satellite)."""
        report: dict = {"replicas": [], "escalations": 0}
        if self._down:
            return report
        self._down = True
        self.autoscaler.stop()
        # In-process thread replicas stop via their worker handle (the
        # subprocess ones get the terminate -> kill loop below).
        for rec in self.replica_records.values():
            w = rec.get("worker")
            if w is not None:
                w.stop()
        # Un-configure the process-global bank: the deployment owns
        # its bank directory; a later Environment (or a bankless
        # caller in the same process, e.g. the test suite) must not
        # keep writing into this one.
        from ..compile.bank import configure_bank

        configure_bank(None)
        # Stop the process-global background compactor for the same
        # reason: its queue holds Machines rooted in THIS deployment's
        # blob/consensus; a later Environment starts a fresh one.
        from ..storage.persist import reset_compaction_service

        reset_compaction_service()
        self.pg.stop()
        self.http.stop()
        self.coord.shutdown()
        from ..utils.retry import policy as _retry_policy

        budget = _retry_policy("shutdown").budget or 5.0
        for p in self.procs:
            p.terminate()
        deadline = _time.monotonic() + budget
        for p in self.procs:
            entry = {"pid": p.pid, "escalated": False}
            try:
                entry["returncode"] = p.wait(
                    timeout=max(deadline - _time.monotonic(), 0.1)
                )
            except subprocess.TimeoutExpired:
                # Escalate: SIGKILL, then a short bounded reap. A
                # process that survives SIGKILL (unkillable D-state)
                # is reported, not waited on forever.
                entry["escalated"] = True
                report["escalations"] += 1
                p.kill()
                try:
                    entry["returncode"] = p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    entry["returncode"] = None
            report["replicas"].append(entry)
        return report


def main() -> None:
    # The axon TPU plugin ignores the JAX_PLATFORMS env var; honor it
    # via the config knob before any backend init (same as replica.py).
    plat = os.environ.get("JAX_PLATFORMS")
    if plat:
        import jax

        jax.config.update("jax_platforms", plat)
    ap = argparse.ArgumentParser(description="materialize_tpu environmentd")
    ap.add_argument("--data-dir", required=True)
    ap.add_argument("--pg-port", type=int, default=6875)
    ap.add_argument("--http-port", type=int, default=6876)
    ap.add_argument("--replicas", type=int, default=1)
    ap.add_argument(
        "--workers", type=int, default=1,
        help="devices per replica (SPMD mesh size)",
    )
    ap.add_argument(
        "--tick-interval", type=float, default=0.05,
        help="load-generator tick seconds",
    )
    ap.add_argument(
        "--recover", action="store_true",
        help="restart-recovery boot: replay the durable catalog, "
        "re-render every dataflow, wait for replicas to re-hydrate "
        "from persist, and print the recovery report before serving",
    )
    args = ap.parse_args()
    env = Environment(
        args.data_dir,
        pg_port=args.pg_port,
        http_port=args.http_port,
        n_replicas=args.replicas,
        workers=args.workers,
        tick_interval=args.tick_interval,
    )
    atexit.register(env.shutdown)
    if args.recover:
        import json as _json

        report = env.await_recovery()
        print("recovery: " + _json.dumps(report, sort_keys=True),
              flush=True)
    print(
        f"materialize_tpu listening: pgwire=127.0.0.1:{env.pg.port} "
        f"http=127.0.0.1:{env.http.port} data={args.data_dir}",
        flush=True,
    )
    try:
        signal.pause()
    except (KeyboardInterrupt, AttributeError):
        while True:
            _time.sleep(3600)


if __name__ == "__main__":
    main()
