"""Arrangements: sorted, consolidated columnar indexes resident in HBM.

Analog of differential arrangements/spines (reference:
doc/developer/arrangements.md; row-spine/src/lib.rs; shared via
TraceManager, compute/src/arrangement/manager.rs:33). Two forms:

- ``Arrangement``: a single fully-consolidated sorted run. Inserts
  merge-path + consolidate into a new run — O(state) per step. Used
  where operator state is output-sized (Reduce groups, distinct keys,
  TopK windows).

- ``Spine``: the amortized multi-run form for input-sized state (join
  arrangements, the output index): a geometric ladder of consolidated
  sorted runs plus, in append-slot ingest mode, a ring of per-step
  slot batches below run 0. Readers see the multiset sum of all runs
  and slots; a row may appear in several with cancelling diffs, which
  downstream consolidation resolves.

Order modes (round-5 redesign, PERF_NOTES.md): an arrangement is
sorted either in ``exact`` SQL-lane order (key columns then remaining
columns — required where readers exploit VALUE order inside a key
range: min/max, TopK) or in ``hash`` order (a 2-lane hash pair of the
key then of the full row). Hash order cuts sort operands and search
lanes from one-per-column to two, which is what lets sorts compile and
merges execute at state scale; EQUALITY remains exact everywhere
(consolidation compares adjacent rows exactly; a hash collision can
only make two different rows adjacent, never merge them).

Cached run lanes (round 6, ISSUE 5): a spine built with lane caching
carries each frozen run's ROW-STACKED sort lanes (``[cap, L]`` uint64)
in its state. Lanes are computed once when a run is (re)built at fold
time and from then on maintained by the merge's own row-gather
(ops/merge.merge_sorted_cached) and the consolidation's compaction
scatter (ops/consolidate.consolidate_sorted_cached) — the per-step
path never re-derives lanes from the columns of unchanged runs, which
was the bulk of the old per-step O(run0) work. Key-only searches slice
the static key-lane prefix of the same array.

Historical multiversion reads are deferred — with barrier-synchronous
micro-batch steps every reader sees the state exactly at the step
frontier, which matches the reference's behavior when logical compaction
keeps `since` at the frontier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..ops.consolidate import (
    consolidate,
    consolidate_sorted,
    consolidate_sorted_cached,
)
from ..ops.lanes import hash_pair, key_lane_width, key_lanes, stack_lanes
from ..ops.merge import merge_sorted, merge_sorted_cached
from ..ops.search import lex_searchsorted_2d
from ..ops.sort import apply_perm, sort_perm
from ..repr.batch import Batch, capacity_tier
from ..repr.schema import Schema


def device_nbytes(tree) -> int:
    """Total bytes of the DEVICE-resident array leaves of a pytree
    (host numpy mirrors excluded): shape * itemsize from the aval —
    pure metadata, never a device read or sync."""
    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        if isinstance(leaf, jax.Array):
            try:
                total += leaf.size * leaf.dtype.itemsize
            except (AttributeError, TypeError):
                pass
    return total


@jax.tree_util.register_pytree_node_class
@dataclass
class Arrangement:
    """A collection arranged (sorted) by a key-column prefix.

    batch: consolidated (no duplicate rows, nonzero diffs), sorted by
    the order mode's lanes. Times in the batch are all forwarded to
    the arrangement's logical `since` (full logical compaction), so
    `batch` is exactly the accumulated multiset.

    ``lanes2d`` is an ADVISORY cache of the batch's stacked sort lanes
    (``[cap, L]`` uint64): attached by Spine.runs() from the spine's
    lane cache, consumed by lookup_range, and deliberately NOT part of
    the pytree (an Arrangement crossing a jit boundary on its own
    simply drops the cache and recomputes)."""

    batch: Batch
    key: tuple  # static: key column indices
    order: str = "exact"  # static: "exact" | "hash"
    lanes2d: object = None  # advisory stacked sort-lane cache

    def tree_flatten(self):
        return (self.batch,), (self.key, self.order)

    @classmethod
    def tree_unflatten(cls, aux, children):
        key, order = aux
        return cls(children[0], key, order)

    @property
    def schema(self) -> Schema:
        return self.batch.schema

    @property
    def capacity(self) -> int:
        return self.batch.capacity

    def _rest(self) -> list:
        return [
            i for i in range(self.schema.arity) if i not in self.key
        ]

    def sort_lanes(self):
        """Lanes defining this arrangement's order.

        exact: key cols then all remaining cols (equal-key rows in
        deterministic SQL-lane order).
        hash: (key hash pair, full-row hash pair) — 4 lanes total."""
        rest = self._rest()
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(self.batch, self.key))
            if not rest:
                # Full-column key: the row hash IS the key hash (same
                # lane sequence) — don't mix the chains twice.
                return [kh1, kh2, kh1, kh2]
            rh1, rh2 = hash_pair(
                key_lanes(self.batch, list(self.key) + rest)
            )
            return [kh1, kh2, rh1, rh2]
        return key_lanes(self.batch, list(self.key) + rest)

    def sort_lanes_2d(self) -> jnp.ndarray:
        """Stacked ``[cap, L]`` sort lanes — the cached array when this
        view carries one, else computed from the columns."""
        if self.lanes2d is not None:
            return self.lanes2d
        return stack_lanes(self.sort_lanes())

    def key_lane_prefix(self) -> int:
        """Static width of the key-only prefix of the sort lanes."""
        if self.order == "hash":
            return 2
        return key_lane_width(self.schema, self.key)

    def key_only_lanes(self):
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(self.batch, self.key))
            return [kh1, kh2]
        return key_lanes(self.batch, list(self.key))

    def key_lanes_2d(self) -> jnp.ndarray:
        """Stacked key-only lanes: the prefix of the (possibly cached)
        sort lanes — except for the empty key, whose single constant
        lane is not a prefix of the full sort-lane sequence."""
        if not self.key:
            return jnp.zeros(
                (self.batch.capacity, 1), dtype=jnp.uint64
            )
        return self.sort_lanes_2d()[:, : self.key_lane_prefix()]

    def probe_lanes(self, batch: Batch, cols):
        """Lanes for probing THIS arrangement with `batch`'s `cols` —
        must match the arrangement's order mode."""
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(batch, cols))
            return [kh1, kh2]
        return key_lanes(batch, cols)

    @staticmethod
    def empty(
        schema: Schema, key, capacity: int = 256, order: str = "exact"
    ) -> "Arrangement":
        return Arrangement(
            Batch.empty(schema, capacity), tuple(key), order
        )

    def map_batches(self, fn) -> "Arrangement":
        """Rebuild with ``fn`` applied to the contained batch (shared
        shape-management protocol with Spine: replication, count
        reshaping, growth). Drops the advisory lane cache."""
        return Arrangement(fn(self.batch), self.key, self.order)


def run_sort_lanes(batch: Batch, key, order: str) -> jnp.ndarray:
    """Stacked sort lanes of a run batch — the lane-cache (re)build,
    used at fold/grow time, never on the per-step path for frozen
    runs."""
    return stack_lanes(Arrangement(batch, tuple(key), order).sort_lanes())


def arrange(
    batch: Batch, key, capacity: int | None = None, order: str = "exact"
) -> Arrangement:
    """Sort+consolidate a batch into an Arrangement (build from scratch).

    An explicit ``capacity`` snaps to the pow2 quantization menu
    (ISSUE 16, plan/decisions.quantize_cap): spine run capacities are
    part of every step program's tier vector, so off-menu sizes would
    mint program-bank keys no other DDL can share. Growth never
    shrinks: the snap rounds up, and ``with_capacity`` forbids
    shrinking below the batch anyway."""
    if capacity is not None:
        from ..plan.decisions import quantize_cap

        capacity = quantize_cap(capacity, minimum=batch.capacity)
    key = tuple(key)
    cons = consolidate(batch, include_time=False)
    # consolidate's output is in full-row HASH order; a hash-mode
    # arrangement whose key is every column IN SCHEMA ORDER is
    # therefore already sorted (its key hash is computed over the same
    # lane sequence as consolidate's row hash) — the common
    # output-index case skips its re-sort entirely. A PERMUTED
    # full-column key hashes a different lane order and must re-sort.
    if order == "hash" and key == tuple(range(batch.schema.arity)):
        sorted_batch = cons
    else:
        arr = Arrangement(cons, key, order)
        perm = sort_perm(arr.sort_lanes(), cons.count, cons.capacity)
        sorted_batch = apply_perm(cons, perm)
    if capacity is not None and capacity != sorted_batch.capacity:
        sorted_batch = sorted_batch.with_capacity(capacity)
    return Arrangement(sorted_batch, key, order)


def insert(
    arr: Arrangement, delta: Batch, out_capacity: int
) -> tuple[Arrangement, jnp.ndarray]:
    """Merge a delta batch into the arrangement: the spine 'merge' step.

    Returns (new_arrangement, overflowed). The caller picks `out_capacity`
    (a tier >= expected survivors); on overflow retry with a larger tier —
    the exert-proportionality analog is that we always fully compact.
    """
    d = arrange(delta, arr.key, capacity=None, order=arr.order)
    merged, overflow = merge_sorted(
        arr.batch,
        arr.sort_lanes_2d(),
        d.batch,
        d.sort_lanes_2d(),
        out_capacity,
    )
    # Merged runs may contain the same row twice (once per side); both
    # sides share the arrangement's order, so equal rows are adjacent
    # in the merge and duplicate summation needs NO sort
    # (consolidate_sorted's exact adjacent comparison).
    cons = consolidate_sorted(merged)
    return Arrangement(cons, arr.key, arr.order), overflow


def lookup_range(arr: Arrangement, probe_lanes) -> tuple:
    """For each probe key, the [lo, hi) row range of matching keys.
    `probe_lanes` must come from Arrangement.probe_lanes (same order
    mode) — a lane list or an already-stacked ``[n, L]`` array.

    Fused form (round 6): both sides travel row-stacked, so each
    binary-search iteration is ONE row-gather — and when the
    arrangement carries cached lanes (a frozen spine run), the probed
    lanes are never re-derived from its columns."""
    lanes_2d = arr.key_lanes_2d()
    query_2d = (
        probe_lanes
        if getattr(probe_lanes, "ndim", None) == 2
        else stack_lanes(probe_lanes)
    )
    lo = lex_searchsorted_2d(
        lanes_2d, arr.batch.count, query_2d, side="left"
    )
    hi = lex_searchsorted_2d(
        lanes_2d, arr.batch.count, query_2d, side="right"
    )
    return lo, hi


@jax.tree_util.register_pytree_node_class
@dataclass
class Spine:
    """Amortized MULTI-RUN arrangement: a geometric ladder of
    consolidated sorted runs, smallest first (``runs_b[0]`` absorbs
    folded deltas; ``runs_b[-1]`` is the base). Logical content is
    the multiset sum of all runs; each run is individually sorted by
    the order mode's lanes and consolidated, but the SAME row may
    appear in several runs — readers combine (probe every run; sum
    diffs downstream).

    The point (differential's geometric spine merges, re-cast for
    fixed XLA shapes): per-step insert cost is O(delta) in append-slot
    mode (O(runs_b[0]) in merge mode); level l is folded into level
    l+1 every ``ratio^l`` compaction ticks, so a row is merged
    O(levels) times over its lifetime and the per-step amortized merge
    cost is O(levels * delta) — NOT O(state). Two levels reproduce the
    round-3/4 base+tail form; the big output index runs 3-4 levels.
    """

    runs_b: tuple  # Batches, smallest-first
    key: tuple  # static: key column indices
    order: str = "exact"  # static: "exact" | "hash"
    # Optional APPEND-SLOT ingest ring (round-5 perf design): S
    # independently sorted slot batches below runs_b[0]. With slots,
    # insert_tail costs O(delta) — the arranged delta BECOMES the next
    # slot (one switch + pad; no merge into a big run per step) — and
    # the level-0 fold tree-merges the slots into runs_b[0] every
    # compact_every steps. `cursor` (device scalar) picks the slot.
    slots: tuple = ()
    cursor: object = None  # int32 scalar when slots != ()
    # Cached run lanes (round 6): stacked [cap_i, L] uint64 sort lanes
    # per run (and per ingest slot), () when caching is off. Computed
    # at fold time, carried through merges by the merge's own gather —
    # see the module docstring for the invariants.
    lanes: tuple = ()
    slot_lanes: tuple = ()

    def tree_flatten(self):
        children = [self.runs_b]
        if self.lanes:
            children.append(self.lanes)
        if self.slots:
            children.append(self.slots)
            if self.lanes:
                children.append(self.slot_lanes)
            children.append(self.cursor)
        return tuple(children), (
            self.key, self.order, bool(self.slots), bool(self.lanes),
        )

    @classmethod
    def tree_unflatten(cls, aux, children):
        key, order, has_slots, has_lanes = aux
        it = iter(children)
        runs_b = next(it)
        lanes = next(it) if has_lanes else ()
        slots, slot_lanes, cursor = (), (), None
        if has_slots:
            slots = next(it)
            if has_lanes:
                slot_lanes = next(it)
            cursor = next(it)
        return cls(
            runs_b, key, order, slots, cursor, lanes, slot_lanes
        )

    @property
    def levels(self) -> int:
        return len(self.runs_b)

    @property
    def base(self) -> Batch:
        return self.runs_b[-1]

    @property
    def tail(self) -> Batch:
        return self.runs_b[0]

    @property
    def schema(self) -> Schema:
        return self.base.schema

    @property
    def capacity(self) -> int:
        """Base-run capacity (the state-size tier)."""
        return self.base.capacity

    @property
    def tail_capacity(self) -> int:
        return self.tail.capacity

    def run_lanes_2d(self, i: int) -> jnp.ndarray:
        """Run i's stacked sort lanes: the cache when present, else
        derived from the run's columns (lane-cache-off compatibility)."""
        if self.lanes:
            return self.lanes[i]
        return run_sort_lanes(self.runs_b[i], self.key, self.order)

    def slot_lanes_2d(self, i: int) -> jnp.ndarray:
        if self.slot_lanes:
            return self.slot_lanes[i]
        return run_sort_lanes(self.slots[i], self.key, self.order)

    def with_run(
        self, i: int, batch: Batch, lanes: jnp.ndarray | None = None
    ) -> "Spine":
        """Replace run i. With lane caching on, ``lanes`` carries the
        new run's stacked sort lanes (folds pass the merge-carried
        array); None means the run's ROWS are unchanged in content
        (e.g. a count reset) and the cached array stays."""
        rs = list(self.runs_b)
        rs[i] = batch
        new_lanes = self.lanes
        if self.lanes:
            ls = list(self.lanes)
            if lanes is not None:
                ls[i] = lanes
            new_lanes = tuple(ls)
        return Spine(
            tuple(rs), self.key, self.order, self.slots, self.cursor,
            new_lanes, self.slot_lanes,
        )

    def with_cursor(self, cursor) -> "Spine":
        """Replace the slot cursor (shape management only — the SPMD
        layout carries it as a per-device ``[P]`` vector at the
        shard_map boundary and reshapes it to the per-worker scalar
        inside the step body; see ShardedDataflow)."""
        return Spine(
            self.runs_b, self.key, self.order, self.slots, cursor,
            self.lanes, self.slot_lanes,
        )

    def device_bytes(self) -> dict:
        """Device-resident bytes per spine component (ISSUE 12: the
        mz_arrangement_sizes byte columns): the run ladder, the
        append-slot ingest ring (+cursor), and the cached sort lanes.
        Pure metadata — shape*itemsize off the avals, no device read."""
        return {
            "runs": device_nbytes(self.runs_b),
            "slots": device_nbytes((self.slots, self.cursor)),
            "lanes": device_nbytes((self.lanes, self.slot_lanes)),
        }

    def runs(self) -> tuple:
        """Single-run Arrangement views for lookup/probe code (base
        first, then progressively smaller runs, then ingest slots),
        each carrying its cached lanes when the spine has them."""
        batches = tuple(reversed(self.runs_b)) + self.slots
        if self.lanes:
            lanes = tuple(reversed(self.lanes)) + self.slot_lanes
        else:
            lanes = (None,) * len(batches)
        return tuple(
            Arrangement(b, self.key, self.order, lanes2d=l)
            for b, l in zip(batches, lanes)
        )

    def map_batches(self, fn) -> "Spine":
        """Rebuild with ``fn`` applied to every run and slot batch. The
        lane cache survives shape-preserving maps (count reshapes, null
        canonicalization — lane values are a function of row content
        and schema only); a map that changes capacities (replication,
        growth) invalidates it, so the cache is dropped and the spine
        continues in lane-cache-off mode."""
        new_runs = tuple(fn(b) for b in self.runs_b)
        new_slots = tuple(fn(b) for b in self.slots)
        lanes, slot_lanes = self.lanes, self.slot_lanes
        if lanes and (
            any(
                nb.capacity != b.capacity
                for nb, b in zip(new_runs, self.runs_b)
            )
            or any(
                nb.capacity != b.capacity
                for nb, b in zip(new_slots, self.slots)
            )
        ):
            lanes, slot_lanes = (), ()
        return Spine(
            new_runs, self.key, self.order, new_slots, self.cursor,
            lanes, slot_lanes,
        )

    @staticmethod
    def empty(
        schema: Schema,
        key,
        capacity: int = 256,
        tail_capacity: int = 1024,
        order: str = "exact",
        levels: int = 2,
        ratio: int = 8,
        ingest_slots: int = 0,
        cache_lanes: bool | None = None,
    ) -> "Spine":
        """Capacities run geometrically from tail_capacity up, with the
        base pinned at ``capacity``. ``ingest_slots`` > 0 adds an
        append-slot ring of that many tail_capacity slots.
        ``cache_lanes`` None resolves the cached_run_lanes dyncfg."""
        from ..utils.dyncfg import CACHED_RUN_LANES, COMPUTE_CONFIGS

        if cache_lanes is None:
            cache_lanes = bool(CACHED_RUN_LANES(COMPUTE_CONFIGS))
        assert levels >= 2
        caps = [tail_capacity * (ratio**i) for i in range(levels - 1)]
        caps.append(capacity)  # base pinned exactly (callers may size
        # it below the mids deliberately to provoke overflow growth)
        key = tuple(key)
        runs = tuple(Batch.empty(schema, c) for c in caps)
        # Slots are null-canonicalized up front: they ride scan carries,
        # whose pytree structure must not change when an insert lands.
        slots = tuple(
            Batch.empty(schema, tail_capacity).canonicalize_nulls()
            for _ in range(ingest_slots)
        )
        cursor = (
            jnp.asarray(0, jnp.int32) if ingest_slots else None
        )
        lanes, slot_lanes = (), ()
        if cache_lanes:
            lanes = tuple(
                run_sort_lanes(b, key, order) for b in runs
            )
            slot_lanes = tuple(
                run_sort_lanes(s, key, order) for s in slots
            )
        return Spine(
            runs, key, order, slots, cursor, lanes, slot_lanes
        )


def _arrange_for_run(delta: Batch, key: tuple, order: str) -> Arrangement:
    """Arrange a delta for insertion into a SPINE RUN. Runs only need
    SORTEDNESS in the spine's order — a run may hold the same content
    at several times (the multiset-sum reader contract already allows
    a row in several runs; fold-time consolidate_sorted merges
    content-duplicates whenever runs combine). So a delta the step
    already content-hash-sorted ("hash_sorted": the step-level
    consolidate's output; "hash_consolidated": the presorted-producer
    guarantee) skips BOTH the sort and the content re-consolidation
    that the general arrange() pays — the second adjacent-compare
    chain per step in the old path (round-6 op census)."""
    if (
        order == "hash"
        and key == tuple(range(delta.schema.arity))
        and (
            "hash_sorted" in delta.hints
            or "hash_consolidated" in delta.hints
        )
    ):
        return Arrangement(delta, key, order)
    return arrange(delta, key, capacity=None, order=order)


def insert_tail(spine: Spine, delta: Batch) -> tuple[Spine, jnp.ndarray]:
    """Absorb a delta batch — the hot-path insert.

    With an append-slot ring: the arranged delta BECOMES slot
    ``cursor`` (O(delta): a pad + one lax.switch placement; no merge
    touches any run). Without slots: merge into the smallest run
    (O(runs_b[0] capacity)). Every other run passes through untouched
    (no copy: same buffers).

    Returns (new_spine, overflowed). On overflow the host grows the
    slot/tail tier (or compacts more often) and replays."""
    d = _arrange_for_run(delta, spine.key, spine.order)
    if spine.slots:
        slot_cap = spine.slots[0].capacity
        nb = d.batch
        overflow = nb.count > slot_cap
        if nb.capacity < slot_cap:
            nb = nb.with_capacity(slot_cap)
        elif nb.capacity > slot_cap:
            from ..ops.sort import shrink

            nb, sovf = shrink(nb, slot_cap)
            overflow = jnp.logical_or(overflow, sovf)
        # Uniform slot pytree structure: canonical null masks, no
        # producer hints (hints are aux metadata; a hinted batch would
        # differ structurally from the empty slots in switch branches
        # and scan carries).
        nb = nb.canonicalize_nulls().replace(hints=())
        caching = bool(spine.lanes)
        nb_lanes = (
            run_sort_lanes(nb, spine.key, spine.order)
            if caching
            else None
        )
        s = len(spine.slots)
        idx = spine.cursor % s

        def place(k):
            def f():
                out = list(
                    sl.canonicalize_nulls() for sl in spine.slots
                )
                out[k] = nb
                if not caching:
                    return tuple(out)
                ls = list(spine.slot_lanes)
                ls[k] = nb_lanes
                return tuple(out), tuple(ls)

            return f

        placed = jax.lax.switch(idx, [place(k) for k in range(s)])
        if caching:
            new_slots, new_slot_lanes = placed
        else:
            new_slots, new_slot_lanes = placed, ()
        new = Spine(
            spine.runs_b, spine.key, spine.order, new_slots,
            spine.cursor + 1, spine.lanes, new_slot_lanes,
        )
        return new, overflow
    tail = spine.tail
    merged, merged_lanes, overflow = merge_sorted_cached(
        tail,
        spine.run_lanes_2d(0),
        d.batch,
        d.sort_lanes_2d(),
        tail.capacity,
    )
    cons, cons_lanes = consolidate_sorted_cached(merged, merged_lanes)
    return spine.with_run(0, cons, cons_lanes), overflow


def _tree_merge_cached(parts: list, out_cap_final: int | None = None):
    """Pairwise merge a list of (sorted batch, stacked lanes) pairs
    into one (capacity = sum; never overflows). Lanes ride the merge
    gathers — no re-hashing at any level of the tree."""
    while len(parts) > 1:
        nxt = []
        for i in range(0, len(parts) - 1, 2):
            (a, al), (b, bl) = parts[i], parts[i + 1]
            m, ml, _ = merge_sorted_cached(
                a, al, b, bl, a.capacity + b.capacity
            )
            nxt.append((m, ml))
        if len(parts) % 2:
            nxt.append(parts[-1])
        parts = nxt
    return parts[0]


def flush_slots(spine: Spine) -> tuple[Spine, jnp.ndarray]:
    """Fold the append-slot ring into runs_b[0]: tree-merge the slots,
    merge the result into run 0, clear the ring. Returns (new_spine,
    run-0 overflow)."""
    if not spine.slots:
        return spine, jnp.asarray(False)
    merged_slots, slot_merged_lanes = _tree_merge_cached(
        [
            (s, spine.slot_lanes_2d(i))
            for i, s in enumerate(spine.slots)
        ]
    )
    r0 = spine.runs_b[0]
    merged, merged_lanes, overflow = merge_sorted_cached(
        r0, spine.run_lanes_2d(0),
        merged_slots, slot_merged_lanes,
        r0.capacity,
    )
    cons, cons_lanes = consolidate_sorted_cached(merged, merged_lanes)
    cleared = tuple(
        s.replace(count=jnp.zeros_like(s.count)) for s in spine.slots
    )
    new_lanes = spine.lanes
    if new_lanes:
        new_lanes = (cons_lanes,) + tuple(spine.lanes[1:])
    return (
        Spine(
            (cons,) + spine.runs_b[1:], spine.key, spine.order,
            cleared, jnp.zeros_like(spine.cursor),
            new_lanes, spine.slot_lanes,
        ),
        overflow,
    )


def compact_depth(spine: Spine) -> int:
    """Number of fold levels this spine has (max compact_level index
    is compact_depth - 1). A slotted spine has one extra level: level
    0 is the slot flush; level l>0 folds run l-1 into run l."""
    return spine.levels - 1 + (1 if spine.slots else 0)


def compact_level(spine: Spine, level: int) -> tuple[Spine, jnp.ndarray]:
    """Fold one ladder level. Slotless: run `level` -> run `level+1`.
    Slotted: level 0 flushes the append-slot ring into run 0; level
    l>0 folds run l-1 into run l. Sort-free: runs share the spine's
    order, so the merge is a binary search + one row-gather per dtype
    family (lanes included — the target run's cached lanes come out of
    the same gather), and duplicate summation is the exact adjacent
    comparison. Returns (new_spine, overflowed) where the flag is the
    TARGET run's capacity overflow."""
    if spine.slots:
        if level == 0:
            return flush_slots(spine)
        lo_i, hi_i = level - 1, level
    else:
        lo_i, hi_i = level, level + 1
    lo, hi = spine.runs_b[lo_i], spine.runs_b[hi_i]
    merged, merged_lanes, overflow = merge_sorted_cached(
        hi,
        spine.run_lanes_2d(hi_i),
        lo,
        spine.run_lanes_2d(lo_i),
        hi.capacity,
    )
    cons, cons_lanes = consolidate_sorted_cached(merged, merged_lanes)
    out = spine.with_run(hi_i, cons, cons_lanes)
    out = out.with_run(
        lo_i, lo.replace(count=jnp.zeros_like(lo.count))
    )
    return out, overflow


_CLONE_JITS: dict = {}


def clone_state_tree(tree):
    """Deep-copy every device leaf of a state pytree (arrangements,
    spines, batches, scalars) to FRESH buffers in ONE fused program.

    Donation safety (the pipelined span executor's checkpoint
    contract): a span program compiled with ``donate_argnums`` hands
    its carry buffers to XLA — after dispatch they are dead and must
    never be read again. The rollback checkpoint therefore cannot hold
    references into the carry; it holds this clone instead. jit
    outputs never alias un-donated inputs, so every returned leaf is a
    fresh buffer."""
    from ..utils.compile_ledger import ledger_jit

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    jitfn = _CLONE_JITS.get(len(leaves))
    if jitfn is None:
        jitfn = ledger_jit(
            jax.jit(lambda *ls: tuple(jnp.copy(l) for l in ls)),
            "clone", "spine", f"clone:{len(leaves)}",
        )
        _CLONE_JITS[len(leaves)] = jitfn
    return jax.tree_util.tree_unflatten(treedef, jitfn(*leaves))


def compact_spine(spine: Spine):
    """Full cascade: fold every slot and run into the base (peeks and
    snapshots read the base as THE consolidated state). Cascades
    bottom-up so the base absorbs everything. Returns (new_spine,
    overflow flags [compact_depth], one per target run, smallest
    target first)."""
    flags = []
    for level in range(compact_depth(spine)):
        spine, ovf = compact_level(spine, level)
        flags.append(ovf)
    return spine, jnp.stack(flags)
