"""Arrangements: sorted, consolidated columnar indexes resident in HBM.

Analog of differential arrangements/spines (reference:
doc/developer/arrangements.md; row-spine/src/lib.rs; shared via
TraceManager, compute/src/arrangement/manager.rs:33). Two forms:

- ``Arrangement``: a single fully-consolidated sorted run. Inserts
  merge-path + consolidate into a new run — O(state) per step. Used
  where operator state is output-sized (Reduce groups, distinct keys,
  TopK windows).

- ``Spine``: the amortized two-run form for input-sized state (join
  arrangements, the output index). Per-step inserts touch only the
  small ``tail`` run (O(tail)); the host periodically dispatches a
  separate ``compact_spine`` program that merges the tail into the
  large ``base`` run — the analog of differential's amortized spine
  merges (row-spine/src/lib.rs:10-14, arrangement_exert_proportionality
  at cluster-client/src/client.rs:26-34). Readers see base ⊎ tail
  (multiset sum): lookups probe both runs; a row may appear in both
  with cancelling diffs, which downstream consolidation resolves.

Order modes (round-5 redesign, PERF_NOTES.md): an arrangement is
sorted either in ``exact`` SQL-lane order (key columns then remaining
columns — required where readers exploit VALUE order inside a key
range: min/max, TopK) or in ``hash`` order (a 2-lane hash pair of the
key then of the full row). Hash order cuts sort operands and search
lanes from one-per-column to two, which is what lets sorts compile and
merges execute at state scale; EQUALITY remains exact everywhere
(consolidation compares full lanes on adjacent rows; a hash collision
can only make two different rows adjacent, never merge them).

Historical multiversion reads are deferred — with barrier-synchronous
micro-batch steps every reader sees the state exactly at the step
frontier, which matches the reference's behavior when logical compaction
keeps `since` at the frontier.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import jax
import jax.numpy as jnp

from ..ops.consolidate import consolidate, consolidate_sorted
from ..ops.lanes import hash_pair, key_lanes
from ..ops.merge import merge_sorted
from ..ops.search import lex_searchsorted
from ..ops.sort import apply_perm, sort_perm
from ..repr.batch import Batch, capacity_tier
from ..repr.schema import Schema


@jax.tree_util.register_pytree_node_class
@dataclass
class Arrangement:
    """A collection arranged (sorted) by a key-column prefix.

    batch: consolidated (no duplicate rows, nonzero diffs), sorted by
    the order mode's lanes. Times in the batch are all forwarded to
    the arrangement's logical `since` (full logical compaction), so
    `batch` is exactly the accumulated multiset.
    """

    batch: Batch
    key: tuple  # static: key column indices
    order: str = "exact"  # static: "exact" | "hash"

    def tree_flatten(self):
        return (self.batch,), (self.key, self.order)

    @classmethod
    def tree_unflatten(cls, aux, children):
        key, order = aux
        return cls(children[0], key, order)

    @property
    def schema(self) -> Schema:
        return self.batch.schema

    @property
    def capacity(self) -> int:
        return self.batch.capacity

    def sort_lanes(self):
        """Lanes defining this arrangement's order.

        exact: key cols then all remaining cols (equal-key rows in
        deterministic SQL-lane order).
        hash: (key hash pair, full-row hash pair) — 4 lanes total."""
        rest = [
            i for i in range(self.schema.arity) if i not in self.key
        ]
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(self.batch, self.key))
            rh1, rh2 = hash_pair(
                key_lanes(self.batch, list(self.key) + rest)
            )
            return [kh1, kh2, rh1, rh2]
        return key_lanes(self.batch, list(self.key) + rest)

    def key_only_lanes(self):
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(self.batch, self.key))
            return [kh1, kh2]
        return key_lanes(self.batch, list(self.key))

    def probe_lanes(self, batch: Batch, cols):
        """Lanes for probing THIS arrangement with `batch`'s `cols` —
        must match the arrangement's order mode."""
        if self.order == "hash":
            kh1, kh2 = hash_pair(key_lanes(batch, cols))
            return [kh1, kh2]
        return key_lanes(batch, cols)

    @staticmethod
    def empty(
        schema: Schema, key, capacity: int = 256, order: str = "exact"
    ) -> "Arrangement":
        return Arrangement(
            Batch.empty(schema, capacity), tuple(key), order
        )

    def map_batches(self, fn) -> "Arrangement":
        """Rebuild with ``fn`` applied to the contained batch (shared
        shape-management protocol with Spine: replication, count
        reshaping, growth)."""
        return Arrangement(fn(self.batch), self.key, self.order)


def arrange(
    batch: Batch, key, capacity: int | None = None, order: str = "exact"
) -> Arrangement:
    """Sort+consolidate a batch into an Arrangement (build from scratch)."""
    key = tuple(key)
    cons = consolidate(batch, include_time=False)
    # consolidate's output is in full-row HASH order; a hash-mode
    # arrangement whose key is every column IN SCHEMA ORDER is
    # therefore already sorted (its key hash is computed over the same
    # lane sequence as consolidate's row hash) — the common
    # output-index case skips its re-sort entirely. A PERMUTED
    # full-column key hashes a different lane order and must re-sort.
    if order == "hash" and key == tuple(range(batch.schema.arity)):
        sorted_batch = cons
    else:
        arr = Arrangement(cons, key, order)
        perm = sort_perm(arr.sort_lanes(), cons.count, cons.capacity)
        sorted_batch = apply_perm(cons, perm)
    if capacity is not None and capacity != sorted_batch.capacity:
        sorted_batch = sorted_batch.with_capacity(capacity)
    return Arrangement(sorted_batch, key, order)


def insert(
    arr: Arrangement, delta: Batch, out_capacity: int
) -> tuple[Arrangement, jnp.ndarray]:
    """Merge a delta batch into the arrangement: the spine 'merge' step.

    Returns (new_arrangement, overflowed). The caller picks `out_capacity`
    (a tier >= expected survivors); on overflow retry with a larger tier —
    the exert-proportionality analog is that we always fully compact.
    """
    d = arrange(delta, arr.key, capacity=None, order=arr.order)
    merged, overflow = merge_sorted(
        arr.batch,
        arr.sort_lanes(),
        d.batch,
        d.sort_lanes(),
        out_capacity,
    )
    # Merged runs may contain the same row twice (once per side); both
    # sides share the arrangement's order, so equal rows are adjacent
    # in the merge and duplicate summation needs NO sort
    # (consolidate_sorted's exact adjacent comparison).
    cons = consolidate_sorted(merged)
    return Arrangement(cons, arr.key, arr.order), overflow


def lookup_range(arr: Arrangement, probe_lanes) -> tuple:
    """For each probe key, the [lo, hi) row range of matching keys.
    `probe_lanes` must come from Arrangement.probe_lanes (same order
    mode)."""
    lanes = arr.key_only_lanes()
    lo = lex_searchsorted(lanes, arr.batch.count, probe_lanes, side="left")
    hi = lex_searchsorted(lanes, arr.batch.count, probe_lanes, side="right")
    return lo, hi


@jax.tree_util.register_pytree_node_class
@dataclass
class Spine:
    """Amortized MULTI-RUN arrangement: a geometric ladder of
    consolidated sorted runs, smallest first (``runs_b[0]`` absorbs
    per-step deltas; ``runs_b[-1]`` is the base). Logical content is
    the multiset sum of all runs; each run is individually sorted by
    the order mode's lanes and consolidated, but the SAME row may
    appear in several runs — readers combine (probe every run; sum
    diffs downstream).

    The point (differential's geometric spine merges, re-cast for
    fixed XLA shapes): per-step insert cost is O(runs_b[0] capacity);
    level l is folded into level l+1 every ``ratio^l`` compaction
    ticks, so a row is merged O(levels) times over its lifetime and
    the per-step amortized merge cost is O(levels * delta) — NOT
    O(state). Two levels reproduce the round-3/4 base+tail form; the
    big output index runs 3-4 levels.
    """

    runs_b: tuple  # Batches, smallest-first
    key: tuple  # static: key column indices
    order: str = "exact"  # static: "exact" | "hash"
    # Optional APPEND-SLOT ingest ring (round-5 perf design): S
    # independently sorted slot batches below runs_b[0]. With slots,
    # insert_tail costs O(delta) — the arranged delta BECOMES the next
    # slot (one switch + pad; no merge into a big run per step) — and
    # the level-0 fold tree-merges the slots into runs_b[0] every
    # compact_every steps. `cursor` (device scalar) picks the slot.
    slots: tuple = ()
    cursor: object = None  # int32 scalar when slots != ()

    def tree_flatten(self):
        if self.slots:
            return (self.runs_b, self.slots, self.cursor), (
                self.key, self.order, True,
            )
        return (self.runs_b,), (self.key, self.order, False)

    @classmethod
    def tree_unflatten(cls, aux, children):
        key, order, has_slots = aux
        if has_slots:
            return cls(children[0], key, order, children[1], children[2])
        return cls(children[0], key, order)

    @property
    def levels(self) -> int:
        return len(self.runs_b)

    @property
    def base(self) -> Batch:
        return self.runs_b[-1]

    @property
    def tail(self) -> Batch:
        return self.runs_b[0]

    @property
    def schema(self) -> Schema:
        return self.base.schema

    @property
    def capacity(self) -> int:
        """Base-run capacity (the state-size tier)."""
        return self.base.capacity

    @property
    def tail_capacity(self) -> int:
        return self.tail.capacity

    def with_run(self, i: int, batch: Batch) -> "Spine":
        rs = list(self.runs_b)
        rs[i] = batch
        return Spine(
            tuple(rs), self.key, self.order, self.slots, self.cursor
        )

    def runs(self) -> tuple:
        """Single-run Arrangement views for lookup/probe code (base
        first, then progressively smaller runs, then ingest slots)."""
        return tuple(
            Arrangement(b, self.key, self.order)
            for b in tuple(reversed(self.runs_b)) + self.slots
        )

    def map_batches(self, fn) -> "Spine":
        return Spine(
            tuple(fn(b) for b in self.runs_b),
            self.key,
            self.order,
            tuple(fn(b) for b in self.slots),
            self.cursor,
        )

    @staticmethod
    def empty(
        schema: Schema,
        key,
        capacity: int = 256,
        tail_capacity: int = 1024,
        order: str = "exact",
        levels: int = 2,
        ratio: int = 8,
        ingest_slots: int = 0,
    ) -> "Spine":
        """Capacities run geometrically from tail_capacity up, with the
        base pinned at ``capacity``. ``ingest_slots`` > 0 adds an
        append-slot ring of that many tail_capacity slots."""
        assert levels >= 2
        caps = [tail_capacity * (ratio**i) for i in range(levels - 1)]
        caps.append(capacity)  # base pinned exactly (callers may size
        # it below the mids deliberately to provoke overflow growth)
        # Slots are null-canonicalized up front: they ride scan carries,
        # whose pytree structure must not change when an insert lands.
        slots = tuple(
            Batch.empty(schema, tail_capacity).canonicalize_nulls()
            for _ in range(ingest_slots)
        )
        cursor = (
            jnp.asarray(0, jnp.int32) if ingest_slots else None
        )
        return Spine(
            tuple(Batch.empty(schema, c) for c in caps),
            tuple(key),
            order,
            slots,
            cursor,
        )


def insert_tail(spine: Spine, delta: Batch) -> tuple[Spine, jnp.ndarray]:
    """Absorb a delta batch — the hot-path insert.

    With an append-slot ring: the arranged delta BECOMES slot
    ``cursor`` (O(delta): a pad + one lax.switch placement; no merge
    touches any run). Without slots: merge into the smallest run
    (O(runs_b[0] capacity)). Every other run passes through untouched
    (no copy: same buffers).

    Returns (new_spine, overflowed). On overflow the host grows the
    slot/tail tier (or compacts more often) and replays."""
    d = arrange(delta, spine.key, capacity=None, order=spine.order)
    if spine.slots:
        slot_cap = spine.slots[0].capacity
        nb = d.batch
        overflow = nb.count > slot_cap
        if nb.capacity < slot_cap:
            nb = nb.with_capacity(slot_cap)
        elif nb.capacity > slot_cap:
            from ..ops.sort import shrink

            nb, sovf = shrink(nb, slot_cap)
            overflow = jnp.logical_or(overflow, sovf)
        # Uniform slot pytree structure: canonical null masks, no
        # producer hints (hints are aux metadata; a hinted batch would
        # differ structurally from the empty slots in switch branches
        # and scan carries).
        nb = nb.canonicalize_nulls().replace(hints=())
        s = len(spine.slots)
        idx = spine.cursor % s

        def place(k):
            def f():
                out = list(
                    sl.canonicalize_nulls() for sl in spine.slots
                )
                out[k] = nb
                return tuple(out)

            return f

        new_slots = jax.lax.switch(
            idx, [place(k) for k in range(s)]
        )
        new = Spine(
            spine.runs_b, spine.key, spine.order, new_slots,
            spine.cursor + 1,
        )
        return new, overflow
    tail = spine.tail
    tail_arr = Arrangement(tail, spine.key, spine.order)
    merged, overflow = merge_sorted(
        tail,
        tail_arr.sort_lanes(),
        d.batch,
        d.sort_lanes(),
        tail.capacity,
    )
    cons = consolidate_sorted(merged)
    return spine.with_run(0, cons), overflow


def _tree_merge(batches: list, key, order) -> Batch:
    """Pairwise merge a list of sorted batches into one sorted batch
    (capacity = sum; never overflows)."""
    while len(batches) > 1:
        nxt = []
        for i in range(0, len(batches) - 1, 2):
            a, b = batches[i], batches[i + 1]
            aa = Arrangement(a, key, order)
            ba = Arrangement(b, key, order)
            m, _ = merge_sorted(
                a, aa.sort_lanes(), b, ba.sort_lanes(),
                a.capacity + b.capacity,
            )
            nxt.append(m)
        if len(batches) % 2:
            nxt.append(batches[-1])
        batches = nxt
    return batches[0]


def flush_slots(spine: Spine) -> tuple[Spine, jnp.ndarray]:
    """Fold the append-slot ring into runs_b[0]: tree-merge the slots,
    merge the result into run 0, clear the ring. Returns (new_spine,
    run-0 overflow)."""
    if not spine.slots:
        return spine, jnp.asarray(False)
    merged_slots = _tree_merge(
        list(spine.slots), spine.key, spine.order
    )
    r0 = spine.runs_b[0]
    r0_arr = Arrangement(r0, spine.key, spine.order)
    m_arr = Arrangement(merged_slots, spine.key, spine.order)
    merged, overflow = merge_sorted(
        r0, r0_arr.sort_lanes(),
        merged_slots, m_arr.sort_lanes(),
        r0.capacity,
    )
    cons = consolidate_sorted(merged)
    cleared = tuple(
        s.replace(count=jnp.zeros_like(s.count)) for s in spine.slots
    )
    return (
        Spine(
            (cons,) + spine.runs_b[1:], spine.key, spine.order,
            cleared, jnp.zeros_like(spine.cursor),
        ),
        overflow,
    )


def compact_depth(spine: Spine) -> int:
    """Number of fold levels this spine has (max compact_level index
    is compact_depth - 1). A slotted spine has one extra level: level
    0 is the slot flush; level l>0 folds run l-1 into run l."""
    return spine.levels - 1 + (1 if spine.slots else 0)


def compact_level(spine: Spine, level: int) -> tuple[Spine, jnp.ndarray]:
    """Fold one ladder level. Slotless: run `level` -> run `level+1`.
    Slotted: level 0 flushes the append-slot ring into run 0; level
    l>0 folds run l-1 into run l. Sort-free: runs share the spine's
    order, so the merge is a binary search + one row-gather per dtype
    family, and duplicate summation is the exact adjacent comparison.
    Returns (new_spine, overflowed) where the flag is the TARGET run's
    capacity overflow."""
    if spine.slots:
        if level == 0:
            return flush_slots(spine)
        lo_i, hi_i = level - 1, level
    else:
        lo_i, hi_i = level, level + 1
    lo, hi = spine.runs_b[lo_i], spine.runs_b[hi_i]
    lo_arr = Arrangement(lo, spine.key, spine.order)
    hi_arr = Arrangement(hi, spine.key, spine.order)
    merged, overflow = merge_sorted(
        hi,
        hi_arr.sort_lanes(),
        lo,
        lo_arr.sort_lanes(),
        hi.capacity,
    )
    cons = consolidate_sorted(merged)
    out = spine.with_run(hi_i, cons)
    out = out.with_run(
        lo_i, lo.replace(count=jnp.zeros_like(lo.count))
    )
    return out, overflow


def compact_spine(spine: Spine):
    """Full cascade: fold every slot and run into the base (peeks and
    snapshots read the base as THE consolidated state). Cascades
    bottom-up so the base absorbs everything. Returns (new_spine,
    overflow flags [compact_depth], one per target run, smallest
    target first)."""
    flags = []
    for level in range(compact_depth(spine)):
        spine, ovf = compact_level(spine, level)
        flags.append(ovf)
    return spine, jnp.stack(flags)
