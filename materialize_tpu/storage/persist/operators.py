"""Dataflow <-> shard bridges: shard_source and persist_sink.

Analog of ``storage-operators/src/persist_source.rs`` (shard -> dataflow
import, consumed at ``compute/src/render.rs:291``) and the MV persist
sink (``compute/src/sink/materialized_view.rs``): a ``MaintainedView``
reads update chunks from input shards, advances the dataflow one
micro-batch step per chunk, and compare-and-appends the output delta to
the view's shard. Resume is the reference's model exactly (SURVEY.md §5
checkpoint/resume): NO operator-state checkpoint — on restart the
dataflow re-renders and re-hydrates from input-shard snapshots at the
output shard's upper.
"""

from __future__ import annotations

import numpy as np

from ...render.dataflow import Dataflow
from ...repr.batch import Batch, capacity_tier
from ...repr.schema import Schema
from .client import PersistClient, ReadHandle, WriteHandle


def updates_to_batch(
    schema: Schema, cols, nulls, time, diff, as_of: int,
    capacity: int | None = None,
) -> Batch:
    """Host update arrays -> device Batch with times forwarded to as_of
    (the step processes one virtual timestamp; logical compaction)."""
    n = len(diff)
    return Batch.from_numpy(
        schema,
        cols,
        np.full(n, as_of, np.uint64),
        diff,
        capacity=capacity,
        nulls=nulls,
    )


class ShardSource:
    """Import one shard into a dataflow: snapshot + listen chunks
    (persist_source analog)."""

    def __init__(self, reader: ReadHandle, schema: Schema):
        self.reader = reader
        self.schema = schema
        self.frontier: int | None = None  # set by snapshot()/resume_at()

    def snapshot(self, as_of: int) -> "tuple[Batch, int]":
        _sch, cols, nulls, time, diff = self.reader.snapshot(as_of)
        self.frontier = as_of + 1
        return (
            updates_to_batch(self.schema, cols, nulls, time, diff, as_of),
            as_of,
        )

    def resume_at(self, frontier: int) -> None:
        self.frontier = frontier

    def poll(self, timeout: float = 5.0):
        """Next chunk beyond the frontier, forwarded to the chunk's last
        time. Returns (batch, chunk_time, new_frontier) or None."""
        assert self.frontier is not None, "snapshot()/resume_at() first"
        got = self.reader.listen_next(self.frontier, timeout)
        if got is None:
            return None
        (_sch, cols, nulls, time, diff), new_upper = got
        t = new_upper - 1
        batch = updates_to_batch(self.schema, cols, nulls, time, diff, t)
        self.frontier = new_upper
        return batch, t, new_upper

    def fetch_to(self, target: int) -> Batch:
        """Chunk [frontier, target), forwarded to target-1. Caller must
        have confirmed target <= shard upper."""
        assert self.frontier is not None and target > self.frontier - 1
        _sch, cols, nulls, time, diff = self.reader.fetch(
            self.frontier, target
        )
        batch = updates_to_batch(
            self.schema, cols, nulls, time, diff, target - 1
        )
        self.frontier = target
        return batch


class MaintainedView:
    """An installed dataflow maintained between shards: sources -> step ->
    output shard. One shard per source name; the output shard's upper is
    the view's write frontier (sink/materialized_view_v2.rs analog —
    self-correcting via compare-and-append: on restart a partially
    written step is retried exactly because the upper didn't advance)."""

    def __init__(
        self,
        client: PersistClient,
        dataflow: Dataflow,
        source_shards: dict[str, tuple[str, Schema]],
        output_shard: str,
    ):
        self.client = client
        self.df = dataflow
        self.sources = {
            name: ShardSource(client.open_reader(shard), schema)
            for name, (shard, schema) in source_shards.items()
        }
        self.writer: WriteHandle = client.open_writer(
            output_shard, dataflow.out_schema
        )
        self.hydrate()

    # -- rehydration -------------------------------------------------------
    def hydrate(self) -> None:
        """Bring the dataflow to the output shard's upper: snapshot every
        input at as_of = upper-1 (or the inputs' max since if the output
        is empty), run one step, append the initial output if needed."""
        out_upper = self.writer.upper
        if out_upper == 0:
            as_of = max(
                s.reader.machine.reload().since
                for s in self.sources.values()
            )
            # Inputs must be readable at as_of; wait for uppers to pass
            # (as-of selection, compute-client/src/as_of_selection.rs).
            for s in self.sources.values():
                if s.reader.wait_for_upper(as_of, timeout=30.0) is None:
                    raise TimeoutError(
                        "input shard upper never passed hydration as_of "
                        f"{as_of}"
                    )
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(as_of)
                inputs[name] = b
            self.df.time = as_of
            self.df.step(inputs)
            out = self._output_snapshot_delta()
            self._append(out, 0, as_of + 1, as_of)
        else:
            as_of = out_upper - 1
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(as_of)
                inputs[name] = b
            self.df.time = as_of
            self.df.step(inputs)  # rebuild arrangements; output delta
            # already durable — do NOT append.

    def _output_snapshot_delta(self) -> Batch:
        # After hydration the output arrangement IS the initial delta.
        return self.df.output.batch

    def _append(self, batch: Batch, lower: int, upper: int, t: int) -> None:
        cols = batch.to_columns()
        data_cols, _time, diff = cols[:-2], cols[-2], cols[-1]
        n = len(diff)
        nulls = [
            None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
        ]
        self.writer.compare_and_append(
            data_cols, nulls, np.full(n, t, np.uint64), diff, lower, upper
        )

    # -- steady state ------------------------------------------------------
    def step(self, timeout: float = 5.0) -> bool:
        """Process all sources' updates up to a COMMON target frontier
        (min over input uppers beyond our own): the micro-batch analog of
        frontier-joined progress. Returns False if the inputs did not
        advance within the timeout."""
        lower = self.writer.upper
        target = None
        for s in self.sources.values():
            upper = s.reader.wait_for_upper(lower, timeout)  # > lower
            if upper is None:
                return False
            target = upper if target is None else min(target, upper)
        polled = {
            name: s.fetch_to(target) for name, s in self.sources.items()
        }
        t = target - 1
        self.df.time = t
        out = self.df.step(polled)
        self._append(out, lower, target, t)
        return True

    def run_until(self, frontier: int, timeout: float = 30.0) -> None:
        """Advance until the output upper reaches ``frontier``."""
        while self.writer.upper < frontier:
            if not self.step(timeout):
                raise TimeoutError(
                    f"sources stalled below frontier {frontier}"
                )

    def peek(self) -> list[tuple]:
        return self.df.peek()
