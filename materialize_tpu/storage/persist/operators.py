"""Dataflow <-> shard bridges: shard_source and persist_sink.

Analog of ``storage-operators/src/persist_source.rs`` (shard -> dataflow
import, consumed at ``compute/src/render.rs:291``) and the MV persist
sink (``compute/src/sink/materialized_view.rs``): a ``MaintainedView``
reads update chunks from input shards, advances the dataflow one
micro-batch step per chunk, and compare-and-appends the output delta to
the view's shard. Resume is the reference's model exactly (SURVEY.md §5
checkpoint/resume): NO operator-state checkpoint — on restart the
dataflow re-renders and re-hydrates from input-shard snapshots at the
output shard's upper.
"""

from __future__ import annotations

import time as _time

import numpy as np

from ...render.dataflow import Dataflow
from ...repr.batch import Batch, capacity_tier
from ...repr.schema import Schema
from .client import PersistClient, ReadHandle, WriteHandle
from .machine import Fenced, UpperMismatch


class SinkConflict(RuntimeError):
    """The durable sink diverged from this replica's chunking (hydration
    race): the view must be rebuilt from the durable shard."""


def updates_to_batch(
    schema: Schema, cols, nulls, time, diff, as_of: int,
    capacity: int | None = None,
) -> Batch:
    """Host update arrays -> device Batch with times forwarded to as_of
    (the step processes one virtual timestamp; logical compaction).

    A fetch that covered only empty upper-advances decodes to ZERO
    column arrays (there were no parts); the batch must still carry the
    declared schema's arity or downstream operators index out of range."""
    n = len(diff)
    if not cols and schema.arity:
        cols = [np.zeros(0, c.dtype) for c in schema.columns]
        nulls = [None] * schema.arity
    return Batch.from_numpy(
        schema,
        cols,
        np.full(n, as_of, np.uint64),
        diff,
        capacity=capacity,
        nulls=nulls,
    )


class ShardSource:
    """Import one shard into a dataflow: snapshot + listen chunks
    (persist_source analog)."""

    def __init__(self, reader: ReadHandle, schema: Schema):
        self.reader = reader
        self.schema = schema
        self.frontier: int | None = None  # set by snapshot()/resume_at()

    def snapshot(self, as_of: int) -> "tuple[Batch, int]":
        _sch, cols, nulls, time, diff = self.reader.snapshot(as_of)
        self.frontier = as_of + 1
        return (
            updates_to_batch(self.schema, cols, nulls, time, diff, as_of),
            as_of,
        )

    def resume_at(self, frontier: int) -> None:
        self.frontier = frontier

    def poll(self, timeout: float = 5.0):
        """Next chunk beyond the frontier, forwarded to the chunk's last
        time. Returns (batch, chunk_time, new_frontier) or None."""
        assert self.frontier is not None, "snapshot()/resume_at() first"
        got = self.reader.listen_next(self.frontier, timeout)
        if got is None:
            return None
        (_sch, cols, nulls, time, diff), new_upper = got
        t = new_upper - 1
        batch = updates_to_batch(self.schema, cols, nulls, time, diff, t)
        self.frontier = new_upper
        return batch, t, new_upper

    def fetch_to(self, target: int) -> Batch:
        """Chunk [frontier, target), forwarded to target-1. Caller must
        have confirmed target <= shard upper."""
        assert self.frontier is not None and target > self.frontier - 1
        _sch, cols, nulls, time, diff = self.reader.fetch(
            self.frontier, target
        )
        batch = updates_to_batch(
            self.schema, cols, nulls, time, diff, target - 1
        )
        self.frontier = target
        return batch


class MaintainedView:
    """An installed dataflow maintained between shards: sources -> step ->
    optional output shard. One shard per source name; with a sink, the
    output shard's upper is the view's write frontier
    (sink/materialized_view_v2.rs analog — self-correcting via
    compare-and-append: on restart a partially written step is retried
    exactly because the upper didn't advance). Without a sink this is an
    INDEX: the output arrangement lives on device, peekable, and the
    frontier is in-memory (restart = full rehydration from inputs, the
    reference's index model)."""

    def __init__(
        self,
        client: PersistClient,
        dataflow: Dataflow,
        source_shards: dict[str, tuple[str, Schema]],
        output_shard: str | None,
    ):
        self.client = client
        self.df = dataflow
        self.sources = {
            name: ShardSource(client.open_reader(shard), schema)
            for name, (shard, schema) in source_shards.items()
        }
        self.writer: WriteHandle | None = (
            client.open_writer(output_shard, dataflow.out_schema)
            if output_shard is not None
            else None
        )
        # The replica-LOCAL processed frontier. Never conflated with the
        # durable sink upper: an active-active sibling may advance the
        # shard ahead of this replica, and stepping from the shard upper
        # would skip inputs locally (stale peeks) and double-count deltas
        # in the sink. Appends behind the durable upper skip benignly
        # (identical content by determinism + 1-timestamp chunks).
        self._upper = 0
        try:
            self.hydrate()
        except BaseException:
            self.expire()  # release reader holds of a failed build
            raise

    @property
    def upper(self) -> int:
        """This replica's processed frontier: the local output reflects
        input times < upper."""
        return self._upper

    def expire(self) -> None:
        """Release this view's shard read holds (must be called when the
        view is dropped or replaced, or the holds pin compaction forever)."""
        for s in self.sources.values():
            try:
                s.reader.expire()
            except Exception:
                pass

    # -- rehydration -------------------------------------------------------
    def hydrate(self) -> None:
        """Bring the dataflow to the output's upper.

        Fresh install: as-of selection picks the LATEST readable time,
        ``max(max input since, min input upper - 1)`` (collapse as much
        history into one snapshot step as possible —
        compute-client/src/as_of_selection.rs); if the inputs are all
        empty and uncompacted the dataflow simply starts at 0 and replays
        updates as they arrive. Resume: snapshot inputs at the durable
        upper-1 and rebuild arrangements without re-appending."""
        out_upper = (
            self.writer.machine.reload().upper
            if self.writer is not None
            else 0
        )
        if out_upper == 0:
            sts = [
                s.reader.machine.reload() for s in self.sources.values()
            ]
            max_since = max((st.since for st in sts), default=0)
            min_upper = min((st.upper for st in sts), default=0)
            as_of = max(max_since, min_upper - 1)
            if as_of <= 0 and max_since == 0:
                # Nothing (or only t=0) ingested and no compaction:
                # replay from scratch, no snapshot step needed.
                for s in self.sources.values():
                    s.resume_at(0)
                self._upper = 0
                return
            # Inputs must be readable at as_of; wait for uppers to pass
            # (can lag when one input is compacted ahead of another).
            for s in self.sources.values():
                if s.reader.wait_for_upper(as_of, timeout=30.0) is None:
                    raise TimeoutError(
                        "input shard upper never passed hydration as_of "
                        f"{as_of}"
                    )
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(as_of)
                inputs[name] = b
            self.df.time = as_of
            self.df.step(inputs)
            out = self.result_batch()
            self._append(out, 0, as_of + 1, as_of)
            self._upper = as_of + 1
        else:
            as_of = out_upper - 1
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(as_of)
                inputs[name] = b
            self.df.time = as_of
            self.df.step(inputs)  # rebuild arrangements; output delta
            # already durable — do NOT append.
            self._upper = out_upper


    def result_batch(self) -> Batch:
        """The maintained output arrangement as a HOST-readable batch
        (SPMD dataflows gather their per-worker shards first)."""
        return self.df.gather_delta(self.df.output.batch)

    def _append(self, batch: Batch, lower: int, upper: int, t: int) -> None:
        """Append the step's output delta. In active-active replication
        every replica computes every step deterministically and races the
        compare-and-append; losing the race (upper already advanced, or
        fenced by the other replica's writer) means the content is
        already durable — identical by determinism — so losing IS
        success (the reference's multi-replica persist-sink model,
        sink/materialized_view_v2.rs)."""
        if self.writer is None:
            return
        cols = batch.to_columns()
        data_cols, diff = cols[:-2], cols[-1]
        n = len(diff)
        nulls = [
            None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
        ]
        for attempt in range(5):
            try:
                self.writer.compare_and_append(
                    data_cols, nulls, np.full(n, t, np.uint64), diff,
                    lower, upper,
                )
                return
            except UpperMismatch as e:
                if e.actual >= upper:
                    # Another replica already wrote these times. Safe to
                    # skip: steady-state chunks are one timestamp and
                    # deltas are deterministic, so the durable content
                    # for [lower, upper) is identical to ours; our LOCAL
                    # frontier still advances only to `upper`.
                    return
                # Another replica durably wrote a SHORTER chunk (a
                # hydration race); our local state has advanced past it
                # and cannot produce the split — the owner must rebuild
                # from the durable shard.
                raise SinkConflict(
                    f"sink chunk [{lower},{upper}) conflicts with "
                    f"durable upper {e.actual}"
                )
            except Fenced:
                if self.writer.machine.reload().upper >= upper:
                    return  # the fencing writer covered it
                # Re-register and retry; jittered sleep breaks epoch
                # ping-pong between active-active siblings.
                self.writer.epoch = self.writer.machine.register_writer()
                _time.sleep(0.001 * (attempt + 1) * (1 + (id(self) % 7)))
        # The delta is NOT lost on this exit: the rebuild path re-derives
        # state from the durable shard and the sources.
        raise SinkConflict(
            f"sink append [{lower},{upper}) kept losing writer fencing"
        )

    # -- steady state ------------------------------------------------------
    def step(self, timeout: float = 5.0) -> bool:
        """Process all sources' updates up to a COMMON target frontier
        (min over input uppers beyond our own): the micro-batch analog of
        frontier-joined progress. Returns False if the inputs did not
        advance within the timeout."""
        lower = self.upper
        if not self.sources:
            # A source-less (pure constant) dataflow: one step at time 0
            # emits the constants, then the frontier is complete.
            if lower > 0:
                return False
            self.df.time = 0
            out = self.df.step({})
            out = self.df.gather_delta(out)
            self._append(out, 0, 1, 0)
            self._upper = 1
            return True
        target = None
        for s in self.sources.values():
            upper = s.reader.wait_for_upper(lower, timeout)  # > lower
            if upper is None:
                return False
            target = upper if target is None else min(target, upper)
        # One timestamp per steady-state step: chunk boundaries are then
        # DETERMINISTIC across active-active replicas, so racing sink
        # appends are byte-identical and losing a race is always safe.
        # (Backlogs are collapsed by hydrate's snapshot, not here; a
        # correction-buffer sink, correction_v2.rs, would lift this.)
        target = min(target, lower + 1)
        polled = {
            name: s.fetch_to(target) for name, s in self.sources.items()
        }
        t = target - 1
        self.df.time = t
        out = self.df.step(polled)
        out = self.df.gather_delta(out)  # no-op on single-device
        self._append(out, lower, target, t)
        self._upper = target
        return True

    def run_until(self, frontier: int, timeout: float = 30.0) -> None:
        """Advance until the output upper reaches ``frontier``."""
        while self.upper < frontier:
            if not self.step(timeout):
                raise TimeoutError(
                    f"sources stalled below frontier {frontier}"
                )

    def peek(self) -> list[tuple]:
        return self.df.peek()
