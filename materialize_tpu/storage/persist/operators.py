"""Dataflow <-> shard bridges: shard_source and persist_sink.

Analog of ``storage-operators/src/persist_source.rs`` (shard -> dataflow
import, consumed at ``compute/src/render.rs:291``) and the MV persist
sink (``compute/src/sink/materialized_view.rs``): a ``MaintainedView``
reads update chunks from input shards, advances the dataflow one
micro-batch step per chunk, and compare-and-appends the output delta to
the view's shard. Resume is the reference's model exactly (SURVEY.md §5
checkpoint/resume): NO operator-state checkpoint — on restart the
dataflow re-renders and re-hydrates from input-shard snapshots at the
output shard's upper (``hydrate()`` below; sink-less indexes re-hydrate
from the inputs' latest readable time). Since ISSUE 10 this path is the
PROVEN recovery spine, not just the documented one: ``environmentd
--recover`` replays the durable catalog through it, the chaos harness
(testing/chaos.py) SIGKILLs processes mid-span and checks exact
oracles, and reconciliation is a counted invariant (``mz_recovery``
rebuilds == 0 for fingerprint-unchanged dataflows).
"""

from __future__ import annotations

import time as _time

import numpy as np

from ...render.dataflow import Dataflow
from ...repr.batch import Batch, capacity_tier
from ...repr.schema import Schema
from .client import PersistClient, ReadHandle, WriteHandle
from .machine import Fenced, UpperMismatch


class SinkConflict(RuntimeError):
    """The durable sink diverged from this replica's chunking (hydration
    race): the view must be rebuilt from the durable shard."""


class AsOfError(RuntimeError):
    """AS OF timestamp outside the readable multiversion window
    [since, upper). Deliberately NOT a ValueError: the replica's build
    retry loop retries transient compaction races — machine.py's
    dedicated ``CompactionRace``, no longer blanket ValueError, so a
    real codec/caller bug surfaces instead of retrying forever — and a
    bad user timestamp must fail immediately."""


def updates_to_batch(
    schema: Schema, cols, nulls, time, diff, as_of: int,
    capacity: int | None = None,
) -> Batch:
    """Host update arrays -> device Batch with times forwarded to as_of
    (the step processes one virtual timestamp; logical compaction).

    A fetch that covered only empty upper-advances decodes to ZERO
    column arrays (there were no parts); the batch must still carry the
    declared schema's arity or downstream operators index out of range."""
    n = len(diff)
    if not cols and schema.arity:
        cols = [np.zeros(0, c.dtype) for c in schema.columns]
        nulls = [None] * schema.arity
    return Batch.from_numpy(
        schema,
        cols,
        np.full(n, as_of, np.uint64),
        diff,
        capacity=capacity,
        nulls=nulls,
    )


class ShardSource:
    """Import one shard into a dataflow: snapshot + listen chunks
    (persist_source analog)."""

    def __init__(self, reader: ReadHandle, schema: Schema):
        self.reader = reader
        self.schema = schema
        self.frontier: int | None = None  # set by snapshot()/resume_at()

    def snapshot(self, as_of: int) -> "tuple[Batch, int]":
        _sch, cols, nulls, time, diff = self.reader.snapshot(as_of)
        self.frontier = as_of + 1
        return (
            updates_to_batch(self.schema, cols, nulls, time, diff, as_of),
            as_of,
        )

    def resume_at(self, frontier: int) -> None:
        self.frontier = frontier

    def poll(self, timeout: float = 5.0):
        """Next chunk beyond the frontier, forwarded to the chunk's last
        time. Returns (batch, chunk_time, new_frontier) or None."""
        assert self.frontier is not None, "snapshot()/resume_at() first"
        got = self.reader.listen_next(self.frontier, timeout)
        if got is None:
            return None
        (_sch, cols, nulls, time, diff), new_upper = got
        t = new_upper - 1
        batch = updates_to_batch(self.schema, cols, nulls, time, diff, t)
        self.frontier = new_upper
        return batch, t, new_upper

    def fetch_to(self, target: int) -> Batch:
        """Chunk [frontier, target), forwarded to target-1. Caller must
        have confirmed target <= shard upper."""
        assert self.frontier is not None and target > self.frontier - 1
        _sch, cols, nulls, time, diff = self.reader.fetch(
            self.frontier, target
        )
        batch = updates_to_batch(
            self.schema, cols, nulls, time, diff, target - 1
        )
        self.frontier = target
        return batch


def _host_updates(batch: Batch):
    """Valid rows of a batch as host arrays (cols, nulls, time, diff)."""
    n = int(batch.count)
    cols = [np.asarray(a)[:n] for a in batch.cols]
    nulls = [
        None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
    ]
    return cols, nulls, np.asarray(batch.time)[:n], np.asarray(
        batch.diff
    )[:n]


def _hist_host(entry):
    """A multiversion-history entry as host update arrays. Entries are
    stored as the step's DEVICE delta batch (the pipelined span path
    records history with zero readbacks — PERF_NOTES round 8) and
    converted lazily on the rare rewind read; pre-existing host-tuple
    entries (SPMD gathers) pass through. Prefer :func:`_hist_host_at`
    when iterating a history list — it memoizes the conversion."""
    if isinstance(entry, tuple):
        return entry
    from ...analysis.donation import guard_read

    # The rewind read is a d2h conversion: under the buffer sanitizer
    # it must prove the retained delta was never donated (history
    # entries are span OUTPUTS, never the carry — an aliased entry
    # here means someone resurrected a donated leaf into history).
    guard_read(entry, "multiversion-history")
    return _host_updates(entry)


def _hist_host_at(history: list, i: int):
    """Host view of ``history[i]``'s update, MEMOIZED in place:
    repeated AS OF rewinds and multiple IndexSource subscribers then
    pay one d2h conversion per entry total, not one per read (through
    the TPU tunnel each conversion is a real round trip)."""
    t, upd = history[i]
    host = _hist_host(upd)
    if host is not upd:
        history[i] = (t, host)
    return host


class IndexSource:
    """Import a live sibling dataflow's output arrangement as an input —
    the TraceManager sharing analog (compute/src/arrangement/manager.rs:33,
    index imports at compute/src/render.rs:384-403): hydration snapshots
    the publisher's device-resident arrangement instead of replaying its
    sources, and each publisher step pushes its output delta to every
    subscriber.

    Implements the ShardSource surface (reader shim with
    machine.reload()/wait_for_upper/expire, snapshot, fetch_to,
    resume_at) so MaintainedView consumes indexes and shards uniformly.
    """

    class _State:
        def __init__(self, since: int, upper: int):
            self.since = since
            self.upper = upper

    class _Reader:
        def __init__(self, src: "IndexSource"):
            self._src = src
            self.machine = self

        def reload(self):
            s = self._src
            # The readable floor is the PUBLISHER's multiversion since:
            # snapshot() rewinds below base_upper-1 within the window.
            return IndexSource._State(
                since=min(s.publisher.since, max(s.base_upper - 1, 0)),
                upper=s.publisher.upper,
            )

        def wait_for_upper(self, frontier: int, timeout: float = 30.0):
            """An upper > frontier. The publisher lives on the SAME
            replica loop, so instead of blocking we actively step it
            forward (its own inputs may still not be there — then
            None, like a shard that never advances)."""
            s = self._src
            deadline = _time.monotonic() + timeout
            while s.publisher.upper <= frontier:
                if _time.monotonic() > deadline:
                    return None
                if not s.publisher.step(
                    timeout=max(deadline - _time.monotonic(), 0.001)
                ):
                    return None
            return s.publisher.upper

        def expire(self) -> None:
            s = self._src
            if s in s.publisher._subscribers:
                s.publisher._subscribers.remove(s)

    def __init__(self, publisher: "MaintainedView", schema: Schema):
        if getattr(publisher.df, "_basic_finalizers", None):
            # The publisher's arrangement carries opaque basic-aggregate
            # digests; a subscriber could never finalize them. The
            # coordinator inlines such views instead of index-importing
            # (coordinator._inline_views); this guard catches direct
            # users.
            raise ValueError(
                "an index over basic aggregates (string_agg/array_agg/"
                "list_agg) cannot be imported by other dataflows"
            )
        self.publisher = publisher
        self.schema = schema
        self.reader = IndexSource._Reader(self)
        # Same-process single-device publishers share arrangements
        # DEVICE-RESIDENT: the base snapshot is the publisher's output
        # spine (compacted in HBM) and per-step deltas are handed over
        # as the very device batches the publisher's step produced —
        # zero host round-trips on the sharing path (round-2 weak #2;
        # the reference's TraceManager shares traces in memory, not
        # through a serialization hop). SPMD publishers gather across
        # workers, so they keep the host path.
        from ...render.dataflow import Dataflow as _SingleDevice

        self._device = type(publisher.df) is _SingleDevice
        self.host_transfers = 0  # observability for tests
        # The base snapshot must be a COMMITTED span boundary: a
        # pipelined publisher may hold an in-flight span whose carry
        # is not yet validated (ISSUE 7 sequencing rule).
        publisher.sync_spans()
        self.base_cloned = False
        if self._device:
            base = publisher.df.output_batch()
            if publisher.donation_requested():
                # Snapshot-at-subscribe (ISSUE 8): the publisher's
                # output spine rides its DONATED span carry — sharing
                # its buffers would hand this subscriber a reference
                # the next donated span kills (the exact aliasing that
                # blocked ROADMAP 4b). Copy-on-share at the subscriber
                # boundary: one state-sized clone HERE, paid only by
                # dataflows that are actually subscribed to AND only
                # when donation is requested — unsubscribed views pay
                # nothing, and the publisher's donation verdict stays
                # provably safe.
                from ...arrangement.spine import clone_state_tree

                base = clone_state_tree(base)
                self.base_cloned = True
            self.base_batch = base
        else:
            self.host_transfers += 1
            self.base = _host_updates(publisher.result_batch())
        self.base_upper = publisher.upper
        # device path: (t, Batch); host path: (t, host update arrays)
        self._pending: list = []
        self.frontier: int | None = None
        publisher._subscribers.append(self)

    def _push(self, t: int, update) -> None:
        self._pending.append((t, update))

    def _take_until(self, target: int):
        taken = [u for t, u in self._pending if t < target]
        self._pending = [
            (t, u) for t, u in self._pending if t >= target
        ]
        return taken

    @staticmethod
    def _concat(parts):
        if not parts:
            return None
        cols = [
            np.concatenate([p[0][i] for p in parts])
            for i in range(len(parts[0][0]))
        ]
        nulls = []
        for i in range(len(parts[0][1])):
            if all(p[1][i] is None for p in parts):
                nulls.append(None)
            else:
                nulls.append(
                    np.concatenate(
                        [
                            p[1][i]
                            if p[1][i] is not None
                            else np.zeros(len(p[3]), dtype=bool)
                            for p in parts
                        ]
                    )
                )
        time = np.concatenate([p[2] for p in parts])
        diff = np.concatenate([p[3] for p in parts])
        return cols, nulls, time, diff

    @staticmethod
    def _forward_times(b: Batch, t: int) -> Batch:
        """Forward every row's time to ``t`` (logical compaction to the
        snapshot/chunk timestamp) — a device-side constant fill; padding
        rows are masked by count downstream."""
        import jax.numpy as jnp

        return b.replace(
            time=jnp.full(b.capacity, t, dtype=jnp.uint64),
            schema=b.schema,
        )

    def _guard(self, tree) -> None:
        """Use-after-donate guard on every device read this subscriber
        performs: the base snapshot and pending deltas must never be
        buffers a publisher's donated span killed (buffer_sanitizer;
        no-op when off)."""
        from ...analysis.donation import guard_read

        guard_read(
            tree,
            f"IndexSource(subscriber of "
            f"{getattr(self.publisher.df, 'name', 'df')!r})",
        )

    def snapshot(self, as_of: int) -> "tuple[Batch, int]":
        if as_of < self.base_upper - 1:
            # Multiversion rewind: the publisher retains a bounded
            # window of output deltas (read-policy lag analog,
            # adapter/src/coord/read_policy.rs); inside it, the base
            # snapshot minus the deltas in (as_of, base_upper)
            # reconstructs the arrangement at as_of.
            pub = self.publisher
            if as_of < pub.since:
                raise AsOfError(
                    f"index import cannot rewind to {as_of}: the "
                    f"publisher's multiversion window is "
                    f"[{pub.since}, {pub.upper})"
                )
            self.frontier = as_of + 1
            if self._device:
                self._guard(self.base_batch)
            parts = [
                _host_updates(self.base_batch)
                if self._device
                else self.base
            ]
            # The rewound-past deltas must ALSO be queued for forward
            # replay: they are folded into the base (not in _pending),
            # and a subscriber stepping past as_of needs them back.
            replay = []
            for hi, (ht, _upd) in enumerate(pub._history):
                if as_of < ht <= self.base_upper - 1:
                    cols, nulls, htime, diff = _hist_host_at(
                        pub._history, hi
                    )
                    parts.append((cols, nulls, htime, np.negative(diff)))
                    replay.append(
                        (
                            ht,
                            updates_to_batch(
                                self.schema, cols, nulls, htime,
                                diff, ht,
                            )
                            if self._device
                            else (cols, nulls, htime, diff),
                        )
                    )
            self._pending = replay + self._pending
            cols, nulls, time, diff = self._concat(parts)
            return (
                updates_to_batch(
                    self.schema, cols, nulls, time, diff, as_of
                ),
                as_of,
            )
        self.frontier = as_of + 1
        if self._device:
            from ...ops.sort import concat_batches

            parts = [self.base_batch] + self._take_until(as_of + 1)
            self._guard(parts)
            b = concat_batches(parts) if len(parts) > 1 else parts[0]
            return (
                self._forward_times(b, as_of).replace(schema=self.schema),
                as_of,
            )
        parts = [self.base] + self._take_until(as_of + 1)
        cols, nulls, time, diff = self._concat(parts)
        return (
            updates_to_batch(
                self.schema, cols, nulls, time, diff, as_of
            ),
            as_of,
        )

    def resume_at(self, frontier: int) -> None:
        self.frontier = frontier

    def fetch_to(self, target: int) -> Batch:
        assert self.frontier is not None and target > self.frontier - 1
        parts = self._take_until(target)
        self.frontier = target
        if self._device:
            from ...ops.sort import concat_batches

            if not parts:
                return Batch.empty(self.schema, 256)
            self._guard(parts)
            b = concat_batches(parts) if len(parts) > 1 else parts[0]
            return self._forward_times(b, target - 1).replace(
                schema=self.schema
            )
        got = self._concat(parts)
        if got is None:
            sch = self.schema
            cols = [np.zeros(0, c.dtype) for c in sch.columns]
            got = (
                cols,
                [None] * sch.arity,
                np.zeros(0, np.uint64),
                np.zeros(0, np.int64),
            )
        cols, nulls, time, diff = got
        return updates_to_batch(
            self.schema, cols, nulls, time, diff, target - 1
        )


class _ViewSpanBarrier:
    """Adapter registering a MaintainedView's span pipeline as its
    dataflow's ``_span_exec`` barrier: df-level state reads
    (``output_batch``/``output_records``/``run_steps``/
    ``peek_errors`` call ``span_barrier()``) then commit the view's
    in-flight span first — the same contract render/span_exec's
    executor provides — instead of relying only on the view-level
    ``sync_spans()`` call sites. ``in_dispatch`` is raised around the
    view's own span dispatch so dispatching never self-syncs (which
    would serialize the double buffer).

    The view's pipeline intentionally re-implements the boundary
    protocol rather than wrapping a SpanExecutor: the executor drives
    ``run_span`` (stacked multiple-of-compact-every spans, one fused
    program), while the view needs per-tick deltas and frontier
    bookkeeping from ``run_steps`` trains — the shared pieces
    (flags snapshots, one-readback commit, window rollback) live in
    ``_DataflowBase``."""

    __slots__ = ("view", "in_dispatch")

    def __init__(self, view: "MaintainedView"):
        self.view = view
        self.in_dispatch = False

    def sync(self) -> None:
        self.view.sync_spans()


class MaintainedView:
    """An installed dataflow maintained between shards: sources -> step ->
    optional output shard. One shard per source name; with a sink, the
    output shard's upper is the view's write frontier
    (sink/materialized_view_v2.rs analog — self-correcting via
    compare-and-append: on restart a partially written step is retried
    exactly because the upper didn't advance). Without a sink this is an
    INDEX: the output arrangement lives on device, peekable, and the
    frontier is in-memory (restart = full rehydration from inputs, the
    reference's index model). Other dataflows may import the index via
    IndexSource; each step's output delta is pushed to subscribers."""

    def __init__(
        self,
        client: PersistClient,
        dataflow: Dataflow,
        source_shards: dict[str, tuple[str, Schema]],
        output_shard: str | None,
        index_sources: dict[str, "IndexSource"] | None = None,
        replica_id: str = "r0",
        as_of: int | None = None,
    ):
        self.client = client
        self.replica_id = replica_id
        self.df = dataflow
        # Multiversion window (read-policy lag analog,
        # adapter/src/coord/read_policy.rs): retain the last N output
        # deltas as host arrays so reads can rewind to any time in
        # [since, upper). since advances as deltas are evicted.
        from ...utils.dyncfg import COMPUTE_CONFIGS, COMPUTE_RETAIN_HISTORY

        self._history: list = []  # [(t, (cols, nulls, time, diff))]
        self.retain = int(COMPUTE_RETAIN_HISTORY(COMPUTE_CONFIGS))
        self._since = 0
        self._as_of_override = as_of
        # MVs over basic aggregates persist MATERIALIZED VALUES: the
        # sink path finalizes each output delta's digest columns into
        # result strings (retractions resolve against the PRE-step
        # multiset) and dictionary-encodes them, so shard parts carry
        # real strings and readers never see a digest
        # (render/reduce.rs:369 + the materialized-view sink analog).
        self._sink_finalizes = bool(
            output_shard
            and getattr(dataflow, "_basic_finalizers", None)
        )
        self._pre_step_multisets = None
        self._subscribers: list = []
        self.sources = {
            name: ShardSource(client.open_reader(shard), schema)
            for name, (shard, schema) in source_shards.items()
        }
        if index_sources:
            self.sources.update(index_sources)
        self._output_shard = output_shard
        self.writer: WriteHandle | None = (
            client.open_writer(output_shard, dataflow.out_schema)
            if output_shard is not None
            else None
        )
        # The replica-LOCAL processed frontier. Never conflated with the
        # durable sink upper: an active-active sibling may advance the
        # shard ahead of this replica, and stepping from the shard upper
        # would skip inputs locally (stale peeks) and double-count deltas
        # in the sink. Appends behind the durable upper skip benignly
        # (identical content by determinism + 1-timestamp chunks).
        self._upper = 0
        # Pipelined span state (ISSUE 7): the DISPATCHED frontier runs
        # ahead of the committed one by at most one span;
        # `_inflight_span` holds (flags snapshot, [(t, delta)], target,
        # input-arrival monotonic stamp) until its boundary readback
        # commits it. `span_epoch` is the monotone span counter peeks
        # and compaction decisions sequence against (reported with
        # every Frontiers message).
        self._dispatched = 0
        self._inflight_span = None
        self._window_ticks: list = []
        self.span_epoch = 0
        # Register as the dataflow's span barrier: any df-level state
        # read sequences through sync_spans() automatically.
        self._barrier = _ViewSpanBarrier(self)
        dataflow._span_exec = self._barrier
        # Donation state (ISSUE 8): the buffer-provenance prover's
        # verdict gates whether this view's run_steps span train
        # donates its carry. Recomputed when the sharing structure
        # (subscriber set / donation request) changes, and only at
        # defer-window boundaries — a window keeps its decision.
        self._donation_sig = None
        self._donation_verdict = None
        self._donation_info: dict | None = None
        self._donation_dirty = False
        self.donated_parts: tuple = ()
        # Sharding state (ISSUE 9): the shard-spec prover's report —
        # SPMD-safety verdict of the slot-ring cursors, resolved
        # ingest mode, communication census. Computed once at build
        # (the SPMD render already ran the prover to gate its ingest
        # mode; single-device dataflows report the trivial fact) and
        # piggybacked on the first frontier report, like donation.
        self._sharding_info: dict | None = None
        self._sharding_dirty = False
        try:
            self.hydrate()
        except BaseException:
            self.expire()  # release reader holds of a failed build
            raise
        self._dispatched = self._upper
        # Decide donation NOW so every installed dataflow has a
        # provenance/donation verdict from its very first frontier
        # report (EXPLAIN ANALYSIS / mz_donation must never be blind
        # on an idle dataflow).
        self._span_donation()
        # Same discipline for the sharding verdict (EXPLAIN ANALYSIS
        # `sharding:` / mz_sharding cover every installed dataflow).
        from ...analysis.shard_prop import dataflow_sharding_report

        self._sharding_info = dataflow_sharding_report(self.df)
        self._sharding_dirty = True

    @property
    def upper(self) -> int:
        """This replica's processed frontier: the local output reflects
        input times < upper."""
        return self._upper

    @property
    def since(self) -> int:
        """Earliest readable time: reads AS OF t are servable for
        since <= t < upper (the multiversion window)."""
        return self._since

    def _record_history(self, t: int, out: Batch) -> None:
        """Retain this step's output delta for the multiversion window;
        evicting the oldest delta advances since (logical compaction of
        the window, persist downgrade_since analog)."""
        if self.retain <= 0:
            self._since = t
            return
        # The delta is retained DEVICE-RESIDENT (host conversion is
        # lazy, on the rare AS OF rewind — _hist_host): recording
        # history must not put a d2h readback on the per-tick hot
        # path, or the pipelined span protocol's one-readback-per-span
        # invariant breaks. SPMD deltas arrive as gathered host
        # batches and convert for free.
        self._history.append((t, out))
        while len(self._history) > self.retain:
            evicted_t, _ = self._history.pop(0)
            self._since = evicted_t

    def device_bytes(self) -> dict:
        """Device-resident bytes by component (ISSUE 12: the
        mz_arrangement_sizes byte columns): the output spine's runs /
        ingest slots / cached lanes plus the multiversion history's
        retained device deltas. Pure aval metadata — no device read,
        safe on the frontier-report path."""
        from ...arrangement.spine import device_nbytes

        out = getattr(self.df, "output", None)
        if out is not None and hasattr(out, "device_bytes"):
            bytes_ = dict(out.device_bytes())
        else:
            bytes_ = {
                "runs": device_nbytes(out) if out is not None else 0,
                "slots": 0,
                "lanes": 0,
            }
        bytes_["history"] = device_nbytes(
            [upd for _t, upd in self._history]
        )
        # Batch-part tiering split (ISSUE 20): hot (host-resident in
        # the client's part cache) vs cold (blob-only, rehydrated on
        # first read) encoded bytes over this view's shards — the
        # mz_arrangement_sizes hot/cold columns that drive the
        # part_hot_bytes budget decision. Cached state only; no
        # consensus read on the frontier-report path.
        hot = cold = 0
        # Index imports have a reader SHIM (IndexSource._Reader) with
        # no shard behind it — only real shard sources tier.
        shards = {
            sh
            for s in self.sources.values()
            if hasattr(s, "reader")
            for sh in [getattr(s.reader.machine, "shard", None)]
            if sh is not None
        }
        if self.writer is not None:
            shards.add(self.writer.machine.shard)
        for shard in shards:
            h, c = self.client.tier_split(shard)
            hot += h
            cold += c
        bytes_["part_hot"] = hot
        bytes_["part_cold"] = cold
        return bytes_

    def updates_as_of(self, t: int):
        """Host update arrays (cols, nulls, time, diff) of the
        maintained result rewound to time ``t``: the current result
        plus the NEGATION of every retained delta in (t, upper). Times
        forward to t (logical compaction to the read time)."""
        if getattr(self.df, "_basic_finalizers", None):
            raise AsOfError(
                "AS OF is not supported over basic aggregates "
                "(string_agg/array_agg/list_agg): their digest "
                "accumulators cannot be rewound"
            )
        self.sync_spans()
        if not (self._since <= t < self._upper):
            raise AsOfError(
                f"Timestamp ({t}) is not valid for all inputs: the "
                f"readable window is [{self._since}, {self._upper})"
            )
        parts = [_host_updates(self.result_batch())]
        for hi, (ht, _upd) in enumerate(self._history):
            if ht > t:
                cols, nulls, htime, diff = _hist_host_at(
                    self._history, hi
                )
                parts.append((cols, nulls, htime, np.negative(diff)))
        cols, nulls, _time, diff = IndexSource._concat(parts)
        return cols, nulls, np.full(len(diff), t, np.uint64), diff

    def expire(self) -> None:
        """Release this view's shard read holds (must be called when the
        view is dropped or replaced, or the holds pin compaction forever)."""
        for s in self.sources.values():
            try:
                s.reader.expire()
            except Exception:
                pass

    # -- rehydration -------------------------------------------------------
    def hydrate(self) -> None:
        """Bring the dataflow to the output's upper.

        Fresh install: as-of selection picks the LATEST readable time,
        ``max(max input since, min input upper - 1)`` (collapse as much
        history into one snapshot step as possible —
        compute-client/src/as_of_selection.rs); if the inputs are all
        empty and uncompacted the dataflow simply starts at 0 and replays
        updates as they arrive. Resume: snapshot inputs at the durable
        upper-1 and rebuild arrangements without re-appending."""
        out_upper = (
            self.writer.machine.reload().upper
            if self.writer is not None
            else 0
        )
        if out_upper == 0:
            sts = [
                s.reader.machine.reload() for s in self.sources.values()
            ]
            max_since = max((st.since for st in sts), default=0)
            min_upper = min((st.upper for st in sts), default=0)
            if self._as_of_override is not None:
                # Explicit AS OF: hydrate at exactly t (as_of_selection
                # honors a user AS OF). Validate against input sinces
                # NOW — a too-old timestamp is a user error, not a
                # transient race to retry.
                as_of = self._as_of_override
                if as_of < max_since:
                    raise AsOfError(
                        f"Timestamp ({as_of}) is not valid for all "
                        f"inputs: less than the as-of frontier "
                        f"{max_since}"
                    )
            else:
                as_of = max(max_since, min_upper - 1)
            if as_of <= 0 and max_since == 0 and self._as_of_override is None:
                # Nothing (or only t=0) ingested and no compaction:
                # replay from scratch, no snapshot step needed.
                for s in self.sources.values():
                    s.resume_at(0)
                self._upper = 0
                return
            # Inputs must be readable at as_of; wait for uppers to pass
            # (can lag when one input is compacted ahead of another).
            for s in self.sources.values():
                if s.reader.wait_for_upper(as_of, timeout=30.0) is None:
                    raise TimeoutError(
                        "input shard upper never passed hydration as_of "
                        f"{as_of}"
                    )
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(as_of)
                inputs[name] = b
            self.df.time = as_of
            self.df.step(inputs)
            out = self.result_batch()
            self._append(out, 0, as_of + 1, as_of)
            self._upper = as_of + 1
            self._since = as_of  # the snapshot collapsed prior history
        else:
            as_of = out_upper - 1
            # Index imports cannot rewind: the publisher arrangement is
            # live at base_upper-1, which may be past the sink upper.
            # Hydrate at the furthest input instead and append ONE
            # correction chunk (desired snapshot ⊖ durable sink content)
            # covering the skipped interval — the reference's v2 sink
            # correction-buffer model (sink/correction_v2.rs).
            # With publisher multiversion windows, an index import can
            # rewind down to the publisher's since — only beyond that
            # does the correction-chunk path engage.
            min_feasible = max(
                (
                    s.publisher.since
                    for s in self.sources.values()
                    if isinstance(s, IndexSource)
                ),
                default=as_of,
            )
            corrected_as_of = max(as_of, min_feasible)
            for s in self.sources.values():
                if s.reader.wait_for_upper(
                    corrected_as_of, timeout=30.0
                ) is None:
                    raise TimeoutError(
                        "input upper never passed resume as_of "
                        f"{corrected_as_of}"
                    )
            inputs = {}
            for name, s in self.sources.items():
                b, _ = s.snapshot(corrected_as_of)
                inputs[name] = b
            self.df.time = corrected_as_of
            self.df.step(inputs)  # rebuild arrangements
            self._since = corrected_as_of
            if corrected_as_of == as_of:
                # output delta already durable — do NOT append.
                self._upper = out_upper
            else:
                self._append_correction(out_upper, corrected_as_of)
                self._upper = corrected_as_of + 1


    def result_batch(self) -> Batch:
        """The maintained output arrangement as a HOST-readable batch
        (SPMD dataflows gather their per-worker shards first). Always
        a COMMITTED span boundary: an in-flight pipelined span is
        completed first."""
        self.sync_spans()
        return self.df.gather_delta(self.df.output_batch())

    def _append_correction(self, out_upper: int, as_of: int) -> None:
        """One chunk [out_upper, as_of+1) bringing the durable sink to
        the freshly hydrated snapshot: correction = desired ⊖ durable
        (the v2 sink correction-buffer model, sink/correction_v2.rs).
        Used when an index import cannot rewind to the sink upper."""
        if self.writer is None:
            return

        def acc_multiset(cols, nulls, diff):
            acc: dict = {}
            n = len(diff)
            for i in range(n):
                key = tuple(
                    None
                    if nulls[j] is not None and nulls[j][i]
                    else cols[j][i].item()
                    for j in range(len(cols))
                )
                acc[key] = acc.get(key, 0) + int(diff[i])
            return acc

        cols, nulls, _t, diff = _host_updates(self.result_batch())
        if self._sink_finalizes:
            # Compare in VALUE space: finalize digests (the current
            # multiset matches result_batch exactly) and encode, so
            # desired keys are the same dictionary codes the durable
            # shard holds.
            cols = self._finalize_sink_columns(list(cols), nulls, diff)
        desired = acc_multiset(cols, nulls, diff)
        # Reader id is stable PER REPLICA: distinct across active-active
        # siblings (a shared identity would let one replica's expire()
        # release the other's since hold mid-snapshot), but stable across
        # restarts of the same replica so a hold leaked by a crash
        # between open and expire is re-registered and released by the
        # next hydration (this persist analog has no lease expiry).
        # Known caveat: a replica crashed in this window and then
        # decommissioned forever leaks its hold — fixing that needs
        # lease-based reader expiry (persist-client/src/read.rs leases),
        # tracked with the read-hold/read-policy work.
        reader = self.client.open_reader(
            self._output_shard, f"sink-correction-{self.replica_id}"
        )
        try:
            _sch, dcols, dnulls, _dt, ddiff = reader.snapshot(
                out_upper - 1
            )
        finally:
            reader.expire()
        durable = acc_multiset(dcols, dnulls, ddiff)
        delta: dict = {}
        for k in set(desired) | set(durable):
            d = desired.get(k, 0) - durable.get(k, 0)
            if d:
                delta[k] = d
        schema = self.df.out_schema
        rows = list(delta.items())
        out_cols, out_nulls = [], []
        for j, c in enumerate(schema.columns):
            vals = np.asarray(
                [0 if k[j] is None else k[j] for k, _ in rows],
                dtype=c.dtype,
            )
            out_cols.append(vals)
            out_nulls.append(
                np.asarray([k[j] is None for k, _ in rows])
                if any(k[j] is None for k, _ in rows)
                else None
            )
        batch = Batch.from_numpy(
            schema,
            out_cols,
            np.full(len(rows), as_of, np.uint64),
            np.asarray([d for _, d in rows], np.int64),
            nulls=out_nulls,
        )
        self._append(batch, out_upper, as_of + 1, as_of)

    def _append(self, batch: Batch, lower: int, upper: int, t: int) -> None:
        """Append the step's output delta. In active-active replication
        every replica computes every step deterministically and races the
        compare-and-append; losing the race (upper already advanced, or
        fenced by the other replica's writer) means the content is
        already durable — identical by determinism — so losing IS
        success (the reference's multi-replica persist-sink model,
        sink/materialized_view_v2.rs)."""
        if self.writer is None:
            return
        cols = batch.to_columns()
        data_cols, diff = cols[:-2], cols[-1]
        n = len(diff)
        nulls = [
            None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
        ]
        if self._sink_finalizes:
            data_cols = self._finalize_sink_columns(
                [np.asarray(c) for c in data_cols], nulls, diff
            )
        for attempt in range(5):
            try:
                self.writer.compare_and_append(
                    data_cols, nulls, np.full(n, t, np.uint64), diff,
                    lower, upper,
                )
                return
            except UpperMismatch as e:
                if e.actual >= upper:
                    # Another replica already wrote these times. Safe to
                    # skip: steady-state chunks are one timestamp and
                    # deltas are deterministic, so the durable content
                    # for [lower, upper) is identical to ours; our LOCAL
                    # frontier still advances only to `upper`.
                    return
                # Another replica durably wrote a SHORTER chunk (a
                # hydration race); our local state has advanced past it
                # and cannot produce the split — the owner must rebuild
                # from the durable shard.
                raise SinkConflict(
                    f"sink chunk [{lower},{upper}) conflicts with "
                    f"durable upper {e.actual}"
                )
            except Fenced:
                if self.writer.machine.reload().upper >= upper:
                    return  # the fencing writer covered it
                # Re-register and retry; jittered sleep breaks epoch
                # ping-pong between active-active siblings.
                self.writer.epoch = self.writer.machine.register_writer()
                _time.sleep(0.001 * (attempt + 1) * (1 + (id(self) % 7)))
        # The delta is NOT lost on this exit: the rebuild path re-derives
        # state from the durable shard and the sources.
        raise SinkConflict(
            f"sink append [{lower},{upper}) kept losing writer fencing"
        )

    def _finalize_sink_columns(self, data_cols, nulls, diff):
        """Digest columns -> materialized result strings -> dictionary
        codes, so the durable shard carries REAL values. Retraction
        rows (diff < 0) finalize against the pre-step multiset capture
        (their digests describe group states the post-step multiset no
        longer holds)."""
        from ...repr.schema import GLOBAL_DICT

        fin = self.df.finalize_basic_columns(
            data_cols, nulls, diffs=diff,
            old_multisets=self._pre_step_multisets,
        )
        for out_col, *_rest in self.df._basic_finalizers:
            fin[out_col] = np.asarray(
                [
                    0 if s is None else GLOBAL_DICT.encode(s)
                    for s in fin[out_col]
                ],
                dtype=np.int64,
            )
        return fin

    # -- steady state ------------------------------------------------------
    def step(self, timeout: float = 5.0) -> bool:
        """Process all sources' updates up to a COMMON target frontier
        (min over input uppers beyond our own): the micro-batch analog of
        frontier-joined progress. Returns False if the inputs did not
        advance within the timeout."""
        self.sync_spans()
        lower = self.upper
        if not self.sources:
            # A source-less (pure constant) dataflow: one step at time 0
            # emits the constants, then the frontier is complete.
            if lower > 0:
                return False
            if self._sink_finalizes:
                self._pre_step_multisets = (
                    self.df.capture_basic_multisets()
                )
            arrived = _time.monotonic()
            self.df.time = 0
            out = self.df.step({})
            out = self.df.gather_delta(out)
            self._append(out, 0, 1, 0)
            self._publish(0, out)
            self._record_history(0, out)
            self._upper = 1
            self._dispatched = 1
            self._record_freshness(1, arrived)
            return True
        target = None
        for s in self.sources.values():
            upper = s.reader.wait_for_upper(lower, timeout)  # > lower
            if upper is None:
                return False
            target = upper if target is None else min(target, upper)
        # One timestamp per steady-state step: chunk boundaries are then
        # DETERMINISTIC across active-active replicas, so racing sink
        # appends are byte-identical and losing a race is always safe.
        # (Backlogs are collapsed by hydrate's snapshot, not here; a
        # correction-buffer sink, correction_v2.rs, would lift this.)
        target = min(target, lower + 1)
        polled = {
            name: s.fetch_to(target) for name, s in self.sources.items()
        }
        # Freshness arrival stamp: taken AFTER the fetch completes, so
        # the recorded lag is the maintenance delay this view adds, not
        # time spent waiting for input to exist (coord/freshness.py).
        arrived = _time.monotonic()
        t = target - 1
        if self._sink_finalizes:
            self._pre_step_multisets = (
                self.df.capture_basic_multisets()
            )
        self.df.time = t
        out = self.df.step(polled)
        out = self.df.gather_delta(out)  # no-op on single-device
        self._append(out, lower, target, t)
        self._publish(t, out)
        self._record_history(t, out)
        self._upper = target
        self._dispatched = target
        self._record_freshness(target, arrived)
        return True

    # -- pipelined span stepping (ISSUE 7: the async control plane) --------
    #
    # The per-tick step() pays one flags readback per tick (run_steps'
    # synchronous overflow check) and leaves the device idle while the
    # host fetches the next chunk. step_span() processes up to
    # span_max_ticks READY micro-batches as one deferred dispatch
    # train and commits them with ONE boundary readback — overlapped,
    # for index (sink-less) views, with the NEXT span's ingest and
    # dispatch: the commit readback for span K runs after span K+1 is
    # already queued on device (double buffering, at most one span in
    # flight ahead of the committed frontier). Peeks, AS OF reads, and
    # subscriber snapshots sequence against COMMITTED span boundaries
    # via sync_spans() — they can never observe a half-applied carry.

    # -- donation decision (ISSUE 8: the prover-gated span train) ----------

    def donation_requested(self) -> bool:
        """Whether donation POLICY asks for a donated carry on this
        view's span train: the ``span_donation`` dyncfg resolved
        through the one shared backend predicate
        (render/dataflow._donation_supported via
        span_exec.resolve_donation), restricted to single-device
        dataflows (SPMD carries cannot alias through shard_map
        boundary specs). The provenance PROVER decides whether the
        request is safe — see :meth:`_span_donation`."""
        from ...render.dataflow import Dataflow as _SingleDevice
        from ...render.span_exec import resolve_donation

        return type(self.df) is _SingleDevice and resolve_donation(None)

    def _span_donation(self) -> tuple:
        """The carry parts this view's next span train donates: the
        buffer-provenance prover's per-argnum verdict, recomputed only
        when the sharing signature (donation request, subscriber set)
        changes, and frozen for the duration of a defer window (a
        window that started un-donated must not start donating
        mid-window — run_steps enforces the same rule)."""
        if getattr(self.df, "_defer_ck", None) is not None:
            return self.donated_parts
        requested = self.donation_requested()
        sig = (requested, tuple(id(s) for s in self._subscribers))
        if sig != self._donation_sig:
            from ...analysis.donation import view_verdict
            from ...render.dataflow import _donation_supported

            name = getattr(self.df, "name", "df")
            v = view_verdict(name, self, requested=requested)
            self._donation_sig = sig
            self._donation_verdict = v
            self.donated_parts = v.donate_parts() if requested else ()
            info = v.to_dict()
            info["donated"] = list(self.donated_parts)
            info["wired"] = bool(
                self.donated_parts and _donation_supported()
            )
            self._donation_info = info
            self._donation_dirty = True
        return self.donated_parts

    def donation_info(self) -> dict | None:
        """The last provenance/donation verdict (replica frontier
        reports carry it to the controller for EXPLAIN ANALYSIS and
        the mz_donation introspection relation)."""
        return self._donation_info

    def sharding_info(self) -> dict | None:
        """The shard-spec prover's report (ISSUE 9: SPMD-safety
        verdict, resolved ingest mode, communication census) —
        replica frontier reports carry it to the controller for
        EXPLAIN ANALYSIS's ``sharding:`` block and the
        ``mz_sharding`` introspection relation."""
        return self._sharding_info

    def step_span(
        self, max_ticks: int | None = None, timeout: float = 0.0
    ) -> bool:
        """Span-batched stepping. Sinked views commit synchronously at
        the span boundary (durability needs the deltas host-side
        anyway); index views pipeline (deferred commit). Views the
        span protocol cannot cover — pure constants, basic-aggregate
        sinks (per-step multiset captures), SPMD dataflows (host
        gathers per tick) — fall back to the per-tick step."""
        from ...render.dataflow import Dataflow as _SingleDevice
        from ...utils.dyncfg import COMPUTE_CONFIGS, SPAN_MAX_TICKS

        if max_ticks is None:
            max_ticks = max(int(SPAN_MAX_TICKS(COMPUTE_CONFIGS)), 1)
        if not self.sources or self._sink_finalizes:
            return self.step(timeout)
        if self.writer is None and type(self.df) is _SingleDevice:
            # Index views pipeline: deferred commit, device-resident
            # history, at most one span in flight.
            return self._step_span_pipelined(max_ticks, timeout)
        # Sinked views (durability reads deltas host-side anyway) and
        # SPMD views (per-tick host gathers) commit synchronously at
        # the span boundary — still one flags readback per span
        # instead of one per tick.
        return self._step_span_sync(max_ticks, timeout)

    def _gather_ready_ticks(
        self, lower: int, max_ticks: int, timeout: float
    ) -> list:
        """Up to max_ticks consecutive one-timestamp input chunks
        beyond ``lower``: [(t, {name: batch})]. Only the FIRST tick
        may wait ``timeout``; later ticks take whatever is already
        ready (the span covers the backlog, it never stalls on it)."""
        ticks: list = []
        for k in range(max_ticks):
            want = lower + k
            target = None
            for s in self.sources.values():
                upper = s.reader.wait_for_upper(
                    want, timeout if k == 0 else 0.0
                )
                if upper is None:
                    target = None
                    break
                target = upper if target is None else min(target, upper)
            if target is None:
                break
            target = min(target, want + 1)
            polled = {
                name: s.fetch_to(target)
                for name, s in self.sources.items()
            }
            ticks.append((target - 1, polled))
        return ticks

    def _step_span_sync(self, max_ticks: int, timeout: float) -> bool:
        """Sinked span: dispatch every ready tick asynchronously, ONE
        flags readback (check_flags — replays on overflow), then the
        per-tick durable appends from validated deltas."""
        self.sync_spans()
        lower = self.upper
        ticks = self._gather_ready_ticks(lower, max_ticks, timeout)
        if not ticks:
            return False
        arrived = _time.monotonic()
        if self.df.time != ticks[0][0]:
            self.df.time = ticks[0][0]
        deltas = self.df.run_steps(
            [inp for _, inp in ticks],
            defer_check=True,
            donate=self._span_donation(),
        )
        if self.df.check_flags():
            deltas = self.df.replayed_deltas
        lo = lower
        for (t, _), out in zip(ticks, deltas):
            out = self.df.gather_delta(out)
            self._append(out, lo, t + 1, t)
            self._publish(t, out)
            self._record_history(t, out)
            lo = t + 1
            self._upper = lo
        self._dispatched = lo
        self.span_epoch += 1
        self._record_freshness(lo, arrived)
        return True

    def _step_span_pipelined(
        self, max_ticks: int, timeout: float
    ) -> bool:
        """Index-view span: dispatch span K+1, then commit span K at
        its boundary readback — the readback waits for K while K+1
        executes. The committed frontier (`upper`, what peeks see)
        trails the dispatched one by at most one span."""
        from ...utils.dyncfg import COMPUTE_CONFIGS, SPAN_WINDOW_SPANS

        lower = self._dispatched
        ticks = self._gather_ready_ticks(lower, max_ticks, timeout)
        if not ticks:
            # No new input: drain the in-flight span so the committed
            # frontier (and peeks waiting on it) still progresses.
            return self._commit_inflight()
        arrived = _time.monotonic()
        if (
            len(self.df._defer_log)
            >= int(SPAN_WINDOW_SPANS(COMPUTE_CONFIGS))
        ):
            # Rollback-window boundary: commit the in-flight span,
            # then validate + clear the defer log (bounds replay
            # memory). One extra readback per window, amortized; the
            # pipeline refills on this very dispatch.
            self.sync_spans()
            if self.df._defer_ck is not None and self.df.check_flags():
                self._recover_window()
            self._window_ticks = []
        if self.df._defer_ck is None:
            self._window_ticks = []
        if self.df.time != ticks[0][0]:
            self.df.time = ticks[0][0]
        # Our own dispatch must not self-sync through the registered
        # span barrier (that would serialize the double buffer).
        from ...utils.trace import TRACER

        t_wall = _time.time()  # host-sync: ok(pure host clock read)
        t0 = _time.perf_counter()
        self._barrier.in_dispatch = True
        try:
            deltas = self.df.run_steps(
                [inp for _, inp in ticks],
                defer_check=True,
                donate=self._span_donation(),
            )
        finally:
            self._barrier.in_dispatch = False
        if TRACER.enabled("debug"):
            TRACER.record(
                "view.span.dispatch", t_wall,
                _time.perf_counter() - t0, level="debug",
                ticks=len(ticks),
            )
        snap = self.df.flags_snapshot()
        entries = [(t, out) for (t, _), out in zip(ticks, deltas)]
        self._window_ticks.extend(entries)
        prev = self._inflight_span
        self._inflight_span = (snap, entries, ticks[-1][0] + 1, arrived)
        self._dispatched = ticks[-1][0] + 1
        if prev is not None:
            self._commit_span(prev)
        return True

    def _commit_span(self, handle) -> None:
        """The span boundary: ONE fused flags readback; clean commits
        publish the span's deltas (device handoff), record history,
        and advance the committed frontier; an overflow triggers the
        whole-window rollback+replay."""
        from ...utils.trace import TRACER

        snap, entries, target, arrived = handle
        t_wall = _time.time()  # host-sync: ok(pure host clock read)
        t0 = _time.perf_counter()
        if self.df.read_flags_snapshot(snap):
            self._recover_window()
            return
        for t, out in entries:
            self._publish(t, out)
            self._record_history(t, out)
            self._upper = t + 1
        self.span_epoch += 1
        self._record_freshness(target, arrived)
        if TRACER.enabled("debug"):
            # The span-commit cadence record (ISSUE 12): boundary
            # readback wait + publish, at DEBUG so the default level
            # keeps the per-span path recorder-free.
            TRACER.record(
                "view.span.commit", t_wall,
                _time.perf_counter() - t0, level="debug",
                ticks=len(entries), epoch=self.span_epoch,
            )

    def _record_freshness(self, frontier: int, arrived: float) -> None:
        """Committed-span-boundary lag recording: wallclock_lag_ms =
        commit time - arrival time of the newest input tick the span
        covers (one definition: coord/freshness.lag_ms). Pure host
        bookkeeping — this function is on the host-sync linter's
        RECORDER_PATH, so a hidden d2h sync here fails CI."""
        from ...coord.freshness import FRESHNESS, lag_ms

        FRESHNESS.record(
            getattr(self.df, "name", "") or "df",
            self.replica_id,
            frontier,
            lag_ms(arrived),
        )

    def _commit_inflight(self) -> bool:
        handle, self._inflight_span = self._inflight_span, None
        if handle is None:
            return False
        self._commit_span(handle)
        return True

    def _recover_window(self) -> None:
        """An overflow rolled the defer window back and replayed it
        against grown tiers (render/dataflow.check_flags). Spans
        committed earlier in the window were validated clean at their
        own boundary — the replay reproduces their deltas identically
        (steps are pure) — so only the uncommitted tail publishes."""
        if self.df._defer_ck is not None:
            self.df.check_flags()
        replayed = getattr(self.df, "replayed_deltas", [])
        for (t, _old), out in zip(self._window_ticks, replayed):
            if t >= self._upper:
                self._publish(t, out)
                self._record_history(t, out)
                self._upper = t + 1
        self._upper = max(self._upper, self._dispatched)
        self._inflight_span = None
        self._window_ticks = []
        self.span_epoch += 1

    def sync_spans(self) -> None:
        """The read barrier: complete + commit the in-flight span, so
        callers (peeks, AS OF reads, subscriber snapshots, DML)
        observe a committed span boundary — never a half-applied
        carry. No-op when nothing is in flight, and exactly ONE
        readback otherwise: the boundary commit's clean snapshot
        already proves every span <= it valid (flags OR-accumulate),
        so the serving path never pays a second validation round trip
        — window teardown happens at the span loop's own boundary
        (_step_span_pipelined) or inside df.check_flags when a
        df-level reader forces it."""
        if self._inflight_span is not None:
            self._commit_inflight()

    def _publish(self, t: int, out: Batch) -> None:
        """Push this step's output delta to index-import subscribers
        (TraceManager sharing: the subscriber's dataflow sees exactly
        the arrangement's change stream). Device-path subscribers get
        the step's device batch itself (no host hop); host-path
        subscribers (SPMD publishers) get host arrays."""
        if not self._subscribers:
            return
        update = None
        for sub in self._subscribers:
            if getattr(sub, "_device", False):
                sub._push(t, out)
            else:
                if update is None:
                    sub.host_transfers += 1
                    update = _host_updates(out)
                sub._push(t, update)

    def run_until(self, frontier: int, timeout: float = 30.0) -> None:
        """Advance until the output upper reaches ``frontier``."""
        while self.upper < frontier:
            if not self.step(timeout):
                raise TimeoutError(
                    f"sources stalled below frontier {frontier}"
                )

    def peek(self) -> list[tuple]:
        self.sync_spans()
        return self.df.peek()
