"""The shard state machine: all transitions via consensus CaS.

Analog of ``persist-client/src/internal/machine.rs:61`` (``Machine``):
every mutation loads the head state, computes the successor state, and
compare-and-sets it at ``seqno + 1``; on CaS loss it reloads and
re-evaluates (some operations then become no-ops or errors, e.g. an
append whose expected upper no longer matches). Compaction and GC are
the background duties (``internal/compact.rs``, ``internal/gc.rs``).
"""

from __future__ import annotations

import time as _time
from dataclasses import replace

import numpy as np

from .codec import concat_update_parts, decode_part, encode_part
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    VersionedData,
    retry_external,
)
from .pubsub import PUBSUB
from .state import HollowBatch, ShardState


class Fenced(RuntimeError):
    """A newer writer registered; this handle must not write again."""


class UpperMismatch(RuntimeError):
    """compare_and_append expected a different shard upper."""

    def __init__(self, expected: int, actual: int):
        super().__init__(f"expected upper {expected}, shard at {actual}")
        self.expected = expected
        self.actual = actual


class CompactionRace(ValueError):
    """A read raced a concurrent compaction: the part it was fetching
    was swapped out, or the since it validated against moved. Transient
    by construction — reloading the state and re-reading always
    succeeds (compaction never changes content, only representation) —
    so retry loops catch exactly this, not blanket ValueError, and a
    real codec/caller bug surfaces immediately. Subclasses ValueError
    because a snapshot below since has always raised ValueError and
    callers pin that contract."""


class CompactorFenced(RuntimeError):
    """The compaction lease moved: this holder's epoch is stale, its
    renew/swap must not land (lease-expiry handoff fencing)."""


class Machine:
    def __init__(self, shard: str, blob: Blob, consensus: Consensus):
        self.shard = shard
        self.blob = blob
        self.consensus = consensus
        self._last_merge_bytes = (0, 0)  # (input, output) of last merge
        self._state = self._load_or_init()

    # -- state plumbing ----------------------------------------------------
    def _load_or_init(self) -> ShardState:
        head = self.consensus.head(self.shard)
        if head is not None:
            return ShardState.from_bytes(head.data)
        init = ShardState(shard=self.shard)
        if self.consensus.compare_and_set(
            self.shard, None, VersionedData(0, init.to_bytes())
        ):
            return init
        return ShardState.from_bytes(self.consensus.head(self.shard).data)

    def reload(self) -> ShardState:
        head = self.consensus.head(self.shard)
        assert head is not None
        self._state = ShardState.from_bytes(head.data)
        return self._state

    @property
    def state(self) -> ShardState:
        return self._state

    def _apply(self, f):
        """CaS loop: state -> (new_state | None, result). None = no-op.
        Reloads the head each attempt: transition errors (Fenced,
        UpperMismatch) must be judged against the current state, not a
        stale cache — a fenced writer with a stale cache would otherwise
        see UpperMismatch instead of Fenced."""
        while True:
            st = self.reload()
            new, result = f(st)
            if new is None:
                return result
            new = replace(new, seqno=st.seqno + 1)
            if self.consensus.compare_and_set(
                self.shard, st.seqno, VersionedData(new.seqno, new.to_bytes())
            ):
                self._state = new
                # Push notification (pubsub.py): wake in-process
                # waiters (wait_for_upper, compactor listeners) the
                # moment the CaS lands.
                PUBSUB.publish(self.shard, new.seqno)
                return result
            self.reload()

    # -- transitions -------------------------------------------------------
    def register_writer(self) -> int:
        """Claim the write epoch, fencing all previous writers
        (``ComputeCommand::Hello{nonce}`` / persist writer-fencing analog)."""

        def f(st):
            epoch = st.writer_epoch + 1
            return replace(st, writer_epoch=epoch), epoch

        return self._apply(f)

    def compare_and_append(
        self,
        keys: tuple[str, ...],
        lower: int,
        upper: int,
        n_updates: int,
        epoch: int,
        n_bytes: int = 0,
    ) -> None:
        """Append a batch [lower, upper) iff lower == shard upper and the
        caller still holds the current write epoch."""
        assert upper > lower, (lower, upper)

        def f(st):
            if epoch != st.writer_epoch:
                raise Fenced(
                    f"epoch {epoch} fenced by {st.writer_epoch}"
                )
            if lower != st.upper:
                raise UpperMismatch(lower, st.upper)
            batch = HollowBatch(
                lower, upper, tuple(keys), n_updates, n_bytes
            )
            return (
                replace(st, upper=upper, batches=st.batches + (batch,)),
                None,
            )

        self._apply(f)

    def register_reader(self, reader_id: str) -> int:
        """Install a read hold at the current since; returns that since."""

        def f(st):
            holds = dict(st.reader_holds)
            if reader_id in holds:
                return None, holds[reader_id]
            holds[reader_id] = st.since
            return (
                replace(st, reader_holds=tuple(sorted(holds.items()))),
                st.since,
            )

        return self._apply(f)

    def downgrade_since(self, reader_id: str, new_since: int) -> int:
        """Advance one reader's hold; shard since = min over holds.
        Returns the resulting shard since."""

        def f(st):
            holds = dict(st.reader_holds)
            cur = holds.get(reader_id, st.since)
            holds[reader_id] = max(cur, new_since)
            since = min(holds.values()) if holds else max(
                st.since, new_since
            )
            since = max(since, st.since)
            return (
                replace(
                    st,
                    since=since,
                    reader_holds=tuple(sorted(holds.items())),
                ),
                since,
            )

        return self._apply(f)

    def expire_reader(self, reader_id: str) -> None:
        def f(st):
            holds = dict(st.reader_holds)
            if reader_id not in holds:
                return None, None
            del holds[reader_id]
            since = min(holds.values()) if holds else st.since
            return (
                replace(
                    st,
                    since=max(st.since, since),
                    reader_holds=tuple(sorted(holds.items())),
                ),
                None,
            )

        self._apply(f)

    # -- compaction leases -------------------------------------------------
    def acquire_compaction_lease(
        self, holder: str, duration_s: float, now: float | None = None
    ) -> int | None:
        """Claim (or re-claim / take over) the shard's compaction lease.
        Succeeds when the lease is free, expired, or already held by
        ``holder``; bumps the compactor epoch — the fencing token every
        later renew/swap must present — and returns it. Returns None
        while a live lease is held by someone else (back off; the
        holder or its expiry will free it). ``now`` is injectable so
        the interleave explorer can drive virtual time."""

        def f(st):
            t = _time.time() if now is None else now
            held = (
                st.compactor_holder
                and st.compactor_holder != holder
                and st.lease_expires > t
            )
            if held:
                return None, None
            return (
                replace(
                    st,
                    compactor_epoch=st.compactor_epoch + 1,
                    compactor_holder=holder,
                    lease_expires=t + duration_s,
                ),
                st.compactor_epoch + 1,
            )

        return self._apply(f)

    def renew_compaction_lease(
        self, epoch: int, duration_s: float, now: float | None = None
    ) -> bool:
        """Extend the lease deadline iff ``epoch`` is still current.
        A False return means the lease moved (expiry + handoff): the
        caller is fenced and must abandon its merge — its swap would
        be rejected anyway, this just saves the work."""

        def f(st):
            if epoch != st.compactor_epoch:
                return None, False
            t = _time.time() if now is None else now
            return replace(st, lease_expires=t + duration_s), True

        return self._apply(f)

    def release_compaction_lease(self, epoch: int) -> None:
        def f(st):
            if epoch != st.compactor_epoch:
                return None, None
            return (
                replace(st, compactor_holder="", lease_expires=0.0),
                None,
            )

        self._apply(f)

    def swap_compacted(
        self,
        prefix: tuple[HollowBatch, ...],
        merged_key: str,
        n: int,
        n_bytes: int,
        epoch: int | None = None,
    ) -> int:
        """Swap ``prefix`` (the exact batches that were merged) for one
        merged batch. Returns the number of replaced parts, 0 when the
        swap lost a race (prefix no longer present — a concurrent
        compaction already replaced some of it; the caller discards its
        merge). With ``epoch`` set, the swap additionally requires the
        compaction lease epoch to still match: a compactor that lost
        its lease mid-merge raises CompactorFenced instead of swapping
        a stale merge over its successor's work."""
        if not prefix:
            return 0
        lower = prefix[0].lower
        upper = prefix[-1].upper
        old_n = sum(len(b.keys) for b in prefix)

        def f(cur):
            if epoch is not None and epoch != cur.compactor_epoch:
                raise CompactorFenced(
                    f"lease epoch {epoch} fenced by {cur.compactor_epoch}"
                )
            if cur.batches[: len(prefix)] != prefix:
                return None, 0  # lost the race; discard our merge
            keep = cur.batches[len(prefix):]
            batch = HollowBatch(
                lower, upper, (merged_key,) if n else (), n,
                n_bytes if n else 0,
            )
            return replace(cur, batches=(batch,) + keep), old_n

        return self._apply(f)

    # -- background duties -------------------------------------------------
    def maybe_compact(self, max_batches: int = 8, ctx: str = "inline") -> int:
        """Merge all current batches into one when the spine grows past
        ``max_batches``: reads parts, forwards times to ``since`` (logical
        compaction), consolidates, writes one merged part, swaps it in,
        then deletes the replaced parts. Returns #parts replaced.

        ``ctx`` attributes the merge work ("inline" = on the caller's
        — i.e. the writer's tick — path, "background" = the detached
        compactor's worker thread) in the counted compaction stats
        (compactor.STATS): the compactor-smoke gate asserts the tick
        path did ZERO of this under compaction_mode=background.

        Concurrency: the swap requires the EXACT batch prefix that was
        merged to still be present (identity on the HollowBatch tuple) —
        a racing compaction that replaced any of those batches makes this
        one a no-op (its merged part is discarded), so no appended or
        concurrently-compacted data can be dropped."""
        st = self.reload()
        if len(st.batches) <= max_batches:
            return 0
        prefix = st.batches
        merged_key, n, old_keys = self._merge_parts(st, ctx=ctx)
        replaced = self.swap_compacted(
            prefix, merged_key, n, self._last_merge_bytes[1]
        )
        from .compactor import STATS

        STATS.record_merge(
            self.shard, ctx, replaced,
            self._last_merge_bytes[0], self._last_merge_bytes[1],
        )
        # Best-effort blob cleanup: state is already durable; a failed
        # delete leaks a part but never corrupts (internal/gc.rs model).
        doomed = old_keys if replaced else ([merged_key] if n else [])
        self._delete_parts(doomed)
        return replaced

    def _delete_parts(self, keys) -> None:
        cache = getattr(self, "part_cache", None)
        if cache is not None:
            cache.evict_keys(keys)
        for k in keys:
            try:
                retry_external(lambda k=k: self.blob.delete(k))
            except ExternalDurabilityError:
                pass

    def _merge_parts(self, st: ShardState, ctx: str = "inline"):
        """Read every part, forward times to since, consolidate, write
        one part. Host-side numpy work (a background task in the
        reference's compaction pool, internal/compact.rs). Leaves
        (input_bytes, output_bytes) in ``self._last_merge_bytes``."""
        schema = None
        parts = []
        old_keys = []
        in_bytes = 0
        self._last_merge_bytes = (0, 0)
        from ...repr.schema import GLOBAL_DICT

        dict_epoch = GLOBAL_DICT.epoch
        for b in st.batches:
            for k in b.keys:
                old_keys.append(k)
                data = retry_external(lambda k=k: self.blob.get(k))
                assert data is not None, f"missing blob part {k}"
                in_bytes += len(data)
                sch, cols, nulls, time, diff = decode_part(data)
                schema = schema or sch
                parts.append((cols, nulls, time, diff))
        if schema is None:
            self._last_merge_bytes = (in_bytes, 0)
            return "", 0, old_keys
        cols, nulls, time, diff = concat_update_parts(
            parts, len(schema.columns)
        )
        # Logical compaction: forward every time to the since frontier.
        time = np.maximum(time, np.uint64(st.since))
        # Consolidate: sum diffs of identical (row, time); drop zeros.
        # Native C++ kernel; float keys grouped by bit pattern (any total
        # order works for grouping), null masks as extra key columns.
        from ... import native

        def as_key(c):
            if c.dtype == np.int64:
                return c
            if c.dtype == np.float64:
                # Normalize -0.0 to +0.0 so a retraction computed with the
                # other zero's bit pattern still cancels; NaNs group by
                # bit pattern, which is stricter than float equality (a
                # NaN never equals itself) and thus still cancels exact
                # re-derivations.
                return np.where(c == 0.0, 0.0, c).view(np.int64)
            return c.astype(np.int64)

        key_cols = [as_key(c) for c in cols]
        key_cols += [
            (
                nl if nl is not None else np.zeros(len(time), np.bool_)
            ).astype(np.int64)
            for nl in nulls
        ]
        key_cols.append(time.astype(np.int64))
        sel, diff = native.consolidate_i64(key_cols, diff)
        cols = [c[sel] for c in cols]
        nulls = [nl[sel] if nl is not None else None for nl in nulls]
        time = time[sel]
        n = len(time)
        if n == 0:
            self._last_merge_bytes = (in_bytes, 0)
            return "", 0, old_keys
        merged_key = f"{self.shard}/compact-{st.seqno}-{st.upper}"
        # Retried like every durability-layer write (ISSUE 10: the
        # chaos storms run compaction under UnreliableBlob, and an
        # injected transient failure must not abort a compaction the
        # part reads already survived).
        data = encode_part(schema, cols, nulls, time, diff)
        retry_external(lambda: self.blob.set(merged_key, data))
        self._last_merge_bytes = (in_bytes, len(data))
        # Write-through: the merged part replaces hot parts, so it is
        # hot itself (a lost swap race evicts it via _delete_parts).
        cache = getattr(self, "part_cache", None)
        if cache is not None:
            cache.put(
                merged_key, schema, cols, nulls, time, diff, len(data),
                dict_epoch=dict_epoch,
            )
        from .compactor import STATS

        STATS.record_blob_write(self.shard, ctx, len(data))
        return merged_key, n, old_keys

    def gc_consensus(self, keep_last: int = 1) -> None:
        """Truncate consensus history below the head (state GC,
        ``internal/gc.rs``): old seqnos are only needed for debugging."""
        head = self.consensus.head(self.shard)
        if head is not None and head.seqno >= keep_last:
            self.consensus.truncate(
                self.shard, head.seqno - keep_last + 1
            )
