"""The shard state machine: all transitions via consensus CaS.

Analog of ``persist-client/src/internal/machine.rs:61`` (``Machine``):
every mutation loads the head state, computes the successor state, and
compare-and-sets it at ``seqno + 1``; on CaS loss it reloads and
re-evaluates (some operations then become no-ops or errors, e.g. an
append whose expected upper no longer matches). Compaction and GC are
the background duties (``internal/compact.rs``, ``internal/gc.rs``).
"""

from __future__ import annotations

from dataclasses import replace

import numpy as np

from .codec import concat_update_parts, decode_part, encode_part
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    VersionedData,
    retry_external,
)
from .state import HollowBatch, ShardState


class Fenced(RuntimeError):
    """A newer writer registered; this handle must not write again."""


class UpperMismatch(RuntimeError):
    """compare_and_append expected a different shard upper."""

    def __init__(self, expected: int, actual: int):
        super().__init__(f"expected upper {expected}, shard at {actual}")
        self.expected = expected
        self.actual = actual


class Machine:
    def __init__(self, shard: str, blob: Blob, consensus: Consensus):
        self.shard = shard
        self.blob = blob
        self.consensus = consensus
        self._state = self._load_or_init()

    # -- state plumbing ----------------------------------------------------
    def _load_or_init(self) -> ShardState:
        head = self.consensus.head(self.shard)
        if head is not None:
            return ShardState.from_bytes(head.data)
        init = ShardState(shard=self.shard)
        if self.consensus.compare_and_set(
            self.shard, None, VersionedData(0, init.to_bytes())
        ):
            return init
        return ShardState.from_bytes(self.consensus.head(self.shard).data)

    def reload(self) -> ShardState:
        head = self.consensus.head(self.shard)
        assert head is not None
        self._state = ShardState.from_bytes(head.data)
        return self._state

    @property
    def state(self) -> ShardState:
        return self._state

    def _apply(self, f):
        """CaS loop: state -> (new_state | None, result). None = no-op.
        Reloads the head each attempt: transition errors (Fenced,
        UpperMismatch) must be judged against the current state, not a
        stale cache — a fenced writer with a stale cache would otherwise
        see UpperMismatch instead of Fenced."""
        while True:
            st = self.reload()
            new, result = f(st)
            if new is None:
                return result
            new = replace(new, seqno=st.seqno + 1)
            if self.consensus.compare_and_set(
                self.shard, st.seqno, VersionedData(new.seqno, new.to_bytes())
            ):
                self._state = new
                return result
            self.reload()

    # -- transitions -------------------------------------------------------
    def register_writer(self) -> int:
        """Claim the write epoch, fencing all previous writers
        (``ComputeCommand::Hello{nonce}`` / persist writer-fencing analog)."""

        def f(st):
            epoch = st.writer_epoch + 1
            return replace(st, writer_epoch=epoch), epoch

        return self._apply(f)

    def compare_and_append(
        self,
        keys: tuple[str, ...],
        lower: int,
        upper: int,
        n_updates: int,
        epoch: int,
    ) -> None:
        """Append a batch [lower, upper) iff lower == shard upper and the
        caller still holds the current write epoch."""
        assert upper > lower, (lower, upper)

        def f(st):
            if epoch != st.writer_epoch:
                raise Fenced(
                    f"epoch {epoch} fenced by {st.writer_epoch}"
                )
            if lower != st.upper:
                raise UpperMismatch(lower, st.upper)
            batch = HollowBatch(lower, upper, tuple(keys), n_updates)
            return (
                replace(st, upper=upper, batches=st.batches + (batch,)),
                None,
            )

        self._apply(f)

    def register_reader(self, reader_id: str) -> int:
        """Install a read hold at the current since; returns that since."""

        def f(st):
            holds = dict(st.reader_holds)
            if reader_id in holds:
                return None, holds[reader_id]
            holds[reader_id] = st.since
            return (
                replace(st, reader_holds=tuple(sorted(holds.items()))),
                st.since,
            )

        return self._apply(f)

    def downgrade_since(self, reader_id: str, new_since: int) -> int:
        """Advance one reader's hold; shard since = min over holds.
        Returns the resulting shard since."""

        def f(st):
            holds = dict(st.reader_holds)
            cur = holds.get(reader_id, st.since)
            holds[reader_id] = max(cur, new_since)
            since = min(holds.values()) if holds else max(
                st.since, new_since
            )
            since = max(since, st.since)
            return (
                replace(
                    st,
                    since=since,
                    reader_holds=tuple(sorted(holds.items())),
                ),
                since,
            )

        return self._apply(f)

    def expire_reader(self, reader_id: str) -> None:
        def f(st):
            holds = dict(st.reader_holds)
            if reader_id not in holds:
                return None, None
            del holds[reader_id]
            since = min(holds.values()) if holds else st.since
            return (
                replace(
                    st,
                    since=max(st.since, since),
                    reader_holds=tuple(sorted(holds.items())),
                ),
                None,
            )

        self._apply(f)

    # -- background duties -------------------------------------------------
    def maybe_compact(self, max_batches: int = 8) -> int:
        """Merge all current batches into one when the spine grows past
        ``max_batches``: reads parts, forwards times to ``since`` (logical
        compaction), consolidates, writes one merged part, swaps it in,
        then deletes the replaced parts. Returns #parts replaced.

        Concurrency: the swap requires the EXACT batch prefix that was
        merged to still be present (identity on the HollowBatch tuple) —
        a racing compaction that replaced any of those batches makes this
        one a no-op (its merged part is discarded), so no appended or
        concurrently-compacted data can be dropped."""
        st = self.reload()
        if len(st.batches) <= max_batches:
            return 0
        prefix = st.batches
        merged_key, n, old_keys = self._merge_parts(st)
        lower = prefix[0].lower
        upper = prefix[-1].upper

        def f(cur):
            if cur.batches[: len(prefix)] != prefix:
                return None, 0  # lost the race; discard our merge
            keep = cur.batches[len(prefix):]
            batch = HollowBatch(lower, upper, (merged_key,) if n else (), n)
            return replace(cur, batches=(batch,) + keep), len(old_keys)

        replaced = self._apply(f)
        # Best-effort blob cleanup: state is already durable; a failed
        # delete leaks a part but never corrupts (internal/gc.rs model).
        doomed = old_keys if replaced else ([merged_key] if n else [])
        for k in doomed:
            try:
                retry_external(lambda k=k: self.blob.delete(k))
            except ExternalDurabilityError:
                pass
        return replaced

    def _merge_parts(self, st: ShardState):
        """Read every part, forward times to since, consolidate, write
        one part. Host-side numpy work (a background task in the
        reference's compaction pool, internal/compact.rs)."""
        schema = None
        parts = []
        old_keys = []
        for b in st.batches:
            for k in b.keys:
                old_keys.append(k)
                data = retry_external(lambda k=k: self.blob.get(k))
                assert data is not None, f"missing blob part {k}"
                sch, cols, nulls, time, diff = decode_part(data)
                schema = schema or sch
                parts.append((cols, nulls, time, diff))
        if schema is None:
            return "", 0, old_keys
        cols, nulls, time, diff = concat_update_parts(
            parts, len(schema.columns)
        )
        # Logical compaction: forward every time to the since frontier.
        time = np.maximum(time, np.uint64(st.since))
        # Consolidate: sum diffs of identical (row, time); drop zeros.
        # Native C++ kernel; float keys grouped by bit pattern (any total
        # order works for grouping), null masks as extra key columns.
        from ... import native

        def as_key(c):
            if c.dtype == np.int64:
                return c
            if c.dtype == np.float64:
                # Normalize -0.0 to +0.0 so a retraction computed with the
                # other zero's bit pattern still cancels; NaNs group by
                # bit pattern, which is stricter than float equality (a
                # NaN never equals itself) and thus still cancels exact
                # re-derivations.
                return np.where(c == 0.0, 0.0, c).view(np.int64)
            return c.astype(np.int64)

        key_cols = [as_key(c) for c in cols]
        key_cols += [
            (
                nl if nl is not None else np.zeros(len(time), np.bool_)
            ).astype(np.int64)
            for nl in nulls
        ]
        key_cols.append(time.astype(np.int64))
        sel, diff = native.consolidate_i64(key_cols, diff)
        cols = [c[sel] for c in cols]
        nulls = [nl[sel] if nl is not None else None for nl in nulls]
        time = time[sel]
        n = len(time)
        if n == 0:
            return "", 0, old_keys
        merged_key = f"{self.shard}/compact-{st.seqno}-{st.upper}"
        # Retried like every durability-layer write (ISSUE 10: the
        # chaos storms run compaction under UnreliableBlob, and an
        # injected transient failure must not abort a compaction the
        # part reads already survived).
        data = encode_part(schema, cols, nulls, time, diff)
        retry_external(lambda: self.blob.set(merged_key, data))
        return merged_key, n, old_keys

    def gc_consensus(self, keep_last: int = 1) -> None:
        """Truncate consensus history below the head (state GC,
        ``internal/gc.rs``): old seqnos are only needed for debugging."""
        head = self.consensus.head(self.shard)
        if head is not None and head.seqno >= keep_last:
            self.consensus.truncate(
                self.shard, head.seqno - keep_last + 1
            )
