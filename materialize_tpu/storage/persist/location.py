"""Durability substrate traits: Blob and Consensus.

Analog of the reference's ``persist/src/location.rs`` (``Blob``:570,
``Consensus``:446): a durable key->bytes store for immutable batch parts,
and a linearizable versioned log for shard state. The reference backs
these with S3/Azure/file and Postgres/CRDB/FoundationDB; here the
production-shaped backends are filesystem blob + SQLite consensus (both
crash-safe on one host), with in-memory variants for tests and an
``UnreliableBlob`` fault-injection wrapper mirroring
``persist/src/unreliable.rs``.
"""

from __future__ import annotations

import os
import sqlite3
import tempfile
import threading
from dataclasses import dataclass


class Blob:
    """Durable key -> immutable bytes store (location.rs:570).

    Values are written once and never mutated; delete exists for GC.
    """

    def set(self, key: str, value: bytes) -> None:
        raise NotImplementedError

    def get(self, key: str) -> bytes | None:
        raise NotImplementedError

    def delete(self, key: str) -> None:
        raise NotImplementedError

    def list_keys(self, prefix: str = "") -> list[str]:
        raise NotImplementedError


class MemBlob(Blob):
    def __init__(self):
        self._data: dict[str, bytes] = {}
        self._lock = threading.Lock()

    def set(self, key: str, value: bytes) -> None:
        with self._lock:
            self._data[key] = bytes(value)

    def get(self, key: str) -> bytes | None:
        with self._lock:
            return self._data.get(key)

    def delete(self, key: str) -> None:
        with self._lock:
            self._data.pop(key, None)

    def list_keys(self, prefix: str = "") -> list[str]:
        with self._lock:
            return sorted(k for k in self._data if k.startswith(prefix))


class FileBlob(Blob):
    """Filesystem-backed blob store with atomic writes (write temp +
    rename, fsync) — the crash-safety discipline of persist's file
    backend (persist/src/file.rs)."""

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)

    def _path(self, key: str) -> str:
        # Keys may contain '/'; map to subdirectories. Reject escapes
        # (shard names flow into keys verbatim).
        p = os.path.join(self.root, key)
        root = os.path.realpath(self.root)
        if os.path.commonpath([os.path.realpath(p), root]) != root:
            raise ValueError(f"blob key escapes the store root: {key!r}")
        return p

    def set(self, key: str, value: bytes) -> None:
        path = self._path(key)
        os.makedirs(os.path.dirname(path), exist_ok=True)
        fd, tmp = tempfile.mkstemp(dir=os.path.dirname(path))
        try:
            with os.fdopen(fd, "wb") as f:
                f.write(value)
                f.flush()
                os.fsync(f.fileno())
            os.replace(tmp, path)
        except BaseException:
            if os.path.exists(tmp):
                os.unlink(tmp)
            raise

    def get(self, key: str) -> bytes | None:
        try:
            with open(self._path(key), "rb") as f:
                return f.read()
        except FileNotFoundError:
            return None

    def delete(self, key: str) -> None:
        try:
            os.unlink(self._path(key))
        except FileNotFoundError:
            pass

    def list_keys(self, prefix: str = "") -> list[str]:
        out = []
        for dirpath, _dirs, files in os.walk(self.root):
            for fn in files:
                rel = os.path.relpath(os.path.join(dirpath, fn), self.root)
                rel = rel.replace(os.sep, "/")
                if rel.startswith(prefix):
                    out.append(rel)
        return sorted(out)


class ExternalDurabilityError(RuntimeError):
    """Injected / environmental durability-layer failure (retryable)."""


def retry_external(
    f, attempts: int | None = None, base_sleep: float | None = None
):
    """Retry transient durability-layer failures with jittered
    exponential backoff (the reference's ore::retry discipline). The
    shape comes from the unified ``retry_policy_durability`` dyncfg
    (utils/retry.py); explicit ``attempts``/``base_sleep`` arguments
    pin a local policy instead (tests)."""
    from ...utils.retry import RetryPolicy, policy

    if attempts is not None or base_sleep is not None:
        pol = RetryPolicy(
            base=base_sleep if base_sleep is not None else 0.01,
            attempts=attempts if attempts is not None else 8,
            jitter=0.0,
        )
    else:
        pol = policy("durability")
    return pol.retry(f, retryable=(ExternalDurabilityError,))


class UnreliableBlob(Blob):
    """Fault-injection wrapper (persist/src/unreliable.rs analog): fails a
    deterministic fraction of operations so retry loops get exercised."""

    def __init__(self, inner: Blob, fail_every: int = 3):
        self.inner = inner
        self.fail_every = fail_every
        self._op = 0

    def _maybe_fail(self):
        self._op += 1
        if self.fail_every and self._op % self.fail_every == 0:
            raise ExternalDurabilityError(
                f"injected blob failure (op {self._op})"
            )

    def set(self, key: str, value: bytes) -> None:
        self._maybe_fail()
        self.inner.set(key, value)

    def get(self, key: str) -> bytes | None:
        self._maybe_fail()
        return self.inner.get(key)

    def delete(self, key: str) -> None:
        self._maybe_fail()
        self.inner.delete(key)

    def list_keys(self, prefix: str = "") -> list[str]:
        return self.inner.list_keys(prefix)


@dataclass(frozen=True)
class VersionedData:
    """One consensus entry: a monotonically increasing sequence number and
    an opaque payload (the serialized shard state diff/snapshot)."""

    seqno: int
    data: bytes


class Consensus:
    """Linearizable per-key versioned log (location.rs:446).

    ``compare_and_set(key, expected, new)`` succeeds iff the key's head
    seqno equals ``expected`` (None for vacant); this is the only write
    path, so all state transitions are totally ordered per shard.
    """

    def head(self, key: str) -> VersionedData | None:
        raise NotImplementedError

    def compare_and_set(
        self, key: str, expected: int | None, new: VersionedData
    ) -> bool:
        raise NotImplementedError

    def scan(self, key: str, from_seqno: int) -> list[VersionedData]:
        raise NotImplementedError

    def truncate(self, key: str, below_seqno: int) -> None:
        """Drop entries with seqno < below_seqno (state GC)."""
        raise NotImplementedError


class MemConsensus(Consensus):
    def __init__(self):
        self._log: dict[str, list[VersionedData]] = {}
        self._lock = threading.Lock()

    def head(self, key: str) -> VersionedData | None:
        with self._lock:
            log = self._log.get(key)
            return log[-1] if log else None

    def compare_and_set(self, key, expected, new) -> bool:
        with self._lock:
            log = self._log.setdefault(key, [])
            head = log[-1].seqno if log else None
            if head != expected:
                return False
            assert new.seqno == (0 if expected is None else expected + 1)
            log.append(new)
            return True

    def scan(self, key, from_seqno) -> list[VersionedData]:
        with self._lock:
            return [
                v for v in self._log.get(key, []) if v.seqno >= from_seqno
            ]

    def truncate(self, key, below_seqno) -> None:
        with self._lock:
            log = self._log.get(key)
            if log:
                self._log[key] = [v for v in log if v.seqno >= below_seqno]


class SqliteConsensus(Consensus):
    """SQLite-backed consensus — the single-host stand-in for the
    reference's Postgres/CRDB consensus (persist/src/postgres.rs).
    Linearizability comes from SQLite's serialized transactions; the
    compare-and-set is one conditional INSERT."""

    def __init__(self, path: str):
        self.path = path
        self._local = threading.local()
        conn = self._conn()
        conn.execute(
            "CREATE TABLE IF NOT EXISTS consensus ("
            " key TEXT NOT NULL, seqno INTEGER NOT NULL, data BLOB NOT NULL,"
            " PRIMARY KEY (key, seqno))"
        )
        conn.commit()

    def _conn(self) -> sqlite3.Connection:
        conn = getattr(self._local, "conn", None)
        if conn is None:
            conn = sqlite3.connect(self.path, timeout=30.0)
            conn.execute("PRAGMA journal_mode=WAL")
            conn.execute("PRAGMA synchronous=NORMAL")
            self._local.conn = conn
        return conn

    def head(self, key: str) -> VersionedData | None:
        row = self._conn().execute(
            "SELECT seqno, data FROM consensus WHERE key=? "
            "ORDER BY seqno DESC LIMIT 1",
            (key,),
        ).fetchone()
        return VersionedData(row[0], row[1]) if row else None

    def compare_and_set(self, key, expected, new) -> bool:
        conn = self._conn()
        try:
            with conn:  # one serialized txn
                row = conn.execute(
                    "SELECT MAX(seqno) FROM consensus WHERE key=?", (key,)
                ).fetchone()
                head = row[0] if row and row[0] is not None else None
                if head != expected:
                    return False
                conn.execute(
                    "INSERT INTO consensus (key, seqno, data) VALUES (?,?,?)",
                    (key, new.seqno, new.data),
                )
            return True
        except sqlite3.IntegrityError:
            return False  # concurrent writer won the seqno

    def scan(self, key, from_seqno) -> list[VersionedData]:
        rows = self._conn().execute(
            "SELECT seqno, data FROM consensus WHERE key=? AND seqno>=? "
            "ORDER BY seqno",
            (key, from_seqno),
        ).fetchall()
        return [VersionedData(r[0], r[1]) for r in rows]

    def truncate(self, key, below_seqno) -> None:
        conn = self._conn()
        with conn:
            conn.execute(
                "DELETE FROM consensus WHERE key=? AND seqno<?",
                (key, below_seqno),
            )
