"""Batch-part codec: host columnar updates <-> self-contained bytes.

Analog of the reference's columnar (Arrow/Parquet) batch parts in Blob
(``persist-client/src/batch.rs``). Parts are self-contained: string
columns are stored as a local dense dictionary (codes remapped through
the process-global dictionary on decode), so a shard can be read by a
fresh process. Layout:

    magic "MTPB" | u32 version | u32 header_len | header JSON
    | column/null/time/diff buffers | u32 crc32 (of all preceding bytes)

Column statistics (min/max per column) ride in the header for filter
pushdown, mirroring persist's part stats (``persist-client/src/stats.rs``
consumed by the abstract interpreter, ``expr/src/interpret.rs``).
"""

from __future__ import annotations

import json
import struct
import zlib

import numpy as np

from ...repr.schema import (
    DIFF_DTYPE,
    GLOBAL_DICT,
    TIME_DTYPE,
    Column,
    ColumnType,
    Schema,
)

MAGIC = b"MTPB"
VERSION = 1


class PartCorruptError(RuntimeError):
    pass


def _enc_buffer(a: np.ndarray) -> tuple[bytes, str]:
    """Encode one buffer; integer columns get native zigzag-varint delta
    compression when it wins (sorted time columns shrink ~8x)."""
    from ... import native

    raw = np.ascontiguousarray(a).tobytes()
    if a.dtype in (np.int64, np.uint64, np.int32):
        v = native.vbyte_encode_i64(
            a.astype(np.int64) if a.dtype != np.int64 else a
        )
        if len(v) < len(raw):
            return v, "vbyte"
    return raw, "raw"


def _dec_buffer(data: bytes, enc: str, dtype: np.dtype, n: int) -> np.ndarray:
    from ... import native

    if enc == "vbyte":
        return native.vbyte_decode_i64(data, n).astype(dtype)
    if enc == "raw":
        return np.frombuffer(data, dtype=dtype, count=n).copy()
    raise PartCorruptError(f"unknown buffer encoding {enc!r}")


def _col_stats(a: np.ndarray, nulls: np.ndarray | None):
    """Min/max over non-null rows, JSON-safe; None when empty/all-null."""
    if nulls is not None:
        a = a[~nulls]
    if a.size == 0 or a.dtype == np.bool_:
        return None
    lo, hi = a.min(), a.max()
    if np.issubdtype(a.dtype, np.floating):
        return [float(lo), float(hi)]
    return [int(lo), int(hi)]


def encode_part(
    schema: Schema,
    cols: list[np.ndarray],
    nulls: list[np.ndarray | None],
    time: np.ndarray,
    diff: np.ndarray,
) -> bytes:
    """Encode one part. Inputs are tight host arrays (no padding)."""
    n = len(diff)
    buffers: list[bytes] = []
    col_meta = []
    # One dictionary snapshot for the whole part: codes in `cols` were
    # assigned under the current (or an earlier same-epoch) labeling; a
    # rebalance concurrent with this encode must not relabel mid-part.
    # The NULL-placeholder "" is ensured BEFORE the snapshot so the
    # snapshot always covers it.
    empty_code = GLOBAL_DICT.encode("")
    gdict = GLOBAL_DICT.snapshot()
    for i, (c, a) in enumerate(zip(schema.columns, cols)):
        a = np.asarray(a)
        assert len(a) == n, f"column {c.name}: {len(a)} rows != {n}"
        nl = nulls[i] if nulls else None
        local_strings = None
        if c.ctype is ColumnType.STRING:
            # Remap process-global codes to a local dense dictionary so
            # the part is self-contained. NULL rows carry placeholder
            # codes that are not dictionary labels — normalize them to
            # a real label first (their value is masked by the null
            # column on decode).
            codes = np.asarray(a, dtype=np.int64).copy()
            if nl is not None:
                codes[np.asarray(nl, bool)] = empty_code
            uniq, inv = np.unique(codes, return_inverse=True)
            local_strings = [gdict.decode(u) for u in uniq]
            a = inv.astype(np.int64)
        buf, enc = _enc_buffer(a)
        buffers.append(buf)
        has_nulls = nl is not None
        if has_nulls:
            buffers.append(
                np.ascontiguousarray(np.asarray(nl, np.bool_)).tobytes()
            )
        col_meta.append(
            {
                "name": c.name,
                "ctype": c.ctype.value,
                "nullable": c.nullable,
                "scale": c.scale,
                "has_nulls": has_nulls,
                "enc": enc,
                "strings": local_strings,
                # Dictionary codes are not order-preserving: no stats for
                # string columns (schema.py is_orderable_on_device).
                "stats": None
                if c.ctype is ColumnType.STRING
                else _col_stats(
                    a, np.asarray(nl, bool) if has_nulls else None
                ),
            }
        )
    tbuf, tenc = _enc_buffer(np.asarray(time, TIME_DTYPE))
    dbuf, denc = _enc_buffer(np.asarray(diff, DIFF_DTYPE))
    buffers.append(tbuf)
    buffers.append(dbuf)
    header = json.dumps(
        {
            "n": int(n),
            "columns": col_meta,
            "buf_lens": [len(b) for b in buffers],
            "time_enc": tenc,
            "diff_enc": denc,
        }
    ).encode()
    body = b"".join(
        [MAGIC, struct.pack("<II", VERSION, len(header)), header, *buffers]
    )
    return body + struct.pack("<I", zlib.crc32(body))


def decode_part(data: bytes):
    """Decode a part -> (schema, cols, nulls, time, diff) host arrays.
    String columns come back as process-global dictionary codes."""
    if len(data) < 12 or data[:4] != MAGIC:
        raise PartCorruptError("bad magic")
    (crc,) = struct.unpack("<I", data[-4:])
    if zlib.crc32(data[:-4]) != crc:
        raise PartCorruptError("crc mismatch")
    version, header_len = struct.unpack("<II", data[4:12])
    if version != VERSION:
        raise PartCorruptError(f"unknown version {version}")
    header = json.loads(data[12 : 12 + header_len])
    n = header["n"]
    off = 12 + header_len
    bufs = []
    for blen in header["buf_lens"]:
        bufs.append(data[off : off + blen])
        off += blen
    cols, nulls, columns = [], [], []
    bi = 0
    for m in header["columns"]:
        ctype = ColumnType(m["ctype"])
        columns.append(Column(m["name"], ctype, m["nullable"], m["scale"]))
        a = _dec_buffer(bufs[bi], m.get("enc", "raw"), ctype.dtype, n)
        bi += 1
        if m["strings"] is not None:
            remap = GLOBAL_DICT.encode_many(m["strings"])
            a = (
                remap[a]
                if len(remap)
                else np.zeros(n, np.int64)
            )
        cols.append(a)
        if m["has_nulls"]:
            nulls.append(np.frombuffer(bufs[bi], dtype=np.bool_, count=n).copy())
            bi += 1
        else:
            nulls.append(None)
    time = _dec_buffer(
        bufs[bi], header.get("time_enc", "raw"), np.dtype(TIME_DTYPE), n
    )
    diff = _dec_buffer(
        bufs[bi + 1], header.get("diff_enc", "raw"), np.dtype(DIFF_DTYPE), n
    )
    return Schema(columns), cols, nulls, time, diff


def concat_update_parts(parts: list, arity: int):
    """Concatenate decoded update parts [(cols, nulls, time, diff), ...]
    into one (cols, nulls, time, diff). Null masks are backfilled with
    all-False where absent; a column whose combined mask has no set bit
    collapses back to None. Shared by ReadHandle.snapshot/fetch and
    compaction so the three read paths cannot diverge."""
    if not parts:
        return (
            [],
            [],
            np.zeros(0, TIME_DTYPE),
            np.zeros(0, DIFF_DTYPE),
        )
    cols = [
        np.concatenate([p[0][i] for p in parts]) for i in range(arity)
    ]
    nulls: list[np.ndarray | None] = []
    for i in range(arity):
        if all(p[1][i] is None for p in parts):
            nulls.append(None)
            continue
        combined = np.concatenate(
            [
                p[1][i]
                if p[1][i] is not None
                else np.zeros(len(p[3]), np.bool_)
                for p in parts
            ]
        )
        nulls.append(combined if combined.any() else None)
    time = np.concatenate([p[2] for p in parts])
    diff = np.concatenate([p[3] for p in parts])
    return cols, nulls, time, diff


def part_stats(data: bytes) -> dict:
    """Header-only read: per-column min/max stats for filter pushdown
    without fetching/decoding column buffers."""
    if data[:4] != MAGIC:
        raise PartCorruptError("bad magic")
    _version, header_len = struct.unpack("<II", data[4:12])
    header = json.loads(data[12 : 12 + header_len])
    return {
        m["name"]: m["stats"] for m in header["columns"]
    }
