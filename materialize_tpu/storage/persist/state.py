"""Shard state: the durable description of one time-varying collection.

Analog of ``persist-client/src/internal/state.rs``: a shard is a totally
ordered sequence of immutable batches of ``(data, time, diff)`` updates,
described by ``[lower, upper)`` time bounds, plus the read frontier
``since`` (readers may ask for any ``as_of >= since``) and the write
frontier ``upper`` (the next append must start exactly there). State is
serialized to JSON and advanced only through consensus compare-and-set
(machine.py), so transitions are totally ordered.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field, replace


@dataclass(frozen=True)
class HollowBatch:
    """A batch by reference: blob part keys + time bounds + row count
    (``persist-client/src/internal/state.rs`` HollowBatch analog)."""

    lower: int
    upper: int
    keys: tuple[str, ...]
    n_updates: int
    # Encoded part bytes (sum over keys). Durable so tiering can split
    # hot/cold bytes without fetching cold parts; 0 on states written
    # before this field existed.
    n_bytes: int = 0

    def to_json(self):
        return {
            "lower": self.lower,
            "upper": self.upper,
            "keys": list(self.keys),
            "n": self.n_updates,
            "bytes": self.n_bytes,
        }

    @staticmethod
    def from_json(d) -> "HollowBatch":
        return HollowBatch(
            d["lower"], d["upper"], tuple(d["keys"]), d["n"],
            d.get("bytes", 0),
        )


@dataclass(frozen=True)
class ShardState:
    shard: str
    seqno: int = 0
    since: int = 0
    upper: int = 0
    # Contiguous: batches[i].upper == batches[i+1].lower; empty time
    # ranges are represented as batches with no keys.
    batches: tuple[HollowBatch, ...] = ()
    # Fencing token: only the writer holding the current epoch may
    # append (persist writer fencing / txn-wal fencing analog).
    writer_epoch: int = 0
    # Opaque per-reader since holds: reader id -> frontier. The shard
    # since is the min of these (read holds, coord/read_policy.rs analog).
    reader_holds: tuple[tuple[str, int], ...] = ()
    # Compaction lease (internal/compact.rs + the PR 7 epoch fencing
    # discipline): at most one compactor holds the lease per shard;
    # the epoch is the fencing token a swap-in must present, so a
    # compactor that lost its lease (expiry + handoff) cannot swap a
    # stale merge over batches a successor already replaced.
    compactor_epoch: int = 0
    compactor_holder: str = ""
    # Wall-clock lease deadline (seconds, time.time domain). A crashed
    # compactor's lease is reclaimable once this passes.
    lease_expires: float = 0.0

    def to_bytes(self) -> bytes:
        return json.dumps(
            {
                "shard": self.shard,
                "seqno": self.seqno,
                "since": self.since,
                "upper": self.upper,
                "batches": [b.to_json() for b in self.batches],
                "writer_epoch": self.writer_epoch,
                "reader_holds": list(map(list, self.reader_holds)),
                "compactor_epoch": self.compactor_epoch,
                "compactor_holder": self.compactor_holder,
                "lease_expires": self.lease_expires,
            }
        ).encode()

    @staticmethod
    def from_bytes(data: bytes) -> "ShardState":
        d = json.loads(data)
        return ShardState(
            shard=d["shard"],
            seqno=d["seqno"],
            since=d["since"],
            upper=d["upper"],
            batches=tuple(HollowBatch.from_json(b) for b in d["batches"]),
            writer_epoch=d["writer_epoch"],
            reader_holds=tuple(
                (r, f) for r, f in d.get("reader_holds", [])
            ),
            compactor_epoch=d.get("compactor_epoch", 0),
            compactor_holder=d.get("compactor_holder", ""),
            lease_expires=d.get("lease_expires", 0.0),
        )

    def referenced_keys(self) -> set[str]:
        out: set[str] = set()
        for b in self.batches:
            out.update(b.keys)
        return out
