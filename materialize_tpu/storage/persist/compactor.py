"""The detached compaction plane: leased, off-path spine maintenance.

Analog of ``persist-client/src/internal/compact.rs`` run the way the
reference deploys it (PAPER.md: persist's compactor service): the
writer's tick path only *requests* compaction — an O(1) enqueue when
the spine passes ``arrangement_compaction_batches`` — and a worker
thread does the reads/merge/blob-write/swap off the serving path.

Safety is lease + epoch fencing (the PR 7 discipline applied to
compaction): a compactor must hold the shard's compaction lease
(``Machine.acquire_compaction_lease``), renew it before the swap, and
present its lease epoch at the swap — a compactor that stalled past its
lease (SIGKILL, GC pause) is fenced out by the successor's epoch bump,
so its stale merge can never overwrite the successor's work. A crashed
compactor leaves at most a held-until-expiry lease and an orphan blob
part; neither affects readable content.

Everything is COUNTED (``STATS``): merges and merged-part blob writes
are attributed to the context that performed them ("inline" = the
writer's tick path, "background" = this service), which is what the
``compactor-smoke`` CI gate and the acceptance criterion assert —
zero tick-path compaction work under ``compaction_mode=background``,
by counter, not by inspection.
"""

from __future__ import annotations

import os
import threading
import time as _time
from collections import deque

from .machine import CompactorFenced, Machine
from .pubsub import PUBSUB


class CompactorCrash(RuntimeError):
    """Injected mid-merge crash (chaos hook): the worker dies leaving
    its lease held and possibly an orphan merged part — exactly the
    durable residue of a SIGKILL at that point."""


class CompactionStats:
    """Process-global counted compaction activity, per shard. Served by
    ``mz_compactions``; replicas piggyback their rows to the controller
    on Frontiers like every other introspection source."""

    FIELDS = (
        "requests",
        "merges_inline",
        "merges_background",
        "merges_lost",
        "blob_writes_inline",
        "blob_writes_background",
        "input_bytes",
        "output_bytes",
        "off_path_s",
        "lease_epoch",
        "fenced",
        "crashes",
    )

    def __init__(self):
        self._lock = threading.Lock()
        self._shards: dict[str, dict] = {}
        self.dirty: set[str] = set()

    def _s(self, shard: str) -> dict:
        s = self._shards.get(shard)
        if s is None:
            s = self._shards[shard] = {f: 0 for f in self.FIELDS}
        self.dirty.add(shard)
        return s

    def record_request(self, shard: str) -> None:
        with self._lock:
            self._s(shard)["requests"] += 1

    def record_merge(
        self, shard: str, ctx: str, replaced: int,
        in_bytes: int, out_bytes: int,
    ) -> None:
        with self._lock:
            s = self._s(shard)
            if replaced:
                s[f"merges_{ctx}"] += 1
                s["input_bytes"] += in_bytes
                s["output_bytes"] += out_bytes
            else:
                s["merges_lost"] += 1

    def record_blob_write(self, shard: str, ctx: str, nbytes: int) -> None:
        with self._lock:
            self._s(shard)[f"blob_writes_{ctx}"] += 1

    def record_offpath(
        self, shard: str, seconds: float, lease_epoch: int
    ) -> None:
        with self._lock:
            s = self._s(shard)
            s["off_path_s"] += seconds
            s["lease_epoch"] = max(s["lease_epoch"], lease_epoch)

    def record_fenced(self, shard: str) -> None:
        with self._lock:
            self._s(shard)["fenced"] += 1

    def record_crash(self, shard: str) -> None:
        with self._lock:
            self._s(shard)["crashes"] += 1

    def rows(self) -> dict[str, dict]:
        with self._lock:
            return {sh: dict(s) for sh, s in self._shards.items()}

    def take_dirty(self) -> dict[str, dict]:
        """Rows changed since the last take (the Frontiers-piggyback
        shipping discipline: only deltas cross the CTP)."""
        with self._lock:
            out = {
                sh: dict(self._shards[sh])
                for sh in self.dirty
                if sh in self._shards
            }
            self.dirty.clear()
            return out

    def totals(self) -> dict:
        with self._lock:
            tot = {f: 0 for f in self.FIELDS}
            for s in self._shards.values():
                for f in self.FIELDS:
                    tot[f] = (
                        max(tot[f], s[f])
                        if f == "lease_epoch"
                        else tot[f] + s[f]
                    )
            return tot

    def reset(self) -> None:
        with self._lock:
            self._shards.clear()
            self.dirty.clear()


STATS = CompactionStats()


class CompactionService:
    """One worker thread draining a deduplicated per-shard request
    queue. ``request`` is the only thing the tick path calls — it never
    blocks on merge work. Multiple services (processes) may target the
    same shard; the lease serializes them and epoch fencing makes the
    loser harmless."""

    def __init__(
        self,
        holder: str | None = None,
        lease_s: float | None = None,
    ):
        self.holder = holder or f"compactor-{os.getpid()}-{id(self):x}"
        self._lease_s = lease_s
        self._cv = threading.Condition()
        self._queue: deque[Machine] = deque()
        self._queued: set[str] = set()
        self._busy = 0
        self._thread: threading.Thread | None = None
        self._stopped = False
        # Chaos hook: consume-once crash injection point, "merge" or
        # "renew" — the worker raises CompactorCrash there, leaving the
        # lease held (the durable residue of a SIGKILL at that write).
        self.crash_next: str | None = None

    # -- tick-path API -----------------------------------------------------
    def request(self, machine: Machine) -> bool:
        """Enqueue one shard for background compaction. O(1), never
        merges, never touches blob: the entire tick-path cost of
        compaction under compaction_mode=background."""
        STATS.record_request(machine.shard)
        with self._cv:
            if self._stopped or machine.shard in self._queued:
                return False
            self._queued.add(machine.shard)
            self._queue.append(machine)
            self._ensure_thread()
            self._cv.notify()
            return True

    # -- worker ------------------------------------------------------------
    def _ensure_thread(self) -> None:
        if self._thread is None or not self._thread.is_alive():
            self._thread = threading.Thread(
                target=self._loop, name="persist-compactor", daemon=True
            )
            self._thread.start()

    def _loop(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._stopped:
                    self._cv.wait(0.5)
                if self._stopped and not self._queue:
                    return
                machine = self._queue.popleft()
                self._queued.discard(machine.shard)
                self._busy += 1
            try:
                self.compact_shard(machine)
            except CompactorCrash:
                STATS.record_crash(machine.shard)
            except Exception:
                pass  # background duty: never take the process down
            finally:
                with self._cv:
                    self._busy -= 1
                    self._cv.notify_all()

    def _config(self):
        from ...utils.dyncfg import (
            ARRANGEMENT_COMPACTION_BATCHES,
            COMPACTION_LEASE_S,
            COMPUTE_CONFIGS,
        )

        lease = (
            self._lease_s
            if self._lease_s is not None
            else COMPACTION_LEASE_S(COMPUTE_CONFIGS)
        )
        return ARRANGEMENT_COMPACTION_BATCHES(COMPUTE_CONFIGS), lease

    def compact_shard(
        self, machine: Machine, max_batches: int | None = None
    ) -> dict:
        """One leased compaction attempt: acquire → merge → renew →
        fenced swap → publish → delete replaced parts → GC consensus →
        release. Returns a report dict (tests + chaos assertions)."""
        threshold, lease_s = self._config()
        if max_batches is None:
            max_batches = threshold
        t0 = _time.monotonic()
        lease = machine.acquire_compaction_lease(self.holder, lease_s)
        if lease is None:
            return {"skipped": "lease-held"}
        held_by_crash = False
        try:
            st = machine.reload()
            if len(st.batches) <= max_batches:
                return {"skipped": "below-threshold"}
            prefix = st.batches
            merged_key, n, old_keys = machine._merge_parts(
                st, ctx="background"
            )
            in_bytes, out_bytes = machine._last_merge_bytes
            if self.crash_next == "merge":
                self.crash_next = None
                held_by_crash = True
                raise CompactorCrash("injected crash after merge")
            # Renew before the durable swap: a lost lease means a
            # successor took over — abandon rather than fight it.
            if not machine.renew_compaction_lease(lease, lease_s):
                STATS.record_fenced(machine.shard)
                machine._delete_parts([merged_key] if n else [])
                return {"fenced": "renew"}
            if self.crash_next == "renew":
                self.crash_next = None
                held_by_crash = True
                raise CompactorCrash("injected crash after renew")
            try:
                replaced = machine.swap_compacted(
                    prefix, merged_key, n, out_bytes, epoch=lease
                )
            except CompactorFenced:
                STATS.record_fenced(machine.shard)
                machine._delete_parts([merged_key] if n else [])
                return {"fenced": "swap"}
            STATS.record_merge(
                machine.shard, "background", replaced, in_bytes, out_bytes
            )
            # Announce the swap: writers learn their request completed,
            # readers with in-flight fetches re-resolve parts via the
            # CompactionRace retry against the new state.
            PUBSUB.publish(
                machine.shard, machine.state.seqno, kind="compaction"
            )
            doomed = old_keys if replaced else ([merged_key] if n else [])
            machine._delete_parts(doomed)
            machine.gc_consensus()
            return {
                "replaced": replaced,
                "merged_key": merged_key,
                "lease_epoch": lease,
                "in_bytes": in_bytes,
                "out_bytes": out_bytes,
            }
        finally:
            STATS.record_offpath(
                machine.shard, _time.monotonic() - t0, lease
            )
            if not held_by_crash:
                machine.release_compaction_lease(lease)

    # -- lifecycle ---------------------------------------------------------
    def drain(self, timeout: float = 30.0) -> bool:
        """Block until the queue is empty and the worker idle (tests,
        gates, bench — never the tick path)."""
        deadline = _time.monotonic() + timeout
        with self._cv:
            while self._queue or self._busy:
                remaining = deadline - _time.monotonic()
                if remaining <= 0:
                    return False
                self._cv.wait(min(remaining, 0.1))
            return True

    def stop(self) -> None:
        with self._cv:
            self._stopped = True
            self._cv.notify_all()
        t = self._thread
        if t is not None and t.is_alive():
            t.join(timeout=5.0)


_SERVICE: CompactionService | None = None
_SERVICE_LOCK = threading.Lock()


def compaction_service() -> CompactionService:
    """The process's shared background compactor (started lazily on the
    first request; daemon thread)."""
    global _SERVICE
    with _SERVICE_LOCK:
        if _SERVICE is None or _SERVICE._stopped:
            _SERVICE = CompactionService()
        return _SERVICE


def reset_compaction_service() -> None:
    """Stop the shared service (environment shutdown / test isolation)."""
    global _SERVICE
    with _SERVICE_LOCK:
        svc, _SERVICE = _SERVICE, None
    if svc is not None:
        svc.stop()
