"""Process-local persist PubSub: push notification of shard state changes.

Analog of ``persist-client/src/rpc.rs`` (PersistPubSubClient): every
successful consensus compare-and-set publishes the shard's new seqno to
in-process subscribers, so readers wait on an event instead of polling
consensus on a 2ms timer (``ReadHandle.wait_for_upper``), and the
background compactor's part swaps announce themselves to writers and
readers the moment they land. Cross-process consumers still poll — the
publish is a latency optimization layered over the durable state, never
a correctness dependency (a missed notification only costs one poll
interval). ROADMAP item 4's multi-process fan-out hubs subscribe to the
same channel.
"""

from __future__ import annotations

import threading


class ShardPubSub:
    """Per-shard broadcast: ``publish`` wakes every in-flight ``wait``
    and invokes registered callbacks. Callbacks run on the publisher's
    thread and must not block (they are on the CaS ack path)."""

    def __init__(self):
        self._lock = threading.Lock()
        # shard -> generation Event: waiters grab the current event;
        # publish sets-and-replaces it, so late subscribers never miss
        # a wakeup that happened before they started waiting.
        self._events: dict[str, threading.Event] = {}
        self._seqnos: dict[str, int] = {}
        self._subs: dict[str, list] = {}
        self.published = 0  # notification count (introspection/bench)

    def _event(self, shard: str) -> threading.Event:
        with self._lock:
            ev = self._events.get(shard)
            if ev is None:
                ev = self._events[shard] = threading.Event()
            return ev

    def publish(self, shard: str, seqno: int, kind: str = "state") -> None:
        with self._lock:
            if seqno <= self._seqnos.get(shard, -1) and kind == "state":
                return
            self._seqnos[shard] = max(self._seqnos.get(shard, -1), seqno)
            ev = self._events.pop(shard, None)
            subs = list(self._subs.get(shard, ()))
            self.published += 1
        if ev is not None:
            ev.set()
        for cb in subs:
            try:
                cb(shard, seqno, kind)
            except Exception:
                pass

    def wait(self, shard: str, timeout: float) -> bool:
        """Block until the next publish for ``shard`` (or timeout).
        Returns True on a wakeup. Callers must re-check the durable
        state either way: this is a hint, not a delivery guarantee."""
        return self._event(shard).wait(timeout)

    def subscribe(self, shard: str, cb) -> None:
        with self._lock:
            self._subs.setdefault(shard, []).append(cb)

    def unsubscribe(self, shard: str, cb) -> None:
        with self._lock:
            subs = self._subs.get(shard, [])
            if cb in subs:
                subs.remove(cb)


#: The process-wide channel (one per process, like the reference's
#: in-process PersistPubSub for a single environmentd).
PUBSUB = ShardPubSub()
