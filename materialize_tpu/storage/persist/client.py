"""Persist client: shard handles over (Blob, Consensus).

Analog of ``persist-client/src/lib.rs`` + ``read.rs``/``write.rs``:
``PersistClient.open(shard)`` yields a ``WriteHandle`` (compare-and-append
of update batches) and a ``ReadHandle`` (snapshot at an ``as_of`` and
``listen`` for updates beyond it). All updates are host columnar
``(cols, nulls, time, diff)``; the dataflow bridges (operators.py) turn
these into device batches.
"""

from __future__ import annotations

import itertools
import threading
import time as _time

import numpy as np

from ...repr.batch import Batch
from ...repr.schema import Schema
from .codec import concat_update_parts, decode_part, encode_part
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    retry_external as _retry,
)
from .machine import Fenced, Machine, UpperMismatch


class WriteHandle:
    def __init__(self, machine: Machine, schema: Schema):
        self.machine = machine
        self.schema = schema
        self.epoch = machine.register_writer()
        self._part_seq = 0

    @property
    def upper(self) -> int:
        return self.machine.state.upper

    def compare_and_append(
        self,
        cols,
        nulls,
        time,
        diff,
        lower: int,
        upper: int,
    ) -> None:
        """Durably append updates with times in [lower, upper); raises
        UpperMismatch if the shard upper moved, Fenced if a newer writer
        registered. An empty update set still advances the upper."""
        time = np.asarray(time, np.uint64)
        diff = np.asarray(diff, np.int64)
        n = len(diff)
        if n:
            assert time.min() >= lower and time.max() < upper, (
                "updates outside [lower, upper)"
            )
            keys = (self._write_part(cols, nulls, time, diff),)
        else:
            keys = ()
        self.machine.compare_and_append(keys, lower, upper, n, self.epoch)

    def append_batch(self, batch: Batch, lower: int, upper: int) -> None:
        """Append a device Batch's valid rows."""
        cols = batch.to_columns()
        data_cols, time, diff = cols[:-2], cols[-2], cols[-1]
        n = len(diff)
        nulls = [
            None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
        ]
        self.compare_and_append(data_cols, nulls, time, diff, lower, upper)

    def _write_part(self, cols, nulls, time, diff) -> str:
        data = encode_part(
            self.schema,
            [np.asarray(c) for c in cols],
            [None if nl is None else np.asarray(nl, bool) for nl in nulls]
            if nulls
            else [None] * len(cols),
            time,
            diff,
        )
        self._part_seq += 1
        key = (
            f"{self.machine.shard}/part-e{self.epoch}-{self._part_seq}"
        )
        _retry(lambda: self.machine.blob.set(key, data))
        return key


class ReadHandle:
    def __init__(self, machine: Machine, reader_id: str):
        self.machine = machine
        self.reader_id = reader_id
        self.since = machine.register_reader(reader_id)

    @property
    def upper(self) -> int:
        return self.machine.reload().upper

    def downgrade_since(self, new_since: int) -> None:
        self.since = max(self.since, new_since)
        self.machine.downgrade_since(self.reader_id, new_since)

    def expire(self) -> None:
        self.machine.expire_reader(self.reader_id)

    def _read_parts(self, batches):
        schema = None
        out = []
        for b in batches:
            for k in b.keys:
                data = _retry(lambda k=k: self.machine.blob.get(k))
                assert data is not None, f"missing part {k}"
                sch, cols, nulls, time, diff = decode_part(data)
                schema = schema or sch
                out.append((cols, nulls, time, diff))
        return schema, out

    def snapshot(self, as_of: int):
        """All updates with time <= as_of, times forwarded to as_of —
        the definite collection at as_of (ASOF semantics,
        doc/developer/overview.md:114-120). Requires since <= as_of <
        upper (once readable, reads are repeatable)."""
        st = self.machine.reload()
        if not (st.since <= as_of < st.upper):
            raise ValueError(
                f"as_of {as_of} outside [since {st.since}, upper {st.upper})"
            )
        # Batches entirely above as_of cannot contribute: skip the fetch.
        schema, parts = self._read_parts(
            [b for b in st.batches if b.lower <= as_of]
        )
        sel = []
        for cols, nulls, time, diff in parts:
            m = time <= np.uint64(as_of)
            if not m.any():
                continue
            sel.append(
                (
                    [c[m] for c in cols],
                    [None if nl is None else nl[m] for nl in nulls],
                    np.full(int(m.sum()), as_of, np.uint64),
                    diff[m],
                )
            )
        arity = len(sel[0][0]) if sel else 0
        cols, nulls, time, diff = concat_update_parts(sel, arity)
        return schema, cols, nulls, time, diff

    def wait_for_upper(self, frontier: int, timeout: float = 5.0):
        """Block until the shard upper passes ``frontier``; returns the
        new upper or None on timeout. The polling analog of persist
        PubSub-notified Listen (persist-client/src/rpc.rs); the
        coordinator swaps in push notification when in-process."""
        deadline = _time.monotonic() + timeout
        while True:
            st = self.machine.reload()
            if st.upper > frontier:
                return st.upper
            if _time.monotonic() > deadline:
                return None
            _time.sleep(0.002)

    def fetch(self, lo: int, hi: int):
        """Updates with lo <= time < hi. Caller must ensure hi <= upper
        (completeness) and lo >= since (not compacted away)."""
        st = self.machine.reload()
        assert hi <= st.upper, f"fetch hi {hi} beyond upper {st.upper}"
        assert lo >= st.since or lo >= hi, (
            f"fetch lo {lo} below since {st.since}"
        )
        batches = [b for b in st.batches if b.upper > lo and b.lower < hi]
        schema, parts = self._read_parts(batches)
        sel = []
        for cols, nulls, time, diff in parts:
            m = (time >= np.uint64(lo)) & (time < np.uint64(hi))
            sel.append(
                (
                    [c[m] for c in cols],
                    [None if nl is None else nl[m] for nl in nulls],
                    time[m],
                    diff[m],
                )
            )
        arity = len(sel[0][0]) if sel else 0
        cols, nulls, time, diff = concat_update_parts(sel, arity)
        return schema, cols, nulls, time, diff

    def listen_next(self, frontier: int, timeout: float = 5.0):
        """Block for the upper to pass ``frontier``; returns (updates in
        [frontier, new_upper), new_upper) or None on timeout."""
        upper = self.wait_for_upper(frontier, timeout)
        if upper is None:
            return None
        return self.fetch(frontier, upper), upper


class PersistClient:
    """Entry point: open shards by name over one (Blob, Consensus) pair."""

    def __init__(self, blob: Blob, consensus: Consensus):
        self.blob = blob
        self.consensus = consensus
        self._machines: dict[str, Machine] = {}
        self._reader_seq = itertools.count()

    def machine(self, shard: str) -> Machine:
        if shard not in self._machines:
            self._machines[shard] = Machine(shard, self.blob, self.consensus)
        return self._machines[shard]

    def open_writer(self, shard: str, schema: Schema) -> WriteHandle:
        return WriteHandle(self.machine(shard), schema)

    def open_reader(self, shard: str, reader_id: str | None = None) -> ReadHandle:
        rid = reader_id or f"r{next(self._reader_seq)}-{id(self):x}"
        return ReadHandle(self.machine(shard), rid)
