"""Persist client: shard handles over (Blob, Consensus).

Analog of ``persist-client/src/lib.rs`` + ``read.rs``/``write.rs``:
``PersistClient.open(shard)`` yields a ``WriteHandle`` (compare-and-append
of update batches) and a ``ReadHandle`` (snapshot at an ``as_of`` and
``listen`` for updates beyond it). All updates are host columnar
``(cols, nulls, time, diff)``; the dataflow bridges (operators.py) turn
these into device batches.
"""

from __future__ import annotations

import itertools
import threading
import time as _time

import numpy as np

from ...repr.batch import Batch
from ...repr.schema import Schema
from .codec import concat_update_parts, decode_part, encode_part
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    retry_external as _retry,
)
from .machine import CompactionRace, Fenced, Machine, UpperMismatch
from .pubsub import PUBSUB


class PartCache:
    """The hot tier of batch-part tiering (ISSUE 20): decoded parts
    kept host-resident so hot recent spans never touch blob on read,
    while cold parts live blob-only and lazily rehydrate on first
    read. LRU over encoded-size accounting against the
    ``part_hot_bytes`` budget; the ``part_tiering`` dyncfg picks
    auto (budgeted) / all_hot (never evict) / all_cold (never cache).

    Cached arrays are shared: readers must mask-copy (they already do
    — ``snapshot``/``fetch`` build new arrays), never mutate. One cache
    per PersistClient, so a client's shard namespace is its cache
    namespace (two tests reusing shard names on fresh blobs cannot
    cross-contaminate)."""

    def __init__(self):
        self._lock = threading.Lock()
        # key -> (schema, cols, nulls, time, diff, encoded_bytes);
        # dict order is the LRU order (move-to-end on hit).
        self._parts: dict[str, tuple] = {}
        self.hot_bytes = 0
        self.hits = 0
        self.misses = 0
        self.rehydrations = 0
        self.evictions = 0
        # Cached columns hold string CODES remapped through the live
        # GLOBAL_DICT at decode time (codec.decode_part); a dictionary
        # rebalance relabels every code, so a changed dict epoch is
        # total invalidation (repr/schema.py epoch contract) — stale
        # hot parts would decode to the WRONG strings.
        from ...repr.schema import GLOBAL_DICT

        self._dict_epoch = GLOBAL_DICT.epoch

    def _check_epoch_locked(self) -> None:
        from ...repr.schema import GLOBAL_DICT

        epoch = GLOBAL_DICT.epoch
        if epoch != self._dict_epoch:
            self._parts.clear()
            self.hot_bytes = 0
            self._dict_epoch = epoch

    @staticmethod
    def _config():
        from ...utils.dyncfg import (
            COMPUTE_CONFIGS,
            PART_HOT_BYTES,
            PART_TIERING,
        )

        return PART_TIERING(COMPUTE_CONFIGS), PART_HOT_BYTES(
            COMPUTE_CONFIGS
        )

    def put(
        self, key, schema, cols, nulls, time, diff, nbytes,
        rehydrated: bool = False,
        dict_epoch: int | None = None,
    ) -> None:
        mode, budget = self._config()
        if mode == "all_cold":
            return
        with self._lock:
            self._check_epoch_locked()
            if (
                dict_epoch is not None
                and dict_epoch != self._dict_epoch
            ):
                # Decoded under a pre-rebalance labeling that a
                # concurrent rebalance just retired: caching it would
                # serve wrong strings. Drop; the next read re-decodes.
                return
            if rehydrated:
                self.rehydrations += 1
            if key in self._parts:
                self.hot_bytes -= self._parts.pop(key)[5]
            self._parts[key] = (schema, cols, nulls, time, diff, nbytes)
            self.hot_bytes += nbytes
            if mode == "auto":
                while self.hot_bytes > budget and len(self._parts) > 1:
                    _k, ent = next(iter(self._parts.items()))
                    del self._parts[_k]
                    self.hot_bytes -= ent[5]
                    self.evictions += 1

    def get(self, key):
        with self._lock:
            self._check_epoch_locked()
            ent = self._parts.pop(key, None)
            if ent is None:
                self.misses += 1
                return None
            self._parts[key] = ent  # move to MRU end
            self.hits += 1
            return ent

    def evict_keys(self, keys) -> None:
        with self._lock:
            for k in keys:
                ent = self._parts.pop(k, None)
                if ent is not None:
                    self.hot_bytes -= ent[5]

    def hot_bytes_for(self, keys) -> int:
        """Encoded bytes of the given part keys currently hot."""
        with self._lock:
            return sum(
                self._parts[k][5] for k in keys if k in self._parts
            )

    def stats(self) -> dict:
        with self._lock:
            return {
                "hot_bytes": self.hot_bytes,
                "parts": len(self._parts),
                "hits": self.hits,
                "misses": self.misses,
                "rehydrations": self.rehydrations,
                "evictions": self.evictions,
            }


class WriteHandle:
    def __init__(
        self, machine: Machine, schema: Schema,
        auto_compaction: bool = False,
    ):
        self.machine = machine
        self.schema = schema
        self.epoch = machine.register_writer()
        self.auto_compaction = auto_compaction
        self._part_seq = 0

    @property
    def upper(self) -> int:
        return self.machine.state.upper

    def compare_and_append(
        self,
        cols,
        nulls,
        time,
        diff,
        lower: int,
        upper: int,
    ) -> None:
        """Durably append updates with times in [lower, upper); raises
        UpperMismatch if the shard upper moved, Fenced if a newer writer
        registered. An empty update set still advances the upper."""
        time = np.asarray(time, np.uint64)
        diff = np.asarray(diff, np.int64)
        n = len(diff)
        nbytes = 0
        if n:
            assert time.min() >= lower and time.max() < upper, (
                "updates outside [lower, upper)"
            )
            key, nbytes = self._write_part(cols, nulls, time, diff)
            keys = (key,)
        else:
            keys = ()
        self.machine.compare_and_append(
            keys, lower, upper, n, self.epoch, n_bytes=nbytes
        )
        if self.auto_compaction:
            self._maybe_request_compaction()

    def _maybe_request_compaction(self) -> None:
        """The writer's entire compaction duty under ISSUE 20: when the
        post-append spine passes the threshold, either request the
        background service (O(1) enqueue — the tick path never merges,
        never blob-writes) or, under compaction_mode=inline, do the
        old on-path merge (kept as the measurable baseline)."""
        from ...utils.dyncfg import (
            ARRANGEMENT_COMPACTION_BATCHES,
            COMPACTION_MODE,
            COMPUTE_CONFIGS,
        )

        mode = COMPACTION_MODE(COMPUTE_CONFIGS)
        if mode == "off":
            return
        threshold = ARRANGEMENT_COMPACTION_BATCHES(COMPUTE_CONFIGS)
        # The just-CaS'd cached state: no consensus read on this path.
        if len(self.machine.state.batches) <= threshold:
            return
        if mode == "inline":
            self.machine.maybe_compact(max_batches=threshold, ctx="inline")
        else:
            from .compactor import compaction_service

            compaction_service().request(self.machine)

    def append_batch(self, batch: Batch, lower: int, upper: int) -> None:
        """Append a device Batch's valid rows."""
        cols = batch.to_columns()
        data_cols, time, diff = cols[:-2], cols[-2], cols[-1]
        n = len(diff)
        nulls = [
            None if nl is None else np.asarray(nl)[:n] for nl in batch.nulls
        ]
        self.compare_and_append(data_cols, nulls, time, diff, lower, upper)

    def _write_part(self, cols, nulls, time, diff) -> tuple[str, int]:
        cols = [np.asarray(c) for c in cols]
        nulls = (
            [None if nl is None else np.asarray(nl, bool) for nl in nulls]
            if nulls
            else [None] * len(cols)
        )
        from ...repr.schema import GLOBAL_DICT

        dict_epoch = GLOBAL_DICT.epoch
        data = encode_part(self.schema, cols, nulls, time, diff)
        self._part_seq += 1
        key = (
            f"{self.machine.shard}/part-e{self.epoch}-{self._part_seq}"
        )
        _retry(lambda: self.machine.blob.set(key, data))
        # Write-through to the hot tier: the freshest span is exactly
        # what readers fetch next, so it must never pay a rehydration.
        cache = getattr(self.machine, "part_cache", None)
        if cache is not None:
            cache.put(
                key, self.schema, cols, nulls, time, diff, len(data),
                dict_epoch=dict_epoch,
            )
        return key, len(data)


class ReadHandle:
    def __init__(self, machine: Machine, reader_id: str):
        self.machine = machine
        self.reader_id = reader_id
        self.since = machine.register_reader(reader_id)
        # Times a read observed a mid-flight compaction swap and
        # retried (chaos asserts the race actually happened).
        self.race_retries = 0

    @property
    def upper(self) -> int:
        return self.machine.reload().upper

    def downgrade_since(self, new_since: int) -> None:
        self.since = max(self.since, new_since)
        self.machine.downgrade_since(self.reader_id, new_since)

    def expire(self) -> None:
        self.machine.expire_reader(self.reader_id)

    def _read_parts(self, batches):
        """Fetch parts, hot tier first. A part key that is GONE from
        blob was swapped out by a concurrent compaction between our
        state load and this fetch: raise CompactionRace — the caller
        reloads and re-reads (the merged part has identical content),
        and ONLY that exception retries (a decode failure is a real
        codec bug and must surface, operators.py AsOfError note)."""
        schema = None
        out = []
        cache = getattr(self.machine, "part_cache", None)
        for b in batches:
            for k in b.keys:
                ent = cache.get(k) if cache is not None else None
                if ent is not None:
                    sch, cols, nulls, time, diff = ent[:5]
                else:
                    data = _retry(lambda k=k: self.machine.blob.get(k))
                    if data is None:
                        raise CompactionRace(
                            f"part {k} swapped out by a concurrent "
                            "compaction"
                        )
                    from ...repr.schema import GLOBAL_DICT

                    dict_epoch = GLOBAL_DICT.epoch
                    sch, cols, nulls, time, diff = decode_part(data)
                    if cache is not None:
                        # Cold part's first read: rehydrate into the
                        # hot tier (counted; doc/perf.md cost model).
                        cache.put(
                            k, sch, cols, nulls, time, diff, len(data),
                            rehydrated=True,
                            dict_epoch=dict_epoch,
                        )
                schema = schema or sch
                out.append((cols, nulls, time, diff))
        return schema, out

    def snapshot(self, as_of: int):
        """All updates with time <= as_of, times forwarded to as_of —
        the definite collection at as_of (ASOF semantics,
        doc/developer/overview.md:114-120). Requires since <= as_of <
        upper (once readable, reads are repeatable). A read racing a
        just-swapped part retries here against the reloaded state —
        compaction never changes content, so the retry is sound and
        bounded (each retry observes a strictly newer seqno)."""
        for attempt in range(8):
            st = self.machine.reload()
            if as_of >= st.upper:
                raise ValueError(
                    f"as_of {as_of} outside [since {st.since}, "
                    f"upper {st.upper})"
                )
            if as_of < st.since:
                # Transient when racing a since downgrade mid-hydration
                # (the replica re-picks as_of); permanent for a user
                # timestamp (AsOfError guards that path earlier).
                raise CompactionRace(
                    f"as_of {as_of} outside [since {st.since}, "
                    f"upper {st.upper})"
                )
            try:
                # Batches entirely above as_of cannot contribute: skip
                # the fetch.
                schema, parts = self._read_parts(
                    [b for b in st.batches if b.lower <= as_of]
                )
                break
            except CompactionRace:
                self.race_retries += 1
                if attempt == 7:
                    raise
        sel = []
        for cols, nulls, time, diff in parts:
            m = time <= np.uint64(as_of)
            if not m.any():
                continue
            sel.append(
                (
                    [c[m] for c in cols],
                    [None if nl is None else nl[m] for nl in nulls],
                    np.full(int(m.sum()), as_of, np.uint64),
                    diff[m],
                )
            )
        arity = len(sel[0][0]) if sel else 0
        cols, nulls, time, diff = concat_update_parts(sel, arity)
        return schema, cols, nulls, time, diff

    def wait_for_upper(self, frontier: int, timeout: float = 5.0):
        """Block until the shard upper passes ``frontier``; returns the
        new upper or None on timeout. The polling analog of persist
        PubSub-notified Listen (persist-client/src/rpc.rs); the
        coordinator swaps in push notification when in-process."""
        deadline = _time.monotonic() + timeout
        while True:
            st = self.machine.reload()
            if st.upper > frontier:
                return st.upper
            remaining = deadline - _time.monotonic()
            if remaining <= 0:
                return None
            # In-process writers publish on every CaS (machine._apply
            # -> pubsub), so this wakes immediately; the short cap is
            # the poll floor for cross-process writers, who only share
            # consensus.
            PUBSUB.wait(self.machine.shard, min(remaining, 0.002))

    def fetch(self, lo: int, hi: int):
        """Updates with lo <= time < hi. Caller must ensure hi <= upper
        (completeness) and lo >= since (not compacted away). Retries
        the part fetch when racing a compaction swap, like snapshot."""
        for attempt in range(8):
            st = self.machine.reload()
            assert hi <= st.upper, f"fetch hi {hi} beyond upper {st.upper}"
            assert lo >= st.since or lo >= hi, (
                f"fetch lo {lo} below since {st.since}"
            )
            batches = [
                b for b in st.batches if b.upper > lo and b.lower < hi
            ]
            try:
                schema, parts = self._read_parts(batches)
                break
            except CompactionRace:
                self.race_retries += 1
                if attempt == 7:
                    raise
        sel = []
        for cols, nulls, time, diff in parts:
            m = (time >= np.uint64(lo)) & (time < np.uint64(hi))
            sel.append(
                (
                    [c[m] for c in cols],
                    [None if nl is None else nl[m] for nl in nulls],
                    time[m],
                    diff[m],
                )
            )
        arity = len(sel[0][0]) if sel else 0
        cols, nulls, time, diff = concat_update_parts(sel, arity)
        return schema, cols, nulls, time, diff

    def listen_next(self, frontier: int, timeout: float = 5.0):
        """Block for the upper to pass ``frontier``; returns (updates in
        [frontier, new_upper), new_upper) or None on timeout."""
        upper = self.wait_for_upper(frontier, timeout)
        if upper is None:
            return None
        return self.fetch(frontier, upper), upper


class PersistClient:
    """Entry point: open shards by name over one (Blob, Consensus) pair.

    ``auto_compaction=True`` (the production deployments: environmentd's
    coordinator client, replica workers) makes every writer request
    background compaction when its append grows the spine past the
    threshold — per the ``compaction_mode`` dyncfg. Bare clients (unit
    tests, tools) keep the manual ``maybe_compact`` discipline."""

    def __init__(
        self, blob: Blob, consensus: Consensus,
        auto_compaction: bool = False,
    ):
        self.blob = blob
        self.consensus = consensus
        self.auto_compaction = auto_compaction
        self.part_cache = PartCache()
        self._machines: dict[str, Machine] = {}
        self._reader_seq = itertools.count()

    def machine(self, shard: str) -> Machine:
        if shard not in self._machines:
            m = Machine(shard, self.blob, self.consensus)
            m.part_cache = self.part_cache
            self._machines[shard] = m
        return self._machines[shard]

    def tier_split(self, shard: str) -> tuple[int, int]:
        """(hot_bytes, cold_bytes) for one shard's referenced parts —
        the mz_arrangement_sizes tier accounting. Uses the cached state
        (no consensus read: this sits on the frontier-report path)."""
        m = self._machines.get(shard)
        if m is None:
            return 0, 0
        st = m.state
        total = sum(b.n_bytes for b in st.batches)
        hot = self.part_cache.hot_bytes_for(st.referenced_keys())
        return hot, max(0, total - hot)

    def open_writer(self, shard: str, schema: Schema) -> WriteHandle:
        return WriteHandle(
            self.machine(shard), schema,
            auto_compaction=self.auto_compaction,
        )

    def open_reader(self, shard: str, reader_id: str | None = None) -> ReadHandle:
        rid = reader_id or f"r{next(self._reader_seq)}-{id(self):x}"
        return ReadHandle(self.machine(shard), rid)
