"""persist-analog: the durable storage engine.

A shard is a durable time-varying collection (persist-client/src/lib.rs:60):
immutable columnar batch parts in a Blob store, described by a small state
machine advanced via Consensus compare-and-set. See location.py, codec.py,
state.py, machine.py, client.py, operators.py.
"""

from .client import PartCache, PersistClient, ReadHandle, WriteHandle
from .codec import decode_part, encode_part, part_stats
from .compactor import (
    STATS as COMPACTION_STATS,
    CompactionService,
    compaction_service,
    reset_compaction_service,
)
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    FileBlob,
    MemBlob,
    MemConsensus,
    SqliteConsensus,
    UnreliableBlob,
    VersionedData,
)
from .machine import (
    CompactionRace,
    CompactorFenced,
    Fenced,
    Machine,
    UpperMismatch,
)
from .operators import (IndexSource, MaintainedView, ShardSource,
                        updates_to_batch)
from .pubsub import PUBSUB, ShardPubSub
from .state import HollowBatch, ShardState

__all__ = [
    "PartCache", "PersistClient", "ReadHandle", "WriteHandle",
    "decode_part", "encode_part", "part_stats",
    "COMPACTION_STATS", "CompactionService", "compaction_service",
    "reset_compaction_service",
    "Blob", "Consensus", "ExternalDurabilityError", "FileBlob", "MemBlob",
    "MemConsensus", "SqliteConsensus", "UnreliableBlob", "VersionedData",
    "CompactionRace", "CompactorFenced", "Fenced", "Machine",
    "UpperMismatch",
    "IndexSource", "MaintainedView", "ShardSource", "updates_to_batch",
    "PUBSUB", "ShardPubSub",
    "HollowBatch", "ShardState",
]
