"""persist-analog: the durable storage engine.

A shard is a durable time-varying collection (persist-client/src/lib.rs:60):
immutable columnar batch parts in a Blob store, described by a small state
machine advanced via Consensus compare-and-set. See location.py, codec.py,
state.py, machine.py, client.py, operators.py.
"""

from .client import PersistClient, ReadHandle, WriteHandle
from .codec import decode_part, encode_part, part_stats
from .location import (
    Blob,
    Consensus,
    ExternalDurabilityError,
    FileBlob,
    MemBlob,
    MemConsensus,
    SqliteConsensus,
    UnreliableBlob,
    VersionedData,
)
from .machine import Fenced, Machine, UpperMismatch
from .operators import (IndexSource, MaintainedView, ShardSource,
                        updates_to_batch)
from .state import HollowBatch, ShardState

__all__ = [
    "PersistClient", "ReadHandle", "WriteHandle",
    "decode_part", "encode_part", "part_stats",
    "Blob", "Consensus", "ExternalDurabilityError", "FileBlob", "MemBlob",
    "MemConsensus", "SqliteConsensus", "UnreliableBlob", "VersionedData",
    "Fenced", "Machine", "UpperMismatch",
    "IndexSource", "MaintainedView", "ShardSource", "updates_to_batch",
    "HollowBatch", "ShardState",
]
