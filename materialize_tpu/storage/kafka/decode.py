"""Record decode/encode: bytes <-> row values for a declared Schema.

Analog of the reference's ``storage/src/decode`` (avro/csv/json/text
decoders selected by FORMAT) and ``src/interchange`` (Avro/JSON
encoding of rows for sinks, Debezium envelope semantics). A decoder
turns a broker Record's key/value bytes into python user-space values
matching the declared relation columns (the same value convention as
COPY FROM text: repr/schema.py parse_text_value).

Avro uses the Confluent wire format (magic 0x00 + big-endian 4-byte
schema id) against a ``FileSchemaRegistry`` (the ccsr analog): a json
file mapping id -> schema, usable by out-of-process producers.
"""

from __future__ import annotations

import csv as _csv
import io
import json
import struct

from ...repr.schema import Column, ColumnType, Schema, parse_text_value
from .avro import AvroSchema, decode as avro_decode, encode as avro_encode


class DecodeError(ValueError):
    pass


def _coerce(v, col: Column):
    """JSON/Avro value -> column value (user space)."""
    if v is None:
        return None
    if isinstance(v, str) and col.ctype not in (ColumnType.STRING,):
        return parse_text_value(v, col)
    if col.ctype is ColumnType.STRING and not isinstance(v, str):
        return json.dumps(v) if isinstance(v, (dict, list)) else str(v)
    if col.ctype is ColumnType.BOOL:
        return bool(v)
    if col.ctype in (ColumnType.INT32, ColumnType.INT64,
                     ColumnType.DATE, ColumnType.TIMESTAMP):
        return int(v)
    if col.ctype is ColumnType.FLOAT64:
        return float(v)
    if col.ctype is ColumnType.DECIMAL:
        import decimal

        # normalize to the column scale so upsert-state comparisons
        # (including state recovered from the shard) are exact
        q = decimal.Decimal(1).scaleb(-col.scale)
        return decimal.Decimal(str(v)).quantize(
            q, rounding=decimal.ROUND_HALF_UP
        )
    return v


class Decoder:
    """value bytes -> row (list of user-space values, one per column)."""

    def __init__(self, schema: Schema):
        self.schema = schema

    def decode(self, data: bytes) -> list:
        raise NotImplementedError


class JsonDecoder(Decoder):
    def decode(self, data: bytes) -> list:
        try:
            obj = json.loads(data)
        except json.JSONDecodeError as e:
            raise DecodeError(f"bad json record: {e}") from e
        if not isinstance(obj, dict):
            raise DecodeError("json record must be an object")
        return [
            _coerce(obj.get(c.name), c) for c in self.schema.columns
        ]


class CsvDecoder(Decoder):
    def decode(self, data: bytes) -> list:
        row = next(_csv.reader(io.StringIO(data.decode())))
        if len(row) != self.schema.arity:
            raise DecodeError(
                f"csv row has {len(row)} fields, expected "
                f"{self.schema.arity}"
            )
        return [
            None if f == "" and c.ctype is not ColumnType.STRING
            else parse_text_value(f, c)
            for f, c in zip(row, self.schema.columns)
        ]


class TextDecoder(Decoder):
    """FORMAT TEXT: the whole value as one text column."""

    def decode(self, data: bytes) -> list:
        if self.schema.arity != 1:
            raise DecodeError("FORMAT TEXT requires a single column")
        return [data.decode()]


class BytesDecoder(Decoder):
    """FORMAT BYTES: value bytes surfaced as latin-1 text (no BYTEA
    device type; the reference surfaces bytea)."""

    def decode(self, data: bytes) -> list:
        if self.schema.arity != 1:
            raise DecodeError("FORMAT BYTES requires a single column")
        return [data.decode("latin-1")]


class FileSchemaRegistry:
    """ccsr analog: id -> Avro schema json, stored in one json file so
    external producers and this process agree on ids."""

    def __init__(self, path: str):
        self.path = path
        self._cache: dict[int, AvroSchema] = {}

    def _load(self) -> dict:
        try:
            with open(self.path) as f:
                return json.load(f)
        except FileNotFoundError:
            return {}

    def get(self, schema_id: int) -> AvroSchema:
        if schema_id not in self._cache:
            reg = self._load()
            if str(schema_id) not in reg:
                raise DecodeError(
                    f"schema id {schema_id} not in registry {self.path}"
                )
            self._cache[schema_id] = AvroSchema.parse(reg[str(schema_id)])
        return self._cache[schema_id]

    def register(self, schema_json: str) -> int:
        import os

        reg = self._load()
        for k, v in reg.items():
            if v == schema_json:
                return int(k)
        new_id = 1 + max((int(k) for k in reg), default=0)
        reg[str(new_id)] = schema_json
        tmp = self.path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(reg, f)
        os.replace(tmp, self.path)
        return new_id


class AvroDecoder(Decoder):
    """Confluent-framed Avro records decoded against the registry; the
    record's fields map to columns by name."""

    def __init__(self, schema: Schema, registry: FileSchemaRegistry):
        super().__init__(schema)
        self.registry = registry

    def decode(self, data: bytes) -> list:
        if len(data) < 5 or data[0] != 0:
            raise DecodeError("bad confluent avro framing")
        (schema_id,) = struct.unpack("!I", data[1:5])
        avsc = self.registry.get(schema_id)
        obj = avro_decode(avsc, data, 5)
        if not isinstance(obj, dict):
            raise DecodeError("avro record must be a record type")
        return [
            _coerce(obj.get(c.name), c) for c in self.schema.columns
        ]


def make_decoder(
    fmt: str, schema: Schema, registry_path: str | None = None
) -> Decoder:
    fmt = fmt.lower()
    if fmt == "json":
        return JsonDecoder(schema)
    if fmt == "csv":
        return CsvDecoder(schema)
    if fmt == "text":
        return TextDecoder(schema)
    if fmt == "bytes":
        return BytesDecoder(schema)
    if fmt == "avro":
        if registry_path is None:
            raise DecodeError("FORMAT AVRO requires a schema registry")
        return AvroDecoder(schema, FileSchemaRegistry(registry_path))
    raise DecodeError(f"unknown format {fmt!r}")


# -- encoding (sink side; interchange/src analog) ---------------------------


def _json_value(v):
    import decimal

    if isinstance(v, decimal.Decimal):
        return float(v)
    return v


class Encoder:
    def __init__(self, schema: Schema):
        self.schema = schema

    def encode(self, row: tuple) -> bytes:
        raise NotImplementedError


class JsonEncoder(Encoder):
    def encode(self, row) -> bytes:
        return json.dumps(
            {
                c.name: _json_value(v)
                for c, v in zip(self.schema.columns, row)
            },
            sort_keys=True,
        ).encode()


_AVRO_TYPES = {
    ColumnType.BOOL: "boolean",
    ColumnType.INT32: "int",
    ColumnType.INT64: "long",
    ColumnType.FLOAT64: "double",
    ColumnType.DATE: {"type": "int", "logicalType": "date"},
    ColumnType.TIMESTAMP: {
        "type": "long", "logicalType": "timestamp-millis"
    },
    ColumnType.STRING: "string",
}


def avro_schema_for(schema: Schema, name: str = "row") -> str:
    """Relation schema -> Avro record schema json (the schema the sink
    publishes to the registry; interchange/src/avro.rs analog)."""
    fields = []
    for c in schema.columns:
        if c.ctype is ColumnType.DECIMAL:
            t = {
                "type": "bytes",
                "logicalType": "decimal",
                "precision": 38,
                "scale": c.scale,
            }
        else:
            t = _AVRO_TYPES[c.ctype]
        fields.append(
            {
                "name": c.name,
                "type": ["null", t] if c.nullable else t,
            }
        )
    return json.dumps(
        {"type": "record", "name": name, "fields": fields}
    )


class AvroEncoder(Encoder):
    def __init__(self, schema: Schema, registry: FileSchemaRegistry,
                 name: str = "row"):
        super().__init__(schema)
        schema_json = avro_schema_for(schema, name)
        self.schema_id = registry.register(schema_json)
        self.avsc = AvroSchema.parse(schema_json)

    def encode(self, row) -> bytes:
        obj = {c.name: v for c, v in zip(self.schema.columns, row)}
        return (
            b"\x00"
            + struct.pack("!I", self.schema_id)
            + avro_encode(self.avsc, obj)
        )


def make_encoder(
    fmt: str, schema: Schema, registry_path: str | None = None,
    name: str = "row",
) -> Encoder:
    fmt = fmt.lower()
    if fmt == "json":
        return JsonEncoder(schema)
    if fmt == "avro":
        if registry_path is None:
            raise DecodeError("FORMAT AVRO requires a schema registry")
        return AvroEncoder(
            schema, FileSchemaRegistry(registry_path), name
        )
    raise DecodeError(f"unknown sink format {fmt!r}")
