"""Exactly-once broker sink: shard tail -> encoded records.

Analog of the reference's Kafka sink (storage/src/sink/kafka.rs):
exactly-once via a PROGRESS TOPIC — each emission transactionally
appends the data records and a progress record carrying the new upper;
on restart the sink reads the last progress record and resumes from
that frontier, so every update is published exactly once even across
crashes. Here the transaction is the broker's atomic multi-topic
append (FileBroker.append_txn), standing in for Kafka transactions.

Envelope DEBEZIUM publishes {"before": ..., "after": ...} pairs per
changed row (consolidated per key within a timestamp); ENVELOPE NONE
(the reference's ENVELOPE DEBEZIUM-free JSON sinks) publishes
{"row": ..., "diff": n} update records.
"""

from __future__ import annotations

import json
import threading
import time as _time

from ...repr.schema import Schema
from .broker import Broker, Record
from .decode import make_encoder


class KafkaSink:
    """Tails a shard (an MV/table output) and publishes its updates."""

    def __init__(
        self,
        client,
        shard: str,
        schema: Schema,
        broker: Broker,
        topic: str,
        fmt: str = "json",
        envelope: str = "none",
        registry: str | None = None,
        key_columns: int = 0,
        sink_id: str = "sink",
    ):
        self.client = client
        self.schema = schema
        self.broker = broker
        self.topic = topic
        self.progress_topic = f"__progress_{sink_id}"
        self.envelope = envelope.lower()
        self.encoder = make_encoder(fmt, schema, registry)
        self.key_columns = key_columns
        broker.create_topic(topic, 1)
        broker.create_topic(self.progress_topic, 1)
        self.reader = client.open_reader(shard, f"sink-{sink_id}")
        # resume frontier: last committed progress record
        self.frontier = 0
        end = broker.end_offset(self.progress_topic, 0)
        if end > 0:
            last = broker.fetch(self.progress_topic, 0, end - 1, 1)[0]
            self.frontier = json.loads(last.value)["frontier"]
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None

    def _encode_update(self, row: tuple, t: int, diff: int) -> list:
        if self.envelope == "debezium":
            body = {
                "payload": {
                    "before": None if diff > 0 else self._obj(row),
                    "after": self._obj(row) if diff > 0 else None,
                    "ts": t,
                }
            }
            return [
                Record(None, json.dumps(body).encode(), timestamp=t)
            ] * abs(diff)
        recs = []
        for _ in range(abs(diff)):
            body = self.encoder.encode(row)
            # ENVELOPE NONE json carries the diff alongside
            if self.envelope == "none":
                obj = json.loads(body)
                body = json.dumps(
                    {"row": obj, "diff": 1 if diff > 0 else -1, "ts": t},
                    sort_keys=True,
                ).encode()
            recs.append(Record(None, body, timestamp=t))
        return recs

    def _obj(self, row: tuple) -> dict:
        import decimal

        return {
            c.name: (float(v) if isinstance(v, decimal.Decimal) else v)
            for c, v in zip(self.schema.columns, row)
        }

    def step(self, timeout: float = 1.0) -> bool:
        """Publish updates in [frontier, shard upper); returns False if
        the shard has not advanced."""
        got = self.reader.listen_next(self.frontier, timeout)
        if got is None:
            return False
        (_sch, cols, nulls, time_, diff), new_upper = got
        from ...repr.schema import decode_result_rows

        rows = decode_result_rows(self.schema, cols, nulls, time_, diff)
        records = []
        for r in rows:
            *vals, t, d = r
            if t < self.frontier:
                continue  # already published (progress says so)
            records.extend(self._encode_update(tuple(vals), t, d))
        progress = Record(
            None,
            json.dumps({"frontier": new_upper}).encode(),
        )
        appends = []
        if records:
            appends.append((self.topic, 0, records))
        # progress entry LAST: see FileBroker.append_txn ordering note
        appends.append((self.progress_topic, 0, [progress]))
        self.broker.append_txn(appends)
        self.frontier = new_upper
        return True

    def run_until(self, frontier: int, timeout: float = 30.0) -> None:
        deadline = _time.time() + timeout
        while self.frontier < frontier:
            if not self.step(timeout=0.5) and _time.time() > deadline:
                raise TimeoutError(
                    f"sink stalled below frontier {frontier}"
                )

    def start(self, interval: float = 0.05) -> None:
        if self._thread is not None:
            return

        def run():
            while not self._stop.is_set():
                if not self.step(timeout=0.2):
                    _time.sleep(interval)

        self._thread = threading.Thread(target=run, daemon=True)
        self._thread.start()

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        try:
            self.reader.expire()
        except Exception:
            pass
