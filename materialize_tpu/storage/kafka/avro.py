"""From-scratch Avro binary codec.

Analog of the reference's from-scratch ``avro`` crate (src/avro, 13k
LoC Rust: reader/writer/schema resolution); this covers the subset the
streaming pipeline needs: schema JSON parsing, binary encode/decode of
null/boolean/int/long/float/double/string/bytes/record/enum/array/map/
union, and the logical types pgwire-visible columns map onto
(date, timestamp-millis, decimal-as-bytes).

Confluent Schema Registry wire framing (magic 0 + 4-byte schema id) is
in decode.py; this module is pure Avro.
"""

from __future__ import annotations

import json
import struct
from dataclasses import dataclass


class AvroError(ValueError):
    pass


@dataclass
class AvroSchema:
    """Parsed schema node. kind is the Avro type name; for records
    ``fields`` is [(name, AvroSchema)], for unions ``options`` is the
    branch list, for enums ``symbols``, for array/map ``items``."""

    kind: str
    name: str = ""
    fields: list = None
    options: list = None
    symbols: list = None
    items: "AvroSchema" = None
    logical: str = ""
    scale: int = 0

    @staticmethod
    def parse(src) -> "AvroSchema":
        if isinstance(src, (str, bytes)):
            src = json.loads(src)
        return _parse_schema(src)


_PRIMITIVES = {
    "null", "boolean", "int", "long", "float", "double", "string",
    "bytes",
}


def _parse_schema(node) -> AvroSchema:
    if isinstance(node, str):
        if node not in _PRIMITIVES:
            raise AvroError(f"unknown type {node!r}")
        return AvroSchema(node)
    if isinstance(node, list):
        return AvroSchema(
            "union", options=[_parse_schema(n) for n in node]
        )
    if not isinstance(node, dict):
        raise AvroError(f"bad schema node {node!r}")
    t = node["type"]
    logical = node.get("logicalType", "")
    if t == "record":
        return AvroSchema(
            "record",
            name=node.get("name", ""),
            fields=[
                (f["name"], _parse_schema(f["type"]))
                for f in node["fields"]
            ],
        )
    if t == "enum":
        return AvroSchema(
            "enum", name=node.get("name", ""), symbols=node["symbols"]
        )
    if t == "array":
        return AvroSchema("array", items=_parse_schema(node["items"]))
    if t == "map":
        return AvroSchema("map", items=_parse_schema(node["values"]))
    if t == "fixed":
        return AvroSchema("bytes", name=node.get("name", ""))
    if t in _PRIMITIVES:
        return AvroSchema(
            t, logical=logical, scale=int(node.get("scale", 0))
        )
    raise AvroError(f"unknown type {t!r}")


# -- binary primitives -------------------------------------------------------


def _zigzag_encode(n: int) -> int:
    return (n << 1) ^ (n >> 63)


def _zigzag_decode(n: int) -> int:
    return (n >> 1) ^ -(n & 1)


def _write_long(out: bytearray, n: int) -> None:
    z = _zigzag_encode(n) & ((1 << 64) - 1)
    while True:
        b = z & 0x7F
        z >>= 7
        if z:
            out.append(b | 0x80)
        else:
            out.append(b)
            return


class _Reader:
    def __init__(self, buf: bytes, pos: int = 0):
        self.buf = buf
        self.pos = pos

    def read_long(self) -> int:
        shift, acc = 0, 0
        while True:
            if self.pos >= len(self.buf):
                raise AvroError("truncated varint")
            b = self.buf[self.pos]
            self.pos += 1
            acc |= (b & 0x7F) << shift
            if not b & 0x80:
                break
            shift += 7
            if shift > 63:
                raise AvroError("varint too long")
        return _zigzag_decode(acc)

    def read(self, n: int) -> bytes:
        if self.pos + n > len(self.buf):
            raise AvroError("truncated data")
        out = self.buf[self.pos : self.pos + n]
        self.pos += n
        return out


# -- decode ------------------------------------------------------------------


def decode(schema: AvroSchema, buf: bytes, pos: int = 0):
    r = _Reader(buf, pos)
    v = _decode(schema, r)
    return v


def _decode(s: AvroSchema, r: _Reader):
    k = s.kind
    if k == "null":
        return None
    if k == "boolean":
        return r.read(1) != b"\x00"
    if k in ("int", "long"):
        n = r.read_long()
        return n  # logical date = days, timestamp-millis = ms: both raw
    if k == "float":
        return struct.unpack("<f", r.read(4))[0]
    if k == "double":
        return struct.unpack("<d", r.read(8))[0]
    if k == "string":
        return r.read(r.read_long()).decode()
    if k == "bytes":
        raw = r.read(r.read_long())
        if s.logical == "decimal":
            unscaled = int.from_bytes(raw, "big", signed=True)
            import decimal

            return decimal.Decimal(unscaled) / (10 ** s.scale)
        return raw
    if k == "record":
        return {name: _decode(fs, r) for name, fs in s.fields}
    if k == "enum":
        i = r.read_long()
        if not 0 <= i < len(s.symbols):
            raise AvroError(f"enum index {i} out of range")
        return s.symbols[i]
    if k == "union":
        i = r.read_long()
        if not 0 <= i < len(s.options):
            raise AvroError(f"union branch {i} out of range")
        return _decode(s.options[i], r)
    if k == "array":
        out = []
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:  # block with byte size
                n = -n
                r.read_long()
            for _ in range(n):
                out.append(_decode(s.items, r))
    if k == "map":
        out = {}
        while True:
            n = r.read_long()
            if n == 0:
                return out
            if n < 0:
                n = -n
                r.read_long()
            for _ in range(n):
                key = r.read(r.read_long()).decode()
                out[key] = _decode(s.items, r)
    raise AvroError(f"cannot decode {k}")


# -- encode ------------------------------------------------------------------


def encode(schema: AvroSchema, value) -> bytes:
    out = bytearray()
    _encode(schema, value, out)
    return bytes(out)


def _encode(s: AvroSchema, v, out: bytearray) -> None:
    k = s.kind
    if k == "null":
        if v is not None:
            raise AvroError(f"non-null {v!r} for null schema")
        return
    if k == "boolean":
        out.append(1 if v else 0)
        return
    if k in ("int", "long"):
        _write_long(out, int(v))
        return
    if k == "float":
        out += struct.pack("<f", float(v))
        return
    if k == "double":
        out += struct.pack("<d", float(v))
        return
    if k == "string":
        b = str(v).encode()
        _write_long(out, len(b))
        out += b
        return
    if k == "bytes":
        if s.logical == "decimal":
            import decimal

            unscaled = int(
                (decimal.Decimal(str(v)) * (10 ** s.scale)).to_integral_value()
            )
            blen = max(1, (unscaled.bit_length() + 8) // 8)
            b = unscaled.to_bytes(blen, "big", signed=True)
        else:
            b = bytes(v)
        _write_long(out, len(b))
        out += b
        return
    if k == "record":
        for name, fs in s.fields:
            _encode(fs, v.get(name) if isinstance(v, dict) else None, out)
        return
    if k == "enum":
        _write_long(out, s.symbols.index(v))
        return
    if k == "union":
        for i, opt in enumerate(s.options):
            if _union_matches(opt, v):
                _write_long(out, i)
                _encode(opt, v, out)
                return
        raise AvroError(f"no union branch for {v!r}")
    if k == "array":
        if v:
            _write_long(out, len(v))
            for item in v:
                _encode(s.items, item, out)
        _write_long(out, 0)
        return
    if k == "map":
        if v:
            _write_long(out, len(v))
            for key, item in v.items():
                kb = str(key).encode()
                _write_long(out, len(kb))
                out += kb
                _encode(s.items, item, out)
        _write_long(out, 0)
        return
    raise AvroError(f"cannot encode {k}")


def _union_matches(s: AvroSchema, v) -> bool:
    if s.kind == "null":
        return v is None
    if v is None:
        return False
    if s.kind == "boolean":
        return isinstance(v, bool)
    if s.kind in ("int", "long"):
        return isinstance(v, int) and not isinstance(v, bool)
    if s.kind in ("float", "double"):
        return isinstance(v, (int, float)) and not isinstance(v, bool)
    if s.kind == "string":
        return isinstance(v, str)
    if s.kind == "bytes":
        import decimal

        if s.logical == "decimal":
            return isinstance(v, (int, float, decimal.Decimal))
        return isinstance(v, (bytes, bytearray))
    if s.kind == "record":
        return isinstance(v, dict)
    if s.kind == "enum":
        return isinstance(v, str) and v in s.symbols
    if s.kind == "array":
        return isinstance(v, list)
    if s.kind == "map":
        return isinstance(v, dict)
    return False
