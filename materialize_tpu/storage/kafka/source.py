"""Broker-backed source: fetch -> decode -> envelope -> update batches.

Analog of the reference's Kafka source pipeline
(storage/src/source/kafka.rs + source_reader_pipeline.rs:165 +
reclocking per source/reclock.rs): each tick consumes every partition
up to its current end offset, decodes records, applies the envelope,
and emits update batches. The offset<->tick binding (the remap
collection) is itself a durable SUBSOURCE ``__remap`` with schema
(partition, end_offset): each tick retracts the old binding row and
asserts the new one, so on restart the adapter reads the remap shard's
latest snapshot and resumes from exactly the offsets the durable data
reflects — re-fetching nothing, re-emitting nothing (the data shard's
upper check skips re-appends of already-durable ticks anyway).

Envelopes (storage/src/upsert.rs + the debezium decode path):
- NONE: every record is an insert (+1)
- UPSERT: key bytes -> latest value; a NULL value is a delete;
  state is rebuilt on restart from the emitted collection itself
  (the key columns are a prefix of the row), the persist-rehydration
  model rather than the reference's RocksDB sidecar
- DEBEZIUM: value {"before": ..., "after": ...}: retract before,
  insert after
"""

from __future__ import annotations

import numpy as np

from ...repr.schema import Column, ColumnType, Schema
from .broker import FileBroker
from .decode import DecodeError, make_decoder

REMAP_SCHEMA = Schema(
    [
        Column("partition", ColumnType.INT64),
        Column("end_offset", ColumnType.INT64),
    ]
)

MAX_RECORDS_PER_TICK = 100_000


class KafkaSourceAdapter:
    """GeneratorAdapter-shaped adapter reading a broker topic.

    Options (CREATE SOURCE ... FROM KAFKA, parsed upstream):
      broker:   FileBroker root path (or a Broker instance in-process)
      topic:    topic name
      format:   json | csv | text | bytes | avro
      envelope: none | upsert | debezium       (default none)
      key_format / key_columns: for UPSERT, how the key maps to the
                leading columns (default: json over the first column)
      registry: schema-registry json path (avro)
    """

    def __init__(self, options: dict, schema: Schema):
        broker = options.get("broker")
        if broker is None:
            raise ValueError("KAFKA source requires BROKER")
        self.broker = (
            broker
            if hasattr(broker, "fetch")
            else FileBroker(str(broker))
        )
        self.topic = options.get("topic")
        if not self.topic:
            raise ValueError("KAFKA source requires TOPIC")
        if self.topic not in self.broker.topics():
            raise ValueError(f"unknown topic {self.topic!r}")
        self.value_schema = schema
        fmt = str(options.get("format", "json"))
        self.decoder = make_decoder(
            fmt, schema, options.get("registry")
        )
        self.envelope = str(options.get("envelope", "none")).lower()
        if self.envelope not in ("none", "upsert", "debezium"):
            raise ValueError(f"unknown envelope {self.envelope!r}")
        nparts = self.broker.partitions(self.topic)
        self.offsets = [0] * nparts
        self.name = options.get("_name", self.topic)
        # progress subsource name mirrors the reference's <source>_progress
        # collections (offset->time bindings, source/reclock.rs)
        self.progress_name = f"{self.name}_progress"
        self.subsources = {
            self.name: schema,
            self.progress_name: REMAP_SCHEMA,
        }
        if self.envelope == "upsert":
            nkey = int(options.get("key_columns", 1))
            self.key_arity = nkey
            self._state: dict[tuple, tuple] = {}
        # DEBEZIUM values are {"before":{...}|null, "after":{...}|null}
        # decoded field-wise with the value decoder.

    # -- envelope machinery -------------------------------------------------
    def _apply_envelope(self, records) -> list:
        """decoded records -> [(row_tuple, diff)]"""
        out = []
        for rec, row in records:
            if self.envelope == "none":
                out.append((tuple(row), 1))
            elif self.envelope == "upsert":
                key = tuple(row[: self.key_arity]) if row is not None \
                    else self._key_from_bytes(rec.key)
                old = self._state.get(key)
                if rec.value is None or row is None:  # delete
                    if old is not None:
                        out.append((old, -1))
                        del self._state[key]
                else:
                    new = tuple(row)
                    if old == new:
                        continue
                    if old is not None:
                        out.append((old, -1))
                    self._state[key] = new
                    out.append((new, 1))
                # (dedup of equal old/new matches upsert.rs semantics)
            else:  # debezium
                before, after = row  # _decode_debezium returns the pair
                if before is not None:
                    out.append((tuple(before), -1))
                if after is not None:
                    out.append((tuple(after), 1))
        return out

    def _key_from_bytes(self, key: bytes | None) -> tuple:
        import json as _json

        if key is None:
            return (None,) * self.key_arity
        try:
            v = _json.loads(key)
        except Exception:
            v = key.decode(errors="replace")
        if isinstance(v, list):
            return tuple(v[: self.key_arity])
        return (v,) + (None,) * (self.key_arity - 1)

    def _decode_record(self, rec):
        if self.envelope == "debezium":
            import json as _json

            try:
                obj = _json.loads(rec.value)
            except Exception as e:
                raise DecodeError(f"bad debezium value: {e}") from e
            payload = obj.get("payload", obj)

            def side(x):
                if x is None:
                    return None
                from .decode import _coerce

                return [
                    _coerce(x.get(c.name), c)
                    for c in self.value_schema.columns
                ]

            return (side(payload.get("before")),
                    side(payload.get("after")))
        if rec.value is None:
            return None  # upsert tombstone
        return self.decoder.decode(rec.value)

    # -- GeneratorAdapter interface -----------------------------------------
    def snapshot(self) -> dict:
        return self.tick(0, 0)

    def tick(self, tick: int, time: int) -> dict:
        decoded = []
        remap_updates = []  # (row, diff)
        budget = MAX_RECORDS_PER_TICK
        for p in range(len(self.offsets)):
            start = self.offsets[p]
            end = self.broker.end_offset(self.topic, p)
            end = min(end, start + budget)
            if end <= start:
                continue
            recs = self.broker.fetch(self.topic, p, start, end - start)
            for rec in recs:
                decoded.append((rec, self._decode_record(rec)))
            remap_updates.append(((p, start), -1))
            remap_updates.append(((p, end), 1))
            self.offsets[p] = end
            budget -= end - start
        out = {}
        updates = self._apply_envelope(decoded)
        if updates:
            out[self.name] = _rows_to_batch(
                self.value_schema, updates, time
            )
        if remap_updates:
            # drop the (p, 0) retraction of a partition's first binding:
            # it was never asserted
            remap_updates = [
                (r, d)
                for r, d in remap_updates
                if not (d == -1 and r[1] == 0)
            ]
            out[self.progress_name] = _rows_to_batch(
                REMAP_SCHEMA, remap_updates, time
            )
        return out

    # -- recovery -----------------------------------------------------------
    def recover_from_shards(self, snapshots: dict, upto: int) -> None:
        """Resume: offsets from the __remap snapshot; upsert state from
        the emitted collection itself (persist-rehydration model)."""
        remap = snapshots.get(self.progress_name, [])
        acc: dict = {}
        for row, d in remap:
            acc[tuple(row)] = acc.get(tuple(row), 0) + d
        for (p, end), d in acc.items():
            if d > 0:
                self.offsets[int(p)] = max(
                    self.offsets[int(p)], int(end)
                )
        if self.envelope == "upsert":
            state: dict = {}
            rows = snapshots.get(self.name, [])
            cnt: dict = {}
            for row, d in rows:
                cnt[tuple(row)] = cnt.get(tuple(row), 0) + d
            for row, d in cnt.items():
                if d > 0:
                    state[row[: self.key_arity]] = row
            self._state = state


def _rows_to_batch(schema: Schema, updates: list, time: int):
    """[(row_user_values, diff)] -> Batch (via the insert encode path)."""
    from ...repr.batch import Batch
    from ...repr.schema import GLOBAL_DICT

    cols, nulls = [], []
    rows = [u[0] for u in updates]
    diffs = np.asarray([u[1] for u in updates], np.int64)
    for j, col in enumerate(schema.columns):
        vals, mask = [], []
        for r in rows:
            v = r[j]
            mask.append(v is None)
            if v is None:
                vals.append(0)
            elif col.ctype is ColumnType.STRING:
                vals.append(GLOBAL_DICT.encode(str(v)))
            elif col.ctype is ColumnType.DECIMAL:
                import decimal

                if isinstance(v, decimal.Decimal):
                    vals.append(
                        int((v * 10**col.scale).to_integral_value())
                    )
                else:
                    vals.append(round(float(v) * 10**col.scale))
            elif col.ctype is ColumnType.BOOL:
                vals.append(bool(v))
            else:
                vals.append(v)  # np.asarray(dtype) coerces numerics
        cols.append(np.asarray(vals, dtype=col.dtype))
        nulls.append(np.asarray(mask, bool) if any(mask) else None)
    return Batch.from_numpy(
        schema,
        cols,
        time=np.full(len(rows), time, np.uint64),
        diff=diffs,
        nulls=nulls,
    )
