"""Minimal partitioned-log broker: the librdkafka-shaped hole.

Interface is the subset of Kafka semantics the source/sink pipeline
needs (storage/src/source/kafka.rs consumes per-partition offset
streams; storage/src/sink/kafka.rs produces with transactional
batches + a progress topic):

- topics with a fixed partition count
- append(topic, partition, records) -> base offset
- fetch(topic, partition, offset, max) -> records from offset
- end_offset(topic, partition)
- append_txn: atomic multi-topic append (the stand-in for Kafka
  transactions backing exactly-once sinks)

``FileBroker`` stores one directory per topic and one segment file per
partition; records are length-prefixed (key, value, timestamp) tuples
with a CRC; an fsync'd offset index makes appends crash-atomic
(truncated tails are discarded on open). Multiple processes may read
while one writes per partition (the Kafka model).
"""

from __future__ import annotations

import json
import os
import struct
import threading
import zlib
from dataclasses import dataclass


@dataclass(frozen=True)
class Record:
    key: bytes | None
    value: bytes | None
    offset: int = -1
    timestamp: int = 0  # ms


_HDR = struct.Struct("!iiqI")  # key_len(-1=None), val_len(-1=None), ts, crc


def _enc_record(r: Record) -> bytes:
    k = b"" if r.key is None else r.key
    v = b"" if r.value is None else r.value
    crc = zlib.crc32(k) ^ zlib.crc32(v)
    return (
        _HDR.pack(
            -1 if r.key is None else len(k),
            -1 if r.value is None else len(v),
            r.timestamp,
            crc,
        )
        + k
        + v
    )


class Broker:
    """Partitioned-log interface."""

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        raise NotImplementedError

    def topics(self) -> dict:
        raise NotImplementedError

    def partitions(self, topic: str) -> int:
        return self.topics()[topic]

    def append(self, topic: str, partition: int, records: list) -> int:
        raise NotImplementedError

    def fetch(
        self, topic: str, partition: int, offset: int, max_records: int
    ) -> list:
        raise NotImplementedError

    def end_offset(self, topic: str, partition: int) -> int:
        raise NotImplementedError

    def append_txn(self, appends: list) -> None:
        """Atomically append [(topic, partition, records), ...]: either
        every batch becomes visible or none (Kafka-transaction analog
        for the exactly-once sink)."""
        raise NotImplementedError


class MemBroker(Broker):
    def __init__(self):
        self._topics: dict[str, list[list[Record]]] = {}
        self._lock = threading.Lock()

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            if topic not in self._topics:
                self._topics[topic] = [[] for _ in range(partitions)]

    def topics(self) -> dict:
        with self._lock:
            return {t: len(ps) for t, ps in self._topics.items()}

    def append(self, topic: str, partition: int, records: list) -> int:
        with self._lock:
            log = self._topics[topic][partition]
            base = len(log)
            for i, r in enumerate(records):
                log.append(
                    Record(r.key, r.value, base + i, r.timestamp)
                )
            return base

    def fetch(self, topic, partition, offset, max_records):
        with self._lock:
            log = self._topics[topic][partition]
            return list(log[offset : offset + max_records])

    def end_offset(self, topic, partition):
        with self._lock:
            return len(self._topics[topic][partition])

    def append_txn(self, appends):
        with self._lock:
            for topic, partition, records in appends:
                log = self._topics[topic][partition]
                base = len(log)
                for i, r in enumerate(records):
                    log.append(
                        Record(r.key, r.value, base + i, r.timestamp)
                    )


class FileBroker(Broker):
    """Durable file-backed broker.

    Layout: root/<topic>/meta.json {partitions}; root/<topic>/p<N>.log
    (record segments) and p<N>.idx (fsync'd little index: one
    '<offset> <byte_pos>\\n' line per COMMITTED record batch). A crash
    mid-append leaves log bytes past the last committed index entry;
    they are ignored and overwritten. append_txn commits one combined
    index update after all segment writes, ordered so that a crash
    leaves either no visible records or all of them.
    """

    def __init__(self, root: str):
        self.root = root
        os.makedirs(root, exist_ok=True)
        self._lock = threading.Lock()
        # (topic, partition) -> [end_offset, end_pos]
        self._ends: dict = {}
        self._replay_journal()

    # -- transaction journal -------------------------------------------------
    def _journal_path(self) -> str:
        return os.path.join(self.root, "txn.journal")

    def _replay_journal(self) -> None:
        """Apply committed-but-unindexed transaction entries: the
        journal fsync is the atomic commit point for append_txn; index
        files are recovered from it after a crash."""
        try:
            with open(self._journal_path()) as f:
                lines = f.readlines()
        except FileNotFoundError:
            return
        for line in lines:
            line = line.strip()
            if not line:
                continue
            try:
                entries = json.loads(line)["entries"]
            except (json.JSONDecodeError, KeyError):
                continue  # torn tail write: uncommitted, ignore
            for topic, p, end_off, end_pos in entries:
                cur = 0
                try:
                    with open(self._idx(topic, p)) as f:
                        for ln in f:
                            ln = ln.strip()
                            if ln:
                                cur = int(ln.split()[0])
                except FileNotFoundError:
                    continue
                if cur < end_off:
                    with open(self._idx(topic, p), "a") as f:
                        f.write(f"{end_off} {end_pos}\n")
                        f.flush()
                        os.fsync(f.fileno())

    # -- layout ------------------------------------------------------------
    def _tdir(self, topic: str) -> str:
        return os.path.join(self.root, topic)

    def _seg(self, topic: str, p: int) -> str:
        return os.path.join(self._tdir(topic), f"p{p}.log")

    def _idx(self, topic: str, p: int) -> str:
        return os.path.join(self._tdir(topic), f"p{p}.idx")

    def create_topic(self, topic: str, partitions: int = 1) -> None:
        with self._lock:
            d = self._tdir(topic)
            os.makedirs(d, exist_ok=True)
            meta = os.path.join(d, "meta.json")
            if not os.path.exists(meta):
                tmp = meta + ".tmp"
                with open(tmp, "w") as f:
                    json.dump({"partitions": partitions}, f)
                os.replace(tmp, meta)
            for p in range(partitions):
                for path in (self._seg(topic, p), self._idx(topic, p)):
                    if not os.path.exists(path):
                        open(path, "ab").close()

    def topics(self) -> dict:
        out = {}
        if not os.path.isdir(self.root):
            return out
        for t in sorted(os.listdir(self.root)):
            meta = os.path.join(self.root, t, "meta.json")
            if os.path.exists(meta):
                with open(meta) as f:
                    out[t] = json.load(f)["partitions"]
        return out

    def _load_end(self, topic: str, p: int):
        key = (topic, p)
        if key in self._ends:
            return self._ends[key]
        end_off, end_pos = 0, 0
        try:
            with open(self._idx(topic, p)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        a, b = line.split()
                        end_off, end_pos = int(a), int(b)
        except FileNotFoundError:
            pass
        self._ends[key] = [end_off, end_pos]
        return self._ends[key]

    # -- write -------------------------------------------------------------
    def append(self, topic, partition, records) -> int:
        with self._lock:
            return self._append_locked(topic, partition, records)

    def _append_locked(self, topic, partition, records) -> int:
        end = self._load_end(topic, partition)
        base = end[0]
        payload = b"".join(_enc_record(r) for r in records)
        with open(self._seg(topic, partition), "r+b") as f:
            f.seek(end[1])
            f.write(payload)
            f.flush()
            os.fsync(f.fileno())
        end[0] += len(records)
        end[1] += len(payload)
        with open(self._idx(topic, partition), "a") as f:
            f.write(f"{end[0]} {end[1]}\n")
            f.flush()
            os.fsync(f.fileno())
        return base

    def append_txn(self, appends) -> None:
        with self._lock:
            # 1. write all segment bytes (invisible until indexed)
            staged = []
            for topic, partition, records in appends:
                end = self._load_end(topic, partition)
                payload = b"".join(_enc_record(r) for r in records)
                with open(self._seg(topic, partition), "r+b") as f:
                    f.seek(end[1])
                    f.write(payload)
                    f.flush()
                    os.fsync(f.fileno())
                staged.append(
                    (topic, partition, end, len(records), len(payload))
                )
            # 2. the journal fsync is the ATOMIC COMMIT POINT for the
            # whole transaction (Kafka-transaction analog): either the
            # line is durable and recovery indexes every batch, or it
            # is absent/torn and none become visible
            entries = [
                [topic, partition, end[0] + nrec, end[1] + nbytes]
                for topic, partition, end, nrec, nbytes in staged
            ]
            with open(self._journal_path(), "a") as f:
                f.write(json.dumps({"entries": entries}) + "\n")
                f.flush()
                os.fsync(f.fileno())
            # 3. apply to index files (recovery replays these from the
            # journal after a crash)
            for topic, partition, end, nrec, nbytes in staged:
                end[0] += nrec
                end[1] += nbytes
                with open(self._idx(topic, partition), "a") as f:
                    f.write(f"{end[0]} {end[1]}\n")
                    f.flush()
                    os.fsync(f.fileno())

    # -- read --------------------------------------------------------------
    def fetch(self, topic, partition, offset, max_records):
        # Readers re-scan the index (cheap text file) so cross-process
        # reads see committed appends.
        end_off, end_pos = 0, 0
        entries = []
        try:
            with open(self._idx(topic, partition)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        a, b = line.split()
                        entries.append((int(a), int(b)))
        except FileNotFoundError:
            return []
        if entries:
            end_off, end_pos = entries[-1]
        if offset >= end_off:
            return []
        out = []
        with open(self._seg(topic, partition), "rb") as f:
            # scan from the latest index entry at or before `offset`
            start_pos, start_off = 0, 0
            for eoff, epos in entries:
                if eoff <= offset:
                    start_off, start_pos = eoff, epos
                else:
                    break
            f.seek(start_pos)
            cur = start_off
            while cur < end_off and len(out) < max_records:
                hdr = f.read(_HDR.size)
                if len(hdr) < _HDR.size:
                    break
                klen, vlen, ts, crc = _HDR.unpack(hdr)
                k = f.read(max(klen, 0)) if klen != 0 else b""
                v = f.read(max(vlen, 0)) if vlen != 0 else b""
                if zlib.crc32(k) ^ zlib.crc32(v) != crc:
                    raise IOError(
                        f"corrupt record at {topic}/p{partition} "
                        f"offset {cur}"
                    )
                if cur >= offset:
                    out.append(
                        Record(
                            None if klen == -1 else k,
                            None if vlen == -1 else v,
                            cur,
                            ts,
                        )
                    )
                cur += 1
        return out

    def end_offset(self, topic, partition):
        # uncached for readers: see committed cross-process appends
        end_off = 0
        try:
            with open(self._idx(topic, partition)) as f:
                for line in f:
                    line = line.strip()
                    if line:
                        end_off = int(line.split()[0])
        except FileNotFoundError:
            pass
        return end_off
