"""Kafka-analog streaming layer.

The reference speaks to real Kafka through librdkafka
(storage/src/source/kafka.rs, storage/src/sink/kafka.rs). librdkafka is
not in this build, so the broker itself is abstracted: ``Broker`` is a
minimal partitioned-log interface with a durable file-backed
implementation (``FileBroker``: one directory per topic, one
length-prefixed segment file per partition) and an in-memory one for
tests. Everything above the broker — decoding (json/csv/text/avro with
Confluent framing), envelopes (none/upsert/debezium), reclocked source
ingestion, and the exactly-once sink — mirrors the reference's
behavior and would speak to real Kafka by implementing ``Broker`` over
librdkafka.
"""

from .broker import Broker, FileBroker, MemBroker, Record
from .decode import make_decoder, make_encoder

__all__ = [
    "Broker",
    "FileBroker",
    "MemBroker",
    "Record",
    "make_decoder",
    "make_encoder",
]
