"""Auction load generator: deterministic, vectorized, host-side.

Analog of the reference's AUCTION load-generator source
(src/storage/src/source/generator/auction.rs): the five-table auction
schema (organizations, users, accounts, auctions, bids). The reference's
generator is insert-only (monotonic); this one adds an optional churn mode
— retracting the bids of auctions that closed a few ticks earlier — so the
AUCTION benchmark (BASELINE.json config 4: "streaming inserts/deletes,
windowed TOP-K + DISTINCT") exercises the retraction path of TopK/Distinct
the way the reference's feature benchmarks do.

Static side tables (organizations/users/accounts) are emitted as a
snapshot; auctions and bids stream per tick.
"""

from __future__ import annotations

from dataclasses import dataclass, field

import numpy as np

from ...repr.batch import Batch
from ...repr.schema import GLOBAL_DICT, Column, ColumnType, Schema

ORGANIZATIONS_SCHEMA = Schema(
    [
        Column("id", ColumnType.INT64),
        Column("name", ColumnType.STRING),
    ]
)

USERS_SCHEMA = Schema(
    [
        Column("id", ColumnType.INT64),
        Column("org_id", ColumnType.INT64),
        Column("name", ColumnType.STRING),
    ]
)

ACCOUNTS_SCHEMA = Schema(
    [
        Column("id", ColumnType.INT64),
        Column("org_id", ColumnType.INT64),
        Column("balance", ColumnType.INT64),
    ]
)

AUCTIONS_SCHEMA = Schema(
    [
        Column("id", ColumnType.INT64),
        Column("seller", ColumnType.INT64),
        Column("item", ColumnType.STRING),
        Column("end_time", ColumnType.TIMESTAMP),
    ]
)

BIDS_SCHEMA = Schema(
    [
        Column("id", ColumnType.INT64),
        Column("buyer", ColumnType.INT64),
        Column("auction_id", ColumnType.INT64),
        Column("amount", ColumnType.INT64),
        Column("bid_time", ColumnType.TIMESTAMP),
    ]
)

_ITEMS = (
    "Signed Memorabilia",
    "City Bar Crawl",
    "Best Pizza in Town",
    "Gift Basket",
    "Custom Art",
)

_COMPANIES = ("Cavern", "Squab", "Pelican", "Buoy", "Quid")


def _mk_batch(schema: Schema, cols, time: int, diffs=None) -> Batch:
    n = len(cols[0]) if cols else 0
    if diffs is None:
        diffs = np.ones(n, np.int64)
    return Batch.from_numpy(
        schema, cols, np.full(n, time, np.uint64), np.asarray(diffs)
    )


@dataclass
class AuctionGenerator:
    """Deterministic auction stream.

    Per tick: `auctions_per_tick` new auctions, each receiving
    `bids_per_auction` bids (one winning-range amount distribution),
    plus — in churn mode — retraction of every bid belonging to auctions
    opened `retract_after` ticks earlier."""

    seed: int = 0
    n_users: int = 128
    auctions_per_tick: int = 8
    bids_per_auction: int = 8
    retract_after: int | None = 4  # None = insert-only (reference behavior)

    def __post_init__(self):
        self._rng = np.random.default_rng(self.seed)
        self._next_auction = 0
        self._next_bid = 0
        # tick -> (bid cols) retained for later retraction
        self._live_bids: dict[int, list] = {}

    # -- static side tables -------------------------------------------------
    def snapshot(self, time: int = 0) -> dict:
        org_ids = np.arange(len(_COMPANIES), dtype=np.int64)
        orgs = _mk_batch(
            ORGANIZATIONS_SCHEMA,
            [org_ids, GLOBAL_DICT.encode_many(_COMPANIES)],
            time,
        )
        uid = np.arange(self.n_users, dtype=np.int64)
        users = _mk_batch(
            USERS_SCHEMA,
            [
                uid,
                uid % len(_COMPANIES),
                GLOBAL_DICT.encode_many([f"user {i}" for i in uid]),
            ],
            time,
        )
        accounts = _mk_batch(
            ACCOUNTS_SCHEMA,
            [uid, uid % len(_COMPANIES), (uid * 97) % 10_000],
            time,
        )
        return {"organizations": orgs, "users": users, "accounts": accounts}

    # -- streaming tables ---------------------------------------------------
    def tick(self, tick: int, time: int) -> dict:
        """One tick of auction/bid traffic: {auctions: Batch, bids: Batch}."""
        rng = self._rng
        na = self.auctions_per_tick
        a_ids = self._next_auction + np.arange(na, dtype=np.int64)
        self._next_auction += na
        sellers = rng.integers(0, self.n_users, na).astype(np.int64)
        items = GLOBAL_DICT.encode_many(
            [_ITEMS[i] for i in rng.integers(0, len(_ITEMS), na)]
        )
        end_times = (np.int64(time) + 10 + rng.integers(0, 10, na)).astype(
            np.int64
        )
        auctions = _mk_batch(
            AUCTIONS_SCHEMA, [a_ids, sellers, items, end_times], time
        )

        nb = na * self.bids_per_auction
        b_ids = self._next_bid + np.arange(nb, dtype=np.int64)
        self._next_bid += nb
        buyers = rng.integers(0, self.n_users, nb).astype(np.int64)
        b_auction = np.repeat(a_ids, self.bids_per_auction)
        amounts = rng.integers(1, 100, nb).astype(np.int64)
        bid_times = np.full(nb, time, dtype=np.int64)
        bid_cols = [b_ids, buyers, b_auction, amounts, bid_times]

        diffs = [np.ones(nb, np.int64)]
        cols = [list(bid_cols)]
        if self.retract_after is not None:
            self._live_bids[tick] = bid_cols
            old = tick - self.retract_after
            old_cols = self._live_bids.pop(old, None)
            if old_cols is not None:
                cols.append(old_cols)
                diffs.append(-np.ones(len(old_cols[0]), np.int64))
        bids = _mk_batch(
            BIDS_SCHEMA,
            [np.concatenate([c[i] for c in cols]) for i in range(5)],
            time,
            np.concatenate(diffs),
        )
        return {"auctions": auctions, "bids": bids}
