"""TPC-H load generator: deterministic, vectorized, host-side.

Analog of the reference's TPCH load-generator source
(src/storage/src/source/generator/tpch.rs): emits the TPC-H tables as an
initial snapshot of inserts, then (like the reference's tick mode) churns
orders — deleting and re-inserting order/lineitem groups — to produce a
sustained update stream. Distributions are the simplified deterministic
ones the reference uses, not the official dbgen text generator: uniform
keys/quantities/discounts, date ranges over 1992-1998.

All columns that the north-star workloads touch are generated with correct
types (DECIMAL as scaled int64, DATE as days-since-epoch, flags as
dictionary-coded strings); long text columns (comments) are omitted — they
are dead weight for every benchmark query.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from ...ops.lanes import hash_pair_host, host_lane_encode
from ...repr.batch import Batch
from ...repr.schema import (
    GLOBAL_DICT,
    Column,
    ColumnType,
    Schema,
)


def presort_hash(schema: Schema, cols, diffs):
    """Host-side replica of the device hash order (ops/lanes.hash_pair
    over row lanes): returns (cols, diffs, n) sorted by (h1, h2) with
    duplicate-content rows merged (diffs summed, zeros dropped) — the
    batch satisfies the "hash_consolidated" hint, so ingest skips the
    device input sort entirely (the large-micro-batch cost ceiling:
    TPU sort execution is ~2us/row; numpy lexsort is ~20ns/row)."""
    lanes = []
    for col, c in zip(cols, schema.columns):
        lanes.extend(host_lane_encode(col, c, None))
    h1, h2 = hash_pair_host(lanes)
    order = np.lexsort((h2, h1))
    cols = [np.asarray(c)[order] for c in cols]
    diffs = np.asarray(diffs)[order]
    h1, h2 = h1[order], h2[order]
    n = len(diffs)
    if n:
        same = np.ones(n, dtype=bool)
        same[0] = False
        same[1:] &= (h1[1:] == h1[:-1]) & (h2[1:] == h2[:-1])
        for c in cols:
            same[1:] &= c[1:] == c[:-1]
        if same.any():
            # Rare duplicate content (e.g. a churn draw colliding with
            # the row it retracts): merge via segment sums.
            import numpy as _np

            seg = _np.cumsum(~same) - 1
            sums = _np.zeros(seg[-1] + 1, dtype=diffs.dtype)
            _np.add.at(sums, seg, diffs)
            leaders = ~same
            keep = leaders & (sums[seg] != 0)
            cols = [c[keep] for c in cols]
            diffs = sums[seg][keep]
    keep = diffs != 0
    if not keep.all():
        cols = [c[keep] for c in cols]
        diffs = diffs[keep]
    return cols, diffs, len(diffs)

_EPOCH_1992 = 8035  # days from 1970-01-01 to 1992-01-01
_DATE_RANGE = 2526  # days spanned by TPCH dates (1992-01-01..1998-12-01)

LINEITEM_SCHEMA = Schema(
    [
        Column("l_orderkey", ColumnType.INT64),
        Column("l_partkey", ColumnType.INT64),
        Column("l_suppkey", ColumnType.INT64),
        Column("l_linenumber", ColumnType.INT32),
        Column("l_quantity", ColumnType.DECIMAL, scale=2),
        Column("l_extendedprice", ColumnType.DECIMAL, scale=2),
        Column("l_discount", ColumnType.DECIMAL, scale=2),
        Column("l_tax", ColumnType.DECIMAL, scale=2),
        Column("l_returnflag", ColumnType.STRING),
        Column("l_linestatus", ColumnType.STRING),
        Column("l_shipdate", ColumnType.DATE),
        Column("l_commitdate", ColumnType.DATE),
        Column("l_receiptdate", ColumnType.DATE),
    ]
)

ORDERS_SCHEMA = Schema(
    [
        Column("o_orderkey", ColumnType.INT64),
        Column("o_custkey", ColumnType.INT64),
        Column("o_orderstatus", ColumnType.STRING),
        Column("o_totalprice", ColumnType.DECIMAL, scale=2),
        Column("o_orderdate", ColumnType.DATE),
        Column("o_orderpriority", ColumnType.STRING),
    ]
)

SUPPLIER_SCHEMA = Schema(
    [
        Column("s_suppkey", ColumnType.INT64),
        Column("s_nationkey", ColumnType.INT64),
        Column("s_name", ColumnType.STRING),
    ]
)

PART_SCHEMA = Schema(
    [
        Column("p_partkey", ColumnType.INT64),
        Column("p_name", ColumnType.STRING),
        Column("p_retailprice", ColumnType.DECIMAL, scale=2),
    ]
)

PARTSUPP_SCHEMA = Schema(
    [
        Column("ps_partkey", ColumnType.INT64),
        Column("ps_suppkey", ColumnType.INT64),
        Column("ps_supplycost", ColumnType.DECIMAL, scale=2),
    ]
)

CUSTOMER_SCHEMA = Schema(
    [
        Column("c_custkey", ColumnType.INT64),
        Column("c_nationkey", ColumnType.INT64),
        Column("c_name", ColumnType.STRING),
    ]
)

NATION_SCHEMA = Schema(
    [
        Column("n_nationkey", ColumnType.INT64),
        Column("n_regionkey", ColumnType.INT64),
        Column("n_name", ColumnType.STRING),
    ]
)

REGION_SCHEMA = Schema(
    [
        Column("r_regionkey", ColumnType.INT64),
        Column("r_name", ColumnType.STRING),
    ]
)

_NATIONS = [
    "ALGERIA", "ARGENTINA", "BRAZIL", "CANADA", "EGYPT", "ETHIOPIA",
    "FRANCE", "GERMANY", "INDIA", "INDONESIA", "IRAN", "IRAQ", "JAPAN",
    "JORDAN", "KENYA", "MOROCCO", "MOZAMBIQUE", "PERU", "CHINA",
    "ROMANIA", "SAUDI ARABIA", "VIETNAM", "RUSSIA", "UNITED KINGDOM",
    "UNITED STATES",
]
_REGIONS = ["AFRICA", "AMERICA", "ASIA", "EUROPE", "MIDDLE EAST"]
_NATION_REGION = [0, 1, 1, 1, 4, 0, 3, 3, 2, 2, 4, 4, 2, 4, 0, 0, 0, 1,
                  2, 3, 4, 2, 3, 3, 1]


@dataclass
class TpchGenerator:
    """Deterministic TPCH generator at a given scale factor.

    Row counts follow the spec: orders = 1.5M * sf, lineitem ~ 4 per
    order, part = 200k * sf, supplier = 10k * sf, customer = 150k * sf.
    """

    sf: float = 0.01
    seed: int = 1

    def __post_init__(self):
        self.n_orders = max(int(1_500_000 * self.sf), 16)
        self.n_part = max(int(200_000 * self.sf), 8)
        self.n_supplier = max(int(10_000 * self.sf), 4)
        self.n_customer = max(int(150_000 * self.sf), 8)
        self._flag_codes = GLOBAL_DICT.encode_many(["R", "A", "N"])
        self._status_codes = GLOBAL_DICT.encode_many(["F", "O"])
        self._prio_codes = GLOBAL_DICT.encode_many(
            ["1-URGENT", "2-HIGH", "3-MEDIUM", "4-NOT SPECIFIED", "5-LOW"]
        )

    # -- per-order generation (deterministic in orderkey) -------------------
    #
    # Counter-based (splitmix64-style) hashing instead of a sequential
    # numpy Generator: every field of every row is a pure function of
    # (seed, version, orderkey, linenumber, field tag). A sequential rng
    # seeded per batch made a row's content depend on the BATCH it was
    # generated in, so a churn tick's "delete the old rows" did not match
    # the snapshot's rows — phantom +/- row pairs that cancel in sums but
    # break EXISTS/DISTINCT/Threshold semantics (negative multiplicities
    # are outside the differential contract; the reference's tpch.rs tick
    # loop deletes exactly the rows it inserted).
    def _mix64(self, *vals):
        with np.errstate(over="ignore"):
            h = np.uint64(0x9E3779B97F4A7C15)
            for v in vals:
                v = np.asarray(v, dtype=np.uint64)
                z = (h ^ v) + np.uint64(0x9E3779B97F4A7C15)
                z = (z ^ (z >> np.uint64(30))) * np.uint64(
                    0xBF58476D1CE4E5B9
                )
                z = (z ^ (z >> np.uint64(27))) * np.uint64(
                    0x94D049BB133111EB
                )
                h = z ^ (z >> np.uint64(31))
        return h

    def _draw(self, lo: int, hi: int, *keys) -> np.ndarray:
        """Uniform ints in [lo, hi), elementwise over broadcast keys."""
        span = np.uint64(hi - lo)
        return (self._mix64(*keys) % span).astype(np.int64) + lo

    def lineitems_for_orders(
        self, orderkeys: np.ndarray, version: int = 0
    ):
        """Generate lineitem rows for the given order keys.

        ``version`` selects the churn generation (0 = snapshot; churn
        tick t writes version 1000+t): rows are deterministic in
        (seed, version, orderkey) alone, never in batch composition.
        """
        sd = np.uint64(self.seed * 1_000_003 + version)
        ok_u = np.asarray(orderkeys, dtype=np.uint64)
        n_lines = self._draw(1, 8, sd, ok_u, 11)  # 1..7, avg 4 per spec
        okeys = np.repeat(orderkeys, n_lines)
        n = len(okeys)
        linenumber = (
            np.arange(n) - np.repeat(np.cumsum(n_lines) - n_lines, n_lines)
        ).astype(np.int32) + 1
        u = okeys.astype(np.uint64)
        li = linenumber.astype(np.uint64)
        partkey = self._draw(1, self.n_part + 1, sd, u, li, 1)
        suppkey = self._draw(1, self.n_supplier + 1, sd, u, li, 2)
        quantity = self._draw(1, 51, sd, u, li, 3) * 100  # 1..50, scale 2
        retail = 90_000 + (partkey * 100) % 200_000 + (partkey % 1000) * 100
        extendedprice = (quantity // 100) * retail
        discount = self._draw(0, 11, sd, u, li, 4)  # 0.00..0.10
        tax = self._draw(0, 9, sd, u, li, 5)  # 0.00..0.08
        orderdate = _EPOCH_1992 + (
            (okeys * 2654435761) % (_DATE_RANGE - 151)
        ).astype(np.int64)
        shipdate = orderdate + self._draw(1, 122, sd, u, li, 6)
        commitdate = orderdate + self._draw(30, 91, sd, u, li, 7)
        receiptdate = shipdate + self._draw(1, 31, sd, u, li, 8)
        today = _EPOCH_1992 + _DATE_RANGE - 151
        returnflag = np.where(
            receiptdate <= today,
            self._flag_codes[self._draw(0, 2, sd, u, li, 9)],
            self._flag_codes[2],
        ).astype(np.int64)
        linestatus = np.where(
            shipdate > today, self._status_codes[1], self._status_codes[0]
        ).astype(np.int64)
        cols = [
            okeys,
            partkey,
            suppkey,
            linenumber,
            quantity.astype(np.int64),
            extendedprice.astype(np.int64),
            (discount).astype(np.int64),
            (tax).astype(np.int64),
            returnflag,
            linestatus,
            shipdate.astype(np.int32),
            commitdate.astype(np.int32),
            receiptdate.astype(np.int32),
        ]
        return cols

    def orders_rows(self, orderkeys: np.ndarray):
        sd = np.uint64(self.seed * 1_000_003)
        u = np.asarray(orderkeys, dtype=np.uint64)
        custkey = self._draw(1, self.n_customer + 1, sd, u, 21)
        status = self._status_codes[
            self._draw(0, 2, sd, u, 22)
        ].astype(np.int64)
        totalprice = self._draw(1_000_00, 500_000_00, sd, u, 23)
        orderdate = _EPOCH_1992 + (
            (orderkeys * 2654435761) % (_DATE_RANGE - 151)
        ).astype(np.int64)
        prio = self._prio_codes[self._draw(0, 5, sd, u, 24)].astype(
            np.int64
        )
        return [
            orderkeys,
            custkey,
            status,
            totalprice.astype(np.int64),
            orderdate.astype(np.int32),
            prio,
        ]

    # -- static dimension tables -------------------------------------------
    def supplier_table(self):
        rng = np.random.default_rng(self.seed + 7)
        keys = np.arange(1, self.n_supplier + 1)
        nation = rng.integers(0, 25, size=len(keys))
        names = GLOBAL_DICT.encode_many(
            [f"Supplier#{k:09d}" for k in keys]
        )
        return [keys, nation.astype(np.int64), names]

    def part_table(self):
        keys = np.arange(1, self.n_part + 1)
        names = GLOBAL_DICT.encode_many([f"part {k % 92}" for k in keys])
        retail = (
            90_000 + (keys * 100) % 200_000 + (keys % 1000) * 100
        ).astype(np.int64)
        return [keys, names, retail]

    def partsupp_table(self):
        rng = np.random.default_rng(self.seed + 11)
        pkeys = np.repeat(np.arange(1, self.n_part + 1), 4)
        skeys = (
            (pkeys + np.tile(np.arange(4), self.n_part) * (
                self.n_supplier // 4 + 1
            )) % self.n_supplier
        ) + 1
        cost = rng.integers(100, 1000_00, size=len(pkeys)).astype(np.int64)
        return [pkeys, skeys, cost]

    def customer_table(self):
        rng = np.random.default_rng(self.seed + 13)
        keys = np.arange(1, self.n_customer + 1)
        nation = rng.integers(0, 25, size=len(keys))
        names = GLOBAL_DICT.encode_many(
            [f"Customer#{k:09d}" for k in keys]
        )
        return [keys, nation.astype(np.int64), names]

    def nation_table(self):
        names = GLOBAL_DICT.encode_many(_NATIONS)
        return [
            np.arange(25, dtype=np.int64),
            np.asarray(_NATION_REGION, dtype=np.int64),
            names,
        ]

    def region_table(self):
        names = GLOBAL_DICT.encode_many(_REGIONS)
        return [np.arange(5, dtype=np.int64), names]

    def table_batch(self, name: str, time: int = 0) -> Batch:
        """A static table as one insert batch (dimension-table snapshot)."""
        schema, cols = {
            "supplier": (SUPPLIER_SCHEMA, self.supplier_table),
            "part": (PART_SCHEMA, self.part_table),
            "partsupp": (PARTSUPP_SCHEMA, self.partsupp_table),
            "customer": (CUSTOMER_SCHEMA, self.customer_table),
            "nation": (NATION_SCHEMA, self.nation_table),
            "region": (REGION_SCHEMA, self.region_table),
        }[name]
        cols = cols()
        n = len(cols[0])
        return Batch.from_numpy(
            schema,
            cols,
            np.full(n, time, np.uint64),
            np.ones(n, np.int64),
        )

    # -- streaming interface ------------------------------------------------
    def snapshot_lineitem_batches(
        self, batch_orders: int = 4096, time: int = 0,
        capacity: int | None = None,
    ):
        """Yield Batch objects of lineitem inserts covering the
        snapshot — host-presorted in the device hash order (ingest
        skips the device sort; presort_hash)."""
        for start in range(1, self.n_orders + 1, batch_orders):
            keys = np.arange(
                start, min(start + batch_orders, self.n_orders + 1)
            )
            cols = self.lineitems_for_orders(keys)
            n = len(cols[0])
            cols, diffs, n = presort_hash(
                LINEITEM_SCHEMA, cols, np.ones(n, np.int64)
            )
            yield Batch.from_numpy(
                LINEITEM_SCHEMA,
                cols,
                np.full(n, time, np.uint64),
                diffs,
                capacity=capacity,
                hints=("hash_consolidated",),
            )

    def churn_lineitem_batch(
        self, n_orders: int, tick: int, time: int, capacity: int | None = None
    ) -> Batch:
        """One tick of order churn: delete + regenerate `n_orders` orders'
        lineitems (the reference's tick loop deletes and re-inserts an
        order per tick, tpch.rs). The generator tracks each order's
        current version so the deletion side matches EXACTLY the rows
        previously inserted for it, even when ticks overlap on orders."""
        rng = np.random.default_rng(self.seed * 31 + tick)
        keys = np.sort(
            rng.choice(
                np.arange(1, self.n_orders + 1), size=n_orders, replace=False
            )
        )
        if not hasattr(self, "_order_version"):
            self._order_version: dict = {}
        new_version = 1000 + tick
        by_version: dict = {}
        for k in keys:
            v = self._order_version.get(int(k), 0)
            by_version.setdefault(v, []).append(int(k))
        old_parts = [
            self.lineitems_for_orders(
                np.asarray(sorted(ks), dtype=keys.dtype), version=v
            )
            for v, ks in sorted(by_version.items())
        ]
        old = [np.concatenate(cols) for cols in zip(*old_parts)]
        new = self.lineitems_for_orders(keys, version=new_version)
        for k in keys:
            self._order_version[int(k)] = new_version
        cols = [np.concatenate([o, nw]) for o, nw in zip(old, new)]
        n_old, n_new = len(old[0]), len(new[0])
        diffs = np.concatenate(
            [np.full(n_old, -1, np.int64), np.ones(n_new, np.int64)]
        )
        cols, diffs, n = presort_hash(LINEITEM_SCHEMA, cols, diffs)
        times = np.full(n, time, np.uint64)
        return Batch.from_numpy(
            LINEITEM_SCHEMA, cols, times, diffs, capacity=capacity,
            hints=("hash_consolidated",),
        )
