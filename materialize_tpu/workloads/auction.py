"""AUCTION workloads: windowed TOP-K + DISTINCT over the bid stream
(BASELINE.json config 4; reference load generator
storage/src/source/generator/auction.rs and the auction demo views).

Two maintained views:

- ``auction_topk_mir``: the top-`k` bids per auction by amount
  (``MonotonicTopK``-shaped plan in the reference; here the single
  sorted-arrangement TopK, ops/topk.py).
- ``auction_winning_bidders_mir``: DISTINCT buyers currently holding a
  top-`k` position — TopK feeding Distinct, the plan shape the reference's
  feature benchmarks exercise.
"""

from __future__ import annotations

from ..expr import relation as mir
from ..storage.generator.auction import BIDS_SCHEMA


def auction_topk_mir(k: int = 3) -> mir.RelationExpr:
    """Top-k bids per auction: group by auction_id, order amount DESC."""
    bids = mir.Get("bids", BIDS_SCHEMA)
    return mir.TopK(
        bids,
        group_key=(2,),  # auction_id
        order_by=((3, True, False),),  # amount DESC
        limit=k,
    )


def auction_winning_bidders_mir(k: int = 3) -> mir.RelationExpr:
    """DISTINCT buyers holding a top-k bid on any auction."""
    return auction_topk_mir(k).project((1,)).distinct()
