"""PageRank over the TPC-H orders→suppliers graph, as WITH MUTUALLY
RECURSIVE (BASELINE.json config 5).

The graph: bipartite orderkey -> suppkey edges from lineitem (each
lineitem links the order placing it to the supplier fulfilling it),
derived from the reference's TPCH load generator relations
(storage/src/source/generator/tpch.rs).

MIR shape (the SQL a user would write with WITH MUTUALLY RECURSIVE):

    ranks(n, r) := SELECT n, SUM(c) FROM (
        SELECT n, 0.15 AS c FROM nodes
        UNION ALL
        SELECT e.dst, 0.85 * r.rank / d.deg
        FROM ranks r JOIN out_deg d ON r.n = d.n
                     JOIN edges  e ON r.n = e.src
    ) GROUP BY n

i.e. rank(k+1) = base + damped incoming of rank(k) — the power-iteration
fixpoint, iterated to ``max_iters`` (float fixpoints stop on the
iteration cap: RETURN AT RECURSION LIMIT semantics, reference
expr/src/relation.rs LetRec limits).
"""

from __future__ import annotations

from ..expr import relation as mir
from ..expr.relation import AggregateExpr, AggregateFunc
from ..expr.scalar import BinaryFunc, CallBinary, ColumnRef, col, lit
from ..repr.schema import Column, ColumnType, Schema


def pagerank_mir(edge_schema: Schema, max_iters: int = 25) -> mir.RelationExpr:
    """rank(n) = 0.15 + 0.85 * sum_{m->n} rank(m) / out_deg(m).

    edges: (src int64, dst int64). Returns (n, rank float64)."""
    edges = mir.Get("edges", edge_schema)

    # out_deg: (src, deg)
    out_deg = edges.reduce(
        (0,), (AggregateExpr(AggregateFunc.COUNT, col(1)),)
    )

    # nodes: distinct src ∪ dst (sink-only nodes still get base rank)
    nodes = mir.Union(
        (edges.project((0,)), edges.project((1,)))
    ).distinct()

    # base contribution rows: (node, 0.15)
    base = nodes.map((lit(0.15),))

    rank_schema = Schema(
        [edge_schema[0], Column("rank", ColumnType.FLOAT64, True)]
    )
    ranks = mir.Get("ranks", rank_schema)

    # (n, r) ⋈ (n, deg) on node  ->  (n, r, n, deg)
    r_with_deg = mir.Join(
        (ranks, out_deg), ((ColumnRef(0), ColumnRef(2)),)
    )
    # ++ (src, dst) joined on n = src  ->  6 cols
    r_deg_edges = mir.Join(
        (r_with_deg, edges), ((ColumnRef(0), ColumnRef(4)),)
    )
    # damped per-edge contribution rows: (dst, 0.85 * r / deg)
    per_edge = CallBinary(BinaryFunc.DIV, col(1) * lit(0.85), col(3))
    contrib = r_deg_edges.map((per_edge,)).project((5, 6))

    # rank(n) = SUM of contribution rows (Union is multiset concatenation;
    # the Reduce does the arithmetic).
    value = mir.Union((base, contrib)).reduce(
        (0,), (AggregateExpr(AggregateFunc.SUM_FLOAT, col(1)),)
    )

    return mir.LetRec(
        names=("ranks",),
        values=(value,),
        value_schemas=(rank_schema,),
        body=mir.Get("ranks", rank_schema),
        max_iters=max_iters,
    )
