"""North-star TPCH workloads as MIR (BASELINE.json gate configs).

These are the maintained-view definitions the driver benchmarks: Q1 (pure
accumulable Reduce), Q15 (join + SUM + MAX), Q9 (6-relation delta join).
Reference analogs: the TPCH load-generator source
(src/storage/src/source/generator/tpch.rs) feeding indexed materialized
views rendered by compute/src/render.rs.
"""

from __future__ import annotations

from ..expr import relation as mir
from ..expr.relation import AggregateExpr, AggregateFunc
from ..expr.scalar import CallUnary, UnaryFunc, col, lit
from ..repr.schema import ColumnType
from ..storage.generator.tpch import (
    LINEITEM_SCHEMA,
    NATION_SCHEMA,
    ORDERS_SCHEMA,
    PART_SCHEMA,
    PARTSUPP_SCHEMA,
    SUPPLIER_SCHEMA,
)

# date '1998-12-01' - 90 days, as a day number since 1970-01-01
Q1_CUTOFF = 8035 + 2526 - 90


def q1_mir() -> mir.RelationExpr:
    """TPCH Q1: GROUP BY returnflag, linestatus with 4 sums + count(*).

    Averages derive from sums/counts in result finishing, as in the
    reference (RowSetFinishing applies post-aggregation arithmetic).
    Exercises ReducePlan::Accumulable (render/reduce.rs:1357).
    """
    sch = LINEITEM_SCHEMA
    i = sch.index_of
    one = lit(100, ColumnType.DECIMAL, 2)  # 1.00 at scale 2
    disc_price = col(i("l_extendedprice")) * (one - col(i("l_discount")))
    charge_rhs = one + col(i("l_tax"))
    return (
        mir.Get("lineitem", sch)
        .filter([col(i("l_shipdate")).lte(lit(Q1_CUTOFF, ColumnType.DATE))])
        .map([disc_price])  # -> col 13, scale 4
        .map([col(13) * charge_rhs])  # -> col 14, scale 6
        .project([i("l_returnflag"), i("l_linestatus"),
                  i("l_quantity"), i("l_extendedprice"), 13, 14])
        .reduce(
            (0, 1),
            (
                AggregateExpr(AggregateFunc.SUM_INT, col(2)),  # sum_qty
                AggregateExpr(AggregateFunc.SUM_INT, col(3)),  # sum_base
                AggregateExpr(AggregateFunc.SUM_INT, col(4)),  # sum_disc
                AggregateExpr(AggregateFunc.SUM_INT, col(5)),  # sum_charge
                AggregateExpr(AggregateFunc.COUNT, lit(True)),  # count(*)
            ),
        )
    )


# Q15 revenue window: [1996-01-01, 1996-04-01) as day numbers.
Q15_LO = 9496
Q15_HI = 9587


def q15_mir() -> mir.RelationExpr:
    """TPCH Q15: top supplier(s) by quarterly revenue.

    revenue(supplier_no, total_revenue) = GROUP BY over a shipdate
    window; result joins supplier with revenue and the GLOBAL MAX of
    total_revenue. Exercises Let sharing, accumulable Reduce, the
    global-aggregate (empty group key) hierarchical MAX, and a 3-input
    linear join (the reference plans this with JoinPlan + ReducePlan
    Hierarchical; render/reduce.rs:850, linear_join.rs:204).

    Output: (s_suppkey, s_name, total_revenue).
    """
    li = LINEITEM_SCHEMA
    i = li.index_of
    one = lit(100, ColumnType.DECIMAL, 2)  # 1.00
    revenue = (
        mir.Get("lineitem", li)
        .filter([
            col(i("l_shipdate")).gte(lit(Q15_LO, ColumnType.DATE)),
            col(i("l_shipdate")).lt(lit(Q15_HI, ColumnType.DATE)),
        ])
        .map([col(i("l_extendedprice")) * (one - col(i("l_discount")))])
        .project([i("l_suppkey"), 13])
        .reduce(
            (0,), (AggregateExpr(AggregateFunc.SUM_INT, col(1)),)
        )
    )  # schema: [l_suppkey, total_revenue]
    rev_schema = revenue.schema()
    rev = mir.Get("__revenue__", rev_schema)
    maxrev = rev.reduce(
        (), (AggregateExpr(AggregateFunc.MAX, col(1)),)
    )  # schema: [max_revenue]
    # global columns: supplier [0..2], revenue [3..4], maxrev [5]
    joined = mir.Join(
        (mir.Get("supplier", SUPPLIER_SCHEMA), rev, maxrev),
        equivalences=((col(0), col(3)), (col(4), col(5))),
    ).project([0, 2, 4])  # s_suppkey, s_name, total_revenue
    return mir.Let("__revenue__", revenue, joined)


def q9_mir() -> mir.RelationExpr:
    """TPCH Q9 (product-type profit): 6-relation delta join + GROUP BY.

    Exercises JoinPlan::Delta — one update pipeline per input over shared
    arrangements (render/join/delta_join.rs:51; BASELINE.json config 3).
    The reference's ``p_name LIKE '%green%'`` filter is omitted
    (dictionary-coded strings have no device substring search yet); the
    join/aggregate plan shape is identical.

    Output: (n_name, o_year, sum_profit scale-4 decimal).
    """
    li, pt, sp = LINEITEM_SCHEMA, PART_SCHEMA, SUPPLIER_SCHEMA
    ps, od, na = PARTSUPP_SCHEMA, ORDERS_SCHEMA, NATION_SCHEMA
    i = li.index_of
    # Global column offsets: lineitem 0..12, part 13..15, supplier 16..18,
    # partsupp 19..21, orders 22..27, nation 28..30.
    joined = mir.Join(
        (
            mir.Get("lineitem", li),
            mir.Get("part", pt),
            mir.Get("supplier", sp),
            mir.Get("partsupp", ps),
            mir.Get("orders", od),
            mir.Get("nation", na),
        ),
        equivalences=(
            (col(i("l_suppkey")), col(16), col(20)),  # = s_suppkey = ps_suppkey
            (col(i("l_partkey")), col(13), col(19)),  # = p_partkey = ps_partkey
            (col(i("l_orderkey")), col(22)),          # = o_orderkey
            (col(17), col(28)),                       # s_nationkey = n_nationkey
        ),
    )
    one = lit(100, ColumnType.DECIMAL, 2)  # 1.00
    amount = col(i("l_extendedprice")) * (one - col(i("l_discount"))) - col(
        21
    ) * col(i("l_quantity"))  # scale 4
    o_year = CallUnary(UnaryFunc.EXTRACT_YEAR, col(26))
    return (
        joined.map([amount, o_year])  # -> cols 31, 32
        .project([30, 32, 31])  # n_name, o_year, amount
        .reduce(
            (0, 1), (AggregateExpr(AggregateFunc.SUM_INT, col(2)),)
        )
    )
