"""Columnar update batches: the device representation of a chunk of a
time-varying collection.

Every collection flows as ``(data, time, diff)`` update triples
(reference: doc/developer/platform/formalism.md:5-25). On TPU the unit of
flow is a fixed-capacity columnar batch: struct-of-arrays data columns plus
``time`` (u64) and ``diff`` (i64) columns and a scalar ``count`` of valid
rows. Rows [0, count) are valid; the tail is padding. Fixed capacities keep
XLA shapes static (SURVEY.md §7 hard part #1); overflow is detected on
device and resolved host-side by retrying at a larger capacity tier.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Sequence

import jax
import jax.numpy as jnp
import numpy as np

from .schema import DIFF_DTYPE, TIME_DTYPE, Column, ColumnType, Schema


def capacity_tier(n: int, minimum: int = 256) -> int:
    """Round up to the capacity tier (power of two) for compile caching."""
    cap = minimum
    while cap < n:
        cap *= 2
    return cap


@jax.tree_util.register_pytree_node_class
@dataclass
class Batch:
    """A fixed-capacity columnar chunk of (data, time, diff) updates.

    cols  : tuple of [cap]-shaped arrays, one per schema column
    nulls : tuple of ([cap] bool array | None), one per schema column
    time  : [cap] uint64
    diff  : [cap] int64
    count : scalar int32 — rows [0, count) are valid
    schema: static aux data (host-side)
    """

    cols: tuple
    nulls: tuple
    time: jnp.ndarray
    diff: jnp.ndarray
    count: jnp.ndarray
    schema: Schema
    # Static producer guarantees (trace-time facts; part of the pytree
    # aux so jit compiles hint-specialized programs). Known hint:
    # "hash_consolidated" — rows sorted by the hash-pair order of their
    # content (ops/lanes.hash_pair), at most one row per content,
    # nonzero diffs. Host producers (load generators) pre-sort with the
    # numpy replica (hash_pair_host), letting the device skip input
    # sorts — sort EXECUTION on TPU is ~2us/row at 32k+, the input-side
    # cost ceiling for large micro-batches.
    hints: tuple = ()

    # -- pytree protocol ---------------------------------------------------
    def tree_flatten(self):
        null_present = tuple(n is not None for n in self.nulls)
        children = (
            self.cols,
            tuple(n for n in self.nulls if n is not None),
            self.time,
            self.diff,
            self.count,
        )
        return children, (self.schema, null_present, self.hints)

    @classmethod
    def tree_unflatten(cls, aux, children):
        schema, null_present, hints = aux
        cols, nulls_packed, time, diff, count = children
        nulls = []
        it = iter(nulls_packed)
        for present in null_present:
            nulls.append(next(it) if present else None)
        return cls(
            tuple(cols), tuple(nulls), time, diff, count, schema, hints
        )

    # -- properties --------------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.diff.shape[0]

    def valid_mask(self) -> jnp.ndarray:
        return jnp.arange(self.capacity, dtype=jnp.int32) < self.count

    def col(self, name: str) -> jnp.ndarray:
        return self.cols[self.schema.index_of(name)]

    # -- construction ------------------------------------------------------
    @staticmethod
    def from_numpy(
        schema: Schema,
        cols: Sequence[np.ndarray],
        time,
        diff,
        capacity: int | None = None,
        nulls: Sequence[np.ndarray | None] | None = None,
        hints: tuple = (),
    ) -> "Batch":
        """Build a Batch from host arrays, padding up to a capacity tier."""
        cols = [np.asarray(c) for c in cols]
        n = len(diff) if np.ndim(diff) else (cols[0].shape[0] if cols else 0)
        cap = capacity if capacity is not None else capacity_tier(max(n, 1))
        assert cap >= n, f"capacity {cap} < rows {n}"

        def pad(a, dtype):
            a = np.asarray(a, dtype=dtype)
            if a.ndim == 0:
                a = np.full(n, a, dtype=dtype)
            out = np.zeros(cap, dtype=dtype)
            out[:n] = a
            return jnp.asarray(out)

        dev_cols = tuple(
            pad(c, col.dtype) for c, col in zip(cols, schema.columns)
        )
        if nulls is None:
            nulls = [None] * len(schema.columns)
        dev_nulls = tuple(
            (pad(nl, np.bool_) if nl is not None else None) for nl in nulls
        )
        b = Batch(
            cols=dev_cols,
            nulls=dev_nulls,
            time=pad(time, TIME_DTYPE),
            diff=pad(diff, DIFF_DTYPE),
            count=jnp.asarray(n, dtype=jnp.int32),
            schema=schema,
            hints=hints,
        )
        # Host-known row count for staging/benchmark code: reading
        # `count` back from the device is a d2h transfer, which through
        # the remote-TPU tunnel permanently de-pipelines dispatch
        # (PERF_NOTES.md). Not a pytree field; lost on tree transforms.
        b._host_count = n
        return b

    @staticmethod
    def empty(schema: Schema, capacity: int = 256) -> "Batch":
        return Batch.from_numpy(
            schema,
            [np.zeros(0, dtype=c.dtype) for c in schema.columns],
            np.zeros(0, dtype=TIME_DTYPE),
            np.zeros(0, dtype=DIFF_DTYPE),
            capacity=capacity,
        )

    # -- host readback -----------------------------------------------------
    def to_numpy(self) -> dict:
        """Read valid rows back to host as a dict of numpy arrays.
        Duplicate column names are disambiguated with a positional suffix —
        use positional access (to_columns/to_rows) when names may repeat."""
        n = int(self.count)
        out = {}
        for i, (c, arr) in enumerate(zip(self.schema.columns, self.cols)):
            name = c.name if c.name not in out else f"{c.name}__{i}"
            out[name] = np.asarray(arr)[:n]
        out["__time__"] = np.asarray(self.time)[:n]
        out["__diff__"] = np.asarray(self.diff)[:n]
        return out

    def to_columns(self) -> list[np.ndarray]:
        """Valid rows of every column, positionally, + time and diff."""
        n = int(self.count)
        return [np.asarray(a)[:n] for a in self.cols] + [
            np.asarray(self.time)[:n],
            np.asarray(self.diff)[:n],
        ]

    def to_rows(self) -> list[tuple]:
        """Valid rows as python tuples (col..., time, diff) — for tests."""
        cols = self.to_columns()
        return [tuple(x.item() for x in row) for row in zip(*cols)]

    # -- shape management --------------------------------------------------
    def with_capacity(self, cap: int) -> "Batch":
        """Grow to a new capacity tier. Shrinking is forbidden: `count` is a
        traced value, so a shrink below it could silently drop valid rows."""
        if cap < self.capacity:
            raise ValueError(
                f"cannot shrink capacity {self.capacity} -> {cap}; "
                "rebuild via compact/consolidate instead"
            )

        def resize(a):
            if a is None:
                return None
            if a.shape[0] == cap:
                return a
            pad = jnp.zeros((cap - a.shape[0],), dtype=a.dtype)
            return jnp.concatenate([a, pad])

        return Batch(
            cols=tuple(resize(c) for c in self.cols),
            nulls=tuple(resize(n) for n in self.nulls),
            time=resize(self.time),
            diff=resize(self.diff),
            count=self.count,
            schema=self.schema,
        )

    def canonicalize_nulls(self) -> "Batch":
        """Make null-mask PRESENCE a function of the schema alone: nullable
        columns get a materialized (possibly all-False) mask, non-nullable
        columns get None. Needed wherever batches cross a fixed-structure
        boundary (lax.while_loop carries: pytree aux must match)."""
        nulls = []
        for c, nl, col in zip(self.cols, self.nulls, self.schema.columns):
            if col.nullable:
                nulls.append(
                    nl if nl is not None else jnp.zeros(c.shape[0], bool)
                )
            else:
                nulls.append(None)
        return self.replace(nulls=tuple(nulls))

    # replace() fields that can never invalidate a sortedness hint:
    # hints claim facts about row CONTENT order/uniqueness (and, for
    # "hash_sorted", times), so swapping cols/nulls/time voids them,
    # while diff (sign flips keep nonzero), count, schema rebrands
    # (same content, new names), and explicit hints do not. Dropping
    # by default here is what keeps the hint-consuming fast paths
    # (ops/consolidate.py, spine._arrange_for_run) sound without every
    # content-changing call site having to remember to launder.
    _HINT_SAFE_FIELDS = frozenset({"diff", "count", "schema", "hints"})

    def replace(self, **kw) -> "Batch":
        d = dict(
            cols=self.cols,
            nulls=self.nulls,
            time=self.time,
            diff=self.diff,
            count=self.count,
            schema=self.schema,
            hints=self.hints,
        )
        if not self._HINT_SAFE_FIELDS.issuperset(kw):
            d["hints"] = ()
        d.update(kw)
        return Batch(**d)
