"""Relation schemas and column types.

Analog of the reference's ``RelationDesc`` / ``SqlScalarType``
(``src/repr/src/relation.rs``) and ``Datum`` (``src/repr/src/scalar.rs:85``),
re-cast columnar: a relation is a struct-of-arrays, each column a fixed-width
device array. Variable-width data (strings) is dictionary-encoded host-side
(int32 codes on device), matching SURVEY.md §7's design stance.
"""

from __future__ import annotations

import enum
from dataclasses import dataclass, field

import jax.numpy as jnp
import numpy as np


class ColumnType(enum.Enum):
    """Device-representable scalar types.

    Subset of the reference's 30 Datum variants (src/repr/src/scalar.rs:85)
    that covers the north-star workloads; exotic types (jsonb, ranges,
    arbitrary-precision numeric) are deferred to host-side fallback.
    """

    BOOL = "bool"
    INT32 = "int32"
    INT64 = "int64"
    FLOAT64 = "float64"
    # Days since the UNIX epoch, like reference Datum::Date.
    DATE = "date"
    # Milliseconds since the UNIX epoch, like mz Timestamp (repr/src/timestamp.rs:46).
    TIMESTAMP = "timestamp"
    # Fixed-point decimal stored as a scaled int64 (reference uses dec i128;
    # scale lives in the Column). Exact accumulation like Accum semigroup
    # (compute/src/render/reduce.rs:1357).
    DECIMAL = "decimal"
    # Dictionary code (int32) into a host-side StringDictionary.
    STRING = "string"

    @property
    def dtype(self) -> np.dtype:
        return np.dtype(_DTYPES[self])

    @property
    def is_orderable_on_device(self) -> bool:
        # Every type, including STRING: dictionary codes are assigned by
        # order-preserving gap labeling (StringDictionary), so integer
        # code comparison == lexicographic string comparison.
        return True


_DTYPES = {
    ColumnType.BOOL: np.bool_,
    ColumnType.INT32: np.int32,
    ColumnType.INT64: np.int64,
    ColumnType.FLOAT64: np.float64,
    ColumnType.DATE: np.int32,
    ColumnType.TIMESTAMP: np.int64,
    ColumnType.DECIMAL: np.int64,
    ColumnType.STRING: np.int64,  # order-preserving dictionary labels
}

# Timestamps of the virtual time axis (not SQL timestamps): u64 ms since epoch,
# matching repr/src/timestamp.rs:46.
TIME_DTYPE = np.uint64
# Update multiplicities: i64, matching repr/src/diff.rs.
DIFF_DTYPE = np.int64


@dataclass(frozen=True)
class Column:
    name: str
    ctype: ColumnType
    nullable: bool = False
    # Decimal scale: value = unscaled / 10**scale.
    scale: int = 0

    @property
    def dtype(self) -> np.dtype:
        return self.ctype.dtype


@dataclass(frozen=True)
class Schema:
    """Column layout of a collection (RelationDesc analog)."""

    columns: tuple[Column, ...]

    def __init__(self, columns):
        object.__setattr__(self, "columns", tuple(columns))

    @property
    def names(self) -> tuple[str, ...]:
        return tuple(c.name for c in self.columns)

    @property
    def arity(self) -> int:
        return len(self.columns)

    def __len__(self) -> int:
        return len(self.columns)

    def __getitem__(self, i: int) -> Column:
        return self.columns[i]

    def index_of(self, name: str) -> int:
        for i, c in enumerate(self.columns):
            if c.name == name:
                return i
        raise KeyError(name)

    def project(self, indices) -> "Schema":
        return Schema([self.columns[i] for i in indices])

    def concat(self, other: "Schema") -> "Schema":
        return Schema(self.columns + other.columns)

    def rename(self, names) -> "Schema":
        assert len(names) == len(self.columns)
        return Schema(
            [
                Column(n, c.ctype, c.nullable, c.scale)
                for n, c in zip(names, self.columns)
            ]
        )


class DictExhausted(RuntimeError):
    """A gap in the label space ran out between two dense neighbors.
    Recoverable: callers that own device-resident state catch this,
    call ``rebalance()``, remap host-side literals, and rebuild their
    dataflows (durable state is safe — persist parts store the actual
    strings, storage/persist/codec.py)."""


class DictSnapshot:
    """An immutable epoch-coherent read view of the dictionary (see
    StringDictionary.snapshot). Decodes codes of ITS generation even
    after a later rebalance has relabeled the live dictionary."""

    __slots__ = ("_codes", "_by_code", "_sorted", "epoch")

    def __init__(self, codes, by_code, sorted_, epoch):
        self._codes = codes
        self._by_code = by_code
        self._sorted = sorted_
        self.epoch = epoch

    def decode(self, code: int) -> str:
        return self._by_code[int(code)]

    def decode_many(self, codes) -> list[str]:
        return [self._by_code[int(c)] for c in np.asarray(codes)]

    def items_sorted(self) -> list[tuple[int, str]]:
        return [(self._codes[s], s) for s in self._sorted]


class StringDictionary:
    """Host-side string dictionary: str <-> ORDER-PRESERVING int64 code.

    Codes are assigned by gap labeling in a 2^63-wide label space:
    a new string gets the midpoint of its lexicographic neighbors'
    labels (append/prepend get a fixed stride so sorted bulk loads do
    not bisect the space). Integer comparison of codes == lexicographic
    comparison of strings, FOREVER — codes never change once assigned,
    so device arrangements sorted by code lanes stay sorted as the
    dictionary grows (the property that unblocks ORDER BY / MIN / MAX /
    TopK over text on device; the reference gets it from sortable Row
    bytes, repr/src/row.rs + doc/developer/row-encoding.md).

    Labels are CONTENT-INTERPOLATED into the neighbor gap: the new
    string's fractional position between its neighbors (computed from
    the bytes after the neighbors' common prefix) picks the label, so
    monotone insertion runs spread proportionally through the gap
    instead of halving it per insert (plain midpoint labeling dies in
    ~60 nested inserts; interpolation handles the common sorted-bulk
    and generated-result patterns). A truly adversarial order can
    still exhaust a gap; that raises rather than silently relabeling,
    since relabeling would corrupt device-resident state.
    """

    MIN_LABEL = -(1 << 62)
    MAX_LABEL = 1 << 62

    def __init__(self):
        import threading

        self._sorted: list[str] = []  # lexicographically sorted
        self._codes: dict[str, int] = {}
        self._by_code: dict[int, str] = {}
        self.version = 0  # bumped on every insert (env-cache key)
        # Relabeling epoch: bumped by rebalance(). Every holder of codes
        # OUTSIDE this object (env caches, device arrangements, MIR
        # literals) must treat a changed epoch as total invalidation.
        self.epoch = 0
        self._lock = threading.RLock()
        # Process-wide recovery hooks: called (with the old->new remap)
        # inside rebalance() so in-process holders of codes (controller
        # command history, replica dataflows) can remap/rebuild.
        self._listeners: list = []

    def snapshot(self) -> "DictSnapshot":
        """An epoch-coherent read view. rebalance() REBINDS the internal
        maps (never mutates them in place), so a snapshot taken before a
        rebalance keeps decoding pre-rebalance codes correctly while the
        live dictionary already serves the new labeling — multi-row read
        operations (env-table builds, result decodes, persist part
        encodes) capture one snapshot at entry so a concurrent rebalance
        can never make them mix labelings mid-operation (torn reads were
        observed as KeyError on decode and garbage env tables)."""
        with self._lock:
            return DictSnapshot(
                self._codes, self._by_code, self._sorted, self.epoch
            )

    def lock(self):
        """The dictionary's reentrant lock: held by rebalance() for the
        whole relabel+listener cycle. Long read-modify cycles that must
        not interleave with a rebalance (the env-table build, which both
        reads items and encodes result strings) run under it."""
        return self._lock

    def add_rebalance_listener(self, fn) -> None:
        with self._lock:
            self._listeners.append(fn)

    def remove_rebalance_listener(self, fn) -> None:
        with self._lock:
            if fn in self._listeners:
                self._listeners.remove(fn)

    def rebalance(self) -> dict:
        """Reassign every code evenly across the label space (order
        preserved) after gap exhaustion. Returns the {old: new} remap.

        Device-resident arrangements and host caches keyed by code
        become garbage: registered listeners fire synchronously (under
        the lock) so the process's holders can remap host literals and
        schedule dataflow rebuilds. Cross-PROCESS holders (a remote
        replica's own dictionary) are not reached — the separate-process
        replica path keeps its own dictionary and must hit its own
        exhaustion independently (documented limitation, L7)."""
        with self._lock:
            n = len(self._sorted)
            span = self.MAX_LABEL - self.MIN_LABEL
            remap: dict[int, int] = {}
            new_codes: dict[str, int] = {}
            new_by_code: dict[int, str] = {}
            for i, s in enumerate(self._sorted):
                new = self.MIN_LABEL + (i + 1) * span // (n + 1)
                remap[self._codes[s]] = new
                new_codes[s] = new
                new_by_code[new] = s
            # REBIND (never mutate) so pre-rebalance snapshots stay
            # coherent: their maps keep the old labeling; _sorted is
            # rebound too because encode() inserts into it in place.
            self._codes = new_codes
            self._by_code = new_by_code
            self._sorted = list(self._sorted)
            self.version += 1
            self.epoch += 1
            for fn in list(self._listeners):
                fn(remap)
            return remap

    @staticmethod
    def _frac(lo_s: str | None, hi_s: str | None, s: str) -> float:
        """Approximate fractional position of ``s`` in (lo_s, hi_s),
        read from the 6 bytes after the neighbors' common prefix."""
        lb = lo_s.encode() if lo_s is not None else b""
        hb = hi_s.encode() if hi_s is not None else None
        sb = s.encode()
        i = 0
        if hb is not None:
            while i < len(lb) and i < len(hb) and lb[i] == hb[i]:
                i += 1

        def val(b) -> int:
            v = 0
            for k in range(6):
                v = (v << 8) | (b[i + k] if i + k < len(b) else 0)
            return v

        lv = val(lb)
        hv = val(hb) if hb is not None else 1 << 48
        sv = val(sb)
        if hv <= lv:
            return 0.5
        f = (sv - lv) / (hv - lv)
        return min(max(f, 1e-4), 1.0 - 1e-4)

    def encode(self, s: str) -> int:
        code = self._codes.get(s)
        if code is not None:
            return code
        import bisect

        with self._lock:
            code = self._codes.get(s)
            if code is not None:
                return code
            i = bisect.bisect_left(self._sorted, s)
            lo_s = self._sorted[i - 1] if i > 0 else None
            hi_s = self._sorted[i] if i < len(self._sorted) else None
            lo = self._codes[lo_s] if lo_s is not None else self.MIN_LABEL
            hi = self._codes[hi_s] if hi_s is not None else self.MAX_LABEL
            gap = hi - lo
            if gap < 2:
                raise DictExhausted(
                    "string dictionary label space exhausted between "
                    f"{lo_s!r} and {hi_s!r}"
                )
            f = self._frac(lo_s, hi_s, s)
            code = lo + max(1, min(gap - 1, int(gap * f)))
            self._sorted.insert(i, s)
            self._codes[s] = code
            self._by_code[code] = s
            self.version += 1
            return code

    def encode_many(self, strings) -> np.ndarray:
        return np.asarray([self.encode(s) for s in strings], dtype=np.int64)

    def encode_bulk(self, strings) -> None:
        """Insert a SET of new strings with positional gap division.

        Content interpolation (encode) fundamentally mislabels
        long-common-prefix families: the whole family maps to a tiny
        content interval, so one-at-a-time inserts pack its members into
        a sliver of the gap regardless of how many there are (observed:
        case-mapped catalog JSON families driving gaps to 1). A bulk
        insert knows every member up front, so each run of new strings
        falling between two existing neighbors divides that gap EVENLY
        by position — 10^6 strings in one gap get even spacing. Env
        table builds (the dominant dense-insert source) use this."""
        import bisect

        with self._lock:
            new = sorted(
                {s for s in strings if s not in self._codes}
            )
            if not new:
                return
            # Group the new strings into runs per existing-neighbor gap.
            runs: list[tuple[int, int, list[str]]] = []
            k = 0
            while k < len(new):
                i = bisect.bisect_left(self._sorted, new[k])
                hi_s = (
                    self._sorted[i] if i < len(self._sorted) else None
                )
                lo = (
                    self._codes[self._sorted[i - 1]]
                    if i > 0
                    else self.MIN_LABEL
                )
                hi = (
                    self._codes[hi_s]
                    if hi_s is not None
                    else self.MAX_LABEL
                )
                run = [new[k]]
                k += 1
                while k < len(new) and (
                    hi_s is None or new[k] < hi_s
                ):
                    run.append(new[k])
                    k += 1
                runs.append((lo, hi, run))
            # Validate EVERY run before mutating anything: a partial
            # insert would leave _sorted stale against _codes, and a
            # concurrent encode() could then hand out an already-taken
            # label (exception atomicity).
            for lo, hi, run in runs:
                if hi - lo <= len(run):
                    raise DictExhausted(
                        f"bulk insert of {len(run)} strings does not "
                        f"fit in gap {hi - lo} at {run[0]!r}"
                    )
            for lo, hi, run in runs:
                gap = hi - lo
                for j, s in enumerate(run, 1):
                    # Even division guarantees uniqueness within the
                    # run; lo/hi are exclusive.
                    code = lo + j * gap // (len(run) + 1)
                    self._codes[s] = code
                    self._by_code[code] = s
            # One sorted rebuild instead of n insorts.
            self._sorted = sorted(self._codes)
            self.version += 1

    def decode(self, code: int) -> str:
        return self._by_code[int(code)]

    def decode_many(self, codes) -> list[str]:
        return [self._by_code[int(c)] for c in np.asarray(codes)]

    def items_sorted(self) -> list[tuple[int, str]]:
        """(code, string) pairs in lexicographic (== code) order."""
        return [(self._codes[s], s) for s in self._sorted]

    def __len__(self) -> int:
        return len(self._sorted)


# A process-global dictionary registry keyed by (collection, column) is
# overkill for now: a single shared dictionary per process is correct (codes
# are only compared for equality) and keeps joins on string columns trivial.
GLOBAL_DICT = StringDictionary()


_EPOCH_DATE = None  # lazy datetime import


def days_to_date(days: int):
    import datetime as _dt

    return _dt.date(1970, 1, 1) + _dt.timedelta(days=int(days))


def date_to_days(d) -> int:
    import datetime as _dt

    return (d - _dt.date(1970, 1, 1)).days


def ms_to_ts(ms: int):
    import datetime as _dt

    return _dt.datetime(1970, 1, 1) + _dt.timedelta(
        milliseconds=int(ms)
    )


def ts_to_ms(ts) -> int:
    import datetime as _dt

    return int((ts - _dt.datetime(1970, 1, 1)).total_seconds() * 1000)


def decode_result_rows(schema: Schema, cols, nulls, time, diff) -> list:
    """Host update arrays -> result rows (vals..., time, diff) with
    STRING dictionary codes decoded to Python strings and NULLs as None.
    Codes are PROCESS-LOCAL, so every surface that hands rows across a
    process boundary (peek responses, SUBSCRIBE events) must decode
    through this one helper."""
    import decimal as _dec

    out = []
    pre_decoded = [
        getattr(c, "dtype", None) == np.dtype(object) for c in cols
    ]
    # One dictionary snapshot for the whole batch: a concurrent
    # rebalance must not relabel codes mid-decode.
    gdict = GLOBAL_DICT.snapshot()
    for i in range(len(diff)):
        vals = []
        for j, col in enumerate(schema.columns):
            if nulls[j] is not None and bool(nulls[j][i]):
                vals.append(None)
            elif pre_decoded[j]:
                # Edge-finalized basic-aggregate columns arrive as raw
                # Python strings (finalize_basic_columns) — they never
                # enter the dictionary.
                vals.append(cols[j][i])
            elif col.ctype is ColumnType.STRING:
                vals.append(gdict.decode(int(cols[j][i])))
            elif col.ctype is ColumnType.DECIMAL and col.scale:
                # scaled int -> exact decimal (the user-facing value;
                # _encode_internal re-scales on the way back in)
                vals.append(
                    _dec.Decimal(int(cols[j][i]))
                    / (10 ** col.scale)
                )
            elif col.ctype is ColumnType.DATE:
                vals.append(days_to_date(cols[j][i]))
            elif col.ctype is ColumnType.TIMESTAMP:
                vals.append(ms_to_ts(cols[j][i]))
            else:
                vals.append(cols[j][i].item())
        out.append(tuple(vals) + (int(time[i]), int(diff[i])))
    return out


def parse_text_value(raw: str, col: Column):
    """pg COPY text-format field -> python value for the column type."""
    import datetime as _dt
    import decimal as _dec

    t = col.ctype
    try:
        if t is ColumnType.BOOL:
            s = raw.strip().lower()
            if s in ("t", "true", "1", "yes", "on"):
                return True
            if s in ("f", "false", "0", "no", "off"):
                return False
            raise ValueError(raw)
        if t in (ColumnType.INT32, ColumnType.INT64):
            return int(raw)
        if t is ColumnType.FLOAT64:
            return float(raw)
        if t is ColumnType.DECIMAL:
            return _dec.Decimal(raw)
        if t is ColumnType.DATE:
            s = raw.strip()
            if s.lstrip("-").isdigit():
                return int(s)  # days-since-epoch shorthand
            return (
                _dt.date.fromisoformat(s) - _dt.date(1970, 1, 1)
            ).days
        if t is ColumnType.TIMESTAMP:
            s = raw.strip()
            if s.lstrip("-").isdigit():
                return int(s)  # ms-since-epoch shorthand
            dt = _dt.datetime.fromisoformat(s.replace("T", " "))
            return int(
                (dt - _dt.datetime(1970, 1, 1)).total_seconds() * 1000
            )
        return raw
    except (ValueError, _dec.InvalidOperation) as exc:
        # ValueError here; callers in the SQL layer surface it as a
        # PlanError-compatible statement failure
        raise ValueError(
            f"invalid {t.value} value {raw!r} for column {col.name!r}"
        ) from exc


# The error-stream schema (one column: the error code; expr/errors.py
# maps codes to messages). Every dataflow maintains an arrangement of
# this shape next to its data output — the ok/err collection pair
# (compute/src/render.rs:12-101).
ERR_SCHEMA = Schema([Column("err_code", ColumnType.INT64)])
